"""FleetRuntime: one replica's view of the active-active fleet, wired
into the Scheduler.

Responsibilities:

- **partition** — maintain the ring assignment (node -> replica) for
  the current membership + node set, recomputed synchronously inside
  the watch filter (under the cluster lock) so ownership answers are
  never staler than the event stream;
- **shard-filtered watch** — the predicate passed to
  ``ClusterState.subscribe(..., filter=...)``: Node events for owned
  nodes, bound-Pod events for pods on owned nodes (plus the routing
  replica, so its queue bookkeeping sees external binds), unbound-Pod
  events for pods the ring routes here; cluster-scoped kinds pass
  through. The replica's cache therefore IS its shard — the smaller
  snapshot is where the fleet's pods/s scaling comes from;
- **resync** — when membership or the partition shifts beyond single
  delivered events, rebuild cache/queue from cluster truth before the
  next solve and re-publish the node inventory;
- **occupancy** — stage/commit/withdraw this replica's label-bearing
  placements on the exchange, and ``admit()`` each solved placement
  against peers' rows before it is assumed (fleet/reconciler.py).

Ownership admission is the overcommit fence: even before a resync has
rebuilt the cache, ``admit`` rejects placements on nodes the current
assignment no longer grants this replica, so two replicas can never
both commit onto one node (the no-global-overcommit invariant the
fleet sim checks).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .. import metrics
from ..api.objects import Pod
from ..state.cluster import ClusterState, Event
from .membership import FleetMembership, shard_index
from .occupancy import (
    AdmitConflict,
    COMMITTED,
    ExchangeUnreachable,
    NodeRow,
    OccupancyExchange,
    PENDING,
    PeerView,
    PodRow,
)
from .reconciler import CrossShardReconciler, ZONE_KEY
from .ring import HashRing, RingNode, _h, ring_nodes_from


@dataclass
class FleetConfig:
    """SchedulerConfig.fleet: turning this on makes the Scheduler one
    active replica of an N-way fleet instead of the sole owner of the
    cluster."""

    replica: str  # this replica's identity
    replicas: tuple[str, ...] = ()  # the configured universe (incl. self)
    # base lease name for the per-shard LeaderElector identity
    # (<lease>-shard-<i>, i = rank of the replica in the sorted universe)
    lease: str = "kubernetes-tpu-scheduler"
    # the occupancy exchange hub. In-process fleets (the sim, tests, the
    # bench A/B) share one OccupancyExchange; cross-process replicas
    # reach a shared hub over the bulk gRPC boundary — pass a
    # RemoteOccupancyExchange here, or just set hub_address below and
    # let FleetRuntime construct one. None + no hub_address = private
    # hub (single-replica fleet degenerates gracefully).
    exchange: object = None
    # "host:port" of a bulk gRPC server whose HubOp method serves the
    # shared hub (config key fleet.hubAddress). Comma-separate several
    # for a replicated hub deployment ("primary:port,standby:port"):
    # RemoteOccupancyExchange fails over between them with jittered
    # backoff, verifying the hub epoch on every reply is monotone.
    # Ignored when an exchange object is passed explicitly.
    hub_address: str = ""
    # production liveness: poll peers' per-shard leases every
    # lease_poll_s seconds and flip membership when one goes stale
    # (utils/leaderelection.py shard= + membership.refresh_from_leases).
    # Off by default: in-process fleets (the sim, tests) drive
    # membership explicitly via set_alive, and polling a lease-less
    # store would mark every peer dead.
    lease_membership: bool = False
    lease_poll_s: float = 2.0
    # occupancy-staleness bound: the maximum age (seconds) of the
    # cross-shard occupancy view admission may trust. Staleness = the
    # time since this replica's last successful hub fetch PLUS the
    # oldest peer's liveness age inside that view (a peer's true
    # silence = its age at fetch time + how long ago the fetch was;
    # any reachability-proving hub contact refreshes a peer's
    # stamp). Beyond the bound,
    # admission turns CONSERVATIVE: cross-shard-constrained placements
    # (hard spread, required anti-affinity) are rejected — requeue and
    # retry once the exchange heals — rather than admitted against
    # rows that may hide peers' placements. Ownership-only pods are
    # unaffected (disjoint shards need no row exchange).
    max_row_age_s: float = 30.0
    # write-behind flush batch for the remote hub adapter (config key
    # fleet.flushBatch): plain row mutations buffer client-side and
    # land as ONE apply_ops RPC at this cap. Auto-tunable at runtime
    # (kubernetes_tpu/tuning, knob "fleet_flush"); 0 = the adapter's
    # built-in default. In-process hubs ignore it (no wire to batch).
    flush_batch: int = 0
    # per-domain CAS versioning (config key fleet.casDomain; the
    # occupancy module docstring's granularity scope note): scope each
    # compare_and_stage to the row's interference domain instead of
    # the one hub-wide version, so N replicas' concurrent write-behind
    # flushes (a fleet backlog drain's steady state) stop costing
    # every constrained admit a spurious re-fetch round. Off by
    # default — measure scheduler_fleet_admit_cas_conflict_total
    # first; the bench fleet-drain ladder turns it on and reports the
    # conflict delta.
    cas_domain: bool = False

    def __post_init__(self) -> None:
        if not self.replicas:
            self.replicas = (self.replica,)
        self.replicas = tuple(sorted(set(self.replicas) | {self.replica}))


class RemoteOccupancyExchange:
    """Client half of the cross-process occupancy hub: the full
    OccupancyExchange surface, each operation one ``HubOp`` RPC on the
    bulk gRPC boundary (server/bulk.py — the same tensorcodec-framed
    wire the 17–37k pods/s bulk solve path uses).

    Semantics mirror the in-process hub exactly — that is the contract
    FleetRuntime leans on:

    - a hub-side ``ExchangeUnreachable`` (the partition seam) arrives
      as gRPC UNAVAILABLE and is re-raised as ``ExchangeUnreachable``,
      so the PR 8 machinery (dirty flag, cached-view aging, the
      occupancy-staleness bound turning admission conservative) runs
      unchanged over the real wire; any other transport failure
      (server down, deadline, broken connection) degrades the same way;
    - typed ``AdmitConflict`` rejections arrive as ABORTED (version
      race) / FAILED_PRECONDITION (hub write fence) and are re-raised
      typed. The underlying BulkClient never retries them — a CAS
      conflict is a semantic answer, not a flake.

    The client is built with ``retries=0``: hub ops have their OWN
    retry story at the fleet layer (requeue, resync republish, the
    staleness bound), and transparent transport retries underneath it
    would stretch the partition-detection latency the staleness bound
    is calibrated against.

    HUB FAILOVER (hub HA): ``target`` may name SEVERAL endpoints
    (comma-separated) — a primary and its standbys. An op that fails
    unreachable-class on the active endpoint (UNAVAILABLE, connection
    loss, a typed ``HubDeposed`` from a hub that lost its lease, or a
    reply carrying a LOWER epoch than one already verified — the
    client-side half of the epoch fence) rotates to the next endpoint
    under full-jitter backoff; semantic ``AdmitConflict`` rejections
    never rotate or retry (the existing rule). When a reply's epoch
    ADVANCES past the highest seen, a failover happened: the adapter
    records it (``consume_failover``) so FleetRuntime forces a
    wholesale resync republish — the new primary's replicated rows may
    trail whatever the old one acked last, and re-registering from
    cluster truth is the PR 8 dirty-heal path that closes the gap.

    IDEMPOTENT FLUSHES: each flush batch is SEALED with a monotone
    ``(flush_client, flush_seq)`` key before its first send, and a
    retry after a lost reply re-sends the SAME sealed batch under the
    SAME key — the hub dedups it whole, which closes the latent
    double-apply hazard where UNAVAILABLE after a server-side apply
    re-landed the entire buffer (double-staged rows, double-appended
    journal lines). The dedup watermark replicates with the rest of
    the hub state, so the retry dedups even when it lands on the
    promoted standby.

    WRITE-BEHIND ROW TRAFFIC: plain ``stage`` / ``commit`` /
    ``withdraw`` calls buffer client-side and flush as ONE
    ``apply_ops`` RPC — before every read (so any view this replica
    admits against reflects its own prior writes), at the buffer cap,
    and at every resync poll. Per-row unary RPCs would otherwise put
    a wire round trip inside the per-pod apply loop (measured ~4x
    throughput loss on the ladder #8 fleet arm). This is sound
    because the admission-critical row landings don't ride the
    buffer: a cross-shard-CONSTRAINED placement lands synchronously
    via ``compare_and_stage`` (the atomic admit), commit is a
    state-only transition the reconciler ignores (pending and
    committed rows count alike), and a lagging withdraw only makes
    peers OVER-count — conservative. The one scope note: an
    UNconstrained label-bearing pod's stage row (a potential selector
    target for someone else's constraint) may lag peers' views by up
    to one flush window (bounded by the buffer cap and the per-cycle
    resync poll), the cross-process analog of the PR 6 scope notes.
    A buffer that cannot flush (hub unreachable) is retained and
    retried; the wholesale resync republish supersedes it either way.
    """

    _BUFFER_CAP = 256  # default flush batch (FleetConfig.flush_batch=0)
    # base of the full-jitter backoff between endpoint attempts during
    # a failover rotation (seconds; doubles per extra hop)
    _FAILOVER_BACKOFF_S = 0.05

    def __init__(
        self,
        target: str,
        replica: str = "",
        *,
        client=None,
        clients=None,
        clock=None,
        flush_batch: int = 0,
        flush_client_id: str = "",
    ) -> None:
        import random

        from ..server.bulk import BulkClient
        from ..utils.clock import Clock

        self._clock = clock or Clock()
        if clients is not None:
            # explicit client objects (the HA sim/tests: LocalHubClient
            # per in-process hub) — endpoint i is clients[i]
            self._clients = list(clients)
            self._targets = [
                f"client-{i}" for i in range(len(self._clients))
            ]
        elif client is not None:
            self._clients = [client]
            self._targets = [target or "client-0"]
        else:
            self._targets = [
                t.strip() for t in str(target).split(",") if t.strip()
            ]
            self._clients = [
                BulkClient(t, retries=0, clock=clock)
                for t in self._targets
            ]
        if not self._clients:
            raise ValueError("RemoteOccupancyExchange needs >= 1 endpoint")
        self._active = 0
        # highest hub epoch verified on any reply — replies below it
        # come from a deposed primary and are structurally ignored
        self._seen_epoch = 0
        self._failover_pending = False
        self.failovers = 0
        # deterministic per-replica jitter stream (the sim's
        # byte-determinism leans on seeded randomness)
        self._rng = random.Random(f"{replica}/hub-failover")
        self._replica = replica
        # flush-idempotency identity: scopes this client incarnation's
        # flush_seq stream at the hub, so a RESTARTED replica starting
        # back at seq 0 is never mistaken for a stale retry. Random —
        # it never lands in journals/traces, so determinism holds.
        if not flush_client_id:
            import uuid

            flush_client_id = f"{replica or 'r'}-{uuid.uuid4().hex[:8]}"
        self._flush_client = flush_client_id
        self._flush_seq = 0
        # sealed flush batches awaiting an acknowledged apply_ops:
        # [(seq, ops)] in send order; the OPEN buffers below seal into
        # one batch at flush time
        self._sealed: list = []
        # instance flush batch: the auto-tunable write-behind cap
        # (kubernetes_tpu/tuning knob "fleet_flush"); class default
        # unless configured
        self._buffer_cap = int(flush_batch) or self._BUFFER_CAP
        # buffered [kind, arg] mutations awaiting one apply_ops RPC;
        # callers are single-threaded per replica (the scheduler's
        # locked apply phase / driver loop)
        self._buffer: list = []
        # journal lines ride the SAME apply_ops flush but live in a
        # SEPARATE buffer: row mutations are superseded by the
        # wholesale resync republish (replace_pod_rows clears them),
        # journal lines are append-only history that nothing
        # re-creates — clearing them with the rows would silently
        # lose hub-aggregation lines the shipping cursor already
        # advanced past (review-caught). Bounded: a long partition
        # drops the OLDEST lines at the cap, counted so the loss is
        # observable instead of silent.
        self._journal_buffer: list = []
        self.journal_lines_dropped = 0
        self._JOURNAL_BUFFER_CAP = 8192
        # a flush observed the hub write fence (this replica was
        # retired): sticky until re-registration, surfaced as a typed
        # AdmitConflict at the NEXT row mutation so FleetRuntime's
        # handlers set _needs_resync exactly like the in-process path
        # (a read-path flush has no caller prepared for the typed
        # conflict, so it cannot raise there — review-caught)
        self._fenced_seen = False

    @property
    def _client(self):
        """The active endpoint's client (kept for introspection and
        the single-endpoint tests that monkeypatch it)."""
        return self._clients[self._active]

    def _call_endpoint(self, client, op: str, **meta) -> dict:
        """One attempt against one endpoint, errors normalized to the
        hub's typed exceptions (a LocalHubClient raises them directly;
        the gRPC transport arrives as status codes)."""
        import grpc

        from .occupancy import (
            AdmitConflict,
            ExchangeUnreachable,
            HubDeposed,
        )

        try:
            return client.hub_op(op, **meta)
        except (AdmitConflict, ExchangeUnreachable):
            raise  # already typed (HubDeposed subclasses unreachable)
        except grpc.RpcError as e:
            code = getattr(e, "code", lambda: None)()
            name = code.name if code is not None else ""
            details = getattr(e, "details", lambda: "")() or name
            if name == "ABORTED":
                raise AdmitConflict(details) from None
            if name == "FAILED_PRECONDITION":
                raise AdmitConflict(details, fenced=True) from None
            if name == "PERMISSION_DENIED":
                raise HubDeposed(details) from None
            raise ExchangeUnreachable(details) from None
        except ConnectionError as e:
            raise ExchangeUnreachable(str(e)) from None

    def _op(self, op: str, **meta) -> dict:
        """One hub op with endpoint failover: unreachable-class
        failures (incl. HubDeposed and stale-epoch replies) rotate
        through the endpoint list under full-jitter backoff; semantic
        AdmitConflict rejections surface immediately from whichever
        endpoint answered (and make it the active one — a hub that
        answers semantically IS the serving primary)."""
        import time

        from .occupancy import AdmitConflict, ExchangeUnreachable

        t0 = time.perf_counter()
        try:
            last: Exception | None = None
            n = len(self._clients)
            for attempt in range(n):
                idx = (self._active + attempt) % n
                if attempt:
                    # full jitter: N replicas failing over at the same
                    # instant must not stampede the standby in lockstep
                    self._clock.sleep(
                        self._rng.uniform(
                            0.0,
                            self._FAILOVER_BACKOFF_S
                            * (2 ** (attempt - 1)),
                        )
                    )
                try:
                    out = self._call_endpoint(
                        self._clients[idx], op, **meta
                    )
                except AdmitConflict:
                    self._active = idx
                    raise
                except ExchangeUnreachable as e:  # incl. HubDeposed
                    last = e
                    continue
                epoch = int(out.get("epoch") or 0)
                if epoch and epoch < self._seen_epoch:
                    # a stale (lower-epoch) hub answered — the epoch
                    # fence says its answer is void: rotate on
                    last = ExchangeUnreachable(
                        f"hub endpoint {self._targets[idx]} answered "
                        f"with stale epoch {epoch} < {self._seen_epoch}"
                    )
                    continue
                if epoch > self._seen_epoch:
                    if self._seen_epoch:
                        # the epoch advanced mid-session: a failover.
                        # Flag it so FleetRuntime forces the wholesale
                        # resync republish at its next poll.
                        self._failover_pending = True
                        self.failovers += 1
                        metrics.hub_failover_total.inc()
                    self._seen_epoch = epoch
                    metrics.hub_epoch.set(epoch)
                self._active = idx
                return out
            raise (
                last
                if last is not None
                else ExchangeUnreachable("no hub endpoints configured")
            )
        finally:
            metrics.fleet_hub_rpc_seconds.labels(op).observe(
                time.perf_counter() - t0
            )

    def consume_failover(self) -> bool:
        """True once per observed hub failover (epoch advance):
        FleetRuntime polls this in maybe_resync and forces a wholesale
        republish from cluster truth — the new primary's replicated
        rows may trail whatever the deposed one acked last."""
        moved, self._failover_pending = self._failover_pending, False
        return moved

    def hub_status(self) -> dict:
        """The serving hub's status plus this client's failover state
        (the ``GET /debug/hub`` body for a remote-hub fleet)."""
        out = self._op("hub_status")
        status = dict(out.get("status") or {})
        status["client"] = {
            "endpoints": list(self._targets),
            "active": self._targets[self._active],
            "seen_epoch": self._seen_epoch,
            "failovers": self.failovers,
            "pending_flush": self._pending_flush(),
        }
        return status

    def flush(self) -> None:
        """Drain the write-behind buffers: the open buffer (rows +
        piggybacked journal lines) SEALS into one batch under a fresh
        ``(flush_client, flush_seq)`` key, then every sealed batch
        ships in order, one apply_ops RPC each (steady state: exactly
        one). On a transport failure the unacknowledged batches are
        RETAINED — a retry re-sends the SAME sealed batch under the
        SAME key, and the hub's dedup drops it whole if the lost reply
        hid a completed apply (the double-apply fix). A fenced
        rejection DROPS that batch's rows — a retired replica's rows
        must not land; its healed incarnation re-registers from truth
        — but NOT the journal half: the hub lands journal lines before
        the fence-checked row ops, so the fenced RPC's lines are
        already aggregated."""
        from .occupancy import AdmitConflict

        if self._buffer or self._journal_buffer:
            ops = [
                ["journal", line] for line in self._journal_buffer
            ] + self._buffer
            self._sealed.append((self._flush_seq, ops))
            self._flush_seq += 1
            self._buffer = []
            self._journal_buffer = []
        while self._sealed:
            seq, ops = self._sealed[0]
            try:
                self._op(
                    "apply_ops", replica=self._replica, ops=ops,
                    flush_seq=seq, flush_client=self._flush_client,
                )
            except AdmitConflict:
                # fenced: the rows must not land — drop the batch, and
                # remember so the next mutation surfaces the typed
                # conflict (the in-process hub raises it inline;
                # silently succeeding here would leave every later row
                # discarded without the replica ever learning to
                # resync). Its journal lines landed pre-fence.
                self._fenced_seen = True
                self._sealed.pop(0)
                continue
            except Exception:
                self._cap_retained()
                raise
            self._sealed.pop(0)

    def _cap_retained(self) -> None:
        """Bound the retained sealed batches through a long partition:
        row ops are droppable (the raise sets the caller's dirty flag
        and the first reachable resync republishes wholesale from
        truth); journal lines have no republish path, so only the
        OLDEST beyond the cap drop, counted so the loss is observable
        instead of silent."""
        rows = sum(
            1
            for _seq, ops in self._sealed
            for kind, _arg in ops
            if kind != "journal"
        )
        if rows > 4 * self._buffer_cap:
            self._strip_sealed_rows()
        jl = sum(
            1
            for _seq, ops in self._sealed
            for kind, _arg in ops
            if kind == "journal"
        )
        excess = jl - self._JOURNAL_BUFFER_CAP
        if excess > 0:
            self.journal_lines_dropped += excess
            trimmed = []
            for seq, ops in self._sealed:
                kept = []
                for op in ops:
                    if op[0] == "journal" and excess > 0:
                        excess -= 1
                        continue
                    kept.append(op)
                trimmed.append((seq, kept))
            self._sealed = trimmed
        # a batch emptied by the caps still consumed its seq — dropping
        # it is safe (the hub's dedup watermark only ever compares <=)
        self._sealed = [(s, ops) for s, ops in self._sealed if ops]

    def _strip_sealed_rows(self) -> None:
        """Drop the ROW halves of retained sealed batches, keeping
        journal ops (rows re-create via the wholesale republish;
        journal history re-creates nowhere). Emptied batches drop
        whole — their consumed seq is safe, the dedup watermark only
        compares <=. Shared by the retention cap and the resync
        republish that supersedes buffered rows."""
        self._sealed = [
            (seq, [o for o in ops if o[0] == "journal"])
            for seq, ops in self._sealed
        ]
        self._sealed = [(s, ops) for s, ops in self._sealed if ops]

    def _pending_flush(self) -> int:
        return (
            len(self._buffer)
            + len(self._journal_buffer)
            + sum(len(ops) for _seq, ops in self._sealed)
        )

    def _buffered(self, kind: str, arg) -> None:
        if self._fenced_seen:
            from .occupancy import AdmitConflict

            # sticky until re-registration: rows of a retired replica
            # must not even buffer, and the caller (FleetRuntime's
            # stage/commit/withdraw handlers) flags the resync that
            # re-registers
            raise AdmitConflict(
                f"replica {self._replica} observed the hub write fence "
                "at a prior flush: no row mutation may land until a "
                "wholesale republish re-registers it",
                fenced=True,
            )
        self._buffer.append([kind, arg])
        if len(self._buffer) >= self._buffer_cap:
            self.flush()

    def set_buffer_cap(self, n: int) -> None:
        """Retarget the write-behind flush batch (the auto-tuner's
        "fleet_flush" knob). Safe at any point: the cap is only
        consulted on append, and a shrink below the current buffer
        length simply flushes at the next mutation."""
        self._buffer_cap = max(int(n), 1)

    # -- the OccupancyExchange surface --

    @property
    def version(self) -> int:
        self.flush()
        return int(self._op("version")["version"])

    def peers_version(self, replica: str) -> int:
        self.flush()
        return int(self._op("peers_version", replica=replica)["version"])

    def publish_nodes(self, replica: str, rows) -> None:
        self.flush()
        self._op(
            "publish_nodes", replica=replica,
            nodes=[[r.node, r.zone] for r in rows],
        )
        self._fenced_seen = False  # wholesale republish re-registers

    def stage(self, replica: str, row: PodRow) -> None:
        from .occupancy import pod_row_to_list

        self._buffered("stage", pod_row_to_list(row))

    def compare_and_stage(
        self, replica: str, row: PodRow, expected_version: int,
        *, domain_scope: bool = False,
    ) -> int:
        from .occupancy import pod_row_to_list

        # the CAS never buffers — it IS the atomic admit. Flush first
        # so expected_version (from the flushed-before read) stays
        # consistent with this replica's own write stream.
        self.flush()
        return int(
            self._op(
                "cas_stage", replica=replica, row=pod_row_to_list(row),
                expect=int(expected_version),
                domain_scope=bool(domain_scope),
            )["version"]
        )

    def replace_pod_rows(self, replica: str, rows) -> None:
        from .occupancy import pod_row_to_list

        # wholesale from truth supersedes anything buffered — open
        # buffer AND the row halves of retained sealed batches (their
        # journal lines still ship; nothing re-creates journal history)
        self._buffer.clear()
        self._strip_sealed_rows()
        self._op(
            "replace_pod_rows", replica=replica,
            rows=[pod_row_to_list(r) for r in rows],
        )
        self._fenced_seen = False  # wholesale republish re-registers

    def commit(self, replica: str, pod_key: str) -> None:
        self._buffered("commit", pod_key)

    def withdraw(self, replica: str, pod_key: str) -> None:
        self._buffered("withdraw", pod_key)

    def retire(self, replica: str) -> None:
        self.flush()
        self._op("retire", replica=replica)

    # -- fleet backlog drain ledger ops (fleet/drain.py) --

    def drain_init(
        self, replica: str, partitions, residual,
        *, membership_version: int = 0,
    ) -> dict:
        self.flush()
        return dict(
            self._op(
                "drain_init", replica=replica,
                partitions={
                    str(r): list(ks) for r, ks in partitions.items()
                },
                residual=list(residual),
                membership_version=int(membership_version),
            ).get("status")
            or {}
        )

    def drain_claim(self, replica: str) -> dict | None:
        self.flush()
        lease = self._op("drain_claim", replica=replica).get("lease")
        return dict(lease) if lease else None

    def drain_progress(self, replica: str, keys) -> int:
        # flush first: the progress report asserts this chunk's rows
        # landed, so the buffered stage/commit ops must precede it
        self.flush()
        return int(
            self._op(
                "drain_progress", replica=replica, keys=list(keys)
            ).get("done")
            or 0
        )

    def drain_complete(self, replica: str, lease_id: str) -> bool:
        self.flush()
        return bool(
            self._op(
                "drain_complete", replica=replica, lease=str(lease_id)
            ).get("ok")
        )

    def drain_status(self) -> dict:
        return dict(self._op("drain_status").get("status") or {})

    def set_degraded(self, replica: str, degraded: bool) -> None:
        self.flush()
        self._op("set_degraded", replica=replica, degraded=bool(degraded))

    def degraded_replicas(self) -> frozenset:
        return frozenset(self._op("degraded_replicas")["replicas"] or ())

    def hand_off(
        self, to_replica: str, pod_key: str, hops: int,
        from_replica: str | None = None,
        trace: str = "",
    ) -> None:
        self.flush()
        self._op(
            "hand_off", to=to_replica, pod=pod_key, hops=int(hops),
            trace=trace,
            **({"from": from_replica} if from_replica is not None else {}),
        )

    def claim_handoffs(self, replica: str) -> list:
        self.flush()
        return [
            (row[0], int(row[1]), str(row[2]) if len(row) > 2 else "")
            for row in self._op("claim_handoffs", replica=replica)[
                "handoffs"
            ]
            or []
        ]

    def ship_journal(self, replica: str, lines) -> None:
        """Journal segments ride the SAME apply_ops flush as the
        buffered row mutations — the tentpole's no-new-RPC-cadence
        contract — but in their own buffer: they are NOT fence-gated
        (append-only observability, so they bypass the sticky-fence
        check — a fenced zombie's history still reaches the hub at
        its next flush), and they must survive the row buffer's
        destructive paths (the resync republish clears rows it
        supersedes; nothing re-creates journal history)."""
        self._journal_buffer.extend(lines)
        if self._pending_flush() >= self._buffer_cap:
            self.flush()

    def journal_lines(self) -> list[str]:
        self.flush()
        return list(self._op("journal_lines")["lines"] or [])

    def pending_handoff_keys(self) -> set:
        self.flush()
        return set(self._op("pending_handoff_keys")["keys"] or ())

    def peers_view(self, replica: str) -> PeerView:
        from .occupancy import pod_row_from_list

        self.flush()
        out = self._op("peers_view", replica=replica)
        return PeerView(
            version=int(out["version"]),
            node_rows=tuple(
                NodeRow(node=n, zone=z) for n, z in out.get("nodes") or []
            ),
            pod_rows=tuple(
                pod_row_from_list(r) for r in out.get("pods") or []
            ),
            peer_ages=tuple(
                (r, float(a)) for r, a in out.get("peerAges") or []
            ),
        )

    def close(self) -> None:
        try:
            self.flush()
        except Exception:
            pass  # teardown is best-effort; resync owns recovery
        for client in self._clients:
            client.close()


class FleetRuntime:
    def __init__(
        self, config: FleetConfig, cluster: ClusterState, clock
    ) -> None:
        self.config = config
        self.cluster = cluster
        self.clock = clock
        self.replica = config.replica
        if config.exchange is not None:
            self.exchange: OccupancyExchange = config.exchange
        elif config.hub_address:
            self.exchange = RemoteOccupancyExchange(
                config.hub_address, config.replica, clock=clock,
                flush_batch=config.flush_batch,
            )
        else:
            self.exchange = OccupancyExchange()
        self.membership = FleetMembership(config.replicas, config.replica)
        self.ring = HashRing(self.membership.universe)
        # alive-subset ring, cached per membership version: routes_pod
        # runs inside the watch filter for every pod event, and
        # rebuilding the ring there would tax the whole ingest path
        self._alive_ring = self.ring
        self._alive_ring_version = self.membership.version
        self.reconciler = CrossShardReconciler(config.replica)
        self.shard = shard_index(self.membership.universe, config.replica)
        self.lease_name = f"{config.lease}-shard-{self.shard}"
        # node -> replica, recomputed on every Node event and membership
        # change. Reads/writes happen under cluster.lock (the watch
        # filter and the scheduler's apply phase both hold it).
        self._assignment: dict[str, str] = {}  # ktpu: guarded-by(cluster.lock)
        self._needs_resync = False  # ktpu: guarded-by(cluster.lock)
        self._seen_membership_version = self.membership.version
        # cross-shard retry wakeup: pods parked by a reconcile conflict
        # have no waking watch event when a PEER's occupancy changes
        # (peer placements are invisible to this replica's informer by
        # design). Track rejections and the exchange version; when the
        # exchange has moved since the last conflict, the next cycle
        # requeues parked pods for another admission attempt.
        self._conflicts_since_wake = 0  # ktpu: guarded-by(cluster.lock)
        self._wake_version = self.exchange.version
        # pod-routing overrides (the handoff protocol): a pod this
        # replica released to a peer no longer routes here even though
        # the hash says so, and a pod claimed from a peer routes here
        # even though the hash says otherwise. Maintained under
        # cluster.lock; swept against cluster truth on every resync.
        self._routed_away: set[str] = set()  # ktpu: guarded-by(cluster.lock)
        self._routed_here: dict[str, int] = {}  # key -> hops  # ktpu: guarded-by(cluster.lock)
        # consecutive reconcile rejections per pod — the handoff
        # trigger (>= _HANDOFF_AFTER with an alive peer to take it)
        self._reject_counts: dict[str, int] = {}  # ktpu: guarded-by(cluster.lock)
        # per-shard lease poll throttle (config.lease_membership)
        self._last_lease_poll = float("-inf")
        # occupancy-staleness bounds: the last successfully fetched
        # peer view and when it was fetched. While the hub is
        # unreachable admission runs against this cache; its growing
        # age (plus the oldest peer publish age inside it) is the
        # staleness admission compares against max_row_age_s.
        self._peer_view: PeerView | None = None  # ktpu: guarded-by(cluster.lock)
        self._view_at = float("-inf")  # ktpu: guarded-by(cluster.lock)
        # hub writes that failed while partitioned: rows must republish
        # wholesale at the next reachable resync
        self._exchange_dirty = False  # ktpu: guarded-by(cluster.lock)
        # retires that failed while the hub was unreachable (a peer
        # died mid-blackout): re-issued at the next reachable poll —
        # a dead peer's frozen publish stamp left on the hub would
        # otherwise age every survivor's staleness bound forever
        self._pending_retires: set[str] = set()  # ktpu: guarded-by(cluster.lock)
        # conservative-admission rejections under stale rows (the sim's
        # hub_partition invariant asserts the path engaged)
        self.stale_rejections = 0  # ktpu: guarded-by(cluster.lock)
        # cross-process atomic admit bookkeeping: pods whose pending
        # row already landed at the hub via compare_and_stage during
        # admit (the apply phase's stage() must not re-send it), and
        # how many CAS rejections this replica has absorbed (typed
        # AdmitConflict — version races and fenced writes)
        self._cas_staged: set[str] = set()  # ktpu: guarded-by(cluster.lock)
        self.cas_conflicts = 0  # ktpu: guarded-by(cluster.lock)
        # journal-shipping cursor: how many of this replica's journal
        # records have been shipped to the hub's aggregation surface
        # (PodDecisionJournal.total_records is monotone, so the cursor
        # survives a bounded journal's deque eviction)
        self._journal_shipped = 0
        with cluster.lock:
            self._recompute(cluster.list_nodes())
        metrics.fleet_replicas.set(len(self.membership.alive()))

    # -- write-behind flush batch (the auto-tuner's fleet_flush knob) --

    def flush_batch(self) -> int | None:
        """Current write-behind flush batch of the remote hub adapter,
        or None for an in-process hub (nothing to batch — the knob is
        not tunable then)."""
        if isinstance(self.exchange, RemoteOccupancyExchange):
            return self.exchange._buffer_cap
        return None

    def set_flush_batch(self, n: int) -> None:
        """Retarget the remote adapter's flush batch (no-op for an
        in-process hub)."""
        if isinstance(self.exchange, RemoteOccupancyExchange):
            self.exchange.set_buffer_cap(n)

    def hub_status(self) -> dict:
        """The ``GET /debug/hub`` body: the serving hub's role / epoch
        / cursors / HA counters, plus this replica's client-side view
        (endpoints, active endpoint, verified epoch, failovers,
        pending flush). Raises ExchangeUnreachable while no hub
        endpoint answers — the HTTP handler maps that to 503."""
        if isinstance(self.exchange, RemoteOccupancyExchange):
            return self.exchange.hub_status()
        status = self.exchange.hub_status()
        status["client"] = {
            "endpoints": ["in-process"],
            "active": "in-process",
            "seen_epoch": status.get("epoch", 0),
            "failovers": 0,
            "pending_flush": 0,
        }
        return status

    # max journal lines per shipped segment: bounds both the hub-side
    # append and the piggybacked flush payload (a mega-drain's burst
    # catches up over the next few cycles instead of one huge RPC)
    _JOURNAL_SEGMENT_LINES = 1024

    def ship_journal_segment(self, scheduler) -> int:
        """Ship this replica's journal records written since the last
        segment to the hub's append-only aggregation surface — the
        cross-replica `obs explain --fleet` source. Piggybacks on the
        existing transport cadence: the remote adapter buffers the
        lines into the SAME write-behind apply_ops flush the row
        mutations ride (no new RPC cadence); the in-process hub is one
        locked append. Bounded per call; returns lines shipped."""
        journal = scheduler.journal
        if journal is None:
            return 0
        pending = journal.total_records - self._journal_shipped
        if pending <= 0:
            return 0
        lines = journal.lines  # flushes the lazy pending records
        start = len(lines) - pending
        if start < 0:
            # a bounded serve journal evicted unshipped lines before
            # they shipped: skip them (the streaming file sink is the
            # durable store; the hub keeps the recent window)
            self._journal_shipped += -start
            start = 0
            pending = len(lines)
        take = min(pending, self._JOURNAL_SEGMENT_LINES)
        if isinstance(lines, list):
            # unbounded journal (sims, mega-drains): O(take) slice,
            # never a full O(total_records) copy per cycle
            segment = lines[start : start + take]
        else:
            from itertools import islice

            segment = list(islice(lines, start, start + take))
        if not segment:
            return 0
        try:
            self.exchange.ship_journal(self.replica, segment)
        except ExchangeUnreachable:
            return 0  # retry next cycle; cursor unmoved
        except AdmitConflict:
            # journal shipping is not fence-gated at the hub, but a
            # remote adapter's piggybacked flush can still surface the
            # sticky fence — flag the resync like every other handler
            with self.cluster.lock:
                self._needs_resync = True
            return 0
        self._journal_shipped += take
        return take

    _HANDOFF_AFTER = 2
    # bounded re-admission rounds when compare_and_stage loses its
    # version race: each round re-fetches the peer view and re-runs the
    # host-side recheck against the rows that beat it. Exhaustion is an
    # ordinary reconcile rejection (requeue + retry), never a stall.
    _CAS_ATTEMPTS = 3

    # -- partition maintenance --

    def _ring_alive(self) -> HashRing:
        if self._alive_ring_version != self.membership.version:
            self._alive_ring = self.ring.with_alive(
                self.membership.alive()
            )
            self._alive_ring_version = self.membership.version
        return self._alive_ring

    # callers hold the cluster lock (watch filter, init, set_alive): ktpu: holds(cluster.lock)
    def _recompute(self, nodes) -> None:
        """Rebuild the assignment; flag a resync when any node other
        than freshly added/deleted ones changed owner relative to this
        replica (those moves have no dedicated watch event)."""
        ring = self._ring_alive()
        new = ring.assign(ring_nodes_from(nodes))
        old = self._assignment
        if old:
            for name in set(old) & set(new):
                mine_before = old[name] == self.replica
                mine_after = new[name] == self.replica
                if mine_before != mine_after:
                    self._needs_resync = True
        self._assignment = new

    # reads the assignment the filter maintains under the lock: ktpu: holds(cluster.lock)
    def owns_node(self, name: str) -> bool:
        return self._assignment.get(name) == self.replica

    # same locked callers as owns_node: ktpu: holds(cluster.lock)
    def routes_pod(self, pod_key: str, pod: Pod | None = None) -> bool:
        if pod_key in self._routed_here:
            return True
        if pod_key in self._routed_away:
            return False
        # pod-group members route by their GANG id, not their own key:
        # the gang gate assembles a group from ONE replica's queue, so
        # splitting members across the ring would make every gang
        # permanently short. Callers that have the Pod pass it; key-only
        # callers (handoff rows) are never gang members (handoff is
        # disabled for them in _apply_group).
        route_key = pod_key
        if pod is not None:
            from ..gang import GangTracker

            gid = GangTracker.gang_of(pod)
            if gid is not None:
                route_key = f"gang:{gid}"
        return self._ring_alive().route(route_key) == self.replica

    def set_alive(self, replicas) -> bool:
        """Membership transition (the sim's replica_loss driver; the
        production path calls refresh_membership below). Flags a
        resync; the scheduler applies it before its next solve."""
        before = set(self.membership.alive())
        changed = self.membership.set_alive(replicas)
        if changed:
            self._membership_changed(before)
        return changed

    def refresh_membership(self) -> bool:
        """Poll peers' per-shard leases (production liveness)."""
        before = set(self.membership.alive())
        changed = self.membership.refresh_from_leases(
            self.cluster, self.config.lease, self.clock.now()
        )
        if changed:
            self._membership_changed(before)
        return changed

    def _membership_changed(self, before: set) -> None:
        """Shared membership-transition tail: recompute the partition,
        flag a resync, and REVOKE the commit fence of every peer that
        just went dead — the commit-path half of the ownership fence.
        The survivors are about to re-own the dead peer's shard; if it
        is actually a zombie (lease stalled, process alive), its next
        bind finds its token revoked at the state service and gets
        Conflict, so it can never double-bind what a survivor re-owns.
        The revocation is committed at the AUTHORITY (the state
        service), which is what makes it partition-safe: the zombie's
        own stale view is irrelevant."""
        with self.cluster.lock:
            self._recompute(self.cluster.list_nodes())
            self._needs_resync = True
            for dead in sorted(before - set(self.membership.alive())):
                i = shard_index(self.membership.universe, dead)
                self.cluster.revoke_fence(
                    f"{self.config.lease}-shard-{i}"
                )
                # retire the dead peer's exchange state too: its
                # committed placements become visible to the adopting
                # replicas through their own resync re-list (keeping
                # the rows would double-count), its pending rows can
                # never commit (fenced), and its frozen publish stamp
                # must not age the survivors' staleness bound forever —
                # a detected-dead peer is handled by membership, not by
                # conservative admission. (A SILENT hub-partitioned
                # peer that is still lease-alive keeps its rows, and
                # their growing age is exactly what turns peers
                # conservative.) An unreachable hub (mid-failover
                # blackout) defers the retire to the dirty-republish
                # resync instead of crashing the membership transition.
                try:
                    self.exchange.retire(dead)
                except ExchangeUnreachable:
                    self._pending_retires.add(dead)
                    self._exchange_dirty = True
        metrics.fleet_replicas.set(len(self.membership.alive()))

    # -- the shard-filtered watch predicate --

    # ClusterState._emit calls this under its lock: ktpu: holds(cluster.lock)
    def event_filter(self, ev: Event) -> bool:
        if ev.kind == "Node":
            # keep the partition current BEFORE answering ownership —
            # an add/delete changes K, so the capped fill can move
            # other nodes too (flagged for resync by _recompute)
            owned_before = self.owns_node(ev.obj.name)
            self._recompute(self.cluster.list_nodes())
            if ev.type == "DELETED":
                # deliver to the previous owner so its cache drops the
                # node (the new assignment no longer mentions it)
                return owned_before
            return self.owns_node(ev.obj.name)
        if ev.kind == "Pod":
            pod = ev.obj
            if pod.node_name:
                # bound: the owning replica maintains its cache; the
                # routing replica also listens so its queue/in-flight
                # bookkeeping sees external binds of pods it tracked
                return self.owns_node(pod.node_name) or self.routes_pod(
                    pod.key, pod
                )
            return self.routes_pod(pod.key, pod)
        # cluster-scoped kinds (DRA objects, Events, ...) pass through
        return True

    # -- resync --

    def maybe_resync(self, scheduler) -> bool:
        """Apply a pending partition change: rebuild the shard-scoped
        cache and queue from cluster truth, invalidate in-flight
        solves, re-publish the node inventory. Called by both
        scheduling loops before popping a batch."""
        if self.config.lease_membership:
            # production liveness: a dead peer's shard lease going
            # stale is the membership signal (the sim drives set_alive
            # directly instead)
            now = self.clock.now()
            if now - self._last_lease_poll >= self.config.lease_poll_s:
                self._last_lease_poll = now
                self.refresh_membership()
        # ship the journal segment written since the last cycle to the
        # hub's aggregation surface (driver thread, outside the cluster
        # lock: the remote adapter only buffers, the in-process hub is
        # one locked append)
        self.ship_journal_segment(scheduler)
        with self.cluster.lock:
            for dead in sorted(self._pending_retires):
                # a retire deferred by a mid-blackout unreachable hub:
                # the dead peer's rows and frozen publish stamp must
                # come off the (new) hub, or the staleness bound stays
                # conservative fleet-wide forever
                try:
                    self.exchange.retire(dead)
                except ExchangeUnreachable:
                    break  # still dark: retry next poll
                self._pending_retires.discard(dead)
            consume = getattr(self.exchange, "consume_failover", None)
            if consume is not None and consume():
                # the hub epoch advanced (a standby promoted): the new
                # primary's replicated state may trail whatever the
                # deposed one acked last — re-register wholesale from
                # cluster truth (the PR 8 dirty-republish heal), which
                # the forced resync below does
                self._needs_resync = True
            if self._exchange_dirty:
                # hub writes failed while partitioned: once the hub is
                # reachable again, force a full resync so rows and
                # inventory republish wholesale from truth
                try:
                    self.exchange.peers_version(self.replica)
                except ExchangeUnreachable:
                    pass
                else:
                    self._exchange_dirty = False
                    self._needs_resync = True
            try:
                handoffs = self.exchange.claim_handoffs(self.replica)
            except ExchangeUnreachable:
                handoffs = []  # claims wait out the partition
            # adopt pods peers handed off to this replica (sorted,
            # deterministic): the claim makes this replica the pod's
            # route owner, so its watch events flow here from now on
            for key, hops, trace in handoffs:
                try:
                    ns, name = key.split("/", 1)
                    pod = self.cluster.get_pod(ns, name)
                except Exception:
                    continue  # deleted while in handoff flight
                if pod.node_name:
                    continue  # bound while in handoff flight
                if trace and scheduler.journal is not None:
                    # trace propagation across the handoff: the
                    # releasing replica's journey trace id rode the
                    # handoff row — seed it so this replica's records
                    # for the pod continue the SAME trace (obs explain
                    # --fleet renders the whole chain as one trace)
                    scheduler.journal.pod_traces[key] = trace
                self._routed_here[key] = hops
                self._routed_away.discard(key)
                if (
                    key not in scheduler.queue.entries()
                    and key not in scheduler._in_flight
                    and key not in scheduler._waiting
                    and pod.scheduler_name in scheduler.solvers
                ):
                    scheduler.queue.add(pod)
            if self._conflicts_since_wake:
                try:
                    version = self.exchange.peers_version(self.replica)
                except ExchangeUnreachable:
                    version = self._wake_version  # no news while cut off
                if version != self._wake_version:
                    # peers' occupancy moved since this replica parked
                    # pods on reconcile conflicts: give them another
                    # admission attempt (backoff still applies)
                    self._wake_version = version
                    self._conflicts_since_wake = 0
                    scheduler.queue.move_all_to_active_or_backoff(
                        "FleetOccupancyExchange"
                    )
            if (
                not self._needs_resync
                and self._seen_membership_version == self.membership.version
            ):
                return False
            self._needs_resync = False
            self._seen_membership_version = self.membership.version
            self._resync_locked(scheduler)
        return True

    # ktpu: holds(cluster.lock)
    def _resync_locked(self, scheduler) -> None:
        metrics.fleet_resyncs_total.inc()
        owned = {
            n for n, r in self._assignment.items() if r == self.replica
        }
        cache = scheduler.cache
        # drop nodes (and their pods) that left the shard
        for name in sorted(set(cache.nodes) - owned):
            cache.remove_node(name)
        # adopt nodes that joined the shard, with their bound pods
        pods = self.cluster.list_pods()
        nodes = self.cluster.list_nodes()
        for node in nodes:
            if node.name in owned and node.name not in cache.nodes:
                cache.add_node(node)
        known_nodes = {
            n for n, info in cache.nodes.items() if info.node is not None
        }
        tracked = scheduler.queue.entries()
        for pod in pods:
            if pod.node_name:
                if (
                    pod.node_name in known_nodes
                    and pod.key
                    not in cache.nodes[pod.node_name].pods
                ):
                    cache.add_pod(pod)
                continue
            # unbound: adopt pods now routed here (a dead replica's
            # orphans), shed pods routed away
            routed = self.routes_pod(pod.key, pod)
            is_tracked = (
                pod.key in tracked
                or pod.key in scheduler._in_flight
                or pod.key in scheduler._waiting
            )
            if routed and not is_tracked:
                if pod.scheduler_name in scheduler.solvers:
                    scheduler.queue.add(pod)
            elif not routed and pod.key in tracked:
                scheduler.queue.delete(pod.key)
        # rebuild this replica's pod ROWS from cluster truth: a node
        # that changed owner takes its pods' future DELETE events to
        # the NEW owner's filter, so withdraw() would never fire here
        # and a ghost row would distort peers' admission forever
        # (review-caught). Committed rows = labeled pods bound on
        # currently-owned nodes; pending rows survive only while this
        # replica still assumes the pod.
        self.rebuild_pod_rows(cache, pods=pods, nodes=nodes)
        # CAS-staged markers are only meaningful between one admit and
        # its stage; the wholesale row rebuild supersedes any leftovers
        self._cas_staged.clear()
        # sweep routing overrides and reject counts against cluster
        # truth (bound/deleted pods need no routing state)
        live_unbound = {p.key for p in pods if not p.node_name}
        self._routed_away &= live_unbound
        self._routed_here = {
            k: v for k, v in self._routed_here.items() if k in live_unbound
        }
        self._reject_counts = {
            k: v
            for k, v in self._reject_counts.items()
            if k in live_unbound
        }
        # in-flight deferred solves were computed against the old shard
        scheduler._conflict_seq += 1
        scheduler._occupancy_seq += 1
        self.publish_inventory()
        metrics.fleet_owned_nodes.set(len(owned))
        scheduler._refresh_pending_gauge()

    # -- occupancy --

    # called from locked regions of the scheduler: ktpu: holds(cluster.lock)
    def publish_inventory(self) -> None:
        rows = [
            NodeRow(node=n.name, zone=n.labels.get(ZONE_KEY, ""))
            for n in self.cluster.list_nodes()
            if self._assignment.get(n.name) == self.replica
        ]
        try:
            self.exchange.publish_nodes(self.replica, rows)
        except ExchangeUnreachable:
            self._exchange_dirty = True

    # called under cluster.lock (resync, the scheduler's recovery
    # pass): ktpu: holds(cluster.lock)
    def rebuild_pod_rows(self, cache, pods=None, nodes=None) -> None:
        """Replace this replica's exchange pod rows wholesale from
        cluster truth + the live cache: committed rows = labeled pods
        bound on currently-owned nodes, pending rows = placements this
        replica currently ASSUMES. Used at every resync and by the
        restart-recovery pass — a dead incarnation's stale PENDING rows
        (assumed but never bound) roll back here, because the fresh
        incarnation's cache assumes nothing yet. ``pods``/``nodes``
        let a caller that already listed the cluster (the resync)
        avoid paying the O(pods)+O(nodes) listing twice under the
        lock."""
        if pods is None:
            pods = self.cluster.list_pods()
        if nodes is None:
            nodes = self.cluster.list_nodes()
        fresh_rows = []
        node_zone = {
            n.name: n.labels.get(ZONE_KEY, "")
            for n in nodes
            if self._assignment.get(n.name) == self.replica
        }
        for pod in pods:
            if pod.labels and pod.node_name in node_zone:
                fresh_rows.append(
                    PodRow.for_pod(
                        pod, pod.node_name,
                        node_zone[pod.node_name], COMMITTED,
                    )
                )
        for pod_key in list(cache._assumed):
            node = cache.pod_node(pod_key)
            if node in node_zone:
                info = cache.nodes.get(node)
                q = info.pods.get(pod_key) if info is not None else None
                if q is not None and q.labels:
                    fresh_rows.append(
                        PodRow.for_pod(q, node, node_zone[node], PENDING)
                    )
        try:
            self.exchange.replace_pod_rows(self.replica, fresh_rows)
        except ExchangeUnreachable:
            self._exchange_dirty = True

    # called under cluster.lock (admit runs in the apply phase): ktpu: holds(cluster.lock)
    def _peers_view_with_age(self) -> "tuple[PeerView | None, float]":
        """The freshest peer view this replica can get, plus its
        staleness: a fresh hub fetch has the age of its oldest peer
        publish; when the hub is unreachable the cached view serves,
        aging from its fetch time. ``(None, inf)`` before any
        successful fetch — maximally conservative."""
        now = self.clock.now()
        try:
            view = self.exchange.peers_view(self.replica)
        except ExchangeUnreachable:
            view = self._peer_view
        else:
            self._peer_view = view
            self._view_at = now
        if view is None:
            return None, float("inf")
        # a peer's true publish age = its age at fetch time + however
        # long ago the fetch was (zero for a fresh fetch)
        fetch_age = max(now - self._view_at, 0.0)
        oldest_peer = max(
            (peer_age for _r, peer_age in view.peer_ages), default=0.0
        )
        return view, fetch_age + oldest_peer

    def _zone_of(self, cache, node_name: str) -> str:
        info = cache.nodes.get(node_name)
        if info is None or info.node is None:
            return ""
        return info.node.labels.get(ZONE_KEY, "")

    @staticmethod
    def _needs_reconcile(pod: Pod) -> bool:
        """Does this pod carry a constraint whose scope can cross the
        shard boundary (hard topology spread, required anti-affinity)?
        Everything else is fully enforced by the shard-local solve.

        Pod-group members always reconcile: each member's pending row
        must land at the hub through the fenced CAS so peers see a
        staging gang (and so a stale view / AdmitConflict on ANY member
        fails the whole gang round before a single bind)."""
        from ..gang import GANG_LABEL

        if GANG_LABEL in pod.labels:
            return True
        if any(
            c.when_unsatisfiable == "DoNotSchedule"
            for c in pod.topology_spread_constraints
        ):
            return True
        anti = (
            pod.affinity.pod_anti_affinity
            if pod.affinity is not None
            else None
        )
        return anti is not None and bool(anti.required)

    # called from _apply_group's locked apply phase: ktpu: holds(cluster.lock)
    def admit(self, pod: Pod, node_name: str, cache) -> str | None:
        """Pre-assume fleet admission: ownership fence first (the
        no-global-overcommit guarantee), then the cross-shard
        constraint recheck against peers' occupancy rows, then —
        for label-bearing cross-shard-constrained pods — the fenced
        compare-and-stage that lands the pending row at the hub
        ATOMICALLY with the recheck's view version. Two replicas
        racing the same hard-spread slot both pass their host-side
        recheck against the same view; the hub serializes their CAS
        calls, exactly one lands, the loser re-fetches (now seeing the
        winner's pending row) and re-admits — or rejects and requeues
        after _CAS_ATTEMPTS rounds of contention.

        Pod-group members stage through this same fenced CAS one row at
        a time; gang atomicity lives one layer up: the scheduler stages
        EVERY member before any binds, a single member's AdmitConflict
        fails the whole gang round, and the release sweep withdraws the
        already-staged rows (scheduler._release_gang_round via
        _unreserve_all → withdraw) so peers never see a half-staged
        gang outlive its round."""
        if not self.owns_node(node_name):
            metrics.fleet_reconcile_conflicts_total.labels(
                "ownership"
            ).inc()
            return (
                f"node {node_name} is no longer owned by replica "
                f"{self.replica} (partition moved)"
            )
        if not self._needs_reconcile(pod):
            # no cross-shard-scoped constraint: ownership (disjoint
            # shards) is the whole fleet story for this pod — skip the
            # O(peer rows) view (the bench's plain sustained arm would
            # otherwise pay it per pod)
            self._reject_counts.pop(pod.key, None)
            return None
        why = None
        for _attempt in range(self._CAS_ATTEMPTS):
            peers, age = self._peers_view_with_age()
            metrics.fleet_occupancy_row_age_seconds.set(
                age if age != float("inf") else -1.0
            )
            if age > self.config.max_row_age_s:
                # occupancy-staleness bound: the view may hide peers'
                # placements (hub unreachable, or a peer stopped
                # publishing). Admitting a cross-shard-constrained
                # placement against it risks exactly the overcommit the
                # exchange exists to prevent — turn CONSERVATIVE and
                # reject; the pod parks and retries when the exchange
                # version moves (the heal republish bumps it) or via
                # the unschedulable flush.
                metrics.fleet_reconcile_conflicts_total.labels(
                    "stale"
                ).inc()
                self.stale_rejections += 1
                self._conflicts_since_wake += 1
                if peers is not None:
                    self._wake_version = peers.version
                self._reject_counts[pod.key] = (
                    self._reject_counts.get(pod.key, 0) + 1
                )
                shown = "inf" if age == float("inf") else f"{age:.0f}s"
                return (
                    f"fleet occupancy view is {shown} stale (bound "
                    f"{self.config.max_row_age_s:.0f}s): conservative "
                    "admission rejects cross-shard-constrained "
                    "placements until the occupancy exchange heals"
                )
            why = self.reconciler.admit(
                pod, node_name, self._zone_of(cache, node_name), cache,
                peers,
            )
            if why is not None:
                break  # a real constraint conflict, not CAS contention
            if not pod.labels:
                # label-free pods publish no row (they can never match
                # a peer's selector/term), so there is nothing for a
                # racing peer to CAS against either way
                self._reject_counts.pop(pod.key, None)
                return None
            try:
                self.exchange.compare_and_stage(
                    self.replica,
                    PodRow.for_pod(
                        pod, node_name,
                        self._zone_of(cache, node_name), PENDING,
                    ),
                    peers.version,
                    domain_scope=self.config.cas_domain,
                )
            # ktpu: ignore[RETRY001]: CAS loop, not a replay — each attempt re-fetches peers.version and re-runs the host-side recheck before re-staging, so a version conflict retries a NEW request; fenced conflicts break out below. Bounded by _CAS_ATTEMPTS.
            except AdmitConflict as e:
                metrics.fleet_admit_cas_conflict_total.labels(
                    "fenced" if e.fenced else "version"
                ).inc()
                self.cas_conflicts += 1
                if e.fenced:
                    # the hub retired this replica (a peer observed its
                    # lease stale): no row may land until the forced
                    # resync re-registers wholesale — reject and let
                    # the bind-time fence / reacquire path sort out
                    # whether this incarnation still owns anything
                    self._needs_resync = True
                    why = (
                        "fleet occupancy hub fenced this replica "
                        "(membership declared it dead): no placement "
                        "row may land until resync re-registers it"
                    )
                    break
                continue  # version moved: re-fetch and re-admit
            except ExchangeUnreachable:
                # the hub vanished between the view fetch and the CAS:
                # the view already passed the staleness bound, so admit
                # against it (PR 8 partition semantics — the bound is
                # the risk window) and republish wholesale at the first
                # reachable resync
                self._exchange_dirty = True
                self._reject_counts.pop(pod.key, None)
                return None
            else:
                # the pending row is already at the hub: the apply
                # phase's stage() must not re-send it
                self._cas_staged.add(pod.key)
                self._reject_counts.pop(pod.key, None)
                return None
        else:
            why = (
                f"fleet occupancy CAS contention: the hub version moved "
                f"{self._CAS_ATTEMPTS} times during admission — requeue "
                "and retry against quieter rows"
            )
        metrics.fleet_reconcile_conflicts_total.labels(
            "spread" if "spread" in why
            else ("anti" if "anti" in why else "cas")
        ).inc()
        self._conflicts_since_wake += 1
        if peers is not None:
            self._wake_version = peers.version
        self._reject_counts[pod.key] = (
            self._reject_counts.get(pod.key, 0) + 1
        )
        return why

    # called from the scheduler's admit-reject branch under
    # cluster.lock: ktpu: holds(cluster.lock)
    def maybe_hand_off(self, pod: Pod, trace: str = "") -> str | None:
        """After _HANDOFF_AFTER consecutive reconcile rejections,
        release the pod to the next alive replica in its rendezvous
        chain — its shard may be able to host what this one legally
        cannot (e.g. the under-filled spread domain lives there). Hop
        counts cap the walk at one lap of the fleet; a pod the whole
        fleet rejected parks unschedulable wherever it stands.
        ``trace`` is the pod's journey trace id — it rides the handoff
        row so the adopting replica's journal continues the same
        trace. Returns the receiving replica, or None to keep the pod
        local."""
        key = pod.key
        if self._reject_counts.get(key, 0) < self._HANDOFF_AFTER:
            return None
        alive = self.membership.alive()
        if len(alive) < 2:
            return None
        hops = self._routed_here.get(key, 0)
        if hops + 1 >= len(alive):
            return None  # walked the whole fleet: stay parked here
        # degraded replicas (open solve breakers, published through the
        # exchange) sort LAST: refugees route to healthy peers first.
        # Every replica reads the same flag set, so the chain stays a
        # fleet-wide consistent rendezvous order. A dark hub (mid-
        # failover blackout) yields no flags — the hand_off below
        # would fail the same way and keep the pod local regardless.
        try:
            degraded = self.exchange.degraded_replicas()
        except ExchangeUnreachable:
            return None
        chain = sorted(
            alive,
            key=lambda r: (r in degraded, -_h("pod", key, r), r),
        )
        target = chain[(chain.index(self.replica) + 1) % len(chain)]
        if target == self.replica:
            return None
        try:
            self.exchange.hand_off(
                target, key, hops + 1, from_replica=self.replica,
                trace=trace,
            )
        except ExchangeUnreachable:
            return None  # can't release through a hub we can't reach
        except AdmitConflict:
            # fenced at the hub: keep the pod local until the forced
            # resync re-registers this replica
            self._needs_resync = True
            return None
        self._routed_here.pop(key, None)
        self._routed_away.add(key)
        self._reject_counts.pop(key, None)
        return target

    def set_solver_degraded(self, degraded: bool) -> None:
        """Resilience hook (Scheduler wires it to the solve breaker):
        publish this replica's degraded flag through the exchange so
        peers prefer it last in handoff chains. The replica keeps
        serving its shard — the fallback ladder guarantees forward
        progress — it just stops attracting refugees while sick."""
        try:
            self.exchange.set_degraded(self.replica, degraded)
        except (AdmitConflict, ExchangeUnreachable):
            # breaker hooks fire outside the cluster lock (the solve
            # loop holds no lock around dispatch): take it for the
            # dirty flag (a fenced write re-registers at resync too)
            with self.cluster.lock:
                self._exchange_dirty = True

    # called from _apply_group's locked apply phase: ktpu: holds(cluster.lock)
    def stage(self, pod: Pod, node_name: str, cache) -> None:
        if pod.key in self._cas_staged:
            # admit()'s compare_and_stage already landed this pending
            # row atomically with the constraint recheck
            self._cas_staged.discard(pod.key)
            return
        if not pod.labels:
            return  # label-free pods can never match a selector/term
        try:
            self.exchange.stage(
                self.replica,
                PodRow.for_pod(
                    pod, node_name, self._zone_of(cache, node_name), PENDING
                ),
            )
        except ExchangeUnreachable:
            # the row republishes wholesale at the first reachable
            # resync (rebuild_pod_rows) — the placement itself is
            # legitimate, the hub just hasn't heard about it yet
            self._exchange_dirty = True
        except AdmitConflict:
            # hub write fence (this replica was retired): the forced
            # resync re-registers from truth; until then the row stays
            # off the hub, which is conservative for peers
            self._needs_resync = True

    # called from _commit_binding's locked confirmation phase: ktpu: holds(cluster.lock)
    def commit(self, pod_key: str) -> None:
        try:
            self.exchange.commit(self.replica, pod_key)
        except ExchangeUnreachable:
            self._exchange_dirty = True
        except AdmitConflict:
            self._needs_resync = True

    # every caller (unreserve/ingest/reap paths) holds the cluster
    # lock: ktpu: holds(cluster.lock)
    def withdraw(self, pod_key: str) -> None:
        self._cas_staged.discard(pod_key)
        try:
            self.exchange.withdraw(self.replica, pod_key)
        except ExchangeUnreachable:
            self._exchange_dirty = True
        except AdmitConflict:
            self._needs_resync = True

    # -- fleet backlog drain (fleet/drain.py ledger, hub-hosted) --

    def drain_init_from_plan(self, planned: dict, keys) -> dict:
        """Coordinator half of the fleet backlog drain: partition the
        globally-planned backlog by planned-node shard ownership and
        install the ledger at the hub. ``planned`` maps pod key to its
        relax-planned node name (None = unplaced); ``keys`` is the
        backlog in plan order. Cross-shard-constrained pods (the
        reconciler predicate) and gangs route per fleet/drain.py's
        partitioner rules. Epoch-fenced at the hub — a deposed
        coordinator's plan never lands."""
        from . import drain as drain_mod
        from ..gang import GangTracker

        with self.cluster.lock:
            assignment = dict(self._assignment)
            membership_version = self.membership.version

        def _pod_of(key):
            try:
                ns, name = key.split("/", 1)
                return self.cluster.get_pod(ns, name)
            except Exception:
                return None

        def _cross_shard(key):
            pod = _pod_of(key)
            return pod is not None and self._needs_reconcile(pod)

        def _gang_of(key):
            pod = _pod_of(key)
            if pod is None:
                return ""
            return GangTracker.gang_of(pod) or ""

        partitions, residual = drain_mod.partition_backlog(
            keys, planned, assignment,
            gang_of=_gang_of, cross_shard=_cross_shard,
        )
        return self.exchange.drain_init(
            self.replica, partitions, residual,
            membership_version=membership_version,
        )

    def drain_claim(self, scheduler, plan_keys=None) -> dict | None:
        """Claim this replica's next drain lease and ADOPT its keys:
        each becomes this replica's routed pod (the claim_handoffs
        adoption pattern) and enters its queue. When ``plan_keys`` —
        the full drain plan's key set — is provided, pods the plan
        assigns to OTHER replicas' leases are SHED from this queue
        (ring routing filled it by pod-key hash; the drain partition
        is by planned-node owner, and a pod queued at two replicas is
        a double-solve at best). Returns the lease dict (with ``id``
        and ``keys``) or None when nothing is claimable."""
        try:
            lease = self.exchange.drain_claim(self.replica)
        except ExchangeUnreachable:
            with self.cluster.lock:
                self._exchange_dirty = True
            return None
        except AdmitConflict:
            with self.cluster.lock:
                self._needs_resync = True
            return None
        if not lease:
            return None
        lease_keys = [str(k) for k in lease.get("keys") or []]
        with self.cluster.lock:
            tracked = scheduler.queue.entries()
            for key in lease_keys:
                try:
                    ns, name = key.split("/", 1)
                    pod = self.cluster.get_pod(ns, name)
                except Exception:
                    continue  # deleted while the ledger held it
                if pod.node_name:
                    # bound while the ledger held it (a prior lease
                    # holder's bind landed before its death)
                    continue
                self._routed_here[key] = 0
                self._routed_away.discard(key)
                if (
                    key not in tracked
                    and key not in scheduler._in_flight
                    and key not in scheduler._waiting
                    and pod.scheduler_name in scheduler.solvers
                ):
                    scheduler.queue.add(pod)
            if plan_keys is not None:
                mine = set(lease_keys)
                tracked = scheduler.queue.entries()
                for key in sorted(
                    (set(plan_keys) & set(tracked)) - mine
                ):
                    if key in scheduler._in_flight:
                        continue  # too late: this solve owns it now
                    self._routed_away.add(key)
                    self._routed_here.pop(key, None)
                    scheduler.queue.delete(key)
        return lease

    def drain_chunk_progress(self, keys) -> int:
        """Per-applied-chunk progress report — the ledger's done map
        AND this replica's liveness refresh: a replica deep in a long
        drain chunk writes nothing else to the hub, and without the
        report's touch its publish stamp would age past max_row_age_s
        and flip every peer's constrained admission conservative."""
        if not keys:
            return 0
        try:
            return self.exchange.drain_progress(
                self.replica, list(keys)
            )
        except ExchangeUnreachable:
            with self.cluster.lock:
                self._exchange_dirty = True
            return 0
        except AdmitConflict:
            with self.cluster.lock:
                self._needs_resync = True
            return 0

    def drain_complete(self, lease_id: str) -> bool:
        try:
            return bool(
                self.exchange.drain_complete(
                    self.replica, str(lease_id)
                )
            )
        except ExchangeUnreachable:
            with self.cluster.lock:
                self._exchange_dirty = True
            return False
        except AdmitConflict:
            with self.cluster.lock:
                self._needs_resync = True
            return False
