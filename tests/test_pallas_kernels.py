"""Pallas kernel parity vs the jax.lax reference (interpret mode on CPU;
the compiled TPU path is exercised by scripts/pallas_smoke.py), plus the
PRODUCTION wiring behind ``tpuSolver.pallas`` (ISSUE 13 satellite): the
per-pod scan's InterPodAffinity domain aggregation routed through the
kernel must produce bit-identical assignments to the segment_sum path,
end to end through ``ExactSolver.solve``."""

import numpy as np
import pytest

from kubernetes_tpu.ops.pallas_kernels import (
    N_TILE,
    T_TILE,
    domain_counts_padded,
    domain_counts_pallas,
    domain_counts_reference,
)


@pytest.mark.parametrize("t,n_tiles,d_pad", [(8, 1, 8), (8, 2, 16), (16, 4, 32)])
def test_domain_counts_parity(t, n_tiles, d_pad):
    rng = np.random.default_rng(42 + t)
    n = n_tiles * N_TILE
    dom = rng.integers(-1, d_pad, size=(t, n)).astype(np.int32)
    cnt = rng.integers(0, 5, size=(t, n)).astype(np.int32)
    got = np.asarray(domain_counts_pallas(dom, cnt, d_pad, interpret=True))
    want = np.asarray(domain_counts_reference(dom, cnt, d_pad))
    np.testing.assert_array_equal(got, want)


def test_domain_counts_excludes_missing_key():
    dom = np.full((8, N_TILE), -1, dtype=np.int32)
    cnt = np.ones((8, N_TILE), dtype=np.int32)
    out = np.asarray(domain_counts_pallas(dom, cnt, 8, interpret=True))
    assert out.sum() == 0


@pytest.mark.parametrize(
    "t,n", [(5, 200), (T_TILE, N_TILE), (9, N_TILE + 1), (1, 130)]
)
def test_padded_adapter_parity_on_untiled_shapes(t, n):
    """The production adapter pads arbitrary (term, node) shapes to the
    kernel tiles (pad lanes carry dom=-1) and slices back — parity with
    the reference on the UNpadded inputs."""
    rng = np.random.default_rng(100 + t + n)
    dom = rng.integers(-1, 6, size=(t, n)).astype(np.int32)
    cnt = rng.integers(0, 5, size=(t, n)).astype(np.int32)
    got = np.asarray(domain_counts_padded(dom, cnt, 8))
    want = np.asarray(domain_counts_reference(dom, cnt, 8))
    np.testing.assert_array_equal(got, want)


def _interpod_cluster():
    """A zone-topology interpod mix whose domains are SHARED across
    nodes (ident=False), so the wired aggregation actually runs inside
    the scan."""
    from kubernetes_tpu.api.wrappers import MakeNode, MakePod

    nodes = [
        MakeNode()
        .name(f"node-{i:03}")
        .capacity({"cpu": "8", "memory": "32Gi", "pods": "50"})
        .label("zone", f"z{i % 2}")
        .label("kubernetes.io/hostname", f"node-{i:03}")
        .obj()
        for i in range(8)
    ]
    be = (
        MakePod().name("be").label("app", "backend").node("node-000").obj()
    )
    rng = np.random.default_rng(7)
    pods = []
    for i in range(16):
        b = MakePod().name(f"m{i:02}").req({"cpu": "200m"})
        r = rng.random()
        if r < 0.35:
            b = b.label("app", "frontend").pod_affinity(
                "zone", match_labels={"app": "backend"}
            )
        elif r < 0.6:
            b = b.label("team", "red").pod_anti_affinity(
                "zone", match_labels={"team": "red"}
            )
        elif r < 0.8:
            b = b.label("app", "web").preferred_pod_affinity(
                int(rng.integers(1, 100)), "zone",
                match_labels={"app": "backend"},
            )
        else:
            b = b.label("app", "plain")
        pods.append(b.obj())
    return nodes, pods, {"node-000": [be]}


def _solve(nodes, pods, placed_by_node, pallas: bool):
    from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig
    from kubernetes_tpu.tensorize.interpod import build_interpod_tensors
    from kubernetes_tpu.tensorize.plugins import (
        build_port_tensors,
        build_static_tensors,
    )
    from kubernetes_tpu.tensorize.schema import (
        ResourceVocab,
        build_node_batch,
        build_pod_batch,
    )
    from kubernetes_tpu.tensorize.spread import build_spread_tensors

    all_pods = pods + [p for ps in placed_by_node.values() for p in ps]
    vocab = ResourceVocab.build(all_pods, nodes)
    nbatch = build_node_batch(nodes, placed_by_node, vocab=vocab)
    pbatch = build_pod_batch(pods, vocab)
    slot_nodes = list(nodes) + [None] * (nbatch.padded - len(nodes))
    placed_by_slot = {
        i: placed_by_node[n.name]
        for i, n in enumerate(nodes)
        if n.name in placed_by_node
    }
    static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded)
    ports = build_port_tensors(
        pods, pbatch, slot_nodes, placed_by_slot, nbatch.padded
    )
    spread = build_spread_tensors(
        pods, static.reps, pbatch, slot_nodes, placed_by_slot,
        nbatch.padded, static.c_pad,
    )
    interpod = build_interpod_tensors(
        pods, static.reps, pbatch, slot_nodes, placed_by_slot,
        nbatch.padded, static.c_pad,
    )
    solver = ExactSolver(
        ExactSolverConfig(tie_break="first", pallas=pallas)
    )
    return solver.solve(
        nbatch, pbatch, static, ports, spread, interpod
    )


def test_production_solve_parity_flag_on_vs_off():
    """tpuSolver.pallas wired into the production scan: the exact same
    interpod batch solved with the kernel aggregation and with the
    segment_sum must pick bit-identical nodes (integer adds either way;
    the f32 MXU contraction is exact far below 2^24 counts)."""
    nodes, pods, placed = _interpod_cluster()
    base = np.asarray(_solve(nodes, pods, placed, pallas=False))
    wired = np.asarray(_solve(nodes, pods, placed, pallas=True))
    np.testing.assert_array_equal(base, wired)
    # non-vacuous: at least one interpod-constrained pod actually placed
    assert (base >= 0).sum() >= len(pods) - 2
