"""Volume binder — the Reserve/PreBind stages of the volumebinding plugin
(volumebinding/volume_binding.go#Reserve -> binder.AssumePodVolumes,
#PreBind -> binder.BindPodVolumes, #Unreserve), closing the VERDICT r2
gap: the static F-stage mask said where a pod COULD bind its volumes; this
actually binds them.

[BOUNDARY] depth per SURVEY §3.2: the in-memory cluster state stands in
for the apiserver, so "API writes + wait for bound" collapses to
synchronous PV/PVC updates under the cluster lock. Dynamic provisioning
remains stubbed (no matching PV and not resolvable -> Reserve fails, the
pod requeues — the same observable outcome as a provisioning timeout).

Flow inside a scheduling batch (matching the reference's cycle order):
  Reserve  : assume_pod_volumes(pod, node) — for each of the pod's unbound
             claims (incl. WaitForFirstConsumer, whose whole point is to
             bind at scheduling time on the CHOSEN node), pick the best
             matching PV (binder.go#findMatchingVolume preference: the
             smallest adequate volume) and record the assumption.
  PreBind  : bind_pod_volumes(pod) — write claimRef/volumeName into the
             cluster state for every assumption.
  failure  : unreserve(pod) — roll back any writes + assumptions.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..api.objects import Node, PersistentVolume, PersistentVolumeClaim, Pod
from ..ops.oracle.volumes import VolumeContext, find_matching_pv
from .cluster import ApiError, ClusterState


class VolumeBindingError(Exception):
    pass


@dataclass
class _Assumption:
    pvc: PersistentVolumeClaim
    pv: PersistentVolume


@dataclass
class VolumeBinder:
    cluster: ClusterState
    # pod key -> assumptions made at Reserve
    _assumed: dict[str, list[_Assumption]] = field(default_factory=dict)

    def assume_pod_volumes(self, pod: Pod, node: Node) -> bool:
        """Reserve. Returns True if anything was assumed (pod has unbound
        claims), False for the no-volume fast path. Raises
        VolumeBindingError when an unbound claim matches no PV on the
        chosen node — the caller unreserves + requeues."""
        if not pod.pvc_names:
            return False
        pvcs = {c.key: c for c in self.cluster.list_pvcs()}
        # one mutable context: assumed PVs are removed as claims take them,
        # so multi-claim pods never share a PV and nothing is copied per
        # claim
        ctx = VolumeContext(
            pvs={pv.name: pv for pv in self.cluster.list_pvs()},
        )
        assumptions: list[_Assumption] = []
        for claim_name in pod.pvc_names:
            key = f"{pod.namespace}/{claim_name}"
            pvc = pvcs.get(key)
            if pvc is None:
                raise VolumeBindingError(f"claim {key} not found")
            if pvc.volume_name:
                continue  # already bound — nothing to assume
            # find_matching_pv already prefers the smallest adequate PV
            pv = find_matching_pv(ctx, pvc, node)
            if pv is None:
                raise VolumeBindingError(
                    f"claim {key}: no matching PersistentVolume on "
                    f"node {node.name}"
                )
            del ctx.pvs[pv.name]  # later claims of this pod can't reuse it
            assumptions.append(_Assumption(pvc=pvc, pv=pv))
        if assumptions:
            self._assumed[pod.key] = assumptions
            return True
        return False

    def bind_pod_volumes(self, pod: Pod) -> None:
        """PreBind: commit every assumption into the cluster state.

        The objects are the cluster's live references, so the in-place
        claim_ref/volume_name writes are visible immediately; unreserve
        reverts UNCONDITIONALLY so a mid-commit failure can never strand a
        half-bound claim."""
        for a in self._assumed.get(pod.key, ()):
            a.pv.claim_ref = a.pvc.key
            a.pvc.volume_name = a.pv.name
            self.cluster.update_pv(a.pv)
            self.cluster.update_pvc(a.pvc)

    def finish(self, pod_key: str) -> None:
        """Binding succeeded: drop the assumption bookkeeping."""
        self._assumed.pop(pod_key, None)

    def unreserve(self, pod_key: str) -> None:
        """Roll back assumptions unconditionally (idempotent: clearing an
        already-clear binding is a no-op write)."""
        for a in self._assumed.pop(pod_key, ()):
            a.pv.claim_ref = ""
            a.pvc.volume_name = ""
            try:
                self.cluster.update_pv(a.pv)
                self.cluster.update_pvc(a.pvc)
            except ApiError:
                pass
