"""Dynamic Resource Allocation (resource.k8s.io subset) — the
dynamicresources plugin behind the DynamicResourceAllocation gate:
wire shapes, claim-feasibility filtering, Reserve-time device allocation,
PreBind status writes, rollback, sharing, and release on pod delete.
Scope/divergences documented in kubernetes_tpu/api/dra.py.
"""

import numpy as np
import pytest

from kubernetes_tpu.api.dra import (
    Device,
    DeviceClass,
    DeviceRequest,
    ResourceClaim,
    ResourceSlice,
)
from kubernetes_tpu.api.objects import Pod
from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.state.cluster import ApiError, ClusterState
from kubernetes_tpu.utils.featuregate import FeatureGates


def mk_cluster(n_nodes=4, gpus_per_node=2):
    cs = ClusterState()
    for i in range(n_nodes):
        cs.create_node(
            MakeNode()
            .name(f"n{i}")
            .capacity({"cpu": "8", "memory": "32Gi", "pods": "20"})
            .obj()
        )
        cs.create_resource_slice(
            ResourceSlice(
                name=f"slice-n{i}",
                node_name=f"n{i}",
                driver="gpu.example.com",
                devices=tuple(
                    Device(name=f"gpu-{j}", attributes={"model": "a100"})
                    for j in range(gpus_per_node)
                ),
            )
        )
    cs.create_device_class(DeviceClass(name="gpu", driver="gpu.example.com"))
    return cs


def mk_sched(cs, batch=64):
    from kubernetes_tpu.utils.clock import FakeClock

    return Scheduler(
        cs,
        SchedulerConfig(
            batch_size=batch,
            feature_gates=FeatureGates.parse("DynamicResourceAllocation=true"),
        ),
        clock=FakeClock(),
    )


def drain(sched, rounds=6):
    """Drain until quiescent, stepping the fake clock over backoffs so a
    Reserve-failed pod's retry lands in a later batch."""
    scheduled, unschedulable = 0, 0
    idle = 0
    for _ in range(rounds * 4):
        r = sched.schedule_batch()
        scheduled += len(r.scheduled)
        unschedulable += len(r.unschedulable)
        if r.scheduled or r.bind_failures:
            idle = 0
            continue
        if hasattr(sched.clock, "advance"):
            sched.clock.advance(11.0)  # past podMaxBackoffSeconds
        idle += 1
        if idle >= 2:
            break
    return scheduled, unschedulable


def test_wire_round_trip():
    claim = ResourceClaim.from_dict(
        {
            "metadata": {"name": "c1", "namespace": "ns1"},
            "spec": {
                "devices": {
                    "requests": [
                        {
                            "name": "req0",
                            "deviceClassName": "gpu",
                            "allocationMode": "ExactCount",
                            "count": 2,
                        }
                    ]
                }
            },
        }
    )
    assert claim.requests[0].count == 2
    claim.allocated_node = "n1"
    rt = ResourceClaim.from_dict(claim.to_dict())
    assert rt.allocated_node == "n1" and rt.requests == claim.requests

    sl = ResourceSlice.from_dict(
        {
            "metadata": {"name": "s"},
            "spec": {
                "nodeName": "n0",
                "driver": "d",
                "devices": [
                    {
                        "name": "dev0",
                        "basic": {
                            "attributes": {"model": {"string": "a100"}}
                        },
                    }
                ],
            },
        }
    )
    assert sl.devices[0].attributes == {"model": "a100"}
    assert ResourceSlice.from_dict(sl.to_dict()) == sl

    # CEL selectors: the two structural shapes parse; anything else makes
    # the class match nothing (conservative), not silently everything
    dc = DeviceClass.from_dict(
        {
            "metadata": {"name": "g"},
            "spec": {
                "selectors": [
                    {"cel": {"expression": 'device.driver == "d"'}},
                    {
                        "cel": {
                            "expression": 'device.attributes["model"] == "a100"'
                        }
                    },
                ]
            },
        }
    )
    assert dc.driver == "d" and dc.match_attributes == {"model": "a100"}
    opaque = DeviceClass.from_dict(
        {
            "metadata": {"name": "o"},
            "spec": {
                "selectors": [
                    {"cel": {"expression": "device.capacity['x'].value > 5"}}
                ]
            },
        }
    )
    assert not opaque.matches("d", Device(name="x"))

    # pod claim refs parse; template-only refs are flagged unresolved
    pod = Pod.from_dict(
        {
            "metadata": {"name": "p"},
            "spec": {
                "containers": [{"name": "c"}],
                "resourceClaims": [
                    {"name": "r0", "resourceClaimName": "c1"},
                    {"name": "r1", "resourceClaimTemplateName": "tpl"},
                ],
            },
        }
    )
    assert pod.resource_claim_names == ("c1",)
    assert pod.claim_templates_unresolved


def test_unsupported_allocation_mode_rejected():
    with pytest.raises(ValueError):
        DeviceRequest.from_dict(
            {"name": "r", "deviceClassName": "gpu", "allocationMode": "All"}
        )


def test_allocation_on_bind():
    cs = mk_cluster(n_nodes=3, gpus_per_node=2)
    cs.create_resource_claim(
        ResourceClaim(
            name="train",
            requests=(DeviceRequest(name="g", device_class_name="gpu", count=2),),
        )
    )
    sched = mk_sched(cs)
    cs.create_pod(
        MakePod().name("p0").req({"cpu": "1", "memory": "1Gi"})
        .resource_claim("train").obj()
    )
    scheduled, _ = drain(sched)
    assert scheduled == 1
    claim = cs.get_resource_claim("default", "train")
    pod = cs.get_pod("default", "p0")
    assert claim.allocated_node == pod.node_name
    assert len(claim.results) == 2
    assert len({r.device for r in claim.results}) == 2
    assert claim.reserved_for == ("default/p0",)


def test_exhaustion_then_release_on_delete():
    """Each node has 2 GPUs; claims ask for 2 => one claim-bearing pod per
    node. The overflow pod parks; deleting a holder frees its devices and
    the ResourceClaim MODIFIED event wakes the parked pod."""
    cs = mk_cluster(n_nodes=2, gpus_per_node=2)
    for i in range(3):
        cs.create_resource_claim(
            ResourceClaim(
                name=f"c{i}",
                requests=(
                    DeviceRequest(name="g", device_class_name="gpu", count=2),
                ),
            )
        )
    sched = mk_sched(cs)
    for i in range(3):
        cs.create_pod(
            MakePod().name(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
            .resource_claim(f"c{i}").obj()
        )
    scheduled, unsched = drain(sched)
    # two bind; the third's Reserve fails (devices taken in-flight) and it
    # PARKS awaiting a claim/slice event — our own reservedFor writes must
    # NOT wake it (review-caught backoff defeat), so it stays parked
    assert scheduled == 2
    bound = {
        p.name: p.node_name for p in cs.list_pods() if p.node_name
    }
    assert len(bound) == 2
    # the 5-minute leftover flush is the reference's safety net: the pod
    # retries and is now properly unschedulable (mask exhausted)
    sched.clock.advance(301.0)
    r = sched.schedule_batch()
    assert len(r.unschedulable) == 1
    victim = next(iter(bound))
    cs.delete_pod("default", victim)
    # the deallocating-controller stand-in cleared the claim
    freed_claim = cs.get_resource_claim("default", f"c{victim[1:]}")
    assert not freed_claim.allocated and not freed_claim.reserved_for
    scheduled2, _ = drain(sched)
    assert scheduled2 == 1
    assert sum(1 for p in cs.list_pods() if p.node_name) == 2


def test_two_claim_pods_race_distinct_devices():
    """Two pods with separate 1-GPU claims on a 2-GPU single node must get
    DISTINCT devices even when they bind in the same batch (the in-flight
    assumption accounting)."""
    cs = mk_cluster(n_nodes=1, gpus_per_node=2)
    for i in range(2):
        cs.create_resource_claim(
            ResourceClaim(
                name=f"c{i}",
                requests=(
                    DeviceRequest(name="g", device_class_name="gpu", count=1),
                ),
            )
        )
    sched = mk_sched(cs)
    for i in range(2):
        cs.create_pod(
            MakePod().name(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
            .resource_claim(f"c{i}").obj()
        )
    scheduled, _ = drain(sched)
    assert scheduled == 2
    devs = [
        r.device
        for i in range(2)
        for r in cs.get_resource_claim("default", f"c{i}").results
    ]
    assert sorted(devs) == ["gpu-0", "gpu-1"]


def test_shared_claim_pins_second_pod_to_allocation_node():
    cs = mk_cluster(n_nodes=3, gpus_per_node=2)
    cs.create_resource_claim(
        ResourceClaim(
            name="shared",
            requests=(DeviceRequest(name="g", device_class_name="gpu", count=1),),
        )
    )
    sched = mk_sched(cs)
    cs.create_pod(
        MakePod().name("p0").req({"cpu": "1", "memory": "1Gi"})
        .resource_claim("shared").obj()
    )
    scheduled, _ = drain(sched)
    assert scheduled == 1
    node0 = cs.get_pod("default", "p0").node_name
    cs.create_pod(
        MakePod().name("p1").req({"cpu": "1", "memory": "1Gi"})
        .resource_claim("shared").obj()
    )
    scheduled, _ = drain(sched)
    assert scheduled == 1
    assert cs.get_pod("default", "p1").node_name == node0
    claim = cs.get_resource_claim("default", "shared")
    assert set(claim.reserved_for) == {"default/p0", "default/p1"}
    assert len(claim.results) == 1  # allocated once, shared


def test_missing_claim_and_template_unschedulable():
    cs = mk_cluster(n_nodes=2)
    sched = mk_sched(cs)
    cs.create_pod(
        MakePod().name("orphan").req({"cpu": "1", "memory": "1Gi"})
        .resource_claim("nope").obj()
    )
    tpl = MakePod().name("tpl").req({"cpu": "1", "memory": "1Gi"}).obj()
    tpl.claim_template_names = ("tpl",)
    cs.create_pod(tpl)
    scheduled, unsched = drain(sched)
    assert scheduled == 0 and unsched == 2


def test_bind_failure_rolls_back_allocation():
    cs = mk_cluster(n_nodes=1, gpus_per_node=1)
    cs.create_resource_claim(
        ResourceClaim(
            name="c0",
            requests=(DeviceRequest(name="g", device_class_name="gpu", count=1),),
        )
    )
    sched = mk_sched(cs)
    from kubernetes_tpu.state.cluster import ApiError

    fails = {"n": 0}

    def fault(pod, node_name):
        if fails["n"] == 0:
            fails["n"] += 1
            raise ApiError("Conflict", "injected bind fault")

    cs.bind_fault = fault
    cs.create_pod(
        MakePod().name("p0").req({"cpu": "1", "memory": "1Gi"})
        .resource_claim("c0").obj()
    )
    r = sched.schedule_batch()
    assert r.bind_failures
    claim = cs.get_resource_claim("default", "c0")
    assert not claim.allocated and not claim.reserved_for  # rolled back
    # retry succeeds and re-allocates
    scheduled, _ = drain(sched)
    assert scheduled == 1
    assert cs.get_resource_claim("default", "c0").allocated


def test_gate_off_ignores_claims():
    """Without the gate, claim references don't constrain scheduling and
    no allocation is written (the pre-round-4 behavior)."""
    cs = mk_cluster(n_nodes=1, gpus_per_node=0)
    cs.create_resource_claim(
        ResourceClaim(
            name="c0",
            requests=(DeviceRequest(name="g", device_class_name="gpu", count=1),),
        )
    )
    sched = Scheduler(cs, SchedulerConfig(batch_size=16))
    cs.create_pod(
        MakePod().name("p0").req({"cpu": "1", "memory": "1Gi"})
        .resource_claim("c0").obj()
    )
    scheduled, _ = drain(sched)
    assert scheduled == 1
    assert not cs.get_resource_claim("default", "c0").allocated


def test_device_class_attribute_matching():
    """Two drivers publish devices on one node; a class selecting on an
    attribute must only count matching devices."""
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n0").capacity(
            {"cpu": "8", "memory": "32Gi", "pods": "20"}
        ).obj()
    )
    cs.create_resource_slice(
        ResourceSlice(
            name="s-a",
            node_name="n0",
            driver="a.dev",
            devices=(Device("d0", {"model": "a100"}), Device("d1", {"model": "v100"})),
        )
    )
    cs.create_resource_claim(
        ResourceClaim(
            name="wants-a100",
            requests=(
                DeviceRequest(name="g", device_class_name="a100", count=2),
            ),
        )
    )
    cs.create_device_class(
        DeviceClass(name="a100", match_attributes={"model": "a100"})
    )
    sched = mk_sched(cs)
    cs.create_pod(
        MakePod().name("p0").req({"cpu": "1", "memory": "1Gi"})
        .resource_claim("wants-a100").obj()
    )
    scheduled, unsched = drain(sched)
    assert scheduled == 0 and unsched == 1  # only one a100 exists


def test_pool_scoped_device_identity():
    """Same device name in two pools of one driver on one node must count
    as two devices (identity is (driver, pool, name))."""
    cs = ClusterState()
    cs.create_node(
        MakeNode().name("n0").capacity(
            {"cpu": "8", "memory": "32Gi", "pods": "20"}
        ).obj()
    )
    for pool in ("p1", "p2"):
        cs.create_resource_slice(
            ResourceSlice(
                name=f"s-{pool}", node_name="n0", driver="d", pool=pool,
                devices=(Device(name="gpu-0"),),
            )
        )
    cs.create_device_class(DeviceClass(name="gpu", driver="d"))
    for i in range(2):
        cs.create_resource_claim(
            ResourceClaim(
                name=f"c{i}",
                requests=(
                    DeviceRequest(name="g", device_class_name="gpu", count=1),
                ),
            )
        )
    sched = mk_sched(cs)
    for i in range(2):
        cs.create_pod(
            MakePod().name(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
            .resource_claim(f"c{i}").obj()
        )
    scheduled, _ = drain(sched)
    assert scheduled == 2
    pools = {
        cs.get_resource_claim("default", f"c{i}").results[0].pool
        for i in range(2)
    }
    assert pools == {"p1", "p2"}


def test_sharer_survives_allocator_rollback():
    """Pod A allocates a claim, pod B reserves it in the same batch, A's
    bind fails AFTER B bound: the claim must stay allocated for B and its
    devices must stay accounted (review-caught rollback hole)."""
    from kubernetes_tpu.state.claim_allocator import ClaimAllocator

    cs = mk_cluster(n_nodes=1, gpus_per_node=2)
    cs.create_resource_claim(
        ResourceClaim(
            name="shared",
            requests=(DeviceRequest(name="g", device_class_name="gpu", count=1),),
        )
    )
    alloc = ClaimAllocator(cs)
    pod_a = MakePod().name("a").resource_claim("shared").obj()
    pod_b = MakePod().name("b").resource_claim("shared").obj()
    assert alloc.assume_pod_claims(pod_a, "n0")
    assert alloc.assume_pod_claims(pod_b, "n0")  # sharer, pinned to n0
    alloc.bind_pod_claims(pod_b)  # B commits first
    alloc.finish(pod_b.key)
    alloc.unreserve(pod_a.key)  # A rolls back
    claim = cs.get_resource_claim("default", "shared")
    assert claim.allocated_node == "n0" and len(claim.results) == 1
    assert claim.reserved_for == ("default/b",)
    # the allocated device is still accounted: a fresh 2-device claim on
    # the 2-GPU node must not fit
    cs.create_resource_claim(
        ResourceClaim(
            name="greedy",
            requests=(DeviceRequest(name="g", device_class_name="gpu", count=2),),
        )
    )
    pod_c = MakePod().name("c").resource_claim("greedy").obj()
    from kubernetes_tpu.state.claim_allocator import ClaimAllocationError

    with pytest.raises(ClaimAllocationError):
        alloc.assume_pod_claims(pod_c, "n0")


def test_cel_conjunction_conflict_matches_nothing():
    dc = DeviceClass.from_dict(
        {
            "metadata": {"name": "x"},
            "spec": {
                "selectors": [
                    {"cel": {"expression": 'device.attributes["m"] == "a"'}},
                    {"cel": {"expression": 'device.attributes["m"] == "b"'}},
                ]
            },
        }
    )
    assert not dc.matches("d", Device(name="g", attributes={"m": "a"}))
    assert not dc.matches("d", Device(name="g", attributes={"m": "b"}))


def test_flat_bool_attribute_normalizes():
    dv = Device.from_dict({"name": "g", "attributes": {"coherent": True}})
    assert dv.attributes["coherent"] == "true"
    dc = DeviceClass(name="c", match_attributes={"coherent": "true"})
    assert dc.matches("d", dv)


def test_pod_template_refs_round_trip():
    pod = Pod.from_dict(
        {
            "metadata": {"name": "p"},
            "spec": {
                "containers": [{"name": "c"}],
                "resourceClaims": [
                    {"name": "r1", "resourceClaimTemplateName": "tpl"}
                ],
            },
        }
    )
    assert pod.claim_templates_unresolved
    rt = Pod.from_dict(pod.to_dict())
    assert rt.claim_template_names == ("tpl",)
    assert rt.claim_templates_unresolved


def test_unresolvable_claim_reason_in_events():
    """A dangling claim reference must surface ITS reason on the
    FailedScheduling event, not the generic 0/N-nodes message."""
    cs = mk_cluster(n_nodes=2)
    sched = mk_sched(cs)
    cs.create_pod(
        MakePod().name("orphan").req({"cpu": "1", "memory": "1Gi"})
        .resource_claim("nope").obj()
    )
    drain(sched)
    notes = [
        e.note
        for e in cs.list_events(regarding_name="orphan")
        if e.reason == "FailedScheduling"
    ]
    assert any("resourceclaim default/nope not found" in n for n in notes), notes


def test_preexisting_allocation_survives_rollback():
    """A claim allocated by an external controller (no reservedFor) must
    NOT lose its allocation when a pod that merely joined it rolls back."""
    from kubernetes_tpu.state.claim_allocator import ClaimAllocator

    cs = mk_cluster(n_nodes=2, gpus_per_node=2)
    cs.create_resource_claim(
        ResourceClaim(
            name="ext",
            requests=(DeviceRequest(name="g", device_class_name="gpu", count=1),),
            allocated_node="n1",
            results=(
                __import__("kubernetes_tpu.api.dra", fromlist=["DeviceResult"])
                .DeviceResult(request="g", driver="gpu.example.com", device="gpu-0"),
            ),
        )
    )
    alloc = ClaimAllocator(cs)
    pod = MakePod().name("joiner").resource_claim("ext").obj()
    assert alloc.assume_pod_claims(pod, "n1")
    alloc.bind_pod_claims(pod)  # reservedFor=(joiner,)
    alloc.unreserve(pod.key)  # bind failed
    claim = cs.get_resource_claim("default", "ext")
    assert claim.allocated_node == "n1" and claim.results  # preserved
    assert claim.reserved_for == ()


def test_duplicate_claim_reference_counts_once():
    """A pod listing the same claim twice uses one allocation, not two."""
    cs = mk_cluster(n_nodes=1, gpus_per_node=1)
    cs.create_resource_claim(
        ResourceClaim(
            name="c0",
            requests=(DeviceRequest(name="g", device_class_name="gpu", count=1),),
        )
    )
    sched = mk_sched(cs)
    cs.create_pod(
        MakePod().name("p0").req({"cpu": "1", "memory": "1Gi"})
        .resource_claim("c0").resource_claim("c0").obj()
    )
    scheduled, _ = drain(sched)
    assert scheduled == 1
    assert len(cs.get_resource_claim("default", "c0").results) == 1


def test_preemption_frees_claim_devices():
    """Upstream's dynamicresources Filter failure is Unschedulable (not
    Unresolvable): a high-priority claim pod must be able to preempt a
    lower-priority pod whose claim holds the only device."""
    cs = mk_cluster(n_nodes=1, gpus_per_node=1)
    for i, name in enumerate(("low", "high")):
        cs.create_resource_claim(
            ResourceClaim(
                name=f"c-{name}",
                requests=(
                    DeviceRequest(name="g", device_class_name="gpu", count=1),
                ),
            )
        )
    sched = mk_sched(cs)
    cs.create_pod(
        MakePod().name("low").priority(1).req({"cpu": "1", "memory": "1Gi"})
        .resource_claim("c-low").obj()
    )
    s, _ = drain(sched)
    assert s == 1
    cs.create_pod(
        MakePod().name("high").priority(100).req({"cpu": "1", "memory": "1Gi"})
        .resource_claim("c-high").obj()
    )
    s2, _ = drain(sched)
    # low was evicted (its claim released on delete), high bound
    assert cs.get_pod("default", "high").node_name == "n0"
    assert cs.get_resource_claim("default", "c-high").allocated_node == "n0"
    low_claim = cs.get_resource_claim("default", "c-low")
    assert not low_claim.allocated and not low_claim.reserved_for


def test_preemption_shared_claim_evicts_all_or_none():
    """A device freed only by evicting EVERY reserver: when all sharers
    are lower priority, both are evicted; when one sharer outranks the
    preemptor, the device is not freeable and nothing is evicted."""
    def build(b_priority):
        cs = mk_cluster(n_nodes=1, gpus_per_node=1)
        cs.create_resource_claim(
            ResourceClaim(
                name="shared",
                requests=(
                    DeviceRequest(name="g", device_class_name="gpu", count=1),
                ),
            )
        )
        cs.create_resource_claim(
            ResourceClaim(
                name="wants",
                requests=(
                    DeviceRequest(name="g", device_class_name="gpu", count=1),
                ),
            )
        )
        sched = mk_sched(cs)
        for n, pr in (("a", 1), ("b", b_priority)):
            cs.create_pod(
                MakePod().name(n).priority(pr)
                .req({"cpu": "1", "memory": "1Gi"})
                .resource_claim("shared").obj()
            )
        s, _ = drain(sched)
        assert s == 2
        cs.create_pod(
            MakePod().name("high").priority(100)
            .req({"cpu": "1", "memory": "1Gi"}).resource_claim("wants").obj()
        )
        drain(sched)
        return cs

    # both sharers lower priority: the victim set extends to both and the
    # preemptor binds
    cs = build(b_priority=1)
    assert cs.get_pod("default", "high").node_name == "n0"
    assert not cs.get_resource_claim("default", "shared").reserved_for

    # one sharer outranks the preemptor: evicting the other alone frees
    # nothing, so nobody is evicted
    cs = build(b_priority=200)
    assert cs.get_pod("default", "high").node_name == ""
    assert {p.name for p in cs.list_pods() if p.node_name} == {"a", "b"}


def test_nonpositive_count_rejected():
    for bad in (-1, 0):
        with pytest.raises(ValueError):
            DeviceRequest.from_dict(
                {"name": "r", "deviceClassName": "gpu", "count": bad}
            )


def test_contradictory_driver_selector_round_trips():
    d = {
        "metadata": {"name": "x"},
        "spec": {
            "driver": "a",
            "selectors": [{"cel": {"expression": 'device.driver == "b"'}}],
        },
    }
    dc = DeviceClass.from_dict(d)
    assert dc.opaque_selector and dc.driver == "a"
    rt = DeviceClass.from_dict(dc.to_dict())
    assert rt.opaque_selector  # still matches nothing after a round trip
    assert not rt.matches("b", Device(name="g"))


def test_dra_widen_does_not_block_resource_preemption():
    """A claim pod failing on CPU (devices fine) must still preempt via
    the ordinary resource dry-run on a DRA-feasible node."""
    cs = mk_cluster(n_nodes=1, gpus_per_node=2)
    cs.create_resource_claim(
        ResourceClaim(
            name="c0",
            requests=(DeviceRequest(name="g", device_class_name="gpu", count=1),),
        )
    )
    sched = mk_sched(cs)
    cs.create_pod(
        MakePod().name("filler").priority(1)
        .req({"cpu": "7", "memory": "1Gi"}).obj()
    )
    s, _ = drain(sched)
    assert s == 1
    cs.create_pod(
        MakePod().name("high").priority(100)
        .req({"cpu": "4", "memory": "1Gi"}).resource_claim("c0").obj()
    )
    drain(sched)
    assert cs.get_pod("default", "high").node_name == "n0"
    assert "filler" not in {p.name for p in cs.list_pods()}  # evicted


def test_fuzz_invariants_under_churn():
    """Random create/schedule/delete churn with the gate on: at every
    quiescent point, no device is owned by two claims, every allocation
    sits on a live node with its devices actually published there, and
    reservedFor only names live pods."""
    rng = np.random.default_rng(42)
    for trial in range(4):
        n_nodes = int(rng.integers(2, 5))
        gpn = int(rng.integers(1, 4))
        cs = mk_cluster(n_nodes=n_nodes, gpus_per_node=gpn)
        sched = mk_sched(cs)
        live_pods: list[str] = []
        for step in range(30):
            op = rng.random()
            if op < 0.55:
                i = trial * 1000 + step
                cs.create_resource_claim(
                    ResourceClaim(
                        name=f"c{i}",
                        requests=(
                            DeviceRequest(
                                name="g",
                                device_class_name="gpu",
                                count=int(rng.integers(1, gpn + 1)),
                            ),
                        ),
                    )
                )
                cs.create_pod(
                    MakePod().name(f"p{i}")
                    .priority(int(rng.integers(0, 5)))
                    .req({"cpu": "1", "memory": "1Gi"})
                    .resource_claim(f"c{i}").obj()
                )
                live_pods.append(f"p{i}")
            elif live_pods:
                victim = live_pods.pop(int(rng.integers(0, len(live_pods))))
                try:
                    cs.delete_pod("default", victim)
                except ApiError as e:
                    # the scheduler's preemption legitimately deletes
                    # lower-priority victims, so NotFound is an expected
                    # race; anything else is a real bug
                    assert e.reason == "NotFound", e
            drain(sched, rounds=2)

            # -- invariants --
            claims = cs.list_resource_claims()
            node_devices = {}
            for s in cs.list_resource_slices():
                node_devices.setdefault(s.node_name, set()).update(
                    (s.driver, s.pool, d.name) for d in s.devices
                )
            owned: dict[tuple, str] = {}
            pod_keys = {p.key for p in cs.list_pods()}
            node_names = {n.name for n in cs.list_nodes()}
            for c in claims:
                for r in c.results:
                    did = (c.allocated_node, r.driver, r.pool, r.device)
                    assert did not in owned, (
                        f"device {did} owned by {owned[did]} and {c.key}"
                    )
                    owned[did] = c.key
                if c.allocated:
                    assert c.allocated_node in node_names
                    published = node_devices.get(c.allocated_node, set())
                    for r in c.results:
                        assert (r.driver, r.pool, r.device) in published
                for k in c.reserved_for:
                    assert k in pod_keys, (
                        f"{c.key} reserves deleted pod {k}"
                    )


def test_update_resource_claim_expect_rv_conflict():
    """update_resource_claim matches the other update verbs' optimistic
    concurrency (r4 advisor finding): a stale expect_rv is rejected with
    Conflict and the store keeps the current object."""
    cs = mk_cluster(n_nodes=1)
    claim = ResourceClaim(
        name="c0",
        namespace="default",
        requests=(DeviceRequest(name="r0", device_class_name="gpu"),),
    )
    cs.create_resource_claim(claim)
    cur = cs.get_resource_claim("default", "c0")
    rv = cur.resource_version
    gen = cs.dra_generation
    # matching expect_rv succeeds and advances the version
    updated = cs.update_resource_claim(cur, expect_rv=rv)
    assert updated.resource_version > rv
    assert cs.dra_generation == gen + 1
    # the original rv is now stale: Conflict, nothing written
    gen2 = cs.dra_generation
    import dataclasses

    stale = dataclasses.replace(
        cs.get_resource_claim("default", "c0"), allocated_node="n0"
    )
    with pytest.raises(ApiError, match="Conflict"):
        cs.update_resource_claim(stale, expect_rv=rv)
    assert cs.dra_generation == gen2
    assert cs.get_resource_claim("default", "c0").allocated_node == ""
    # expect_rv omitted keeps the unconditional-update behavior
    cs.update_resource_claim(cs.get_resource_claim("default", "c0"))
