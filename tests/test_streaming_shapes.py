"""The streaming dispatcher (ISSUE 10 tentpole): run_streaming replaces
run_pipelined's three modes with one persistent device-resident solve
loop — popped batches chain on the previous batch's device-resident
occupancy carry, deferred reads drain through a completion thread, and
fence discards invalidate individual stream slots. These tests pin:

1. streaming ≡ sync binding AND journal equivalence per hard shape
   (plain/ports/spread/anti/DRA), with cross-batch chaining actually
   engaging (ExactSolver.dispatch_counts["stream_chained"]) on
   uniform-shape traffic;
2. per-slot fence epochs — a conflicting/occupancy event kills exactly
   the affected stream slot (scheduler_stream_slot_discard_total), a
   plain slot rides out occupancy events, and the retry schedules
   against post-event truth;
3. the tensorize staging micro-opt — the port-occupancy vocab/used
   staging reuses across consecutive unchanged-cache batches and
   invalidates on any cache mutation;
4. the sustained_stream sim profile is byte-deterministic and actually
   drives the streaming loop.
"""

import numpy as np

from kubernetes_tpu import metrics
from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.obs import ObsConfig
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState

ZONE = "topology.kubernetes.io/zone"
HOST = "kubernetes.io/hostname"


def mk_cluster(n_nodes=6, cpu="8"):
    cs = ClusterState()
    for i in range(n_nodes):
        cs.create_node(
            MakeNode()
            .name(f"n{i}")
            .capacity({"cpu": cpu, "memory": "32Gi", "pods": "110"})
            .label(ZONE, f"z{i % 3}")
            .label(HOST, f"n{i}")
            .obj()
        )
    return cs


def mk_sched(cs, batch=8, group=4, depth=4, journal=False, **cfg):
    return Scheduler(
        cs,
        SchedulerConfig(
            batch_size=batch,
            stream_depth=depth,
            solver=ExactSolverConfig(tie_break="first", group_size=group),
            obs=ObsConfig(journal=True) if journal else None,
            **cfg,
        ),
    )


def shape_pod(i: int, kind: str):
    b = MakePod().name(f"{kind}{i:03}").req({"cpu": "100m", "memory": "256Mi"})
    if kind == "spread":
        b = b.label("app", "spread").spread_constraint(
            1, ZONE, "DoNotSchedule", {"app": "spread"}
        )
    elif kind == "anti":
        b = b.label("app", "anti").pod_anti_affinity(HOST, {"app": "anti"})
    elif kind == "ports":
        b = b.host_port(8000 + i % 3)
    return b.obj()


def bindings(cs):
    return sorted((p.name, p.node_name) for p in cs.list_pods())


# -- 1. streaming ≡ sync equivalence, with chaining engaged ------------------


def _equivalence(kind, n_pods=24, n_nodes=6, batch=8):
    cs1 = mk_cluster(n_nodes)
    s1 = mk_sched(cs1, batch=batch, journal=True)
    for i in range(n_pods):
        cs1.create_pod(shape_pod(i, kind))
    s1.run_until_settled()

    cs2 = mk_cluster(n_nodes)
    s2 = mk_sched(cs2, batch=batch, journal=True)
    for i in range(n_pods):
        cs2.create_pod(shape_pod(i, kind))
    before = metrics.pipeline_mode_total.labels("stream")._value.get()
    s2.run_streaming()
    assert (
        metrics.pipeline_mode_total.labels("stream")._value.get() > before
    ), kind
    assert bindings(cs1) == bindings(cs2), kind
    # journal equivalence: every pod's terminal outcome + node match
    o1 = {
        pod: (rec.get("outcome"), rec.get("node"))
        for pod, rec in s1.journal.last_outcomes().items()
    }
    o2 = {
        pod: (rec.get("outcome"), rec.get("node"))
        for pod, rec in s2.journal.last_outcomes().items()
    }
    assert o1 == o2, kind
    return cs2, s2


def test_plain_streaming_matches_sync_and_chains():
    _, s = _equivalence("plain")
    # uniform plain batches chain across pops (the trivial occupancy
    # vocabulary fingerprints identically)
    assert s.solver.dispatch_counts.get("stream_chained", 0) > 0


def test_ports_streaming_matches_sync():
    cs, s = _equivalence("ports")
    assert s.solver.dispatch_counts.get("stream_chained", 0) > 0
    per = {}
    for p in cs.list_pods():
        if p.node_name:
            for port in p.host_ports():
                key = (p.node_name, port)
                assert key not in per, f"hostPort clash on {key}"
                per[key] = p.name


def test_spread_streaming_matches_sync():
    cs, s = _equivalence("spread")
    assert s.solver.dispatch_counts.get("stream_chained", 0) > 0
    from collections import Counter

    zones = Counter()
    node_zone = {n.name: n.labels[ZONE] for n in cs.list_nodes()}
    for p in cs.list_pods():
        if p.node_name and p.name.startswith("spread"):
            zones[node_zone[p.node_name]] += 1
    assert max(zones.values()) - min(zones.values()) <= 1


def test_anti_streaming_matches_sync():
    """Required hostname anti-affinity across chained batches: batch
    k+1's pods must see batch k's DEVICE-side placements through the
    carried interpod term counts (host tensorize never saw them)."""
    cs, s = _equivalence("anti", n_pods=12, n_nodes=12, batch=4)
    assert s.solver.dispatch_counts.get("stream_chained", 0) > 0
    anti_nodes = [p.node_name for p in cs.list_pods() if p.node_name]
    assert len(set(anti_nodes)) == len(anti_nodes) == 12


def test_dra_streaming_matches_sync():
    from kubernetes_tpu.api.dra import (
        Device,
        DeviceClass,
        DeviceRequest,
        ResourceClaim,
        ResourceSlice,
    )
    from kubernetes_tpu.utils.featuregate import FeatureGates

    def mk():
        cs = ClusterState()
        for i in range(3):
            cs.create_node(
                MakeNode()
                .name(f"n{i}")
                .capacity({"cpu": "8", "memory": "32Gi", "pods": "20"})
                .obj()
            )
            cs.create_resource_slice(
                ResourceSlice(
                    name=f"slice-n{i}",
                    node_name=f"n{i}",
                    driver="gpu.example.com",
                    devices=(Device(name="gpu-0"), Device(name="gpu-1")),
                )
            )
        cs.create_device_class(
            DeviceClass(name="gpu", driver="gpu.example.com")
        )
        for i in range(4):
            cs.create_resource_claim(
                ResourceClaim(
                    name=f"c{i}",
                    namespace="default",
                    requests=(
                        DeviceRequest(name="r0", device_class_name="gpu"),
                    ),
                )
            )
        s = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=2,
                solver=ExactSolverConfig(tie_break="first", group_size=1),
                feature_gates=FeatureGates.parse(
                    "DynamicResourceAllocation=true"
                ),
            ),
        )
        for i in range(4):
            cs.create_pod(
                MakePod()
                .name(f"p{i}")
                .req({"cpu": "1"})
                .resource_claim(f"c{i}")
                .obj()
            )
        return cs, s

    cs1, s1 = mk()
    s1.run_until_settled()
    cs2, s2 = mk()
    s2.run_streaming()
    assert bindings(cs1) == bindings(cs2)
    assert all(p.node_name for p in cs2.list_pods())


def test_multi_profile_streaming_matches_sync():
    from kubernetes_tpu.api.objects import DEFAULT_SCHEDULER_NAME

    def mk():
        cs = mk_cluster(4)
        s = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=8,
                profiles={
                    DEFAULT_SCHEDULER_NAME: ExactSolverConfig(
                        tie_break="first", group_size=4
                    ),
                    "alt": ExactSolverConfig(
                        tie_break="first", group_size=4
                    ),
                },
            ),
        )
        for i in range(6):
            cs.create_pod(
                MakePod().name(f"a{i}").req({"cpu": "500m"}).obj()
            )
            cs.create_pod(
                MakePod()
                .name(f"b{i}")
                .scheduler_name("alt")
                .req({"cpu": "500m"})
                .obj()
            )
        return cs, s

    cs1, s1 = mk()
    s1.run_until_settled()
    cs2, s2 = mk()
    s2.run_streaming()
    assert bindings(cs1) == bindings(cs2)


def test_chain_survives_ring_fill():
    """Cross-batch chaining must stay ALIVE once the stream ring fills:
    from then on every dispatch interleaves with a ring-slot apply,
    whose host-side assume dirties snapshot columns — but the device
    already assumed exactly those placements at solve time, so the
    carry's own baseline (note_stream_applied) keeps can_chain true. A
    regression here silently degrades steady-state streaming to
    carry-mode drain-per-batch (the exact regime the dispatcher exists
    for) while every shallow drive still passes."""
    n_pods, batch, depth = 40, 4, 2
    cs1 = mk_cluster(6)
    s1 = mk_sched(cs1, batch=batch, journal=True)
    for i in range(n_pods):
        cs1.create_pod(shape_pod(i, "spread"))
    s1.run_until_settled()

    cs2 = mk_cluster(6)
    s2 = mk_sched(cs2, batch=batch, depth=depth, journal=True)
    for i in range(n_pods):
        cs2.create_pod(shape_pod(i, "spread"))
    s2.run_streaming()
    # 10 popped batches against a depth-2 ring: batches 4..10 dispatch
    # with a clean apply in between each — all but the first pop must
    # chain through them
    assert s2.solver.dispatch_counts.get("stream_chained", 0) >= 8
    assert bindings(cs1) == bindings(cs2)


# -- 2. per-slot fence epochs ------------------------------------------------


def _event_mid_stream(s, fire):
    """Install a one-shot post-dispatch hook that lands ``fire`` while
    the FIRST dispatched slot is in flight (the one real window where a
    concurrent actor's events race a deferred solve)."""
    state = {"fired": False}

    def hook(_flight):
        if not state["fired"]:
            state["fired"] = True
            fire()

    s._post_dispatch_hook = hook
    return state


def test_occupancy_event_kills_exactly_one_stream_slot():
    """A spread slot in flight when an assigned-pod label re-key lands
    must discard — and ONLY that slot: the follow-up batch re-tensorizes
    against post-event truth and applies cleanly, so the run converges
    with exactly one slot discard."""
    cs = mk_cluster()
    s = mk_sched(cs, batch=4)
    cs.create_pod(
        MakePod().name("old").label("app", "spread").req({"cpu": "1"}).obj()
    )
    cs.bind("default", "old", "n0")
    for i in range(8):
        cs.create_pod(shape_pod(i, "spread"))

    import dataclasses

    def fire():
        old = cs.get_pod("default", "old")
        cs.update_pod(dataclasses.replace(old, labels={"app": "other"}))

    _event_mid_stream(s, fire)
    slot0 = metrics.stream_slot_discard_total._value.get()
    disc0 = metrics.solves_discarded_total._value.get()
    s.run_streaming()
    assert metrics.stream_slot_discard_total._value.get() - slot0 == 1
    # the slot had one sub-flight: sub-flight discards match slot count
    assert metrics.solves_discarded_total._value.get() - disc0 >= 1
    assert all(p.node_name for p in cs.list_pods())


def test_plain_slot_survives_occupancy_events():
    """Selectivity: plain fit slots carry no occupancy vocabulary, so
    an assigned-pod delete/label flap mid-flight must NOT discard them
    (the fit carry absorbs frees conservatively) — zero slot discards,
    everything binds in the first attempt."""
    cs = mk_cluster(3)
    s = mk_sched(cs, batch=4)
    cs.create_pod(
        MakePod().name("old").label("app", "x").req({"cpu": "1"}).obj()
    )
    cs.bind("default", "old", "n0")
    for i in range(8):
        cs.create_pod(shape_pod(i, "plain"))

    def fire():
        cs.delete_pod("default", "old")

    _event_mid_stream(s, fire)
    slot0 = metrics.stream_slot_discard_total._value.get()
    results = s.run_streaming()
    assert metrics.stream_slot_discard_total._value.get() - slot0 == 0
    assert sum(len(r.scheduled) for r in results) == 8


def test_conflict_event_discards_chained_successors_together():
    """Chained slots share one fence epoch by construction (the chain
    only extends inside an unchanged fence window): a node-capacity
    event landing after two chained dispatches kills both slots, and
    every pod still reaches a terminal outcome on the retry."""
    cs = mk_cluster(4)
    s = mk_sched(cs, batch=4, depth=4)
    for i in range(8):
        cs.create_pod(shape_pod(i, "plain"))

    fired = {"n": 0}

    def hook(_flight):
        fired["n"] += 1
        if fired["n"] == 2:
            # both slots dispatched, neither applied: shrink a node
            import dataclasses

            node = cs.get_node("n3")
            alloc = dict(node.allocatable)
            alloc["cpu"] = max(alloc.get("cpu", 0) - 1000, 1000)
            cs.update_node(
                dataclasses.replace(node, allocatable=alloc)
            )

    s._post_dispatch_hook = hook
    slot0 = metrics.stream_slot_discard_total._value.get()
    s.run_streaming()
    assert metrics.stream_slot_discard_total._value.get() - slot0 == 2
    assert all(p.node_name for p in cs.list_pods())


# -- 3. tensorize staging reuse ----------------------------------------------


def test_port_staging_reuses_across_unchanged_batches():
    from kubernetes_tpu.tensorize.plugins import (
        PortStaging,
        build_port_tensors,
    )
    from kubernetes_tpu.tensorize.schema import build_pod_batch
    from kubernetes_tpu.state.snapshot import Snapshot
    from kubernetes_tpu.state.cache import SchedulerCache
    from kubernetes_tpu.utils.clock import Clock

    cs = mk_cluster(2)
    cache = SchedulerCache(Clock())
    for n in cs.list_nodes():
        cache.add_node(n)
    placed = MakePod().name("old").req({"cpu": "1"}).host_port(9000).obj()
    placed.node_name = "n0"
    cache.add_pod(placed)
    snap = Snapshot()
    batch = snap.update(cache)
    slot_nodes = [
        cache.nodes[name].node if name else None for name in snap.names
    ]
    placed_by_slot = {
        slot: list(cache.nodes[name].pods.values())
        for slot, name in enumerate(snap.names)
        if name and cache.nodes[name].pods
    }
    staging = PortStaging()
    pods1 = [shape_pod(i, "ports") for i in range(3)]
    pb1 = build_pod_batch(pods1, batch.vocab)
    key = (cache.generation, batch.padded)
    t1 = build_port_tensors(
        pods1, pb1, slot_nodes, placed_by_slot, batch.padded,
        staging=staging, staging_key=key,
    )
    assert staging.misses == 1 and staging.hits == 0
    # identical cache, next batch: the placed scan is skipped
    pods2 = [shape_pod(i + 3, "ports") for i in range(3)]
    pb2 = build_pod_batch(pods2, batch.vocab)
    t2 = build_port_tensors(
        pods2, pb2, slot_nodes, placed_by_slot, batch.padded,
        staging=staging, staging_key=key,
    )
    assert staging.hits == 1
    # the staged occupancy is identical to a fresh build
    fresh = build_port_tensors(
        pods2, pb2, slot_nodes, placed_by_slot, batch.padded
    )
    assert t2.vocab[: len(fresh.vocab)] == fresh.vocab or set(
        fresh.vocab
    ) <= set(t2.vocab)
    for entry in fresh.vocab:
        fi = fresh.vocab.index(entry)
        ti = t2.vocab.index(entry)
        np.testing.assert_array_equal(fresh.used[fi], t2.used[ti])
    # t1's vocab was not retroactively grown by t2's interning
    assert len(t1.vocab) <= t1.pod_conflict.shape[1]
    # a cache mutation invalidates
    cache.add_pod(
        MakePod().name("new").req({"cpu": "1"}).host_port(9100).obj()
    )
    t3 = build_port_tensors(
        pods2, pb2, slot_nodes, placed_by_slot, batch.padded,
        staging=staging, staging_key=(cache.generation, batch.padded),
    )
    assert staging.misses == 2
    assert t3 is not None


def test_streaming_uses_port_staging():
    """End to end: consecutive ports batches in one streaming burst hit
    the staging (the cache is unchanged between tensorizes)."""
    cs = mk_cluster()
    s = mk_sched(cs, batch=4)
    for i in range(12):
        cs.create_pod(shape_pod(i, "ports"))
    s.run_streaming()
    assert s._port_staging.hits > 0


# -- 4. sustained_stream profile ---------------------------------------------


def test_sustained_stream_profile_deterministic():
    from kubernetes_tpu.sim import run_sim

    r1 = run_sim("sustained_stream", seed=3, cycles=4)
    r2 = run_sim("sustained_stream", seed=3, cycles=4)
    assert r1.summary["streaming"] is True
    assert not r1.violations, r1.violations
    assert r1.journal_lines == r2.journal_lines
    assert r1.trace.lines == r2.trace.lines


def test_streaming_dispatcher_override_drives_existing_profiles():
    """--dispatcher streaming re-drives an existing profile through
    run_streaming (the CI chaos/crash smokes lean on this)."""
    from kubernetes_tpu.sim import run_sim

    before = metrics.pipeline_mode_total.labels("stream")._value.get()
    res = run_sim("preemption_pressure", seed=0, cycles=3, streaming=True)
    assert res.summary["streaming"] is True
    assert not res.violations, res.violations
    assert (
        metrics.pipeline_mode_total.labels("stream")._value.get() > before
    )
