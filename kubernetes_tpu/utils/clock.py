"""Injectable clock, mirroring k8s.io/utils/clock — the queue/cache tests
need deterministic time (reference queue tests inject
k8s.io/utils/clock/testing#FakeClock)."""

from __future__ import annotations

import time


class Clock:
    def now(self) -> float:
        return time.monotonic()


class FakeClock(Clock):
    def __init__(self, start: float = 0.0):
        self._now = start

    def now(self) -> float:
        return self._now

    def advance(self, seconds: float) -> None:
        self._now += seconds

    def set(self, t: float) -> None:
        self._now = t
