"""CLI: explain a pod's scheduling history / validate a trace file.

    # from a recorded journal or flight-recorder dump
    python -m kubernetes_tpu.obs explain default/pod-3 --trace journal.jsonl
    python -m kubernetes_tpu.obs explain <pod-uid> --trace dump.jsonl

    # from a live scheduler's flight recorder (serve --mode scheduler)
    python -m kubernetes_tpu.obs explain pod-3 --url http://127.0.0.1:10259

    # schema-check a journal / dump (the CI obs smoke)
    python -m kubernetes_tpu.obs validate journal.jsonl

Exit status: 0 found/valid; 1 pod not found or schema errors; 2 usage.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path


def _load_lines(args) -> list[str]:
    if args.trace:
        return Path(args.trace).read_text().splitlines()
    if args.url:
        import json
        import urllib.request

        from .recorder import canonical

        url = args.url.rstrip("/") + "/debug/flightrecorder"
        with urllib.request.urlopen(url, timeout=10.0) as r:
            doc = json.loads(r.read().decode())
        return [canonical(rec) for rec in doc.get("decisions") or []] + [
            canonical(sp) for sp in doc.get("spans") or []
        ]
    raise SystemExit("error: one of --trace or --url is required")


def cmd_explain(args) -> int:
    from .explain import explain_pod, parse_stream

    decisions, spans = parse_stream(_load_lines(args))
    out = explain_pod(decisions, args.pod, spans=spans)
    print(out.render())
    return 0 if out.found else 1


def cmd_validate(args) -> int:
    from .journal import validate_lines

    lines = Path(args.trace).read_text().splitlines()
    errors = validate_lines(lines)
    for err in errors:
        print(f"{args.trace}: {err}", file=sys.stderr)
    n = sum(1 for ln in lines if ln.strip())
    if errors:
        print(f"{args.trace}: {len(errors)} schema error(s) in {n} record(s)")
        return 1
    print(f"{args.trace}: {n} record(s), schema OK")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.obs",
        description="Scheduling-trace tools: explain pods, validate traces.",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_explain = sub.add_parser(
        "explain", help="reconstruct one pod's scheduling history"
    )
    p_explain.add_argument(
        "pod", help="pod uid, ns/name key, or bare pod name"
    )
    p_explain.add_argument(
        "--trace", metavar="FILE",
        help="journal / flight-recorder JSONL to read",
    )
    p_explain.add_argument(
        "--url", metavar="URL",
        help="base URL of a live scheduler (reads /debug/flightrecorder)",
    )
    p_explain.set_defaults(fn=cmd_explain)

    p_val = sub.add_parser(
        "validate", help="schema-check a journal / flight-recorder JSONL"
    )
    p_val.add_argument("trace", metavar="FILE")
    p_val.set_defaults(fn=cmd_validate)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
