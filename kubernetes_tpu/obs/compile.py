"""Compile observability: make XLA compilations visible per cache key,
so a streaming-hot-path recompile — the silent killer at sustained
stream / mega-drain scale, where one retracing shape turns a ~2 ms
dispatch into a multi-second compile stall — shows up in metrics and on
the dispatch span instead of only in a wall-clock mystery.

Mechanism: one process-wide listener on ``jax.monitoring``'s duration
events. ``/jax/core/compile/backend_compile_duration`` fires per actual
XLA backend compile and ``/jax/core/compile/jaxpr_trace_duration`` per
retrace (a persistent-disk-cache hit still pays the retrace, which is
why retraces are the better "known shape came back cold" signal).
Attribution: the scheduler brackets each solver dispatch with
``CompileWatcher.scope(key)`` — ``key`` is the dispatch's shape/static
fingerprint — and any compile event firing inside the bracket counts
against that key; events outside any bracket count under ``"other"``
(eager ops, warmup, tensorizer helpers).

The watcher is always on (installed at the first Scheduler
construction): the listener is a few dict updates per *compile*, which
only happens when the expensive thing already happened. Span
attribution additionally lands on the dispatch span when tracing is
enabled: ``compiles=N compile_s=...`` — absent on the (steady-state)
batches that compiled nothing.

Exported as the gauge pair ``scheduler_xla_compile_cache_keys`` (how
many distinct compile scopes this process has paid for) and
``scheduler_xla_recompilations`` (compiles beyond the first per scope —
the hot-path regression signal a known-shape test pins at zero), plus
the raw ``scheduler_xla_compilations_total`` /
``scheduler_xla_compile_seconds_total`` counters.
"""

from __future__ import annotations

import threading

from .. import metrics

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"

OTHER_SCOPE = "other"


class CompileWatcher:
    """Process-wide compile counter with scope attribution. All state
    is lock-guarded: compiles fire on whichever thread dispatched."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._local = threading.local()
        # scope key -> [compiles, retraces, seconds]
        self.by_scope: dict[str, list] = {}
        self.compiles = 0
        self.retraces = 0
        self.compile_seconds = 0.0
        self._installed = False

    # -- scope bracketing --

    def scope(self, key: str):
        return _Scope(self, key)

    def _current(self) -> str:
        stack = getattr(self._local, "stack", None)
        return stack[-1] if stack else OTHER_SCOPE

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    # -- the jax.monitoring listener --

    def _on_event(self, event: str, duration: float, **kw) -> None:
        if event == _COMPILE_EVENT:
            with self._lock:
                self.compiles += 1
                self.compile_seconds += duration
                row = self.by_scope.setdefault(
                    self._current(), [0, 0, 0.0]
                )
                row[0] += 1
                row[2] += duration
            metrics.xla_compilations_total.inc()
            metrics.xla_compile_seconds_total.inc(duration)
            self._export()
        elif event == _TRACE_EVENT:
            with self._lock:
                self.retraces += 1
                self.by_scope.setdefault(
                    self._current(), [0, 0, 0.0]
                )[1] += 1

    def _export(self) -> None:
        with self._lock:
            keys = len(self.by_scope)
            compiled = sum(r[0] for r in self.by_scope.values())
            known = sum(1 for r in self.by_scope.values() if r[0])
        metrics.xla_compile_cache_keys.set(keys)
        # recompilations = compiles beyond the first per scope: a
        # steady-state loop re-paying a compile for a shape it already
        # compiled is exactly the silent hot-path killer
        metrics.xla_recompilations.set(max(compiled - known, 0))

    def install(self) -> None:
        """Register the jax.monitoring listener once (idempotent).
        Guarded: an environment without the monitoring surface keeps
        the watcher as a no-op counter."""
        with self._lock:
            if self._installed:
                return
            self._installed = True
        try:
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(
                self._on_event
            )
        except Exception:  # pragma: no cover - jax surface drift
            pass

    # -- reads (tests, spans, /debug) --

    def totals(self) -> tuple[int, int, float]:
        with self._lock:
            return self.compiles, self.retraces, self.compile_seconds

    def scope_counts(self) -> dict[str, tuple]:
        with self._lock:
            return {k: tuple(v) for k, v in self.by_scope.items()}


class _Scope:
    __slots__ = ("_w", "_key", "compiles0", "seconds0")

    def __init__(self, watcher: CompileWatcher, key: str) -> None:
        self._w = watcher
        self._key = key
        self.compiles0 = 0
        self.seconds0 = 0.0

    def __enter__(self) -> "_Scope":
        self._w._stack().append(self._key)
        self.compiles0, _, self.seconds0 = self._w.totals()
        return self

    def __exit__(self, *exc) -> bool:
        stack = self._w._stack()
        if stack and stack[-1] == self._key:
            stack.pop()
        return False

    def delta(self) -> tuple[int, float]:
        """(compiles, seconds) attributed since __enter__ — the
        dispatch span's attribution read."""
        c, _, s = self._w.totals()
        return c - self.compiles0, s - self.seconds0


WATCHER = CompileWatcher()
