"""Hub high availability: the lease that grants hub epochs, the
standby replicator, and the in-process hub client.

The occupancy hub (fleet/occupancy.py) was a single process — a crash
meant fleet-wide conservative admission until an operator intervened.
This module is the failover half of hub HA:

- ``HubLease`` — one lease per fleet deployment granting monotone
  **hub epochs**: the LeaderElector discipline (duration > renew
  cadence, takeover only after expiry) applied per-hub, on the
  injectable clock so the failover sim runs fully virtual-time. The
  epoch grant is the fencing token of the hub tier — exactly the PR 8
  bind-fence / PR 11 hub-write-fence ladder, one level up.
- ``StandbyReplicator`` — pull-based consumption of the primary's
  append-only op log (``repl_sync``): log catch-up while the cursor is
  inside the retained window, snapshot re-join when it is not, and the
  ``scheduler_hub_replication_lag_rows`` gauge either way. The standby
  holds the same versioned row state, handoff queue, journal
  aggregation deque, and flush-dedup watermarks as the primary, so a
  promotion continues the CAS version counter without a gap (version
  continuity across the epoch boundary — the core failover invariant).
- ``LocalHubClient`` — the ``hub_op`` surface of ``BulkClient``
  dispatched straight against a hub object, no socket: the HA sim and
  tests drive ``RemoteOccupancyExchange``'s endpoint-failover machinery
  deterministically through the SAME ``dispatch_hub_op`` table the gRPC
  server uses, so in-process and on-wire semantics cannot drift.

Scope note: ``HubLease`` coordinates hubs within one process tree (the
sim, tests, the bench ladder). A multi-host deployment backs the same
interface with a real coordination store (the Lease objects the
per-shard LeaderElectors already use); the hub only ever calls
``try_acquire`` / ``renew`` / ``valid``.
"""

from __future__ import annotations

import threading

from .. import metrics
from .occupancy import OccupancyExchange, dispatch_hub_op


class HubLease:
    """Monotone epoch grants with expiry-gated takeover. ``duration_s``
    is the fencing window: a primary that fails to renew within it can
    be superseded, and once superseded its own ``valid`` check fails —
    so a deposed zombie self-fences even before hearing anything."""

    def __init__(self, clock=None, duration_s: float = 10.0) -> None:
        from ..utils.clock import Clock

        self._clock = clock or Clock()
        self.duration_s = float(duration_s)
        self._lock = threading.Lock()
        self._holder: str | None = None
        self._epoch = 0
        self._renewed_at = float("-inf")

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    @property
    def holder(self) -> str | None:
        with self._lock:
            return self._holder

    def try_acquire(self, holder: str) -> int | None:
        """Grant (or re-confirm) the lease. A new holder only acquires
        after the incumbent's lease EXPIRED — never concurrently — and
        every ownership change bumps the epoch. The incumbent
        re-acquiring is a renewal, not a new epoch."""
        with self._lock:
            now = self._clock.now()
            if self._holder == holder:
                self._renewed_at = now
                return self._epoch
            if (
                self._holder is None
                or now - self._renewed_at > self.duration_s
            ):
                self._holder = holder
                self._epoch += 1
                self._renewed_at = now
                return self._epoch
            return None

    def renew(self, holder: str) -> bool:
        """Refresh the lease — only the current holder, and only while
        its lease has not already expired (an expired holder must go
        back through try_acquire and risk losing the race, exactly the
        LeaderElector renewDeadline discipline)."""
        with self._lock:
            now = self._clock.now()
            if (
                self._holder != holder
                or now - self._renewed_at > self.duration_s
            ):
                return False
            self._renewed_at = now
            return True

    def valid(self, holder: str) -> bool:
        with self._lock:
            return (
                self._holder == holder
                and self._clock.now() - self._renewed_at
                <= self.duration_s
            )

    def release(self, holder: str) -> None:
        """Hand the lease back without waiting out the duration (a hub
        that acquired it and then refused to serve — the stale-
        re-promotion race). The epoch is NOT rewound: monotone gaps
        are harmless, a reused epoch is not."""
        with self._lock:
            if self._holder == holder:
                self._renewed_at = float("-inf")


class LocalHubClient:
    """In-process ``hub_op`` client: same call shape as
    ``BulkClient.hub_op``, dispatched through the shared
    ``dispatch_hub_op`` table, raising the hub's typed exceptions
    directly (the gRPC transport maps them to status codes and the
    remote adapter maps them back — this client just skips the wire)."""

    def __init__(self, hub: OccupancyExchange) -> None:
        self._hub = hub

    def hub_op(self, op: str, **meta) -> dict:
        return dispatch_hub_op(self._hub, op, meta)

    def close(self) -> None:
        pass


class StandbyReplicator:
    """Pull-based standby catch-up: ``poll()`` fetches the primary's
    op log past this standby's cursor (``repl_sync``) and applies it;
    a cursor behind the primary's retained window re-joins via
    snapshot. The source is anything with ``hub_op`` — a
    ``LocalHubClient`` in-process, a ``BulkClient`` across processes —
    so replication rides the same transport as everything else."""

    def __init__(self, standby: OccupancyExchange, source) -> None:
        self.standby = standby
        self._source = source
        self.snapshots_installed = 0
        self.ops_applied = 0
        self.lag = 0

    def poll(self) -> int:
        """One replication round; returns entries applied (a snapshot
        install counts as one). Raises ExchangeUnreachable when the
        source is gone — the caller (the standby's serving loop / the
        sim harness) just polls again later; a dead primary is exactly
        when the standby stops being able to catch up and promotion
        decides instead."""
        from .occupancy import ExchangeUnreachable

        since = self.standby.opseq
        if getattr(self.standby, "needs_catchup", False):
            # re-join after a deposition: this hub's history may have
            # diverged from the successor's and its opseq cursor is
            # meaningless against the new timeline — force a full
            # snapshot (since=-1 is always below the retained window)
            # so the successor's state REPLACES the stale one
            since = -1
        try:
            out = self._source.hub_op("repl_sync", since=since)
        except ExchangeUnreachable:
            raise
        except ConnectionError as e:
            raise ExchangeUnreachable(str(e)) from None
        except Exception as e:
            # a BulkClient source surfaces transport failures as raw
            # grpc.RpcError (the unreachable mapping lives in the
            # remote adapter, which replication does not ride) —
            # normalize so the caller's documented contract holds
            # (review-caught). Anything without a status code is a
            # real bug and propagates.
            if callable(getattr(e, "code", None)):
                raise ExchangeUnreachable(str(e)) from None
            raise
        latest = int(out.get("latest") or 0)
        applied = 0
        if out.get("snapshot") is not None:
            self.standby.install_snapshot(out["snapshot"])
            self.snapshots_installed += 1
            applied = 1
        else:
            for entry in out.get("ops") or []:
                self.standby.apply_replicated(entry)
                applied += 1
        self.ops_applied += applied
        self.lag = max(latest - self.standby.opseq, 0)
        metrics.hub_replication_lag_rows.set(self.lag)
        if self.lag == 0:
            # caught up to the source: a previously-deposed hub
            # becomes eligible for re-promotion again
            self.standby.note_caught_up()
        return applied
