"""Benchmark entry point (driver-run on real TPU hardware).

Two measurements:
1. BASELINE.json config #2 — 5k homogeneous pods onto 1k nodes through the
   full stack (state service -> queue -> snapshot -> exact TPU solve ->
   bind), the batched equivalent of scheduler_perf's SchedulingBasic-style
   throughput measurement (test/integration/scheduler_perf, SURVEY.md §4.5).
2. The NORTH STAR (BASELINE.md): 50k pods x 10k nodes batch-solved via the
   single-shot auction solver; target < 1 s device time.

Prints ONE JSON line:
  {"metric": ..., "value": pods/s, "unit": "pods/s", "vs_baseline": ...}
with the north-star numbers as extra fields
(north_star_*: solve seconds + x-vs-1s-target).

vs_baseline compares against the reference default scheduler's ~300 pods/s
sustained upper bound from BASELINE.md (API-bound 5k-node density tests).
Steady-state throughput excludes the first batch (XLA compile); total wall
including compile is reported alongside, as is pure device solve time
(BASELINE.md measurement protocol: service time vs solve time separated).
"""

from __future__ import annotations

import json
import time

N_NODES = 1_000
N_PODS = 5_000
BATCH = 4_096
BASELINE_PODS_PER_SEC = 300.0

NS_NODES = 10_240
NS_PODS = 51_200
NS_TARGET_S = 1.0


def north_star() -> dict:
    """50k x 10k single-shot rebalance: device solve time, steady state."""
    import numpy as np
    import jax.numpy as jnp

    from kubernetes_tpu.solver.single_shot import (
        SingleShotConfig,
        _single_shot_jit,
    )

    rng = np.random.default_rng(0)
    k, c, rc = 3, 8, 8
    alloc = np.zeros((k, NS_NODES), dtype=np.int64)
    alloc[0] = 16_000
    alloc[1] = 64 * 1024**3
    rc_req = np.zeros((rc, k), dtype=np.int64)
    rc_req[:, 0] = rng.integers(1, 9, rc) * 250
    rc_req[:, 1] = rng.integers(1, 5, rc) * 1024**3
    rc_static = (np.arange(rc) % c).astype(np.int32)
    rc_of = rng.integers(0, rc, NS_PODS).astype(np.int32)
    priority = rng.integers(0, 10, NS_PODS).astype(np.int32)
    cfg = SingleShotConfig()

    def fresh():
        return [
            jnp.asarray(x)
            for x in (
                alloc,
                np.zeros((k, NS_NODES), np.int64),
                np.zeros(NS_NODES, np.int32),
                np.full(NS_NODES, 110, np.int32),
                np.ones(NS_NODES, bool),
                np.ones((c, NS_NODES), bool),
                rc_req,
                rc_static,
                rc_of,
                priority,
                np.ones(NS_PODS, bool),
            )
        ]

    kw = dict(
        max_rounds=cfg.max_rounds, price_step=cfg.price_step, top_t=cfg.top_t
    )
    t0 = time.perf_counter()
    out = _single_shot_jit(*fresh(), **kw)
    out[0].block_until_ready()
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = _single_shot_jit(*fresh(), **kw)
    out[0].block_until_ready()
    solve_s = time.perf_counter() - t0
    placed = int((np.asarray(out[0]) >= 0).sum())
    return {
        "north_star_pods": NS_PODS,
        "north_star_nodes": NS_NODES,
        "north_star_solve_s": round(solve_s, 4),
        "north_star_compile_s": round(compile_s, 2),
        "north_star_placed": placed,
        "north_star_vs_1s_target": round(NS_TARGET_S / solve_s, 2),
    }


def _warmup(n_nodes: int, n_pods: int, batch: int) -> float:
    """Compile the exact-scan pipeline on the shapes the timed run will use
    (VERDICT r1 #2: startup warmup on bucketed shapes). A throwaway
    cluster of identical shape triggers the same executable; with the
    persistent compilation cache it deserializes from disk on restarts."""
    from kubernetes_tpu.api.wrappers import MakeNode, MakePod
    from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
    from kubernetes_tpu.solver.exact import ExactSolverConfig
    from kubernetes_tpu.state.cluster import ClusterState

    t0 = time.perf_counter()
    cs = ClusterState()
    for i in range(n_nodes):
        cs.create_node(
            MakeNode()
            .name(f"warm-node-{i:05}")
            .capacity({"cpu": "16", "memory": "64Gi", "pods": "110"})
            .obj()
        )
    sched = Scheduler(
        cs,
        SchedulerConfig(
            batch_size=batch, solver=ExactSolverConfig(tie_break="random")
        ),
    )
    for i in range(min(n_pods, batch + batch // 2)):
        cs.create_pod(
            MakePod()
            .name(f"warm-pod-{i:05}")
            .req({"cpu": "250m", "memory": "512Mi"})
            .obj()
        )
    # two batches: the second exercises the device-session heal path
    # (dirty-column scatter) so its executable is also warm before timing
    sched.schedule_batch()
    sched.schedule_batch()
    return time.perf_counter() - t0


def main() -> None:
    import jax

    # jax 0.9 + axon ignores the JAX_ENABLE_X64 env var; resource arithmetic
    # is int64 (memory bytes overflow int32), so set it via config.
    jax.config.update("jax_enable_x64", True)
    from kubernetes_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()

    from kubernetes_tpu.api.wrappers import MakeNode, MakePod
    from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
    from kubernetes_tpu.solver.exact import ExactSolverConfig
    from kubernetes_tpu.state.cluster import ClusterState

    warmup_s = _warmup(N_NODES, N_PODS, BATCH)

    cs = ClusterState()
    for i in range(N_NODES):
        cs.create_node(
            MakeNode()
            .name(f"node-{i:05}")
            .capacity({"cpu": "16", "memory": "64Gi", "pods": "110"})
            .obj()
        )
    sched = Scheduler(
        cs,
        SchedulerConfig(batch_size=BATCH, solver=ExactSolverConfig(tie_break="random")),
    )

    t_create0 = time.perf_counter()
    for i in range(N_PODS):
        cs.create_pod(
            MakePod()
            .name(f"pod-{i:05}")
            .req({"cpu": "250m", "memory": "512Mi"})
            .obj()
        )
    create_seconds = time.perf_counter() - t_create0

    batch_times: list[float] = []
    solve_times: list[float] = []
    scheduled = 0
    t0 = time.perf_counter()
    while True:
        tb = time.perf_counter()
        r = sched.schedule_batch()
        n = len(r.scheduled)
        if n == 0 and not r.unschedulable and not r.bind_failures:
            break
        batch_times.append((time.perf_counter() - tb, n))
        solve_times.append(r.solve_seconds)
        scheduled += n
    total = time.perf_counter() - t0

    assert scheduled == N_PODS, f"only {scheduled}/{N_PODS} scheduled"

    # warm-start throughput over the whole workload: compilation happened in
    # _warmup (persistent cache + device session), so every timed batch runs
    # the production path
    pods_per_sec = scheduled / total if total else float("inf")
    # per-pod p99 latency: pods in a batch all land when the batch commits
    per_pod = sorted(t for t, n in batch_times for _ in range(n))
    p99 = per_pod[int(0.99 * (len(per_pod) - 1))]

    ns = north_star()
    print(
        json.dumps(
            {
                "metric": "pods scheduled/sec, 5k pods x 1k nodes, full default plugin pipeline (warm start, end-to-end)",
                "value": round(pods_per_sec, 1),
                "unit": "pods/s",
                "vs_baseline": round(pods_per_sec / BASELINE_PODS_PER_SEC, 2),
                "total_wall_s": round(total, 3),
                "first_batch_s": round(batch_times[0][0], 3) if batch_times else None,
                "device_solve_s": round(sum(solve_times), 3),
                "p99_batch_latency_s": round(p99, 4),
                "warmup_s": round(warmup_s, 3),
                "pod_create_s": round(create_seconds, 3),
                "pods": N_PODS,
                "nodes": N_NODES,
                **ns,
            }
        )
    )


if __name__ == "__main__":
    main()
