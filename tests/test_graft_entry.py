"""Driver-contract tests for __graft_entry__.py.

These are the two artifacts the driver actually runs (compile-check of
entry() single-chip; dryrun_multichip(N) on a virtual CPU mesh). Round 2
shipped a _make_step signature change without updating _STATIC_KW and the
232-green suite never noticed — this module exists so that class of break
turns the suite red (VERDICT round 2, missing #1 / weak #2).
"""

import os
import subprocess
import sys

import jax
import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_entry_compiles_and_runs():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn).lower(*args).compile()
    assignments, state = out(*args)
    assignments = np.asarray(assignments)
    n_pods = args[2]["req"].shape[0]
    assert assignments.shape == (n_pods,)
    # the example workload trivially fits: every pod must place
    assert int((assignments >= 0).sum()) == n_pods
    # conservation: total used cpu equals the sum of placed requests
    used = np.asarray(state["used"])
    req = np.asarray(args[2]["req"])
    assert used[0].sum() == req[assignments >= 0, 0].sum()


def test_static_kw_matches_make_step_signature():
    """Every required keyword-only parameter of _make_step (minus the ones
    entry() supplies itself) must be present in _STATIC_KW — the exact
    mismatch that broke round 2's driver runs."""
    import inspect

    import __graft_entry__ as ge
    from kubernetes_tpu.solver.exact import _make_step, _mask_and_score

    # _make_step forwards its **pipe_kw catch-all to _mask_and_score, so the
    # full required set is the union of both signatures' keyword-only params
    params: dict = {}
    for fn in (_make_step, _mask_and_score):
        params.update(inspect.signature(fn).parameters)
    required = {
        name
        for name, p in params.items()
        if p.kind is inspect.Parameter.KEYWORD_ONLY
        and p.default is inspect.Parameter.empty
    }
    supplied = set(ge._STATIC_KW) | {"fdtype"}
    missing = required - supplied
    assert not missing, f"_STATIC_KW missing required solver kwargs: {missing}"
    unknown = set(ge._STATIC_KW) - set(params)
    assert not unknown, f"_STATIC_KW has kwargs the solver no longer takes: {unknown}"


def test_dryrun_multichip_8_devices():
    """Run the driver's multi-chip dryrun in a fresh subprocess (device count
    is fixed at backend init, so it can't share this process's backend)."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"],
        capture_output=True,
        text=True,
        timeout=600,
        cwd=_REPO_ROOT,
    )
    assert proc.returncode == 0, (
        f"dryrun_multichip(8) failed (rc={proc.returncode})\n"
        f"stdout: {proc.stdout}\nstderr: {proc.stderr}"
    )
    assert "dryrun_multichip ok: 8 devices" in proc.stdout
