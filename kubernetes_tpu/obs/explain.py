"""Reconstruct one pod's scheduling history from a decision journal (or
a flight-recorder dump): the `kubectl describe pod` events story, but
sourced from the scheduler's own trace layer and including per-plugin
rejection attribution.

Input is any JSONL stream mixing ``{"k": "dec"}`` decision records and
``{"k": "span"}`` spans (a journal file, a flight-recorder dump, or the
``/debug/flightrecorder`` JSON body re-flattened by the CLI). Pods
match by exact uid, exact ``ns/name`` key, or bare pod name.

``--fleet`` mode (``explain_pod(..., fleet=True)``) reconstructs the
CROSS-REPLICA history: the input is replicas' merged journals (the hub
aggregation surface, several per-replica files, or one combined dump),
records are ordered by the PR 8 fleet merge/tie-break key
(``journal.fleet_merge_key`` — the same rule the fleet sim's
journal-completeness invariant proved), and the render shows each
record's writing replica plus the journey ``trace`` id the handoff
rows propagated, so an enqueue→handoff→re-admit→solve→bind journey
reads as ONE trace even though it crossed processes.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field

from .journal import TERMINAL_OUTCOMES, fleet_merge_key, summarize_plugins

# gang-record reason shapes (scheduler.py _gang_gate / _release_gang_round
# / _quarantine_gang write these verbatim — the parse below is the read
# side of that contract)
_GANG_PARK = re.compile(
    r"waiting for pod group (?P<gid>\S+): "
    r"(?P<have>\d+)/(?P<need>\d+) members present"
)
_GANG_GID = re.compile(r"pod group (?P<gid>[^\s:]+)")


@dataclass
class Explanation:
    ref: str
    records: list[dict] = field(default_factory=list)  # journal order
    spans: list[dict] = field(default_factory=list)  # terminal batch's spans
    fleet: bool = False  # cross-replica mode (render replica columns)

    @property
    def found(self) -> bool:
        return bool(self.records)

    @property
    def replicas(self) -> list[str]:
        """Writing replicas in first-appearance order (the handoff
        chain the pod traversed)."""
        seen: list[str] = []
        for rec in self.records:
            r = rec.get("replica", "")
            if r and r not in seen:
                seen.append(r)
        return seen

    @property
    def traces(self) -> list[str]:
        """Distinct journey trace ids in first-appearance order. A
        single-element list is the propagation proof: every record —
        across every replica — shares one trace."""
        seen: list[str] = []
        for rec in self.records:
            t = rec.get("trace", "")
            if t and t not in seen:
                seen.append(t)
        return seen

    @property
    def gang_events(self) -> list[dict]:
        """The pod's gang assembly chain, reconstructed from its
        ``gang_incomplete`` / gang-quarantine records: per round, the
        pod group id, how many of N members were present (parked
        rounds), which member's failure released a staged round, and
        the quarantine verdict. Empty for non-gang pods."""
        events: list[dict] = []
        for rec in self.records:
            outcome = rec.get("outcome", "")
            reason = rec.get("reason", "")
            if outcome == "gang_incomplete":
                park = _GANG_PARK.search(reason)
                if park:
                    events.append(
                        {
                            "kind": "parked",
                            "step": rec.get("step"),
                            "gid": park.group("gid"),
                            "have": int(park.group("have")),
                            "need": int(park.group("need")),
                        }
                    )
                    continue
                kind = "released"
                if reason.startswith("gang quarantined:"):
                    kind = "quarantine_release"
                elif reason.startswith("gang bind failed:"):
                    kind = "bind_failed"
                gid = _GANG_GID.search(reason)
                events.append(
                    {
                        "kind": kind,
                        "step": rec.get("step"),
                        "gid": gid.group("gid") if gid else "",
                        "reason": reason,
                    }
                )
            elif outcome == "quarantined" and "pod group" in reason:
                gid = _GANG_GID.search(reason)
                events.append(
                    {
                        "kind": "quarantined",
                        "step": rec.get("step"),
                        "gid": gid.group("gid") if gid else "",
                        "reason": reason,
                    }
                )
        return events

    @property
    def terminal(self) -> dict | None:
        """The pod's last terminal-outcome record (None = still open:
        every record is a permit_wait/discarded intermediate)."""
        for rec in reversed(self.records):
            if rec.get("outcome") in TERMINAL_OUTCOMES:
                return rec
        return None

    def render(self) -> str:
        if not self.records:
            return f"pod {self.ref!r}: no journal records found"
        first = self.records[0]
        uid = first.get("uid") or "?"
        lines = [f"pod {first['pod']} (uid {uid}): {len(self.records)} record(s)"]
        if self.fleet:
            reps = self.replicas
            lines.append(
                "  replicas: "
                + (" -> ".join(reps) if reps else "(none tagged)")
            )
            traces = self.traces
            if len(traces) == 1:
                lines.append(f"  trace: {traces[0]} (one journey trace)")
            elif traces:
                lines.append(
                    f"  trace: {len(traces)} distinct journeys "
                    f"({', '.join(traces)})"
                )
        term = self.terminal
        if term is None:
            last = self.records[-1]
            lines.append(
                f"  state: OPEN — last record is {last['outcome']!r} at "
                f"step {last['step']} (no terminal outcome yet)"
            )
        elif term["outcome"] == "bound":
            lines.append(
                f"  terminal outcome: bound to {term.get('node', '?')} "
                f"(step {term['step']}, t={term['t']})"
            )
        else:
            lines.append(
                f"  terminal outcome: {term['outcome']} "
                f"(step {term['step']}, t={term['t']})"
            )
            if term.get("plugins"):
                lines.append(f"    plugins: {summarize_plugins(term['plugins'])}")
            if term.get("reason"):
                lines.append(f"    reason: {term['reason']}")
        gang = self.gang_events
        if gang:
            gid = next((e["gid"] for e in gang if e["gid"]), "?")
            lines.append(f"  gang assembly (pod group {gid}):")
            for e in gang:
                if e["kind"] == "parked":
                    lines.append(
                        f"    step {e['step']}: parked — "
                        f"{e['have']}/{e['need']} members present"
                    )
                elif e["kind"] == "quarantined":
                    lines.append(
                        f"    step {e['step']}: quarantined — {e['reason']}"
                    )
                else:
                    verb = {
                        "released": "round released",
                        "bind_failed": "atomic bind failed, round released",
                        "quarantine_release": (
                            "staged round rolled back for quarantine"
                        ),
                    }[e["kind"]]
                    lines.append(
                        f"    step {e['step']}: {verb} — {e['reason']}"
                    )
        lines.append("  history:")
        for rec in self.records:
            bits = [
                f"step {rec['step']}",
                f"cycle {rec['cycle']}",
                f"t={rec['t']}",
                rec["outcome"],
            ]
            if self.fleet and rec.get("replica"):
                bits.insert(0, f"[{rec['replica']}]")
            if rec.get("node"):
                bits.append(f"-> {rec['node']}")
            if rec.get("nominated"):
                bits.append(f"nominated={rec['nominated']}")
            if rec.get("attempts"):
                bits.append(f"attempt {rec['attempts']}")
            if rec.get("drain_chunk") is not None:
                # backlog drains (Scheduler.drain_backlog) tag records
                # with the chunk that solved them
                bits.append(f"drain_chunk={rec['drain_chunk']}")
            line = "    " + " ".join(bits)
            if rec.get("plugins"):
                line += f"  [{summarize_plugins(rec['plugins'])}]"
            if rec.get("reason"):
                line += f"  ({rec['reason']})"
            lines.append(line)
        if self.spans:
            lines.append("  spans of the terminal batch:")
            for sp in self.spans:
                indent = "      " if sp.get("parent") else "    "
                lines.append(
                    f"{indent}{sp['name']}: {sp['dur'] * 1e3:.3f} ms"
                    + (f" {sp['attrs']}" if sp.get("attrs") else "")
                )
        return "\n".join(lines)


def parse_stream(lines) -> tuple[list[dict], list[dict]]:
    """(decisions, spans) from a JSONL iterable; unknown/broken lines
    are skipped (a flight-recorder dump may be truncated mid-crash)."""
    decisions: list[dict] = []
    spans: list[dict] = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        kind = rec.get("k") if isinstance(rec, dict) else None
        if kind == "dec":
            decisions.append(rec)
        elif kind == "span":
            spans.append(rec)
    return decisions, spans


def _matches(rec: dict, ref: str) -> bool:
    if rec.get("uid") == ref or rec.get("pod") == ref:
        return True
    pod = rec.get("pod") or ""
    return "/" in pod and pod.split("/", 1)[1] == ref


def merge_fleet_records(records: list[dict]) -> list[dict]:
    """Total-order one pod's records gathered from SEVERAL replicas'
    journals: the PR 8 merge/tie-break key first (latest-t wins,
    terminal then 'bound' preferred on ties, within-replica step as
    the same-replica tiebreak), the writing replica as the final
    cross-replica determinism tiebreak. Byte-deterministic for any
    input permutation of the same record set — the `--selfcheck`
    contract of the fleet explain smoke."""
    return sorted(
        records,
        key=lambda r: (fleet_merge_key(r), r.get("replica", "")),
    )


def explain_pod(
    decisions: list[dict],
    ref: str,
    spans: list[dict] | None = None,
    fleet: bool = False,
) -> Explanation:
    records = [r for r in decisions if _matches(r, ref)]
    if fleet:
        records = merge_fleet_records(records)
    out = Explanation(ref=ref, records=records, fleet=fleet)
    term = out.terminal
    if term is not None and spans:
        if fleet:
            # step counters are per-replica (the merge key's own
            # caveat), so a bare-step join would attach another
            # replica's unrelated batch: require the span to carry the
            # terminal record's replica tag too (the scheduler's root
            # spans do; untagged spans stay unattributed rather than
            # wrongly attributed)
            term_replica = term.get("replica", "")
            out.spans = [
                s
                for s in spans
                if s.get("trace") == term["step"]
                and (s.get("attrs") or {}).get("replica", "")
                == term_replica
            ]
        else:
            out.spans = [
                s for s in spans if s.get("trace") == term["step"]
            ]
    return out
