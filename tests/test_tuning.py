"""Closed-loop auto-tuning (kubernetes_tpu/tuning, ISSUE 13).

Three layers:

- HillClimber convergence properties on seeded synthetic objective
  traces: settles within a bounded number of observations, never
  oscillates past the hysteresis margin, never leaves its bounds or
  alignment, never applies a guard-rejected candidate.
- CounterWindow: the split-rule EWMAs match the formula the scheduler
  used before the move (satellite: ONE home for the estimates), batch
  samples carry counter deltas, the rate signature is pop-boundary
  robust.
- TuningRuntime on a REAL Scheduler: the streaming drive converges and
  journals; the drain-chunk controller's HBM guardrail rejects
  over-budget candidates BEFORE application (BudgetExceeded never
  raised by a tuner-proposed shape); the tuned profile round-trips
  through the standard config loader.
"""

from __future__ import annotations

import pytest

from kubernetes_tpu.tuning.controllers import HillClimber
from kubernetes_tpu.tuning.runtime import TuningConfig, TuningRuntime
from kubernetes_tpu.tuning.window import CounterWindow
from kubernetes_tpu.utils.clock import FakeClock

from _hypothesis_compat import given, settings, st


def drive(climber, objective, batches):
    """Feed ``batches`` observations of ``objective(value)``; returns
    the decision list. The objective is evaluated at the climber's
    CURRENT value each batch — exactly the closed loop the runtime
    runs."""
    out = []
    for _ in range(batches):
        d = climber.observe(objective(climber.value), 1.0)
        if d is not None:
            out.append(d)
        if climber.settled:
            break
    return out


class TestHillClimber:
    def test_climbs_to_a_clean_peak_and_settles(self):
        # unimodal objective peaking at 8: the climber must walk there
        # from 2 and settle
        c = HillClimber(
            "k", 2, 1, 64, eval_batches=2, hysteresis=0.05,
            settle_after=1,
        )
        drive(c, lambda v: 100 - abs(v - 8) * 10, 200)
        assert c.settled
        assert c.value == 8
        assert c.moves >= 2  # 2 -> 4 -> 8

    def test_descends_when_down_is_better(self):
        # 1000/v doubles the objective per halving — every down-probe
        # clears the relative margin all the way to the floor
        c = HillClimber(
            "k", 32, 1, 64, eval_batches=2, hysteresis=0.05,
            settle_after=1,
        )
        drive(c, lambda v: 1000.0 / v, 200)
        assert c.settled
        assert c.value == 1

    def test_flat_objective_settles_at_start_value(self):
        # no direction improves past the margin: stay put (the tuned
        # bench arm's >= static guarantee rides on this)
        c = HillClimber(
            "k", 4, 1, 16, eval_batches=2, hysteresis=0.05,
            settle_after=1,
        )
        drive(c, lambda v: 50.0, 200)
        assert c.settled
        assert c.value == 4
        assert c.moves == 0

    def test_accepts_require_strict_hysteresis_margin(self):
        # a 3% improvement is under the 5% margin: never accepted
        c = HillClimber(
            "k", 4, 1, 64, eval_batches=2, hysteresis=0.05,
            settle_after=1,
        )
        drive(c, lambda v: 100.0 * (1.03 if v > 4 else 1.0), 200)
        assert c.settled
        assert c.value == 4
        assert c.moves == 0

    def test_never_leaves_bounds_or_alignment(self):
        c = HillClimber(
            "k", 64, 32, 512, eval_batches=1, hysteresis=0.05,
            settle_after=2, align=32,
        )
        seen = set()
        for i in range(300):
            c.observe(float((i * 37) % 11), 1.0)
            seen.add(c.value)
            if c.settled:
                break
        assert all(32 <= v <= 512 and v % 32 == 0 for v in seen), seen

    def test_guard_rejected_candidate_is_never_applied(self):
        # guard forbids anything above 8: the climber must not even
        # transiently hold a larger value
        tried = []

        def guard(v):
            tried.append(v)
            return v <= 8

        c = HillClimber(
            "k", 8, 1, 64, eval_batches=1, hysteresis=0.05,
            settle_after=1, guard=guard,
        )
        seen = set()
        for i in range(100):
            c.observe(float(i % 7), 1.0)
            seen.add(c.value)
            if c.settled:
                break
        assert max(seen) <= 8
        assert c.guard_rejections >= 1
        assert any(v > 8 for v in tried)  # it DID propose, guard vetoed

    def test_probe_budget_bounds_a_noisy_objective(self):
        # adversarial noise that keeps "improving" on every probe:
        # without the probe budget this random-walks forever
        c = HillClimber(
            "k", 4, 1, 4096, eval_batches=1, hysteresis=0.05,
            settle_after=3, max_probes=6,
        )
        n = [0.0]

        def noisy(_v):
            n[0] += 10.0  # strictly increasing: every probe accepts
            return n[0]

        for _ in range(500):
            c.observe(noisy(c.value), 1.0)
            if c.settled:
                break
        assert c.settled
        assert c.probes <= 6

    def test_no_oscillation_past_hysteresis(self):
        # an A<->B cycle needs obj(B) > obj(A)*(1+h) AND
        # obj(A) > obj(B)*(1+h) — impossible for a fixed objective; the
        # value sequence must never revisit an abandoned direction flip
        # more than the settle budget allows
        c = HillClimber(
            "k", 8, 1, 64, eval_batches=2, hysteresis=0.05,
            settle_after=2,
        )
        values = []
        for i in range(400):
            c.observe(100 - abs(c.value - 16) * 2, 1.0)
            values.append(c.value)
            if c.settled:
                break
        assert c.settled
        assert c.value == 16
        # each accepted move is unique (monotone walk), so accepts are
        # bounded by the octave distance, not the batch count
        accepts = [d for d in c.history if d.action == "accept"]
        assert len(accepts) == len({(d.old, d.new) for d in accepts})

    def test_unsettle_reopens_and_reconverges(self):
        c = HillClimber(
            "k", 2, 1, 64, eval_batches=2, hysteresis=0.05,
            settle_after=1,
        )
        drive(c, lambda v: 100 - abs(v - 8) * 10, 200)
        assert c.settled and c.value == 8
        c.unsettle({"why": "test"})
        assert not c.settled
        drive(c, lambda v: 100 - abs(v - 32) * 2, 400)
        assert c.settled
        assert c.value == 32

    @settings(max_examples=30, deadline=None)
    @given(
        st.integers(min_value=0, max_value=2**31 - 1),
        st.integers(min_value=1, max_value=6),
        st.integers(min_value=1, max_value=3),
    )
    def test_property_always_settles_in_bounds(
        self, seed, eval_batches, settle_after
    ):
        """Any seeded objective trace: the climber settles within the
        structural bound (probe budget x window) and never exits its
        bounds/alignment."""
        import random

        rng = random.Random(seed)
        c = HillClimber(
            "k", 8, 2, 256, eval_batches=eval_batches,
            hysteresis=0.1, settle_after=settle_after, align=2,
            max_probes=8,
        )
        # structural bound: every window is eval_batches observations;
        # episodes end after max_probes probes; between consecutive
        # probes there is at most one measure window
        limit = eval_batches * (2 * c.max_probes + 4) + eval_batches
        steps = 0
        while not c.settled and steps < 10_000:
            c.observe(rng.uniform(0, 100), 1.0)
            steps += 1
            assert 2 <= c.value <= 256 and c.value % 2 == 0
        assert c.settled, f"never settled in {steps} steps"
        assert steps <= limit, (steps, limit)


class TestCounterWindow:
    def test_note_read_ewma_matches_the_moved_formula(self):
        # the exact update rule that lived in Scheduler._note_flight_timing
        w = CounterWindow(FakeClock())
        w.note_read(0.2, 0.1, 10)
        assert w.rtt_ewma == pytest.approx(0.2)
        assert w.pod_solve_ewma == pytest.approx(0.3 / 10)
        w.note_read(0.4, 0.1, 10)
        assert w.rtt_ewma == pytest.approx(0.7 * 0.2 + 0.3 * 0.4)
        # sub-millisecond reads carry no signal (post-overlap reads are
        # the overlap working)
        before = w.rtt_ewma
        w.note_read(0.0005, 0.1, 10)
        assert w.rtt_ewma == before

    def test_split_estimate_rule(self):
        w = CounterWindow(FakeClock())
        assert w.split_estimate(100, 8) == 1  # no estimates yet
        # exact binary fractions so the rule's integer truncation is
        # deterministic in the test
        w.rtt_ewma = 0.125
        w.pod_solve_ewma = 0.0009765625  # 2^-10
        # est_solve = 0.0977 <= 2 * rtt: no split
        assert w.split_estimate(100, 8) == 1
        # est_solve = 4 s = 32x rtt: split, capped
        assert w.split_estimate(4096, 8) == 8
        assert w.split_estimate(4096, 4) == 4
        w.pod_solve_ewma = 0.0005  # est = 0.5 s = 4x rtt
        assert w.split_estimate(1000, 8) == 4

    def test_note_batch_samples_counter_deltas(self):
        from kubernetes_tpu import metrics

        clock = FakeClock()
        w = CounterWindow(clock)
        metrics.stream_unhidden_reads_total.inc(3)
        clock.advance(2.0)
        s = w.note_batch(pods=5, solve_s=0.1)
        assert s.deltas["unhidden_reads"] == 3
        assert s.pods == 5
        assert s.wall_s == pytest.approx(2.0)
        # second sample: delta resets
        s2 = w.note_batch(pods=4)
        assert s2.deltas["unhidden_reads"] == 0

    def test_rate_is_pop_boundary_robust(self):
        # one 15-pod cycle popped as [15] or as [8, 7] must read the
        # same rate (the per-batch mean would differ by 2x)
        clock = FakeClock()
        a = CounterWindow(clock)
        clock.advance(1.0)
        a.note_batch(pods=15)
        b = CounterWindow(clock)
        clock.advance(1.0)
        b.note_batch(pods=8)
        b.note_batch(pods=7)
        assert a.rate(4) == pytest.approx(b.rate(4))


def _mk_cluster(n_nodes=8, cpu="32", mem="128Gi", clock=None):
    from kubernetes_tpu.api.wrappers import MakeNode
    from kubernetes_tpu.state.cluster import ClusterState

    cs = ClusterState(clock=clock)
    for i in range(n_nodes):
        cs.create_node(
            MakeNode()
            .name(f"n{i}")
            .capacity({"cpu": cpu, "memory": mem, "pods": "110"})
            .obj()
        )
    return cs


def _mk_pods(cs, n, prefix="p"):
    from kubernetes_tpu.api.wrappers import MakePod

    for i in range(n):
        cs.create_pod(
            MakePod()
            .name(f"{prefix}{i:04}")
            .req({"cpu": "500m", "memory": "1Gi"})
            .obj()
        )


class TestRuntimeOnScheduler:
    def _scheduler(self, clock, tuning=None, n_nodes=8, cpu="32", **cfg_kw):
        from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig

        cs = _mk_cluster(n_nodes=n_nodes, cpu=cpu, clock=clock)
        cfg = SchedulerConfig(
            batch_size=8,
            tuning=tuning
            or TuningConfig(
                eval_batches=2, settle_after=1, hysteresis=0.5,
                max_probes=4,
            ),
            **cfg_kw,
        )
        return cs, Scheduler(cs, cfg, clock=clock)

    def test_streaming_drive_converges_and_journals(self):
        from kubernetes_tpu import metrics

        clock = FakeClock()
        cs, s = self._scheduler(clock)
        for c in range(20):
            _mk_pods(cs, 6, prefix=f"c{c}-")
            s.run_streaming(max_batches=50)
            clock.advance(1.0)
        summary = s.tuner.summary()
        assert summary["probes"] >= 1
        assert summary["settled"] == 1
        assert summary["guardrail_breaches"] == 0
        assert 1 <= summary["knobs"]["stream_depth"] <= 16
        assert 1 <= summary["knobs"]["pipeline_split"] <= 8
        # the applied value and the journaled gauge agree with config
        assert s.config.stream_depth == summary["knobs"]["stream_depth"]
        assert metrics.tuning_knob_value.labels(
            "stream_depth"
        )._value.get() == float(s.config.stream_depth)
        # every decision journaled through the metric family
        assert len(s.tuner.decisions) == summary["adjustments"]

    def test_choose_split_prefers_tuner_then_window(self):
        clock = FakeClock()
        cs, s = self._scheduler(clock)
        # without a tuner attachment yet: the window's EWMA rule
        s.window.rtt_ewma = 0.1
        s.window.pod_solve_ewma = 0.001
        assert s._choose_split(1000) == s.window.split_estimate(1000, 8)
        # attach: the split controller owns the knob outright
        s.tuner.attach(s)
        assert s._choose_split(1000) == s.tuner.split_override()
        # a fixed config split is a static pin over both
        s.config.pipeline_split = 3
        assert s._choose_split(1000) == 3

    def test_pipelined_drive_settles_despite_inactive_stream_knob(self):
        """Review-caught: the stream_depth controller never ticks on a
        pipelined drive (its dispatch mode never runs) — a never-ticked
        controller must not pin settled=0 forever."""
        clock = FakeClock()
        cs, s = self._scheduler(clock)
        for c in range(20):
            _mk_pods(cs, 6, prefix=f"c{c}-")
            s.run_pipelined(max_batches=50)
            clock.advance(1.0)
        summary = s.tuner.summary()
        assert summary["settled"] == 1, summary
        depth = s.tuner.controllers["stream_depth"]
        assert depth.ticks == 0 and not depth.settled  # idle, not failed

    def test_first_sample_is_a_warm_batch(self):
        """Review-caught: the first sample's wall spans scheduler
        construction (JIT compile) — it must re-anchor the window but
        feed no controller, or the deflated baseline lets the first
        probe win unconditionally."""
        clock = FakeClock()
        cs, s = self._scheduler(clock)
        clock.advance(100.0)  # "construction + compile" gap
        _mk_pods(cs, 6)
        s.run_streaming(max_batches=10)
        assert all(
            c.ticks == 0 for c in s.tuner.controllers.values()
        )
        assert len(s.window.samples) >= 1  # the window DID sample

    def test_static_pin_by_dropping_the_knob(self):
        clock = FakeClock()
        cs, s = self._scheduler(
            clock,
            tuning=TuningConfig(
                eval_batches=2, settle_after=1,
                knobs=("pipeline_split",),
            ),
        )
        for c in range(8):
            _mk_pods(cs, 6, prefix=f"c{c}-")
            s.run_streaming(max_batches=50)
            clock.advance(1.0)
        # stream_depth untouched (not governed), split governed
        assert "stream_depth" not in s.tuner.controllers
        assert s.config.stream_depth == 4
        assert "pipeline_split" in s.tuner.controllers

    def test_drain_guardrail_rejects_over_budget_chunks(self):
        """The acceptance clause: a tuner-proposed chunk must pass the
        HBM budget model BEFORE application — BudgetExceeded is never
        raised by a tuner-proposed shape, and the up-probes against a
        budget pinned one byte above the base chunk's estimate are
        rejected, not applied."""
        from kubernetes_tpu.solver import budget as hbm

        clock = FakeClock()
        cs, s = self._scheduler(clock, n_nodes=12, cpu="64")
        # chunk = LANE (128): the smallest chunk whose DOUBLING grows
        # the pod-axis padding bucket (everything below 128 floors to
        # one bucket and costs the same HBM — growth there is free and
        # correctly allowed)
        _mk_pods(cs, 768)
        shape = s.drain_shape(128)
        budget = hbm.estimate(shape).per_device_bytes + 1
        report = s.drain_backlog(chunk_pods=128, budget_bytes=budget)
        assert report.drained == 768  # the drain completed
        summary = s.tuner.summary()
        assert summary["guardrail_breaches"] == 0
        # the chunk controller's up-probes (256-pod bucket) were
        # guard-vetoed: one byte of headroom cannot fit a bigger bucket
        assert summary["guardrail_rejections"] >= 1
        # and the applied chunk never exceeded the guarded start value
        assert report.final_chunk_pods <= 128

    def test_drain_chunk_stays_group_aligned(self):
        from kubernetes_tpu.solver.exact import ExactSolverConfig

        clock = FakeClock()
        cs, s = self._scheduler(
            clock, solver=ExactSolverConfig(group_size=8)
        )
        _mk_pods(cs, 128)
        s.drain_backlog(chunk_pods=16)
        chunk = s.tuner.knob_values().get("backlog_chunk")
        # chunk started group-aligned (16 = 2 groups): every candidate
        # the controller may have applied stays a whole-group multiple
        assert chunk is not None and chunk % 8 == 0

    def test_tuned_profile_round_trips_through_standard_config(self):
        from kubernetes_tpu.config import types as config_types
        from kubernetes_tpu.tuning.profile import tuned_profile

        clock = FakeClock()
        cs, s = self._scheduler(clock)
        for c in range(12):
            _mk_pods(cs, 6, prefix=f"c{c}-")
            s.run_streaming(max_batches=50)
            clock.advance(1.0)
        doc = tuned_profile(s)
        cfg = config_types.load(doc)
        sched_cfg = config_types.scheduler_config(cfg)
        knobs = s.tuner.knob_values()
        assert sched_cfg.stream_depth == knobs["stream_depth"]
        assert sched_cfg.pipeline_split == knobs["pipeline_split"]
        assert sched_cfg.tuning is None  # standard config out: tuner off

    def test_stream_depth_applies_at_ring_drain_boundary(self):
        """An in-flight ring keeps the depth it was dispatched under:
        the loop's bound variable refreshes from config only when the
        ring is empty."""
        clock = FakeClock()
        cs, s = self._scheduler(clock, tuning=None)
        s.tuner = None  # drive the knob by hand
        s.config.stream_depth = 2
        _mk_pods(cs, 32)
        depths = []
        orig = s._dispatch_stream

        def spy(prep, **kw):
            depths.append(s.config.stream_depth)
            return orig(prep, **kw)

        s._dispatch_stream = spy
        s.run_streaming(max_batches=50)
        assert depths  # dispatches happened under depth 2
        # a live change takes effect on the next (ring-empty) entry
        s.config.stream_depth = 5
        _mk_pods(cs, 16, prefix="q")
        s.run_streaming(max_batches=50)
        assert s.config.stream_depth == 5


class TestFleetFlushKnob:
    def test_remote_exchange_buffer_cap_retargets(self):
        """The fleet_flush knob's application surface: the write-behind
        cap is an instance setting consulted on append, so a retarget
        at any moment is safe — a shrink below the live buffer simply
        flushes at the next mutation."""
        from kubernetes_tpu.fleet.runtime import RemoteOccupancyExchange

        calls = []

        class FakeClient:
            def hub_op(self, op, **meta):
                calls.append((op, meta))
                return {"version": 1}

            def close(self):
                pass

        ex = RemoteOccupancyExchange("x:1", "r0", client=FakeClient())
        assert ex._buffer_cap == RemoteOccupancyExchange._BUFFER_CAP
        ex.set_buffer_cap(2)
        from kubernetes_tpu.fleet.occupancy import PodRow

        def row(i):
            return PodRow(
                pod=f"default/p{i}", node="n0", zone="z0",
                namespace="default", labels=(),
            )

        ex.stage("r0", row(0))
        assert not any(op == "apply_ops" for op, _ in calls)
        ex.stage("r0", row(1))  # cap 2 reached -> one apply_ops flush
        flushes = [m for op, m in calls if op == "apply_ops"]
        assert len(flushes) == 1 and len(flushes[0]["ops"]) == 2

    def test_empty_knob_list_pins_everything(self):
        """Review-caught: `tuning: {knobs: []}` must mean "govern
        nothing" (the documented pin-everything recipe), not silently
        expand to all four knobs."""
        from kubernetes_tpu.config import types as config_types

        cfg = config_types.load("tuning: {enabled: true, knobs: []}")
        assert cfg.tuning.knobs == []
        sc = config_types.scheduler_config(cfg)
        assert sc.tuning.knobs == ()
        # absent key still means all knobs
        cfg2 = config_types.load("tuning: {enabled: true}")
        assert set(cfg2.tuning.knobs) == set(config_types.TUNABLE_KNOBS)

    def test_max_probes_parses_and_validates(self):
        from kubernetes_tpu.config import types as config_types

        cfg = config_types.load("tuning: {enabled: true, maxProbes: 5}")
        assert config_types.scheduler_config(cfg).tuning.max_probes == 5
        with pytest.raises(ValueError):
            config_types.load("tuning: {maxProbes: 0}")
        # TuningConfig.validate shares the SAME checker
        with pytest.raises(ValueError):
            TuningConfig(max_probes=0).validate()

    def test_config_flush_batch_threads_to_the_adapter(self):
        from kubernetes_tpu.config import types as config_types

        cfg = config_types.load(
            "fleet:\n  replica: r0\n  flushBatch: 64\n"
        )
        sc = config_types.scheduler_config(cfg)
        assert sc.fleet.flush_batch == 64
        import pytest

        with pytest.raises(ValueError):
            config_types.load("fleet:\n  replica: r0\n  flushBatch: -1\n")


class TestTuningInvariant:
    """Known-bad fixtures for sim/invariants.check_tuning: every clause
    must fire on a summary violating exactly it."""

    GOOD = {
        "probes": 4,
        "moves": 1,
        "max_knob_moves": 1,
        "settled": 1,
        "guardrail_breaches": 0,
        "shifts": 1,
        "batches_since_unsettle": 100,
        "settle_bound": 24,
        "knobs": {"stream_depth": 4},
    }

    def _violations(self, summary, **kw):
        from kubernetes_tpu.sim.invariants import check_tuning

        v = []
        check_tuning(0, v, summary=summary, **kw)
        return v

    def test_clean_summary_passes(self):
        assert self._violations(dict(self.GOOD), expect_shift=True) == []

    def test_never_engaged(self):
        v = self._violations(dict(self.GOOD, probes=0))
        assert len(v) == 1 and "never probed" in v[0].detail

    def test_unsettled(self):
        v = self._violations(dict(self.GOOD, settled=0))
        assert any("unsettled" in x.detail for x in v)
        # ... but NOT when the last unsettle (a late-detected shift)
        # left fewer batches than the structural settle bound: the
        # tuner is legitimately mid-re-convergence, not broken
        v2 = self._violations(
            dict(self.GOOD, settled=0, batches_since_unsettle=10)
        )
        assert v2 == []

    def test_guardrail_breach(self):
        v = self._violations(dict(self.GOOD, guardrail_breaches=2))
        assert any("guardrail breach" in x.detail for x in v)

    def test_knob_thrash(self):
        v = self._violations(dict(self.GOOD, max_knob_moves=40))
        assert any("thrash" in x.detail for x in v)

    def test_missed_shift(self):
        v = self._violations(dict(self.GOOD, shifts=0), expect_shift=True)
        assert any("never detected" in x.detail for x in v)
        # and not required when the profile never shifted
        assert (
            self._violations(dict(self.GOOD, shifts=0), expect_shift=False)
            == []
        )


class TestSimAcceptance:
    @pytest.mark.slow
    def test_tuning_convergence_profile_settles_and_reconverges(self):
        from kubernetes_tpu.sim.harness import run_sim

        res = run_sim("tuning_convergence", seed=0, cycles=24)
        assert res.ok, res.violations
        tu = res.summary["tuning"]
        assert tu["settled"] == 1
        assert tu["shifts"] >= 1
        assert tu["guardrail_breaches"] == 0
        assert res.tuned_profile is not None

    def test_tuning_convergence_deterministic(self):
        from kubernetes_tpu.sim.harness import run_sim

        a = run_sim("tuning_convergence", seed=3, cycles=10)
        b = run_sim("tuning_convergence", seed=3, cycles=10)
        assert a.trace.lines == b.trace.lines
        assert a.journal_lines == b.journal_lines
        assert a.summary["tuning"] == b.summary["tuning"]
