"""Deterministic cluster simulator + fault-injection harness.

Drives the real :class:`~kubernetes_tpu.scheduler.Scheduler` (both the
synchronous and pipelined loops) through the real
:class:`~kubernetes_tpu.state.cluster.ClusterState` under seeded churn
and injected faults, on ``FakeClock`` virtual time, checking
correctness invariants after every drive and recording a replayable
trace. See sim/README.md for profiles, fault points, and the replay
workflow; CLI: ``python -m kubernetes_tpu.sim --help``.
"""

from .harness import SimHarness, SimResult, replay_trace, run_sim
from .invariants import Violation
from .profiles import PROFILES, Profile, get_profile
from .trace import TraceError, TraceReader, TraceWriter

__all__ = [
    "SimHarness",
    "SimResult",
    "run_sim",
    "replay_trace",
    "Violation",
    "Profile",
    "PROFILES",
    "get_profile",
    "TraceWriter",
    "TraceReader",
    "TraceError",
]
