"""HBM capacity planning for accelerator-resident solves (ISSUE 12).

The backlog-drain engine chunks a mega-backlog (512k pods) through the
streaming dispatcher's slot ring against the node-axis-sharded resident
session. Every array that trip holds in HBM follows the tensorizers'
padding discipline — ``Snapshot.pad_multiple`` / ``schema.bucket_pow2``
on the node axis, the pow2/batch-size bucket on the pod axis, the
``CLASS_PAD``/``PORT_PAD``/``INST_PAD`` floors on the class/port/
instance axes — so the device-memory footprint of a (pods, nodes,
vocab, mesh) shape is *computable before dispatch*. This module is that
computation: an analytic per-component byte model mirroring exactly the
arrays ``ExactSolver.solve`` uploads and keeps resident, asserted
against the per-device budget BEFORE a chunk dispatches. An over-budget
chunk auto-splits (``plan_chunk`` halves group-aligned) instead of
OOMing mid-drain; a shape that cannot fit at any chunk size raises the
typed ``BudgetExceeded``.

The model is checkable: ``ShapeEstimate.session_upload_bytes`` mirrors
the exact byte accounting ``solve`` feeds the
``scheduler_tpu_host_to_device_bytes_total`` counter, and
tests/test_budget.py validates the prediction against the measured
counter delta within a documented tolerance. The resident-set half
multiplies by ``WORKSPACE_FACTOR`` for XLA scratch (scan intermediates,
fusion temporaries) — a deliberate safety margin, documented rather
than hidden.

``assert_index_headroom`` is the companion index-dtype audit for the
512k x 102k shape: the flattened-index products the compiled programs
form (grouped quota positions, auction admission sort keys, unique
per-node random keys) are checked against their container widths with
a typed ``IndexWidthError`` — widened arithmetic in the kernels plus
this host-side guard means a future 2^31-scale shape fails loudly at
dispatch instead of silently wrapping on device.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from ..tensorize.interpod import INST_PAD as IPA_INST_PAD
from ..tensorize.plugins import CLASS_PAD, PORT_PAD
from ..tensorize.schema import LANE, bucket_pow2
from ..tensorize.spread import DOM_PAD, INST_PAD as SPREAD_INST_PAD

# Fallback per-device budget when the runtime reports no bytes_limit
# (CPU backends, older PJRT): one conservative accelerator-die floor.
DEFAULT_DEVICE_BUDGET_BYTES = 8 << 30

# Compiled-program workspace multiplier over the analytic resident set:
# XLA scratch (scan carries, fused temporaries, donation double-buffers)
# is not enumerable from the host, so the resident estimate carries an
# explicit 1.5x safety factor instead of a hidden guess. Measured on
# the ladder shapes the true overhead is well under this.
WORKSPACE_FACTOR = 1.5


class BudgetExceeded(Exception):
    """The shape does not fit the per-device HBM budget at ANY chunk
    size >= the minimum chunk. Raised by ``plan_chunk`` — the caller
    decides (refuse the drain, shrink the node axis, add devices);
    nothing was dispatched, so no device state is at risk."""

    def __init__(self, estimate: "ShapeEstimate", budget_bytes: int):
        self.estimate = estimate
        self.budget_bytes = budget_bytes
        super().__init__(
            f"per-device estimate {estimate.per_device_bytes:,} B exceeds "
            f"the {budget_bytes:,} B budget even at the minimum chunk "
            f"({estimate.chunk_pods} pods x {estimate.nodes} nodes)"
        )


class IndexWidthError(Exception):
    """A flattened-index product in the solve pipeline would overflow
    its container dtype at this shape (the 512k x 102k audit's typed
    failure — loud at dispatch, never a silent device-side wrap)."""


def node_padding(nodes: int, pad_multiple: int = 1) -> int:
    """The snapshot's node-axis padding for ``nodes`` live nodes:
    pow2 bucket (>= LANE) rounded up to lcm(LANE, devices) when the
    solve is mesh-sharded — exactly ``Snapshot._ensure_capacity``."""
    cap = bucket_pow2(max(nodes, LANE))
    if pad_multiple > 1:
        q = math.lcm(LANE, pad_multiple)
        cap = ((cap + q - 1) // q) * q
    return cap


def pod_padding(chunk_pods: int, group: int) -> int:
    """The pod-axis bucket a drain chunk tensorizes into: the grouped
    fast path keeps the batch-size bucket exactly when it is
    group-aligned (scheduler._tensorize_group's pod_pad), else the
    pow2 bucket."""
    if group > 1 and chunk_pods > 0 and chunk_pods % group == 0:
        return chunk_pods
    return bucket_pow2(max(chunk_pods, 1))


@dataclass(frozen=True)
class DrainShape:
    """The inputs the footprint of a drain chunk is a function of.
    Row counts default to the tensorizers' floor pads (PORT_PAD /
    INST_PAD = 8): workloads with wide port vocabularies or many
    spread/interpod instances should pass the real padded counts."""

    nodes: int
    chunk_pods: int
    vocab_k: int = 3
    classes: int = 1
    # per-family activity: inactive families still upload their
    # floor-padded trivial rows (bstate/class tables), but their
    # PER-POD rows only exist when the batch carries the shape
    spread: bool = False
    interpod: bool = False
    port_rows: int = PORT_PAD
    spread_rows: int = SPREAD_INST_PAD
    ipa_in_rows: int = IPA_INST_PAD
    ipa_ex_rows: int = IPA_INST_PAD
    d_pad: int = DOM_PAD
    mesh_devices: int = 1
    group: int = 64
    stream_depth: int = 4
    pad_multiple: int = 0  # 0 = mesh_devices (the scheduler default)


@dataclass(frozen=True)
class ShapeEstimate:
    """Analytic footprint of one drain-chunk shape. ``components`` maps
    name -> (bytes, sharded) for observability; the headline numbers:

    - ``per_device_bytes``: worst-case resident HBM per device with the
      stream ring full (node-sharded tables divided across the mesh,
      replicated per-pod arrays per in-flight slot, x WORKSPACE_FACTOR)
      — what ``plan_chunk`` asserts against the budget;
    - ``session_upload_bytes``: host->device bytes of a FRESH-session
      first chunk (tables + state + per-pod arrays), mirroring the
      ``scheduler_tpu_host_to_device_bytes_total`` accounting so the
      model is checkable against the measured counter;
    - ``chunk_upload_bytes`` / ``chunk_upload_bytes_compact``: the
      steady-state per-chunk upload with full per-pod rows vs the
      compact wire (one representative row per group chunk — the
      uniform-backlog fast path); a CHAINED chunk additionally skips
      ``bstate_bytes``.
    """

    nodes: int
    chunk_pods: int
    node_pad: int
    pod_pad: int
    devices: int
    sharded_bytes: int
    replicated_bytes: int
    per_device_bytes: int
    session_upload_bytes: int
    chunk_upload_bytes: int
    chunk_upload_bytes_compact: int
    bstate_bytes: int
    components: tuple


def estimate(shape: DrainShape) -> ShapeEstimate:
    """Per-component byte model of one drain-chunk dispatch, mirroring
    the arrays ``ExactSolver.solve`` uploads/keeps resident (the
    packed-transfer layer's wire protocol) under the tensorizers' own
    padding discipline."""
    pad_mult = shape.pad_multiple or shape.mesh_devices
    n = node_padding(shape.nodes, pad_mult)
    p = pod_padding(shape.chunk_pods, shape.group)
    k = shape.vocab_k
    c = bucket_pow2(max(shape.classes, 1), floor=CLASS_PAD)
    b = max(shape.port_rows, 1)
    s = max(shape.spread_rows, 1)
    ti = max(shape.ipa_in_rows, 1)
    te = max(shape.ipa_ex_rows, 1)

    # -- node-sharded residents (trailing node axis) --
    node_tables = k * n * 8 + n * 4 + n  # alloc + max_pods + valid
    persist = k * n * 8 + 2 * n * 8 + n * 4  # used + nonzero + pod_count
    class_tables = (
        c * n * (1 + 4 + 4 + 4)  # mask + taint + nodeaff + image
        + s * n * (4 + 1)  # spr.dom + spr.elig
        + (ti + te) * n * 4  # ipa.in_dom + ipa.ex_dom
        # per-instance/per-class scalar tables (max_skew, min_domains,
        # self_match, is_hostname, hard, soft, in_pref_w, cls_* rows,
        # ex_anti): node-axis-free, a rounding error at drain scale
        + s * 10 + ti * 4 + te + c * 5 * 4
    )
    bstate = (b + s + ti + te) * n * 4  # port_used + cnt0 + in/ex rows
    # the stream carry keeps one extra generation of the occupancy rows
    # resident while the next chained solve donates through
    carry = bstate
    sharded = node_tables + persist + class_tables + bstate + carry

    # -- replicated per-pod arrays, one set per in-flight ring slot --
    i64_w = (k + 2) * 8  # req [K] + nonzero_req [2]
    i32_w = (1 + b) * 4  # class_of + pod_takes [B]
    bool_w = k + 1 + b  # req_mask + pod_valid + pod_conflict [B]
    if shape.spread:
        bool_w += s  # spr_placed
    if shape.interpod:
        i32_w += (2 * ti + te) * 4  # in_match + m_w [Ti], ex_owned [Te]
        bool_w += te + 1  # m_anti [Te] + self_aff
    per_pod = i64_w + i32_w + bool_w
    kinds_vcnt = (p // max(shape.group, 1)) * 8 + 8 + 4  # kinds+vcnt+dummies
    slot = p * per_pod + p * 4 + kinds_vcnt  # + assignments
    slots_live = shape.stream_depth + 1
    replicated = slots_live * slot

    devices = max(shape.mesh_devices, 1)
    per_device = int(
        WORKSPACE_FACTOR * (math.ceil(sharded / devices) + replicated)
    )

    chunk_upload = p * per_pod + bstate + kinds_vcnt
    chunk_upload_compact = (p // max(shape.group, 1)) * per_pod + bstate + kinds_vcnt
    session_upload = node_tables + persist + class_tables + chunk_upload

    return ShapeEstimate(
        nodes=shape.nodes,
        chunk_pods=shape.chunk_pods,
        node_pad=n,
        pod_pad=p,
        devices=devices,
        sharded_bytes=sharded,
        replicated_bytes=replicated,
        per_device_bytes=per_device,
        session_upload_bytes=session_upload,
        chunk_upload_bytes=chunk_upload,
        chunk_upload_bytes_compact=chunk_upload_compact,
        bstate_bytes=bstate,
        components=(
            ("node_tables", node_tables, True),
            ("persist", persist, True),
            ("class_tables", class_tables, True),
            ("bstate_rows", bstate, True),
            ("stream_carry", carry, True),
            ("per_pod_slots", replicated, False),
        ),
    )


@dataclass(frozen=True)
class RelaxEstimate:
    """Analytic per-device footprint of one relaxation solve
    (solver/relax.py): the [RC, N] class tables + [K, N] duals shard
    over the node axis; the per-pod rank/searchsorted workspace
    replicates. Same WORKSPACE_FACTOR discipline as the drain model."""

    node_pad: int
    pod_pad: int
    rc_pad: int
    sharded_bytes: int
    replicated_bytes: int
    per_device_bytes: int
    components: tuple


def relax_estimate(
    nodes: int,
    pods: int,
    rc: int,
    vocab_k: int = 3,
    mesh_devices: int = 1,
    group: int = 64,
) -> RelaxEstimate:
    """Byte model of the relaxation's resident set at (pods, nodes,
    rc): what ``RelaxSolver`` asserts against the device budget before
    the 2M-pod mega-shape dispatches. Mirrors the arrays ``_relax``
    materializes — fractional mass / logits / quota tables on [RC, N],
    duals and integer capacities on [K, N], the flat quota prefix on
    [RC * N], and the per-pod sort/rank/searchsorted workspace."""
    pad_mult = mesh_devices if mesh_devices > 1 else 1
    n = node_padding(nodes, pad_mult)
    p = pod_padding(pods, group)
    k = vocab_k
    # [RC, N] lanes: x + softmax workspace (z, logits, pen) f32, the
    # static ok mask (bool), desired + clamped quotas (int32)
    class_tables = rc * n * (4 * 4 + 1 + 2 * 4)
    # [K, N]: lam f32, free int64, alloc/used int64, inv_free f32
    duals = k * n * (4 + 8 + 8 + 8 + 4) + n * (4 + 4 + 4)  # + mu/cnt/score
    flat_prefix = rc * n * 8 * 2  # flat_q + gcum, int64
    sharded = class_tables + duals + flat_prefix
    # per-pod workspace: sort key + argsort (int64), rc_of/priority/
    # rank/assigned (int32), valid (bool), g/flat_cell (int64)
    per_pod = 8 + 8 + 4 * 4 + 1 + 8 + 8
    replicated = p * per_pod
    devices = max(mesh_devices, 1)
    per_device = int(
        WORKSPACE_FACTOR * (math.ceil(sharded / devices) + replicated)
    )
    return RelaxEstimate(
        node_pad=n,
        pod_pad=p,
        rc_pad=rc,
        sharded_bytes=sharded,
        replicated_bytes=replicated,
        per_device_bytes=per_device,
        components=(
            ("class_tables", class_tables, True),
            ("duals", duals, True),
            ("flat_prefix", flat_prefix, True),
            ("pod_workspace", replicated, False),
        ),
    )


def device_budget_bytes(override: int = 0) -> int:
    """The per-device HBM budget: an explicit override, else the
    runtime-reported ``bytes_limit`` (PJRT memory stats), else the
    conservative DEFAULT_DEVICE_BUDGET_BYTES floor."""
    if override > 0:
        return override
    try:
        import jax

        stats = jax.devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit > 0:
            return limit
    except Exception:
        pass
    return DEFAULT_DEVICE_BUDGET_BYTES


def split_fleet_budget(
    total_bytes: int, replicas: int, *, replica_index: int = 0
) -> int:
    """One replica's slice of a shared per-device HBM budget for the
    FLEET backlog drain. Multi-process replicas own exclusive device
    slices and pass the full budget through (replicas=1); co-hosted
    replicas (sim, tests) drain CONCURRENTLY against the same device,
    so each must plan its chunks against an even split — the remainder
    goes to the low indices, and every replica gets at least one byte
    so ``plan_chunk`` fails typed (BudgetExceeded), not on a zero."""
    replicas = max(int(replicas), 1)
    total = max(int(total_bytes), replicas)
    share, rem = divmod(total, replicas)
    return share + (1 if int(replica_index) % replicas < rem else 0)


def plan_chunk(
    shape: DrainShape,
    budget_bytes: int,
    min_chunk: int = 0,
) -> tuple[ShapeEstimate, int]:
    """Largest group-aligned chunk <= ``shape.chunk_pods`` whose
    per-device estimate fits ``budget_bytes``. Returns (estimate,
    splits) where ``splits`` counts the halvings taken — the
    budget-driven auto-split the drain metrics report. Raises the typed
    ``BudgetExceeded`` when even the minimum chunk (one group, floor
    LANE/8) does not fit: nothing has touched the device, so the caller
    can refuse cleanly instead of OOMing mid-drain."""
    import dataclasses

    group = max(shape.group, 1)
    floor = max(min_chunk, min(group, shape.chunk_pods), 1)
    chunk = shape.chunk_pods
    splits = 0
    while True:
        est = estimate(dataclasses.replace(shape, chunk_pods=chunk))
        assert_index_headroom(
            est.pod_pad, est.node_pad, d_pad=shape.d_pad, group=group
        )
        if est.per_device_bytes <= budget_bytes:
            return est, splits
        if chunk <= floor:
            raise BudgetExceeded(est, budget_bytes)
        half = chunk // 2
        if half >= group:
            half = (half // group) * group  # keep the grouped bucket
        chunk = max(half, floor)
        splits += 1


def assert_index_headroom(
    pod_pad: int,
    node_pad: int,
    d_pad: int = DOM_PAD,
    group: int = 64,
    max_rounds_shift: int = 32,
    rc_pad: int = 0,
) -> None:
    """Typed overflow audit for the flattened-index arithmetic the
    compiled solve programs form at this shape (the 512k x 102k scale
    check). Each clause names the kernel-side product it guards:

    - grouped quota positions (`rank * d_present + d_rank`,
      solver/exact.py wf_accept): accepted ranks are < group and the
      scatter clamps to it, so the int32 container needs
      (group + 1) * d_pad + d_pad < 2^31;
    - unique per-node random keys (`randint(2^20) * n + iota`,
      exact.py winner_accept): int64 needs 2^20 * node_pad < 2^63;
    - auction admission sort keys (`target * 2^32 + inv_prio`,
      single_shot.py): int64 needs node_pad * 2^32 < 2^63;
    - class-rank keys (`rc_of * P + pod_idx`, single_shot.py): int64
      needs pod_pad^2 < 2^62 (rc count is bounded by pod count);
    - int32 per-pod/segment counters (cumsum ranks, pod counts):
      pod_pad and node_pad and d_pad each < 2^31;
    - with ``rc_pad`` > 0 (the relaxation mega-planner, solver/
      relax.py): the flat quota-prefix cell index (`rc * N`, int64)
      needs rc_pad * node_pad < 2^63, and the class-priority rank key
      (`rc * 2^32 + inv_prio`, int64) must stay strictly below the
      2^62 invalid-pod sentinel — the relaxation's own flattened-index
      lanes, audited at dispatch like the auction's.
    """
    i32 = 1 << 31
    i63 = 1 << 63
    if pod_pad >= i32 or node_pad >= i32 or d_pad >= i32:
        raise IndexWidthError(
            f"axis exceeds int32 index range: pods={pod_pad} "
            f"nodes={node_pad} domains={d_pad}"
        )
    if (group + 1) * d_pad + d_pad >= i32:
        raise IndexWidthError(
            f"grouped quota position (group={group} x d_pad={d_pad}) "
            "would overflow its int32 container"
        )
    if (1 << 20) * node_pad + node_pad >= i63:
        raise IndexWidthError(
            f"per-node random key (2^20 x nodes={node_pad}) would "
            "overflow int64"
        )
    if node_pad * (1 << max_rounds_shift) + (1 << 32) >= i63:
        raise IndexWidthError(
            f"admission sort key (nodes={node_pad} << 32) would "
            "overflow int64"
        )
    if pod_pad * pod_pad >= (1 << 62):
        raise IndexWidthError(
            f"class-rank key (P^2, P={pod_pad}) would overflow int64"
        )
    if rc_pad > 0:
        if rc_pad * node_pad >= i63:
            raise IndexWidthError(
                f"relax flat quota-prefix cell (rc={rc_pad} x "
                f"nodes={node_pad}) would overflow int64"
            )
        if rc_pad * (1 << 32) + (1 << 32) >= (1 << 62):
            raise IndexWidthError(
                f"relax class-priority rank key (rc={rc_pad} << 32) "
                "would cross the invalid-pod sentinel (2^62)"
            )
