"""Device-mesh sharding for the pods × nodes solve (SURVEY §6.7).

The reference's only parallelism is a 16-goroutine parallel-for across
nodes inside one pod's cycle (framework/parallelize/parallelism.go) plus
node sampling and active/passive replication. The TPU framework's
parallelism is the hardware kind: the NODE axis is this problem's
"sequence/context" dimension, sharded over a `jax.sharding.Mesh` so per-
step reductions (argmax, cumsum, segment sums) become XLA collectives over
ICI — the scaling-book recipe: pick a mesh, annotate shardings, let GSPMD
insert the collectives.

Conventions (used by SingleShotSolver.solve(mesh=...), the exact scan's
multichip dryrun, and tests/test_sharding.py):
- node-resident arrays carry the node axis LAST -> P(None, "nodes") for
  2-D tables, P("nodes") for 1-D columns;
- per-pod / per-class / per-instance arrays replicate (they are small and
  every shard needs them for its local mask/score block);
- results are device-count invariant BIT-EXACTLY: integer score
  arithmetic and stable reductions make sharded == unsharded, which the
  tests assert on the 8-device virtual CPU mesh.
"""

from __future__ import annotations

import numpy as np

NODE_AXIS = "nodes"


def node_mesh(n_devices: int | None = None):
    """A 1-D mesh over the node axis (the v5e-8 shape: 8 chips, ICI ring).

    Uses the first ``n_devices`` visible devices (default: all)."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), axis_names=(NODE_AXIS,))


def node_sharding(mesh, ndim: int):
    """NamedSharding for a node-resident array: node axis last."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if ndim == 1:
        return NamedSharding(mesh, P(NODE_AXIS))
    return NamedSharding(mesh, P(*([None] * (ndim - 1) + [NODE_AXIS])))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def shard_node_tree(mesh, tree, replicate_names: frozenset[str] = frozenset()):
    """Map a pytree of arrays to shardings: arrays shard over their
    trailing node axis unless their dict key is in ``replicate_names``
    (per-class / per-instance tables without a node axis)."""
    import jax.tree_util as jtu

    repl = replicated(mesh)

    def one(path, a):
        key = path[-1].key if path and hasattr(path[-1], "key") else None
        if key in replicate_names:
            return repl
        return node_sharding(mesh, np.ndim(a))

    return jtu.tree_map_with_path(one, tree)


def device_put_tree(tree, shardings):
    """jax.device_put each leaf with its sharding."""
    import jax
    import jax.tree_util as jtu

    return jtu.tree_map(jax.device_put, tree, shardings)
