"""Lease-based leader election (client-go tools/leaderelection analog):
acquire, renew, challenge, expiry takeover, and optimistic-concurrency
races over the state service's Lease store."""

import threading

from kubernetes_tpu.state.cluster import ApiError, ClusterState
from kubernetes_tpu.utils.clock import FakeClock
from kubernetes_tpu.utils.leaderelection import Lease, LeaderElector


def mk(cs, ident, clock):
    return LeaderElector(
        cs,
        identity=ident,
        lease_duration=15.0,
        renew_deadline=10.0,
        retry_period=2.0,
        clock=clock,
    )


def test_acquire_renew_and_challenge():
    cs = ClusterState()
    clock = FakeClock()
    a = mk(cs, "a", clock)
    b = mk(cs, "b", clock)
    assert a.try_acquire_or_renew() and a.is_leader
    lease = cs.get_lease("kube-system", "kubernetes-tpu-scheduler")
    assert lease.holder_identity == "a"
    # a fresh lease blocks the challenger
    assert not b.try_acquire_or_renew() and not b.is_leader
    # the holder renews: renewTime advances
    clock.advance(5.0)
    t0 = lease.renew_time
    assert a.try_acquire_or_renew()
    assert cs.get_lease("kube-system", "kubernetes-tpu-scheduler").renew_time > t0
    # still blocked (renewal reset the expiry window)
    clock.advance(12.0)
    assert not b.try_acquire_or_renew()


def test_takeover_after_expiry():
    cs = ClusterState()
    clock = FakeClock()
    a = mk(cs, "a", clock)
    b = mk(cs, "b", clock)
    assert a.try_acquire_or_renew()
    # a crashes (stops renewing); past leaseDuration the challenger wins
    clock.advance(15.1)
    assert b.try_acquire_or_renew() and b.is_leader
    lease = cs.get_lease("kube-system", "kubernetes-tpu-scheduler")
    assert lease.holder_identity == "b"
    assert lease.acquire_time == clock.now()
    # the old leader's next renew attempt loses
    assert not a.try_acquire_or_renew() and not a.is_leader


def test_update_race_loses_cleanly():
    """A stale-rv update (someone else re-acquired between the read and
    the write) must report not-leader, never raise."""
    cs = ClusterState()
    clock = FakeClock()
    a = mk(cs, "a", clock)
    assert a.try_acquire_or_renew()
    # sneak a competing acquisition in with a bumped rv
    lease = cs.get_lease("kube-system", "kubernetes-tpu-scheduler")
    clock.advance(16.0)
    lease.holder_identity = "c"
    lease.renew_time = clock.now()
    cs.update_lease(lease)
    assert not a.try_acquire_or_renew()
    assert cs.get_lease("kube-system", "kubernetes-tpu-scheduler").holder_identity == "c"


def test_creation_race():
    """Two electors racing the initial create: exactly one wins."""
    cs = ClusterState()
    clock = FakeClock()
    a = mk(cs, "a", clock)
    # simulate the race by pre-creating the lease between a's NotFound
    # read and its create: create directly, then call a
    cs.create_lease(
        Lease(
            name="kubernetes-tpu-scheduler",
            holder_identity="z",
            lease_duration_seconds=15.0,
            renew_time=clock.now(),
        )
    )
    assert not a.try_acquire_or_renew()


def test_run_loop_active_passive_handover():
    """Elector A leads; when its renewals stop, elector B's run() loop
    takes over and fires on_started_leading."""
    cs = ClusterState()
    clock = FakeClock()
    a = mk(cs, "a", clock)
    assert a.try_acquire_or_renew()

    b = mk(cs, "b", clock)
    b.retry_period = 0.01  # fast wall-clock loop; expiry is FakeClock time
    started = threading.Event()
    stop = threading.Event()
    t = threading.Thread(
        target=b.run, args=(stop,), kwargs=dict(on_started_leading=started.set)
    )
    t.start()
    assert not started.wait(timeout=0.3)  # a's lease is fresh
    clock.advance(20.0)  # a expires
    assert started.wait(timeout=10)
    assert b.is_leader
    stop.set()
    t.join(timeout=10)
    assert cs.get_lease("kube-system", "kubernetes-tpu-scheduler").holder_identity == "b"


def test_losing_challenger_cannot_corrupt_store():
    """get_lease returns snapshots: a challenger that mutates its read
    and loses the rv CAS must leave the store showing the real winner
    (review-caught split-brain window)."""
    cs = ClusterState()
    clock = FakeClock()
    a = mk(cs, "a", clock)
    assert a.try_acquire_or_renew()
    clock.advance(16.0)  # expired: both challengers see it takeable
    b = mk(cs, "b", clock)
    c = mk(cs, "c", clock)
    # b reads+wins first; c's stale-rv update must fail AND the store
    # must still show b
    stale = cs.get_lease("kube-system", "kubernetes-tpu-scheduler")
    assert b.try_acquire_or_renew() and b.is_leader
    stale.holder_identity = "c"
    stale.renew_time = clock.now()
    try:
        cs.update_lease(stale, expect_rv=stale.resource_version)
    except ApiError:
        pass
    lease = cs.get_lease("kube-system", "kubernetes-tpu-scheduler")
    assert lease.holder_identity == "b"
    # b keeps renewing successfully (no split brain)
    clock.advance(5.0)
    assert b.try_acquire_or_renew()
    assert not c.try_acquire_or_renew()


def test_run_loop_reports_loss_after_renew_deadline():
    """The one remaining protocol branch: a holder whose renewals keep
    failing (lease stolen with a fresh renew_time) fires
    on_stopped_leading once the injected clock passes renew_deadline."""
    cs = ClusterState()
    clock = FakeClock()
    a = mk(cs, "a", clock)
    a.retry_period = 0.01  # fast wall loop; deadline measured on FakeClock
    lost = threading.Event()
    stop = threading.Event()
    t = threading.Thread(
        target=a.run, args=(stop,), kwargs=dict(on_stopped_leading=lost.set)
    )
    t.start()
    # wait for leadership
    for _ in range(500):
        if a.is_leader:
            break
        threading.Event().wait(0.01)
    assert a.is_leader
    # steal the lease with a perpetually-fresh foreign holder
    def keep_fresh():
        while not lost.is_set() and not stop.is_set():
            le = cs.get_lease("kube-system", "kubernetes-tpu-scheduler")
            le.holder_identity = "z"
            le.renew_time = clock.now()
            cs.update_lease(le)
            clock.advance(3.0)  # march time toward a's renew_deadline
            threading.Event().wait(0.01)
    th = threading.Thread(target=keep_fresh)
    th.start()
    assert lost.wait(timeout=30), "loss path never fired"
    assert not a.is_leader
    stop.set()
    t.join(timeout=10)
    th.join(timeout=10)


def test_timing_invariants_validated_at_construction():
    """leaderelection.go#LeaderElectionConfig validation (r4 advisor
    finding): the protocol is only sound with
    leaseDuration > renewDeadline > retryPeriod > 0 — each inversion
    must be rejected before the elector ever touches the store."""
    import pytest

    cs = ClusterState()
    clock = FakeClock()

    def mk_cfg(lease, renew, retry):
        return LeaderElector(
            cs,
            identity="x",
            lease_duration=lease,
            renew_deadline=renew,
            retry_period=retry,
            clock=clock,
        )

    # valid defaults construct fine
    assert mk_cfg(15.0, 10.0, 2.0) is not None
    with pytest.raises(ValueError, match="lease_duration must exceed"):
        mk_cfg(10.0, 10.0, 2.0)  # lease == renew deadline
    with pytest.raises(ValueError, match="lease_duration must exceed"):
        mk_cfg(5.0, 10.0, 2.0)  # lease < renew deadline
    with pytest.raises(ValueError, match="renew_deadline must exceed"):
        mk_cfg(15.0, 2.0, 2.0)  # renew deadline == retry period
    with pytest.raises(ValueError, match="retry_period must be positive"):
        mk_cfg(15.0, 10.0, 0.0)
