"""docs/METRICS.md drift gate: the committed auto-generated metrics
reference must match the registry (`python -m kubernetes_tpu.metrics
--doc` regenerates it), and every registered series must appear."""

from pathlib import Path

from kubernetes_tpu.metrics.__main__ import doc_path, render_doc


class TestMetricsDoc:
    def test_committed_doc_matches_registry(self):
        path = doc_path()
        assert path.exists(), (
            "docs/METRICS.md is missing — generate it with "
            "`python -m kubernetes_tpu.metrics --doc`"
        )
        assert path.read_text() == render_doc(), (
            "docs/METRICS.md is stale: a series was added/changed "
            "without regenerating — run "
            "`python -m kubernetes_tpu.metrics --doc`"
        )

    def test_every_registered_series_is_documented(self):
        from prometheus_client import Counter, Gauge, Histogram

        from kubernetes_tpu import metrics as m

        doc = render_doc()
        for attr in dir(m):
            obj = getattr(m, attr)
            if isinstance(obj, (Counter, Gauge, Histogram)):
                name = obj._name
                if isinstance(obj, Counter):
                    name += "_total"
                assert f"`{name}`" in doc, f"{name} missing from doc"

    def test_doc_rows_carry_labels(self):
        doc = render_doc()
        # a known labeled series renders its label names
        row = next(
            ln for ln in doc.splitlines()
            if "`scheduler_slo_error_budget_burn`" in ln
        )
        assert "window" in row

    def test_check_mode_detects_drift(self, tmp_path, monkeypatch):
        import kubernetes_tpu.metrics.__main__ as mm

        stale = tmp_path / "METRICS.md"
        stale.write_text("# stale\n")
        monkeypatch.setattr(mm, "doc_path", lambda: stale)
        assert mm.main(["--check"]) == 1
        stale.write_text(render_doc())
        assert mm.main(["--check"]) == 0
