"""Stateless batched filter/score evaluation — the device path behind the
served extender boundary (SURVEY §8.2).

The extender protocol (pkg/scheduler/extender.go#HTTPExtender) is advisory:
/filter and /prioritize report feasibility and scores for ONE pod against a
node list, and the CALLING kube-scheduler does the assume/bind. So unlike
the exact solver's lax.scan (which carries node state across pods), the
served evaluation is a pure function of the current snapshot: a vmap of the
same fused filter+score pipeline (`solver.exact._mask_and_score`) over a pod
batch, yielding `[P, N]` scores with -1 on infeasible lanes. Concurrent
webhook requests micro-batch into one such call (server/batching.py), which
is how per-request latency stays flat while the device does P×N work.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..api.objects import Node, Pod
from ..tensorize.interpod import build_interpod_tensors, trivial_interpod_tensors
from ..tensorize.plugins import (
    build_port_tensors,
    build_static_tensors,
    trivial_port_tensors,
)
from ..tensorize.schema import build_node_batch, build_pod_batch
from ..tensorize.spread import build_spread_tensors, trivial_spread_tensors
from .exact import ExactSolverConfig, _mask_and_score

_PIPE_STATICS = (
    "scoring_strategy",
    "w_cpu",
    "w_mem",
    "rtc_shape",
    "disabled",
    "w_fit",
    "w_balanced",
    "w_taint",
    "w_nodeaff",
    "w_image",
    "w_spread",
    "w_interpod",
    "use_spread",
    "use_interpod",
    "d_pad",
    "ipa_d_pad",
    "fdtype",
    "spread_soft",
    "ipa_ident",
    "ipa_score",
    "use_extra_score",
)


@partial(jax.jit, static_argnames=_PIPE_STATICS)
def _eval_jit(tables, st, xs, **kw):
    return jax.vmap(lambda x: _mask_and_score(tables, st, x, **kw))(xs)


class BatchEvaluator:
    """Object-level entry: pods × nodes → score matrix on device.

    Reuses the solver's tensorizers so the served scores are bit-identical
    to what the exact solver would compute for each pod against the same
    snapshot (the first scan step sees exactly this state).
    """

    def __init__(self, config: ExactSolverConfig | None = None):
        self.config = config or ExactSolverConfig()
        if not jax.config.jax_enable_x64:
            jax.config.update("jax_enable_x64", True)
        from ..utils.compile_cache import enable_persistent_cache

        enable_persistent_cache()

    def evaluate(
        self,
        pods: list[Pod],
        nodes: list[Node],
        pods_by_node: dict[str, list[Pod]],
        services: list | None = None,
        pvs: list | None = None,
        pvcs: list | None = None,
    ) -> np.ndarray:
        """Returns scores [len(pods), len(nodes)] int32; -1 = infeasible.

        Node index space is the order of ``nodes``; ``pods_by_node`` carries
        already-placed pods (the extender's watch-fed NodeInfo view).
        """
        cfg = self.config
        batch = build_node_batch(nodes, pods_by_node)
        pbatch = build_pod_batch(pods, batch.vocab)
        slot_nodes: list[Node | None] = list(nodes) + [None] * (
            batch.padded - len(nodes)
        )
        placed_by_slot = {
            i: list(pods_by_node[n.name])
            for i, n in enumerate(nodes)
            if pods_by_node.get(n.name)
        }

        services = services or []
        need_spread = any(p.topology_spread_constraints for p in pods)
        class_key_extra = None
        if services and cfg.spread_defaulting == "System":
            from ..ops.oracle.spread import default_selector, default_selector_key

            need_spread = need_spread or any(
                not p.topology_spread_constraints
                and default_selector(p, services) is not None
                for p in pods
            )

            def class_key_extra(p):
                if p.topology_spread_constraints:
                    return None
                return default_selector_key(p, services)

        def has_pod_affinity(p: Pod) -> bool:
            return p.affinity is not None and (
                p.affinity.pod_affinity is not None
                or p.affinity.pod_anti_affinity is not None
            )

        need_interpod = any(has_pod_affinity(p) for p in pods) or any(
            has_pod_affinity(q)
            for placed in pods_by_node.values()
            for q in placed
        )
        need_ports = any(p.host_ports() for p in pods)

        volume_ctx = None
        if any(p.pvc_names for p in pods):
            from ..ops.oracle.volumes import VolumeContext

            volume_ctx = VolumeContext.build(
                pvs or [], pvcs or [], dict(pods_by_node)
            )

        static = build_static_tensors(
            pods, pbatch, slot_nodes, batch.padded, volume_ctx,
            disabled=frozenset(cfg.disabled_filters),
            added_affinity=cfg.added_affinity,
            class_key_extra=class_key_extra,
        )
        if need_ports:
            ports = build_port_tensors(
                pods, pbatch, slot_nodes, placed_by_slot, batch.padded
            )
        else:
            ports = trivial_port_tensors(pbatch, batch.padded)
        if need_spread:
            spread = build_spread_tensors(
                pods, static.reps, pbatch, slot_nodes, placed_by_slot,
                batch.padded, static.c_pad,
                services=services, defaulting=cfg.spread_defaulting,
            )
        else:
            spread = trivial_spread_tensors(pbatch, batch.padded, static.c_pad)
        if need_interpod:
            interpod = build_interpod_tensors(
                pods, static.reps, pbatch, slot_nodes, placed_by_slot,
                batch.padded, static.c_pad,
                hard_pod_affinity_weight=cfg.hard_pod_affinity_weight,
            )
        else:
            interpod = trivial_interpod_tensors(
                pbatch, batch.padded, static.c_pad
            )
        return self.evaluate_tensors(
            batch, pbatch, static, ports, spread, interpod
        )[:, : len(nodes)]

    def evaluate_tensors(
        self, batch, pbatch, static, ports, spread, interpod
    ) -> np.ndarray:
        """Low-level entry: prepared tensors -> scores
        [num_pods, padded_nodes] int32 (-1 = infeasible). Shared by the
        object path above and the bulk gRPC path's columnar batches."""
        cfg = self.config
        use_spread = not spread.empty
        use_interpod = not interpod.empty

        tables = {
            "alloc": jnp.asarray(batch.allocatable),
            "max_pods": jnp.asarray(batch.max_pods),
            "node_valid": jnp.asarray(batch.valid),
            "static_mask": jnp.asarray(static.mask),
            "taint_cnt": jnp.asarray(static.taint_cnt),
            "nodeaff_pref": jnp.asarray(static.nodeaff_pref),
            "image_score": jnp.asarray(static.image_score),
            **(
                {"extra_score": jnp.asarray(static.extra_score)}
                if static.extra_score is not None
                else {}
            ),
            "spr": {
                "dom": jnp.asarray(spread.dom),
                "elig": jnp.asarray(spread.elig),
                "max_skew": jnp.asarray(spread.max_skew),
                "min_domains": jnp.asarray(spread.min_domains),
                "self_match": jnp.asarray(spread.self_match),
                "is_hostname": jnp.asarray(spread.is_hostname),
                "hard": jnp.asarray(spread.hard),
                "soft": jnp.asarray(spread.soft),
            },
            "ipa": {
                "in_dom": jnp.asarray(interpod.in_dom),
                "in_pref_w": jnp.asarray(interpod.in_pref_w),
                "cls_req_aff": jnp.asarray(interpod.cls_req_aff),
                "cls_req_anti": jnp.asarray(interpod.cls_req_anti),
                "cls_pref": jnp.asarray(interpod.cls_pref),
                "ex_dom": jnp.asarray(interpod.ex_dom),
                "ex_anti": jnp.asarray(interpod.ex_anti),
            },
        }
        st = {
            "used": jnp.asarray(batch.used),
            "nonzero_used": jnp.asarray(batch.nonzero_used),
            "pod_count": jnp.asarray(batch.pod_count),
            "port_used": jnp.asarray(ports.used),
            "spr_cnt": jnp.asarray(spread.cnt0),
            "ipa_in": jnp.asarray(interpod.in_cnt0),
            "ipa_ex": jnp.asarray(interpod.ex_cnt0),
        }
        pod_valid = pbatch.valid & pbatch.feasible_static
        xs = {
            "req": jnp.asarray(pbatch.req),
            "req_mask": jnp.asarray(pbatch.req_mask),
            "nonzero_req": jnp.asarray(pbatch.nonzero_req),
            "class_of": jnp.asarray(static.class_of),
            "pod_conflict": jnp.asarray(ports.pod_conflict),
        }
        if use_interpod:
            xs["ipa_m_anti"] = jnp.asarray(interpod.m_anti)
            xs["ipa_m_w"] = jnp.asarray(interpod.m_w)
            xs["ipa_self_aff"] = jnp.asarray(interpod.self_aff)

        fdtype = (
            jnp.float64 if cfg.balanced_fdtype == "float64" else jnp.float32
        )
        scores = _eval_jit(
            tables,
            st,
            xs,
            scoring_strategy=cfg.scoring_strategy,
            w_cpu=cfg.cpu_weight,
            w_mem=cfg.mem_weight,
            rtc_shape=tuple(tuple(p) for p in cfg.rtc_shape),
            disabled=tuple(sorted(cfg.disabled_filters)),
            w_fit=cfg.fit_weight,
            w_balanced=cfg.balanced_weight,
            w_taint=cfg.taint_weight,
            w_nodeaff=cfg.node_affinity_weight,
            w_image=cfg.image_weight,
            w_spread=cfg.spread_weight,
            w_interpod=cfg.interpod_weight,
            use_spread=use_spread,
            use_interpod=use_interpod,
            d_pad=spread.d_pad,
            ipa_d_pad=interpod.d_pad,
            fdtype=fdtype,
            spread_soft=spread.has_soft,
            ipa_ident=interpod.ident,
            ipa_score=interpod.has_score,
            use_extra_score=static.extra_score is not None,
        )
        scores = np.asarray(scores)[: pbatch.num_pods]
        # statically infeasible pods (unknown resource) never fit anywhere
        return np.where(
            pod_valid[: pbatch.num_pods, None], scores, np.int32(-1)
        )
