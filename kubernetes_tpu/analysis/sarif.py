"""SARIF 2.1.0 serialization for CI artifact upload.

One run, one tool (``ktpu-analysis``), one result per finding.
Suppressed findings are carried as SARIF ``suppressions`` entries
(kind ``inSource``) instead of being dropped, so the artifact is a
complete audit trail — the same contract as ``--json``. Output is
deterministic: results arrive pre-sorted from the runner and the
rules index is sorted by id.
"""

from __future__ import annotations

import json

# rule id -> short description, for the driver rules table; unknown
# ids (KTPU000/KTPU001 synthetics) get a generic entry
_RULE_HELP = {
    "TPU001": "host<->device sync in traced/hot scope",
    "TPU002": "traced-value branch in python control flow",
    "TPU003": "weak dtype discipline in solver tensors",
    "TPU004": "cross-module host-sync escape",
    "LOCK001": "guarded attribute touched outside its lock",
    "LOCK002": "lock-order cycle / self-deadlock",
    "FENCE001": "replicated state touched without role/epoch fence",
    "RETRY001": "retry-discipline violation",
    "MET001": "unregistered metric series name",
    "MET002": "metrics registry <-> docs drift",
    "KTPU000": "suppression without a reason",
    "KTPU001": "unparsable source file",
}


def to_sarif(findings) -> dict:
    rule_ids = sorted({f.rule for f in findings} | set(_RULE_HELP))
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "level": "warning" if f.suppressed else "error",
            "message": {
                "text": f.message + (f" (hint: {f.hint})" if f.hint else "")
            },
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {"startLine": max(f.line, 1)},
                    }
                }
            ],
        }
        if f.suppressed:
            result["suppressions"] = [
                {
                    "kind": "inSource",
                    "justification": f.suppress_reason,
                }
            ]
        results.append(result)
    return {
        "version": "2.1.0",
        "$schema": (
            "https://docs.oasis-open.org/sarif/sarif/v2.1.0/errata01/os/"
            "schemas/sarif-schema-2.1.0.json"
        ),
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "ktpu-analysis",
                        "informationUri": (
                            "kubernetes_tpu/analysis/README.md"
                        ),
                        "rules": [
                            {
                                "id": rid,
                                "shortDescription": {
                                    "text": _RULE_HELP.get(
                                        rid, "kubernetes_tpu analyzer rule"
                                    )
                                },
                            }
                            for rid in rule_ids
                        ],
                    }
                },
                "results": results,
            }
        ],
    }


def render_sarif(findings) -> str:
    return json.dumps(to_sarif(findings), indent=2, sort_keys=True)
