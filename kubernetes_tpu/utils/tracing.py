"""Profiling traces — SURVEY §6.1's TPU equivalent of the reference's
utiltrace step-traces + pprof: `jax.profiler` TensorBoard traces around
device solves, plus the per-stage wall-time histograms the metrics module
already exports under the reference's names.

Enable with `--trace-dir DIR` on `serve`/`perf` (or programmatically via
``enable(dir)``): each schedule_batch runs inside a
``jax.profiler.StepTraceAnnotation`` and the whole session's device
activity lands in DIR as a TensorBoard trace
(`tensorboard --logdir DIR` → Profile tab). Tracing is off by default —
the profiler's overhead belongs in a debugging session, not the hot path.
"""

from __future__ import annotations

import contextlib

_trace_dir: str | None = None
_started = False


def enable(trace_dir: str) -> None:
    global _trace_dir
    _trace_dir = trace_dir


def enabled() -> bool:
    return _trace_dir is not None


@contextlib.contextmanager
def step(name: str, step_num: int = 0):
    """Annotate one scheduling batch; starts the session trace lazily on
    first use so importing this module never touches the profiler."""
    global _started
    if _trace_dir is None:
        yield
        return
    import jax

    if not _started:
        jax.profiler.start_trace(_trace_dir)
        _started = True
    with jax.profiler.StepTraceAnnotation(name, step_num=step_num):
        yield


def stop() -> None:
    """Flush the session trace (atexit-safe: no-op when never started)."""
    global _started
    if _started:
        import jax

        jax.profiler.stop_trace()
        _started = False
