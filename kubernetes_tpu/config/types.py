"""KubeSchedulerConfiguration — typed config mirroring the reference's
component-config field names so reference YAML mostly parses unchanged
(pkg/scheduler/apis/config/types.go + v1/defaults.go + validation/,
SURVEY.md §6.6), plus the TPU solver section.

Covered surface:
- top level: parallelism, percentageOfNodesToScore, podInitialBackoffSeconds,
  podMaxBackoffSeconds, profiles[], extenders[]
- per profile: schedulerName, plugins{score.enabled[{name,weight}],
  filter/score disabled[...]} (the subset that changes solver behavior),
  pluginConfig[{name,args}] for NodeResourcesFitArgs.scoringStrategy
  (LeastAllocated | MostAllocated | RequestedToCapacityRatio),
  InterPodAffinityArgs.hardPodAffinityWeight,
  PodTopologySpreadArgs.defaultingType, NodeAffinityArgs.addedAffinity
- extenders[]: urlPrefix, filterVerb/prioritizeVerb/preemptVerb/bindVerb,
  weight, nodeCacheCapable, ignorable, managedResources
- tpuSolver (ours): batchSize, tieBreak, seed, balancedFdtype, singleShot
  {maxRounds, priceStep, topT, repairRounds}, enablePreemption, groupSize,
  meshDevices (node-axis solve mesh: 0 = all visible devices)
- rebalance (ours): enabled, intervalSeconds, maxMovesPerCycle,
  minPackingUtilization, minGainPoints, nominate — the continuous
  defragmentation loop (kubernetes_tpu/rebalance)
- fleet (ours): replica, replicas, hubAddress (a bulk gRPC server whose
  HubOp method serves the shared occupancy hub), meshSlice ("rank/count"
  — this replica's EXCLUSIVE contiguous slice of the visible device
  set), maxRowAgeSeconds — the active-active scale-out tier
  (kubernetes_tpu/fleet)
- gang (ours): enabled, minMemberTimeoutSeconds, quarantineAfter,
  throughputWeight, classThroughput / classThroughputPath — all-or-
  nothing pod-group scheduling plus the heterogeneity-aware
  effective-throughput objective (kubernetes_tpu/gang)

Unknown plugin names and unsupported pluginConfig args are collected into
`warnings` rather than rejected — the validation posture of a scheduler that
must accept configs written for the full reference plugin set.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping

import yaml

DEFAULT_SCHEDULER_NAME = "default-scheduler"

# default score weights: apis/config/v1/default_plugins.go
DEFAULT_WEIGHTS = {
    "NodeResourcesFit": 1,
    "NodeResourcesBalancedAllocation": 1,
    "TaintToleration": 3,
    "NodeAffinity": 2,
    "PodTopologySpread": 2,
    "InterPodAffinity": 2,
    "ImageLocality": 1,
}

KNOWN_PLUGINS = set(DEFAULT_WEIGHTS) | {
    "NodeName",
    "NodePorts",
    "NodeUnschedulable",
    "SchedulingGates",
    "PrioritySort",
    "DefaultPreemption",
    "DefaultBinder",
    "VolumeBinding",
    "VolumeRestrictions",
    "VolumeZone",
    "NodeVolumeLimits",
}


@dataclass
class ScoringStrategy:
    type: str = "LeastAllocated"  # | MostAllocated | RequestedToCapacityRatio
    resources: list[dict] = field(
        default_factory=lambda: [
            {"name": "cpu", "weight": 1},
            {"name": "memory", "weight": 1},
        ]
    )
    # RequestedToCapacityRatio shape points [{utilization, score}]
    shape: list[dict] = field(default_factory=list)


@dataclass
class Profile:
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    score_weights: dict[str, int] = field(
        default_factory=lambda: dict(DEFAULT_WEIGHTS)
    )
    disabled_filters: set[str] = field(default_factory=set)
    scoring_strategy: ScoringStrategy = field(default_factory=ScoringStrategy)
    hard_pod_affinity_weight: int = 1
    spread_defaulting_type: str = "System"  # System | List
    added_affinity: dict | None = None  # NodeAffinityArgs.addedAffinity


@dataclass
class Extender:
    url_prefix: str = ""
    filter_verb: str = ""
    prioritize_verb: str = ""
    preempt_verb: str = ""
    bind_verb: str = ""
    weight: int = 1
    node_cache_capable: bool = False
    ignorable: bool = False
    managed_resources: list[dict] = field(default_factory=list)


@dataclass
class SingleShotSection:
    max_rounds: int = 32
    price_step: int = 8
    top_t: int = 1024
    # full-width repair rounds closing the scarcity gap (0 = off)
    repair_rounds: int = 16


@dataclass
class RebalanceSection:
    """``rebalance:`` — the continuous defragmentation loop
    (kubernetes_tpu/rebalance). Ours, like tpuSolver: no reference
    analog (upstream delegates to the out-of-tree descheduler)."""

    enabled: bool = False
    interval_seconds: float = 60.0
    # max-churn budget: evictions per rebalance cycle
    max_moves_per_cycle: int = 512
    # dominant-resource packed-utilization threshold below which the
    # in-use nodes count as fragmented
    min_packing_utilization: float = 0.7
    # minimum strict packing-score gain (percent points) per move
    min_gain_points: int = 1
    # carry the auction target as a nominated-node hint on eviction
    nominate: bool = True


@dataclass
class FleetSection:
    """``fleet:`` — the active-active fleet tier (kubernetes_tpu/fleet).
    Ours, like tpuSolver: the reference's only HA is active/passive
    leader election."""

    # this replica's identity; empty = fleet mode off
    replica: str = ""
    # the configured universe (the replica itself is always included)
    replicas: list[str] = field(default_factory=list)
    # "host:port" of a bulk gRPC server serving the shared occupancy
    # hub over its HubOp method (fleet/runtime.RemoteOccupancyExchange);
    # comma-separate several for a replicated hub (primary + standbys —
    # the client fails over between them, hub HA); empty = an
    # in-process private hub (single-replica degenerate)
    hub_address: str = ""
    # "rank/count": this replica's EXCLUSIVE mesh slice — contiguous
    # first-N partition of the visible device set, so N replicas on one
    # host solve against disjoint devices. None = no slice.
    mesh_slice: "tuple[int, int] | None" = None
    # occupancy-staleness bound (FleetConfig.max_row_age_s)
    max_row_age_seconds: float = 30.0
    # write-behind flush batch for the remote hub adapter
    # (FleetConfig.flush_batch); 0 = the adapter default. Auto-tunable
    # (tuning knob "fleet_flush").
    flush_batch: int = 0


@dataclass
class GangSection:
    """``gang:`` — all-or-nothing pod-group scheduling and the
    heterogeneity-aware effective-throughput objective
    (kubernetes_tpu/gang). Ours, like tpuSolver: the reference's gang
    support lives out of tree (scheduler-plugins coscheduling)."""

    enabled: bool = False
    # how long an incomplete group may wait for its remaining members
    # before the whole gang is quarantined
    min_member_timeout_seconds: float = 30.0
    # consecutive failed all-or-nothing rounds before the gang is
    # quarantined instead of requeued
    quarantine_after: int = 3
    # score points per unit of relative throughput (0 = objective off)
    throughput_weight: int = 0
    # inline (workload class -> accelerator class -> relative
    # throughput) matrix; mutually exclusive with classThroughputPath
    class_throughput: dict = field(default_factory=dict)
    # path to a JSON file holding the same matrix
    class_throughput_path: str = ""


@dataclass
class TpuSolverSection:
    batch_size: int = 1024
    tie_break: str = "random"  # random | first
    seed: int = 0
    balanced_fdtype: str = "float32"
    enable_preemption: bool = True
    # grouped fast-path chunk size (ExactSolverConfig.group_size; 0 = off)
    group_size: int = 64
    # node-axis mesh device count (SchedulerConfig.mesh_devices):
    # 0 = all visible devices, 1 = force single-device, N > 1 = first N.
    # Results are bit-exactly device-count invariant.
    mesh_devices: int = 0
    # streaming dispatcher work-ring depth (SchedulerConfig.stream_depth)
    stream_depth: int = 4
    # RTT-hiding batch split (SchedulerConfig.pipeline_split): 0 =
    # adaptive (CounterWindow EWMA rule / the tuning controller), 1 =
    # never split, > 1 = fixed cap
    pipeline_split: int = 0
    # backlog drain chunk (SchedulerConfig.backlog_chunk_pods): 0 =
    # plan from the HBM budget model starting at batchSize
    backlog_chunk_pods: int = 0
    # Pallas-kernel tier (ExactSolverConfig.pallas): route the
    # InterPodAffinity domain aggregation through the MXU kernel.
    # Default off — see ops/pallas_kernels.py's measured decision.
    pallas: bool = False
    single_shot: SingleShotSection = field(default_factory=SingleShotSection)


# the tunable hot-path knobs (kubernetes_tpu/tuning runtime names);
# kept literal here so parsing a config never imports the tuning (and
# transitively metrics/prometheus) machinery
TUNABLE_KNOBS = (
    "backlog_chunk",
    "stream_depth",
    "pipeline_split",
    "fleet_flush",
)


@dataclass
class TuningSection:
    """``tuning:`` — closed-loop hot-path auto-tuning
    (kubernetes_tpu/tuning). Ours, like tpuSolver. ``knobs`` names what
    the runtime may govern; to pin one knob statically, set its
    tpuSolver/fleet value and drop it from the list (the tuned-profile
    emitter writes exactly such a pinned document back out). An
    explicit empty list pins EVERYTHING — the runtime is inert; an
    absent key means all knobs."""

    enabled: bool = False
    eval_batches: int = 6
    hysteresis: float = 0.05
    settle_after: int = 2
    max_probes: int = 16
    shift_threshold: float = 0.75
    knobs: list[str] = field(
        default_factory=lambda: list(TUNABLE_KNOBS)
    )


def validate_tuning_params(
    eval_batches: int,
    hysteresis: float,
    settle_after: int,
    max_probes: int,
    shift_threshold: float,
    knobs,
) -> None:
    """The ONE home of the tuning-parameter range checks: the YAML
    loader below and ``TuningConfig.validate`` (kubernetes_tpu/tuning/
    runtime.py) both call it, so a bound change cannot land in one and
    not the other. Pure — importable from config parsing without
    dragging the tuning/metrics machinery in."""
    if eval_batches < 1:
        raise ValueError(
            f"tuning.evalBatches must be >= 1 (got {eval_batches})"
        )
    if not 0.0 < hysteresis < 1.0:
        raise ValueError(
            f"tuning.hysteresis must be in (0, 1) (got {hysteresis})"
        )
    if settle_after < 1:
        raise ValueError(
            f"tuning.settleAfter must be >= 1 (got {settle_after})"
        )
    if max_probes < 1:
        raise ValueError(
            f"tuning.maxProbes must be >= 1 (got {max_probes})"
        )
    if shift_threshold <= 0:
        raise ValueError(
            f"tuning.shiftThreshold must be > 0 (got {shift_threshold})"
        )
    unknown = set(knobs) - set(TUNABLE_KNOBS)
    if unknown:
        # a typo'd knob name would silently leave the intended knob
        # static — the quiet-misconfiguration failure mode, rejected
        # hard like fleet.meshSlice
        raise ValueError(
            f"tuning.knobs: unknown {sorted(unknown)}; "
            f"known: {list(TUNABLE_KNOBS)}"
        )


@dataclass
class KubeSchedulerConfiguration:
    parallelism: int = 16  # accepted for parity; the TPU solve is dense
    percentage_of_nodes_to_score: int = 0  # 0 = all (we always score all)
    pod_initial_backoff_seconds: float = 1.0
    pod_max_backoff_seconds: float = 10.0
    profiles: list[Profile] = field(default_factory=lambda: [Profile()])
    extenders: list[Extender] = field(default_factory=list)
    tpu_solver: TpuSolverSection = field(default_factory=TpuSolverSection)
    rebalance: RebalanceSection = field(default_factory=RebalanceSection)
    fleet: FleetSection = field(default_factory=FleetSection)
    tuning: TuningSection = field(default_factory=TuningSection)
    gang: GangSection = field(default_factory=GangSection)
    warnings: list[str] = field(default_factory=list)

    def profile_for(self, scheduler_name: str) -> Profile | None:
        for p in self.profiles:
            if p.scheduler_name == scheduler_name:
                return p
        return None


def _parse_plugin_config(profile: Profile, items, warnings: list[str]) -> None:
    for pc in items or ():
        name = pc.get("name")
        args = pc.get("args") or {}
        if name == "NodeResourcesFit":
            strat = (args.get("scoringStrategy") or {})
            if strat:
                profile.scoring_strategy = ScoringStrategy(
                    type=strat.get("type") or "LeastAllocated",
                    resources=strat.get("resources")
                    or ScoringStrategy().resources,
                    shape=(
                        (strat.get("requestedToCapacityRatio") or {}).get(
                            "shape"
                        )
                        or []
                    ),
                )
        elif name == "InterPodAffinity":
            if "hardPodAffinityWeight" in args:
                profile.hard_pod_affinity_weight = int(
                    args["hardPodAffinityWeight"]
                )
        elif name == "PodTopologySpread":
            if "defaultingType" in args:
                profile.spread_defaulting_type = args["defaultingType"]
        elif name == "NodeAffinity":
            if "addedAffinity" in args:
                profile.added_affinity = args["addedAffinity"]
        elif name in ("DefaultPreemption", "VolumeBinding"):
            pass  # accepted, defaults apply
        else:
            warnings.append(f"pluginConfig for {name!r} not consumed")


def _parse_profile(d: Mapping, warnings: list[str]) -> Profile:
    profile = Profile(
        scheduler_name=d.get("schedulerName") or DEFAULT_SCHEDULER_NAME
    )
    plugins = d.get("plugins") or {}
    for point in ("score", "multiPoint"):
        sec = plugins.get(point) or {}
        for e in sec.get("enabled") or ():
            name = e.get("name")
            if name not in KNOWN_PLUGINS:
                warnings.append(f"unknown plugin {name!r} enabled")
                continue
            if "weight" in e and name in DEFAULT_WEIGHTS:
                profile.score_weights[name] = int(e["weight"])
        for e in sec.get("disabled") or ():
            name = e.get("name")
            if name == "*":
                profile.score_weights = {k: 0 for k in profile.score_weights}
            elif name in DEFAULT_WEIGHTS:
                profile.score_weights[name] = 0
    for e in (plugins.get("filter") or {}).get("disabled") or ():
        name = e.get("name")
        if name:
            profile.disabled_filters.add(name)
    _parse_plugin_config(profile, d.get("pluginConfig"), warnings)
    return profile


def _nn(value, default):
    """``value`` unless it is None — the null-tolerant default for
    keys where falsy values (0, False) are meaningful, so neither
    ``get(k, d)`` (misses explicit YAML nulls) nor ``get(k) or d``
    (swallows 0/False) is right."""
    return default if value is None else value


def load(data: Mapping | str) -> KubeSchedulerConfiguration:
    """Parse a KubeSchedulerConfiguration YAML document (string or mapping)."""
    if isinstance(data, str):
        data = yaml.safe_load(data) or {}
    cfg = KubeSchedulerConfiguration()
    warnings = cfg.warnings

    api_version = data.get("apiVersion", "")
    if api_version and not api_version.startswith("kubescheduler.config.k8s.io/"):
        warnings.append(f"unexpected apiVersion {api_version!r}")

    if "parallelism" in data:
        cfg.parallelism = int(data["parallelism"])
    if "percentageOfNodesToScore" in data:
        cfg.percentage_of_nodes_to_score = int(data["percentageOfNodesToScore"])
        if cfg.percentage_of_nodes_to_score not in (0, 100):
            warnings.append(
                "percentageOfNodesToScore: the TPU solve always scores all "
                "nodes (dense is free); sampling is parsed but not applied"
            )
    if "podInitialBackoffSeconds" in data:
        cfg.pod_initial_backoff_seconds = float(data["podInitialBackoffSeconds"])
    if "podMaxBackoffSeconds" in data:
        cfg.pod_max_backoff_seconds = float(data["podMaxBackoffSeconds"])

    if data.get("profiles"):
        cfg.profiles = [_parse_profile(p, warnings) for p in data["profiles"]]
    names = [p.scheduler_name for p in cfg.profiles]
    if len(names) != len(set(names)):
        raise ValueError(f"duplicate profile schedulerName in {names}")

    for e in data.get("extenders") or ():
        cfg.extenders.append(
            Extender(
                url_prefix=e.get("urlPrefix") or "",
                filter_verb=e.get("filterVerb") or "",
                prioritize_verb=e.get("prioritizeVerb") or "",
                preempt_verb=e.get("preemptVerb") or "",
                bind_verb=e.get("bindVerb") or "",
                weight=int(e.get("weight") or 1),
                node_cache_capable=bool(e.get("nodeCacheCapable")),
                ignorable=bool(e.get("ignorable")),
                managed_resources=list(e.get("managedResources") or ()),
            )
        )

    ts = data.get("tpuSolver") or {}
    ss = ts.get("singleShot") or {}
    cfg.tpu_solver = TpuSolverSection(
        batch_size=int(ts.get("batchSize") or 1024),
        tie_break=ts.get("tieBreak") or "random",
        seed=int(ts.get("seed") or 0),
        balanced_fdtype=ts.get("balancedFdtype") or "float32",
        enable_preemption=bool(ts.get("enablePreemption", True)),
        group_size=int(ts.get("groupSize", 64)),
        mesh_devices=int(ts.get("meshDevices", 0)),
        stream_depth=int(_nn(ts.get("streamDepth"), 4)),
        pipeline_split=int(_nn(ts.get("pipelineSplit"), 0)),
        backlog_chunk_pods=int(_nn(ts.get("backlogChunkPods"), 0)),
        pallas=bool(_nn(ts.get("pallas"), False)),
        single_shot=SingleShotSection(
            max_rounds=int(ss.get("maxRounds") or 32),
            price_step=int(ss.get("priceStep") or 8),
            top_t=int(ss.get("topT") or 1024),
            # .get-with-default + explicit None check: 0 is meaningful
            # (repair off), so the usual `or`-default shape is wrong,
            # and an explicit YAML null must still default, not
            # TypeError out of int()
            repair_rounds=int(_nn(ss.get("repairRounds"), 16)),
        ),
    )
    if cfg.tpu_solver.tie_break not in ("random", "first"):
        raise ValueError(f"tpuSolver.tieBreak: {cfg.tpu_solver.tie_break!r}")
    if cfg.tpu_solver.stream_depth < 1:
        raise ValueError(
            "tpuSolver.streamDepth must be >= 1 "
            f"(got {cfg.tpu_solver.stream_depth})"
        )
    if cfg.tpu_solver.pipeline_split < 0:
        # 0 is the adaptive mode; a negative would silently behave as
        # adaptive too — reject the ambiguity
        raise ValueError(
            "tpuSolver.pipelineSplit must be >= 0 "
            f"(got {cfg.tpu_solver.pipeline_split})"
        )
    if cfg.tpu_solver.backlog_chunk_pods < 0:
        raise ValueError(
            "tpuSolver.backlogChunkPods must be >= 0 "
            f"(got {cfg.tpu_solver.backlog_chunk_pods})"
        )
    if cfg.tpu_solver.single_shot.repair_rounds < 0:
        # a negative would silently disable the repair phase (the
        # solver gates on > 0) — reject like the rebalance knobs do
        raise ValueError(
            "tpuSolver.singleShot.repairRounds must be >= 0 "
            f"(got {cfg.tpu_solver.single_shot.repair_rounds})"
        )

    rb = data.get("rebalance") or {}
    cfg.rebalance = RebalanceSection(
        enabled=bool(_nn(rb.get("enabled"), False)),
        interval_seconds=float(_nn(rb.get("intervalSeconds"), 60.0)),
        max_moves_per_cycle=int(_nn(rb.get("maxMovesPerCycle"), 512)),
        min_packing_utilization=float(
            _nn(rb.get("minPackingUtilization"), 0.7)
        ),
        min_gain_points=int(_nn(rb.get("minGainPoints"), 1)),
        nominate=bool(_nn(rb.get("nominate"), True)),
    )
    if cfg.rebalance.max_moves_per_cycle < 0:
        raise ValueError(
            "rebalance.maxMovesPerCycle must be >= 0 "
            f"(got {cfg.rebalance.max_moves_per_cycle})"
        )
    if not 0.0 < cfg.rebalance.min_packing_utilization <= 1.0:
        raise ValueError(
            "rebalance.minPackingUtilization must be in (0, 1] "
            f"(got {cfg.rebalance.min_packing_utilization})"
        )
    if cfg.rebalance.interval_seconds <= 0:
        raise ValueError(
            "rebalance.intervalSeconds must be > 0 "
            f"(got {cfg.rebalance.interval_seconds})"
        )
    if cfg.rebalance.min_gain_points < 1:
        # > 0 is what guarantees each move strictly increases packing
        # potential, the termination argument that keeps repeated
        # cycles from thrashing (rebalance/runtime.py)
        raise ValueError(
            "rebalance.minGainPoints must be >= 1 "
            f"(got {cfg.rebalance.min_gain_points})"
        )

    fl = data.get("fleet") or {}
    cfg.fleet = FleetSection(
        replica=str(_nn(fl.get("replica"), "")),
        replicas=[str(r) for r in _nn(fl.get("replicas"), []) or []],
        hub_address=str(_nn(fl.get("hubAddress"), "")),
        mesh_slice=_parse_mesh_slice(fl.get("meshSlice")),
        max_row_age_seconds=float(_nn(fl.get("maxRowAgeSeconds"), 30.0)),
        flush_batch=int(_nn(fl.get("flushBatch"), 0)),
    )
    if cfg.fleet.flush_batch < 0:
        raise ValueError(
            "fleet.flushBatch must be >= 0 (0 = the adapter default; "
            f"got {cfg.fleet.flush_batch})"
        )
    if cfg.fleet.hub_address:
        # one or more comma-separated endpoints (a replicated hub
        # deployment lists primary + standbys); each must be host:port
        # — a typo silently degrading to a private hub is the failure
        # mode this hard validation exists to prevent
        endpoints = [
            t.strip() for t in cfg.fleet.hub_address.split(",")
        ]
        if not all(t and ":" in t for t in endpoints):
            raise ValueError(
                'fleet.hubAddress must be "host:port" (comma-separate '
                f"several for a replicated hub; got "
                f"{cfg.fleet.hub_address!r})"
            )
    if cfg.fleet.max_row_age_seconds <= 0:
        raise ValueError(
            "fleet.maxRowAgeSeconds must be > 0 "
            f"(got {cfg.fleet.max_row_age_seconds})"
        )
    if (
        cfg.fleet.replicas
        or cfg.fleet.hub_address
        or cfg.fleet.mesh_slice is not None
    ) and not cfg.fleet.replica:
        # meshSlice especially: honoring a slice with fleet mode off
        # would silently pin the sole scheduler to a fraction of the
        # devices — exactly the quiet capacity loss this section's
        # hard validation exists to prevent
        raise ValueError(
            "fleet.replica is required when any other fleet key is set "
            "(a replica must know its own identity)"
        )

    tu = data.get("tuning") or {}
    # knobs: an ABSENT key means all knobs; an explicit empty list
    # means "govern nothing" (everything pinned) — the falsy-`or`
    # shape would silently expand [] to all four, the exact quiet
    # misconfiguration the unknown-knob check rejects hard
    knobs_raw = tu.get("knobs")
    cfg.tuning = TuningSection(
        enabled=bool(_nn(tu.get("enabled"), False)),
        eval_batches=int(_nn(tu.get("evalBatches"), 6)),
        hysteresis=float(_nn(tu.get("hysteresis"), 0.05)),
        settle_after=int(_nn(tu.get("settleAfter"), 2)),
        max_probes=int(_nn(tu.get("maxProbes"), 16)),
        shift_threshold=float(_nn(tu.get("shiftThreshold"), 0.75)),
        knobs=(
            list(TUNABLE_KNOBS)
            if knobs_raw is None
            else [str(k) for k in knobs_raw]
        ),
    )
    validate_tuning_params(
        cfg.tuning.eval_batches,
        cfg.tuning.hysteresis,
        cfg.tuning.settle_after,
        cfg.tuning.max_probes,
        cfg.tuning.shift_threshold,
        cfg.tuning.knobs,
    )

    gg = data.get("gang") or {}
    cfg.gang = GangSection(
        enabled=bool(_nn(gg.get("enabled"), False)),
        min_member_timeout_seconds=float(
            _nn(gg.get("minMemberTimeoutSeconds"), 30.0)
        ),
        quarantine_after=int(_nn(gg.get("quarantineAfter"), 3)),
        throughput_weight=int(_nn(gg.get("throughputWeight"), 0)),
        class_throughput=dict(_nn(gg.get("classThroughput"), {}) or {}),
        class_throughput_path=str(_nn(gg.get("classThroughputPath"), "")),
    )
    if cfg.gang.min_member_timeout_seconds <= 0:
        raise ValueError(
            "gang.minMemberTimeoutSeconds must be > 0 "
            f"(got {cfg.gang.min_member_timeout_seconds})"
        )
    if cfg.gang.quarantine_after < 1:
        # 0 would quarantine every gang on its first incomplete round —
        # plausibly intended as "off", so reject the ambiguity hard
        raise ValueError(
            "gang.quarantineAfter must be >= 1 "
            f"(got {cfg.gang.quarantine_after})"
        )
    if cfg.gang.throughput_weight < 0:
        raise ValueError(
            "gang.throughputWeight must be >= 0 (0 = objective off; "
            f"got {cfg.gang.throughput_weight})"
        )
    if cfg.gang.class_throughput and cfg.gang.class_throughput_path:
        # the quiet failure mode: both set, one silently wins
        raise ValueError(
            "gang.classThroughput and gang.classThroughputPath are "
            "mutually exclusive"
        )
    _validate_throughput_table(cfg.gang.class_throughput)
    return cfg


def _validate_throughput_table(table: Mapping) -> None:
    """Hard-validate the inline (workload -> accelerator -> relative
    throughput) matrix — a malformed row silently scoring 0 is exactly
    the quiet capacity loss gang scoring exists to prevent."""
    for wl, per in table.items():
        if not isinstance(per, Mapping):
            raise ValueError(
                f"gang.classThroughput[{wl!r}] must be a mapping of "
                f"accelerator class -> relative throughput (got {per!r})"
            )
        for ac, rel in per.items():
            try:
                val = float(rel)
            except (TypeError, ValueError):
                raise ValueError(
                    f"gang.classThroughput[{wl!r}][{ac!r}] must be a "
                    f"number (got {rel!r})"
                ) from None
            if val < 0:
                raise ValueError(
                    f"gang.classThroughput[{wl!r}][{ac!r}] must be "
                    f">= 0 (got {val})"
                )


def _parse_mesh_slice(value) -> "tuple[int, int] | None":
    """fleet.meshSlice "rank/count" -> (rank, count). Null/empty = no
    slice; anything malformed is a hard error (a typo silently sharing
    devices between replicas is the failure mode this key exists to
    prevent)."""
    if value is None or value == "":
        return None
    try:
        rank_s, count_s = str(value).split("/", 1)
        rank, count = int(rank_s), int(count_s)
    except ValueError:
        raise ValueError(
            'fleet.meshSlice must be "rank/count" (e.g. "0/4"); '
            f"got {value!r}"
        ) from None
    if count < 1 or not 0 <= rank < count:
        raise ValueError(
            f"fleet.meshSlice needs 0 <= rank < count; got {value!r}"
        )
    return (rank, count)


def load_file(path: str) -> KubeSchedulerConfiguration:
    with open(path) as f:
        return load(yaml.safe_load(f) or {})


from ..tensorize.plugins import VOLUME_PLUGINS as VOLUME_FILTER_PLUGINS

# filter-point plugin names the solver/tensorizer can actually disable
DISABLEABLE_FILTERS = VOLUME_FILTER_PLUGINS | {
    "NodeResourcesFit", "NodePorts", "NodeName", "NodeUnschedulable",
    "TaintToleration", "NodeAffinity", "PodTopologySpread",
    "InterPodAffinity",
}


def _solver_config(cfg: KubeSchedulerConfiguration, p: Profile):
    from ..solver.exact import ExactSolverConfig

    w = p.score_weights
    # scoringStrategy.resources -> cpu/memory weights (the NonZero scoring
    # pipeline tracks exactly those two; anything else is warned away)
    res_weights = {"cpu": 1, "memory": 1}
    for r in p.scoring_strategy.resources:
        name = r.get("name")
        if name in res_weights:
            res_weights[name] = int(r.get("weight") or 1)
        else:
            cfg.warnings.append(
                f"scoringStrategy resource {name!r}: only cpu/memory are "
                "tracked by the NonZero scoring pipeline; ignored"
            )
    # requestedToCapacityRatio.shape validation
    # (apis/config/validation#validateFunctionShape semantics): every point
    # needs utilization+score, utilization strictly ascending; a malformed
    # shape warns and falls back to LeastAllocated instead of raising, the
    # same degradation already used for the empty-shape case.
    rtc_shape: tuple = ()
    try:
        rtc_shape = tuple(
            (int(s["utilization"]), int(s["score"]))
            for s in p.scoring_strategy.shape
        )
    except (KeyError, TypeError, ValueError) as e:
        cfg.warnings.append(
            "scoringStrategy requestedToCapacityRatio.shape entry is "
            f"malformed ({e!r}); falling back to LeastAllocated"
        )
    if rtc_shape and any(
        b[0] <= a[0] for a, b in zip(rtc_shape, rtc_shape[1:])
    ):
        cfg.warnings.append(
            "scoringStrategy requestedToCapacityRatio.shape utilization "
            "breakpoints must be strictly ascending; falling back to "
            "LeastAllocated"
        )
        rtc_shape = ()
    if p.scoring_strategy.type == "RequestedToCapacityRatio" and not rtc_shape:
        cfg.warnings.append(
            "scoringStrategy RequestedToCapacityRatio without a valid "
            "requestedToCapacityRatio.shape (upstream validation rejects "
            "this); falling back to LeastAllocated"
        )
    disabled = []
    for name in sorted(p.disabled_filters):
        if name in DISABLEABLE_FILTERS:
            disabled.append(name)
            if name in VOLUME_FILTER_PLUGINS:
                cfg.warnings.append(
                    f"filter {name!r} disabled: the volume plugin family is "
                    "fused in the static mask, so all four volume filters "
                    "are disabled together"
                )
        else:
            cfg.warnings.append(f"cannot disable filter {name!r}; ignored")
    added = None
    if p.added_affinity is not None:
        from ..api.objects import NodeAffinity

        added = NodeAffinity.from_dict(p.added_affinity)
    return ExactSolverConfig(
        tie_break=cfg.tpu_solver.tie_break,
        seed=cfg.tpu_solver.seed,
        balanced_fdtype=cfg.tpu_solver.balanced_fdtype,
        group_size=cfg.tpu_solver.group_size,
        scoring_strategy=p.scoring_strategy.type,
        cpu_weight=res_weights["cpu"],
        mem_weight=res_weights["memory"],
        rtc_shape=rtc_shape,
        fit_weight=w.get("NodeResourcesFit", 1),
        balanced_weight=w.get("NodeResourcesBalancedAllocation", 1),
        taint_weight=w.get("TaintToleration", 3),
        node_affinity_weight=w.get("NodeAffinity", 2),
        image_weight=w.get("ImageLocality", 1),
        spread_weight=w.get("PodTopologySpread", 2),
        interpod_weight=w.get("InterPodAffinity", 2),
        hard_pod_affinity_weight=p.hard_pod_affinity_weight,
        disabled_filters=tuple(disabled),
        added_affinity=added,
        spread_defaulting=p.spread_defaulting_type,
        pallas=cfg.tpu_solver.pallas,
    )


def scheduler_config(cfg: KubeSchedulerConfiguration):
    """Build the runtime SchedulerConfig — ALL profiles become solver
    entries so pods route by spec.schedulerName (profile.NewMap)."""
    from ..scheduler import SchedulerConfig

    profiles = {
        p.scheduler_name: _solver_config(cfg, p) for p in cfg.profiles
    }
    rebalance = None
    if cfg.rebalance.enabled:
        from ..rebalance.runtime import RebalanceConfig

        rebalance = RebalanceConfig(
            interval_s=cfg.rebalance.interval_seconds,
            max_moves_per_cycle=cfg.rebalance.max_moves_per_cycle,
            min_packing=cfg.rebalance.min_packing_utilization,
            min_gain=cfg.rebalance.min_gain_points,
            nominate=cfg.rebalance.nominate,
        )
    fleet = None
    if cfg.fleet.replica:
        from ..fleet.runtime import FleetConfig

        # hub_address (not an exchange object) so nothing network-
        # shaped is constructed at config-build time: FleetRuntime
        # builds the RemoteOccupancyExchange when the Scheduler starts
        fleet = FleetConfig(
            replica=cfg.fleet.replica,
            replicas=tuple(cfg.fleet.replicas),
            hub_address=cfg.fleet.hub_address,
            max_row_age_s=cfg.fleet.max_row_age_seconds,
            flush_batch=cfg.fleet.flush_batch,
        )
    gang = None
    if cfg.gang.enabled:
        from ..gang import GangConfig, load_throughput_table

        table = cfg.gang.class_throughput
        if cfg.gang.class_throughput_path:
            table = load_throughput_table(cfg.gang.class_throughput_path)
            _validate_throughput_table(table)
        gang = GangConfig(
            min_member_timeout=cfg.gang.min_member_timeout_seconds,
            quarantine_after=cfg.gang.quarantine_after,
            throughput_weight=cfg.gang.throughput_weight,
            class_throughput=dict(table),
        )
    tuning = None
    if cfg.tuning.enabled:
        from ..tuning.runtime import TuningConfig

        tuning = TuningConfig(
            eval_batches=cfg.tuning.eval_batches,
            hysteresis=cfg.tuning.hysteresis,
            settle_after=cfg.tuning.settle_after,
            max_probes=cfg.tuning.max_probes,
            shift_threshold=cfg.tuning.shift_threshold,
            knobs=tuple(cfg.tuning.knobs),
        )
    return SchedulerConfig(
        batch_size=cfg.tpu_solver.batch_size,
        enable_preemption=cfg.tpu_solver.enable_preemption,
        mesh_devices=cfg.tpu_solver.mesh_devices,
        mesh_slice=cfg.fleet.mesh_slice,
        stream_depth=cfg.tpu_solver.stream_depth,
        pipeline_split=cfg.tpu_solver.pipeline_split,
        backlog_chunk_pods=cfg.tpu_solver.backlog_chunk_pods,
        solver=profiles[cfg.profiles[0].scheduler_name],
        profiles=profiles,
        # honored, not just parsed: the scheduler consults these via the
        # outbound HTTP client during every solve
        extenders=tuple(cfg.extenders),
        rebalance=rebalance,
        fleet=fleet,
        tuning=tuning,
        gang=gang,
    )
