"""Hub-coordinated fleet backlog drain (ROADMAP item #5a).

One coordinator — whoever hosts the hub primary, epoch-fenced by the
same ``HubLease`` every other hub write rides — takes the backlog, runs
the relax mega-plan ONCE globally, and partitions pods to replicas by
the shard that owns each pod's planned node. Pods the plan left
unplaced, pods whose planned node no shard owns, and cross-shard-
CONSTRAINED pods (spread / anti-affinity — correctness must not be
traded for parallelism) fall into a small *residual cohort* that drains
serialized, after the shard partitions, against near-final occupancy.

Each replica then claims a *drain lease* over its partition and drains
it through its own ``drain_backlog`` slot ring under its own HBM
budget. The lease ledger lives on the hub (``OccupancyExchange`` hosts
it, replicates it to standbys, and fences every mutation with the
epoch + write-fence discipline all row traffic uses), so:

- a pod belongs to exactly ONE granted lease at a time — no pod drains
  twice;
- a replica death returns its lease (``return_leases`` rides the hub's
  ``retire``): outstanding keys become *orphans* and the next claimant
  adopts them — no pod is lost;
- the residual cohort is a single lease granted only once every shard
  lease has completed — serialized by construction.

This module is deliberately PURE: functions over a JSON-able state
dict. The hub owns locking, fencing, version bumps, and replication
(`occupancy.py`); replicas talk to it through ``FleetRuntime`` /
``RemoteOccupancyExchange`` drain ops. Keeping the ledger logic free of
I/O is what makes the known-bad sim fixtures and the unit suite cheap.
"""

from __future__ import annotations

__all__ = [
    "GRANTED",
    "DONE",
    "RETURNED",
    "partition_backlog",
    "new_state",
    "claim",
    "progress",
    "complete",
    "return_leases",
    "outstanding_keys",
    "status",
]

GRANTED = "granted"
DONE = "done"
RETURNED = "returned"


def partition_backlog(
    keys, planned, assignment, *, gang_of=None, cross_shard=None
):
    """Split the backlog into per-replica partitions + the residual.

    ``keys`` is the backlog in PLAN ORDER (the relax warm-start rank —
    partitions preserve it so each replica drains its slice in the same
    global-plan order a single replica would). ``planned`` maps pod key
    to its relax-planned node name (or None when the plan left it
    unplaced); ``assignment`` maps node name to owning replica (the
    ring's node assignment). ``gang_of`` returns a pod's gang id (""
    for none): a gang drains WHOLE at the replica owning its first
    planned member — splitting an all-or-nothing group across drain
    leases would deadlock its barrier. ``cross_shard`` is the
    constraint predicate (spread / anti-affinity): True sends the pod
    to the residual cohort, where serialization keeps the existing
    fenced-CAS admit semantics intact.

    Returns ``(partitions, residual)`` — ``{replica: [keys...]}`` plus
    the residual key list, both deterministic in plan order.
    """
    gang_of = gang_of or (lambda key: "")
    cross_shard = cross_shard or (lambda key: False)
    target: dict = {}
    gang_target: dict = {}
    gang_residual: set = set()
    for k in keys:
        node = planned.get(k)
        owner = assignment.get(node) if node else None
        if cross_shard(k):
            owner = None
        target[k] = owner
        gid = gang_of(k)
        if gid:
            if owner is None:
                # one residual member sends the WHOLE gang residual
                gang_residual.add(gid)
            elif gid not in gang_target:
                gang_target[gid] = owner
    partitions: dict = {}
    residual: list = []
    for k in keys:
        gid = gang_of(k)
        if gid:
            owner = (
                None if gid in gang_residual else gang_target.get(gid)
            )
        else:
            owner = target[k]
        if owner is None:
            residual.append(k)
        else:
            partitions.setdefault(owner, []).append(k)
    return partitions, residual


def new_state(
    partitions, residual, *, epoch=0, membership_version=0
) -> dict:
    """A fresh drain ledger. JSON-able end to end: it replicates to
    hub standbys as an op-log payload and rides snapshots, so string
    keys and plain lists only."""
    return {
        "epoch": int(epoch),
        "membershipVersion": int(membership_version),
        "partitions": {
            str(r): list(ks) for r, ks in sorted(partitions.items())
        },
        "residual": list(residual),
        # replica -> lease id of its base-partition claim ("" once the
        # partition was orphaned by return_leases — never regrant it)
        "claimed": {},
        # lease id -> {replica, keys, state: granted|done|returned,
        #              epoch, membershipVersion, kind}
        "leases": {},
        "done": {},  # pod key -> replica that drained it
        "orphans": [],  # returned keys awaiting reassignment
        "residualGranted": False,
        "nextLease": 1,
        "reassigned": 0,
    }


def _grant(state: dict, replica: str, keys, kind: str) -> dict:
    lid = f"L{state['nextLease']}"
    state["nextLease"] += 1
    lease = {
        "replica": str(replica),
        "keys": list(keys),
        "state": GRANTED,
        "epoch": state["epoch"],
        "membershipVersion": state["membershipVersion"],
        "kind": kind,
    }
    state["leases"][lid] = lease
    return dict(lease, id=lid)


def _granted_leases(state: dict):
    for lid in sorted(state["leases"], key=lambda s: int(s[1:])):
        if state["leases"][lid]["state"] == GRANTED:
            yield lid, state["leases"][lid]


def claim(state: dict, replica: str):
    """Grant ``replica`` its next drain lease. Deterministic order:

    1. an already-granted lease re-serves verbatim (idempotent — the
       claim RPC may be retried after a lost reply);
    2. the replica's own base partition, once;
    3. the orphan pool (a dead replica's returned work), whole — this
       is the reassignment path, counted in ``reassigned``;
    4. the residual cohort, as ONE lease to the first claimant after
       every shard lease completed — serialized by construction.

    Returns ``(lease_dict_with_id | None, reassigned: bool)``.
    """
    replica = str(replica)
    for lid, lease in _granted_leases(state):
        if lease["replica"] == replica:
            return dict(lease, id=lid), False
    if replica in state["partitions"] and replica not in state["claimed"]:
        keys = [
            k
            for k in state["partitions"][replica]
            if k not in state["done"]
        ]
        out = _grant(state, replica, keys, "partition")
        state["claimed"][replica] = out["id"]
        return out, False
    if state["orphans"]:
        keys = [k for k in state["orphans"] if k not in state["done"]]
        state["orphans"] = []
        state["reassigned"] += 1
        return _grant(state, replica, keys, "orphan"), True
    if (
        state["residual"]
        and not state["residualGranted"]
        and not any(True for _ in _granted_leases(state))
        and all(r in state["claimed"] for r in state["partitions"])
    ):
        keys = [k for k in state["residual"] if k not in state["done"]]
        state["residualGranted"] = True
        return _grant(state, replica, keys, "residual"), False
    return None, False


def progress(state: dict, replica: str, keys) -> int:
    """Record pods ``replica`` drained under its granted lease.
    Returns how many were newly marked done. Keys outside the lease
    (concurrently admitted non-backlog pods riding the same flight)
    and keys already done are ignored — the ledger only ever records a
    pod done ONCE, so a zombie's late report after its lease was
    returned and reassigned cannot double-count."""
    replica = str(replica)
    lease_keys: set = set()
    for _lid, lease in _granted_leases(state):
        if lease["replica"] == replica:
            lease_keys.update(lease["keys"])
    if not lease_keys:
        return 0
    n = 0
    for k in keys:
        if k in lease_keys and k not in state["done"]:
            state["done"][k] = replica
            n += 1
    return n


def complete(state: dict, replica: str, lease_id: str) -> bool:
    """Mark a granted lease done. Keys the replica did NOT report
    drained stay un-done in the ledger — they remain the replica's
    pods through the ordinary fleet routing it adopted them under
    (queued or waiting), so the status surface stays truthful without
    double-tracking them as orphans."""
    lease = state["leases"].get(str(lease_id))
    if (
        lease is None
        or lease["replica"] != str(replica)
        or lease["state"] != GRANTED
    ):
        return False
    lease["state"] = DONE
    return True


def return_leases(state: dict, replica: str) -> int:
    """Return a dead replica's drain work for reassignment (rides the
    hub's ``retire``). Outstanding keys of its granted leases — and
    its base partition if it died before ever claiming — become
    orphans the next claimant adopts. Returns how many keys were
    orphaned."""
    replica = str(replica)
    orphaned = 0
    for _lid, lease in list(_granted_leases(state)):
        if lease["replica"] != replica:
            continue
        for k in lease["keys"]:
            if k not in state["done"]:
                state["orphans"].append(k)
                orphaned += 1
        lease["state"] = RETURNED
    if replica in state["partitions"] and replica not in state["claimed"]:
        state["claimed"][replica] = ""  # never regrant the base claim
        for k in state["partitions"][replica]:
            if k not in state["done"]:
                state["orphans"].append(k)
                orphaned += 1
    return orphaned


def outstanding_keys(state: dict) -> list:
    """Every backlog key not yet drained, in plan order — the sim's
    lost-pod invariant counts these as hub-tracked (like pending
    handoffs): mid-reassignment they sit in no replica's queue."""
    out = []
    for r in sorted(state["partitions"]):
        out.extend(
            k for k in state["partitions"][r] if k not in state["done"]
        )
    out.extend(k for k in state["residual"] if k not in state["done"])
    return out


def status(state: dict) -> dict:
    """Counts-only summary (footer lines, metrics, drain_status op)."""
    total = sum(
        len(ks) for ks in state["partitions"].values()
    ) + len(state["residual"])
    done = len(state["done"])
    return {
        "pods": total,
        "partitions": len(state["partitions"]),
        "residual": len(state["residual"]),
        "done": done,
        "outstanding": total - done,
        "orphans": len(state["orphans"]),
        "reassigned": state["reassigned"],
        "leases": len(state["leases"]),
        "granted": sum(1 for _ in _granted_leases(state)),
        "residualGranted": bool(state["residualGranted"]),
        "complete": total == done,
    }
