"""Heterogeneity-aware scoring: Gavel's per-accelerator-class
effective-throughput objective, folded into the batched score
pipeline.

Nodes advertise an accelerator class via the
``scheduling.x-k8s.io/accelerator-class`` label (``tpu-v4``,
``tpu-v5e``, ``gpu-a100``, ...); pods advertise a workload class via
``scheduling.x-k8s.io/workload-class`` (``resnet``, ``transformer``,
...). The configured matrix maps (workload class, accelerator class)
to a relative effective throughput, and ``fold_throughput`` converts
it into integer score points accumulated into the static tensors'
``extra_score`` table — the same generic donor every solver path
(fused and grouped) already adds to the score when present
(``use_extra_score``), so the objective costs ZERO new kernel surface:
a gang lands on the class where its throughput-per-chip is highest,
not merely where it fits.

The fold is pure per (class representative, node) — the contract the
out-of-tree/extender folds already obey — so it composes with the
fold cache (which replaces ``extra_score`` BEFORE this fold runs) and
rides the pipelined/streaming overlap untouched.
"""

from __future__ import annotations

import json

import numpy as np

ACCEL_CLASS_LABEL = "scheduling.x-k8s.io/accelerator-class"
WORKLOAD_CLASS_LABEL = "scheduling.x-k8s.io/workload-class"


def load_throughput_table(path: str) -> dict:
    """Load a class-throughput matrix from a JSON file:
    ``{"resnet": {"tpu-v4": 1.0, "tpu-v5e": 0.62}, ...}``. Validation
    mirrors the inline-table rules in config/types.py."""
    with open(path, encoding="utf-8") as f:
        raw = json.load(f)
    if not isinstance(raw, dict):
        raise ValueError(
            f"gang.classThroughputPath {path}: top level must be an "
            "object of workload classes"
        )
    return raw


def fold_throughput(static, slot_nodes, config) -> None:
    """Accumulate weighted throughput points into
    ``static.extra_score`` (created on first contribution, accumulated
    in place otherwise — the extender fold's discipline)."""
    table = config.class_throughput
    weight = config.throughput_weight
    if not table or weight <= 0:
        return
    node_cls: list[str | None] = [
        n.labels.get(ACCEL_CLASS_LABEL) if n is not None else None
        for n in slot_nodes
    ]
    if not any(node_cls):
        return  # homogeneous / unlabeled cluster: nothing to prefer
    extra = static.extra_score
    for ci, rep in enumerate(static.reps):
        wl = rep.labels.get(WORKLOAD_CLASS_LABEL)
        if not wl:
            continue
        per = table.get(wl)
        if not per:
            continue
        for j, nc in enumerate(node_cls):
            if nc is None:
                continue
            rel = per.get(nc)
            if not rel:
                continue
            if extra is None:
                extra = np.zeros(static.mask.shape, dtype=np.int32)
            extra[ci, j] += int(round(weight * float(rel)))
    if extra is not None and extra is not static.extra_score:
        if extra.any():
            static.extra_score = extra
