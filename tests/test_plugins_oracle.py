"""Unit tests for the static-plugin oracles (hand cases derived from the
reference semantics in SURVEY.md §3.2)."""

import pytest

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.ops.oracle import plugins as opl


# -- NodeName ---------------------------------------------------------------


def test_node_name_filter():
    n = MakeNode().name("a").obj()
    assert opl.node_name_filter(MakePod().name("p").obj(), n)
    assert opl.node_name_filter(MakePod().name("p").node("a").obj(), n)
    assert not opl.node_name_filter(MakePod().name("p").node("b").obj(), n)


# -- NodeUnschedulable ------------------------------------------------------


def test_node_unschedulable():
    n = MakeNode().name("a").unschedulable().obj()
    assert not opl.node_unschedulable_filter(MakePod().obj(), n)
    tolerating = (
        MakePod()
        .toleration(key="node.kubernetes.io/unschedulable", operator="Exists",
                    effect="NoSchedule")
        .obj()
    )
    assert opl.node_unschedulable_filter(tolerating, n)
    # an Exists toleration with empty key+effect tolerates everything
    tolerate_all = MakePod().toleration(operator="Exists").obj()
    assert opl.node_unschedulable_filter(tolerate_all, n)
    assert opl.node_unschedulable_filter(MakePod().obj(), MakeNode().name("b").obj())


# -- TaintToleration --------------------------------------------------------


def test_taint_filter_effects():
    node = (
        MakeNode().name("a")
        .taint("k1", "v1", "NoSchedule")
        .taint("k2", "v2", "PreferNoSchedule")
        .obj()
    )
    # PreferNoSchedule is not a filter-effect: pod without tolerations passes
    # only if NoSchedule taints are tolerated
    assert not opl.taint_toleration_filter(MakePod().obj(), node)
    p = MakePod().toleration(key="k1", value="v1", effect="NoSchedule").obj()
    assert opl.taint_toleration_filter(p, node)
    # value mismatch with default Equal operator
    p2 = MakePod().toleration(key="k1", value="other", effect="NoSchedule").obj()
    assert not opl.taint_toleration_filter(p2, node)
    # empty-effect toleration matches all effects
    p3 = MakePod().toleration(key="k1", value="v1").obj()
    assert opl.taint_toleration_filter(p3, node)


def test_taint_score_counts_prefer_no_schedule():
    node = (
        MakeNode().name("a")
        .taint("a", "1", "PreferNoSchedule")
        .taint("b", "2", "PreferNoSchedule")
        .taint("c", "3", "NoSchedule")
        .obj()
    )
    assert opl.taint_toleration_score(MakePod().obj(), node) == 2
    p = MakePod().toleration(key="a", operator="Exists").obj()
    assert opl.taint_toleration_score(p, node) == 1


# -- NodeAffinity -----------------------------------------------------------


def test_node_selector_and_affinity():
    node = MakeNode().name("a").label("zone", "z1").label("disk", "ssd").obj()
    assert opl.node_affinity_filter(MakePod().node_selector({"zone": "z1"}).obj(), node)
    assert not opl.node_affinity_filter(
        MakePod().node_selector({"zone": "z2"}).obj(), node
    )
    # required affinity: OR of terms
    p = MakePod().node_affinity_in("zone", ["z2", "z1"]).obj()
    assert opl.node_affinity_filter(p, node)
    p2 = MakePod().node_affinity_not_in("disk", ["ssd"]).obj()
    assert not opl.node_affinity_filter(p2, node)
    # nodeSelector AND affinity must both hold
    p3 = (
        MakePod()
        .node_selector({"zone": "z1"})
        .node_affinity_in("disk", ["hdd"])
        .obj()
    )
    assert not opl.node_affinity_filter(p3, node)


def test_node_affinity_score_sums_weights():
    node = MakeNode().name("a").label("zone", "z1").label("disk", "ssd").obj()
    p = (
        MakePod()
        .preferred_node_affinity(10, "zone", ["z1"])
        .preferred_node_affinity(5, "disk", ["hdd"])
        .preferred_node_affinity(3, "disk", ["ssd"])
        .obj()
    )
    assert opl.node_affinity_score(p, node) == 13
    assert opl.node_affinity_score(MakePod().obj(), node) == 0


# -- NodePorts --------------------------------------------------------------


def test_port_conflicts_wildcard_semantics():
    # want wildcard conflicts with any ip on same (proto, port)
    assert opl.port_conflicts(("0.0.0.0", "TCP", 80), [("10.0.0.1", "TCP", 80)])
    # want specific conflicts with wildcard used
    assert opl.port_conflicts(("10.0.0.2", "TCP", 80), [("0.0.0.0", "TCP", 80)])
    # different specific IPs don't conflict
    assert not opl.port_conflicts(("10.0.0.2", "TCP", 80), [("10.0.0.1", "TCP", 80)])
    # protocol isolation
    assert not opl.port_conflicts(("0.0.0.0", "UDP", 80), [("0.0.0.0", "TCP", 80)])
    # port 0 never conflicts
    assert not opl.port_conflicts(("0.0.0.0", "TCP", 0), [("0.0.0.0", "TCP", 0)])


def test_node_ports_filter():
    pod = MakePod().host_port(8080).obj()
    assert opl.node_ports_filter(pod, [])
    assert not opl.node_ports_filter(pod, [("0.0.0.0", "TCP", 8080)])


# -- ImageLocality ----------------------------------------------------------


MB = 1024 * 1024


def test_normalized_image_name():
    assert opl.normalized_image_name("nginx") == "nginx:latest"
    assert opl.normalized_image_name("nginx:1.2") == "nginx:1.2"
    assert opl.normalized_image_name("reg:5000/img") == "reg:5000/img:latest"
    assert opl.normalized_image_name("img@sha256:abcd") == "img@sha256:abcd"


def test_image_locality_score_scaling():
    # image on 1 of 2 nodes, size 500MB -> scaled = 500MB * 1/2 = 250MB
    n1 = MakeNode().name("n1").image("big:latest", 500 * MB).obj()
    n2 = MakeNode().name("n2").obj()
    states = opl.build_image_states([n1, n2])
    assert states["big:latest"] == (500 * MB, 1)
    pod = MakePod().container_image("big:latest").obj()
    # sum=250MB, 1 container: (250-23)/(1000-23) * 100 = 23.23 -> 23
    s1 = opl.image_locality_score(pod, n1, states, 2)
    assert s1 == 100 * (250 * MB - 23 * MB) // (977 * MB)
    # node without the image scores 0
    assert opl.image_locality_score(pod, n2, states, 2) == 0


def test_image_locality_thresholds():
    n = MakeNode().name("n").image("huge:latest", 3000 * MB).obj()
    states = opl.build_image_states([n])
    pod = MakePod().container_image("huge:latest").obj()
    assert opl.image_locality_score(pod, n, states, 1) == 100  # clamped at max
    n2 = MakeNode().name("n2").image("tiny:latest", MB).obj()
    states2 = opl.build_image_states([n2])
    pod2 = MakePod().container_image("tiny:latest").obj()
    assert opl.image_locality_score(pod2, n2, states2, 1) == 0  # below min


# -- DefaultNormalizeScore --------------------------------------------------


def test_default_normalize():
    assert opl.default_normalize_score([1, 2, 4], reverse=False) == [25, 50, 100]
    assert opl.default_normalize_score([1, 2, 4], reverse=True) == [75, 50, 0]
    assert opl.default_normalize_score([0, 0], reverse=True) == [100, 100]
    assert opl.default_normalize_score([0, 0], reverse=False) == [0, 0]
