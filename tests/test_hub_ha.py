"""Occupancy hub high availability (ISSUE 15): replicated hub state
(op log + snapshot catch-up), epoch-fenced failover (HubLease grants,
HubDeposed rejections, client-side monotone epoch verification), the
endpoint-failover client, and the idempotent write-behind flush path
that closes the double-apply hazard."""

import pytest

from kubernetes_tpu.fleet import (
    AdmitConflict,
    ExchangeUnreachable,
    HubDeposed,
    HubLease,
    LocalHubClient,
    NodeRow,
    OccupancyExchange,
    PENDING,
    PodRow,
    RemoteOccupancyExchange,
    SqliteHubLease,
    StandbyReplicator,
)
from kubernetes_tpu.utils.clock import FakeClock


def _row(pod="default/p", node="n1", zone="z0", labels=(("app", "x"),)):
    return PodRow(
        pod=pod, node=node, zone=zone, namespace="default",
        labels=labels, state=PENDING,
    )


def _ha_pair(clock=None, lease_s=2.0, lease=None, **hub_kw):
    """Primary (epoch 1) + standby under one lease on a FakeClock."""
    clock = clock or FakeClock()
    if lease is None:
        lease = HubLease(clock=clock, duration_s=lease_s)
    primary = OccupancyExchange(
        clock=clock, hub_id="hub-a", lease=lease, **hub_kw
    )
    assert primary.try_promote() == 1
    standby = OccupancyExchange(
        clock=clock, hub_id="hub-b", lease=lease, **hub_kw
    )
    return clock, lease, primary, standby


# -- HubLease ----------------------------------------------------------------


@pytest.fixture(params=["memory", "sqlite"])
def make_lease(request, tmp_path):
    """Lease-store factory covering both backends: the in-memory
    HubLease and the file-backed SqliteHubLease (ISSUE 20 leg b) must
    be contract-interchangeable, so the fencing/failover tests in
    this module run against each."""
    if request.param == "memory":
        return lambda clock, lease_s=2.0: HubLease(
            clock=clock, duration_s=lease_s
        )
    return lambda clock, lease_s=2.0: SqliteHubLease(
        str(tmp_path / "hub_lease.db"), clock=clock, duration_s=lease_s
    )


class TestHubLease:
    def test_grant_renew_and_expiry_takeover(self, make_lease):
        clock = FakeClock()
        lease = make_lease(clock)
        assert lease.try_acquire("a") == 1
        assert lease.try_acquire("b") is None  # live lease: no takeover
        clock.advance(1.0)
        assert lease.renew("a") is True
        clock.advance(1.5)  # 1.5 since renew: still valid
        assert lease.valid("a") and not lease.valid("b")
        clock.advance(1.0)  # 2.5 since renew: expired
        assert lease.renew("a") is False  # expired holder can't renew
        assert lease.try_acquire("b") == 2  # takeover bumps the epoch

    def test_same_holder_reacquire_keeps_epoch(self, make_lease):
        """The steady-state maintenance path: an incumbent re-acquiring
        (even after its own expiry, unclaimed) renews WITHOUT bumping
        the epoch — otherwise every idle stretch would read as a
        failover."""
        clock = FakeClock()
        lease = make_lease(clock)
        assert lease.try_acquire("a") == 1
        clock.advance(5.0)
        assert lease.try_acquire("a") == 1
        assert lease.epoch == 1


# -- replication (op log + snapshot) ----------------------------------------


class TestReplication:
    def test_oplog_catchup_mirrors_state_and_version(self):
        clock, _lease, primary, standby = _ha_pair()
        rep = StandbyReplicator(standby, LocalHubClient(primary))
        primary.publish_nodes("r0", [NodeRow("n1", "z0")])
        primary.stage("r0", _row())
        primary.commit("r0", "default/p")
        primary.hand_off("r1", "default/h", 2, trace="t-1")
        primary.set_degraded("r0", True)
        primary.ship_journal("r0", ['{"a":1}'])
        rep.poll()
        assert standby.version == primary.version  # CAS continuity
        assert standby.opseq == primary.opseq
        assert rep.lag == 0
        assert standby.replica_rows("r0") == primary.replica_rows("r0")
        assert standby.pending_handoff_keys() == {"default/h"}
        # degraded flags are a role-fenced replica-facing read: the
        # standby mirror is asserted through the debug surface
        assert standby.debug_state()["degraded"] == ["r0"]
        assert standby.journal_lines() == ['{"a":1}']

    def test_claim_and_withdraw_replicate(self):
        clock, _lease, primary, standby = _ha_pair()
        rep = StandbyReplicator(standby, LocalHubClient(primary))
        primary.stage("r0", _row())
        primary.hand_off("r1", "default/h", 1)
        rep.poll()
        assert standby.pending_handoff_keys() == {"default/h"}
        primary.claim_handoffs("r1")
        primary.withdraw("r0", "default/p")
        rep.poll()
        assert standby.pending_handoff_keys() == set()
        assert standby.replica_rows("r0")[1] == ()

    def test_snapshot_join_when_log_window_moved(self):
        """A standby further behind than the retained op-log window
        re-joins via snapshot (and the lag gauge covers both paths)."""
        clock, _lease, primary, standby = _ha_pair(oplog_capacity=4)
        rep = StandbyReplicator(standby, LocalHubClient(primary))
        for i in range(12):  # 12 ops through a 4-entry window
            primary.stage("r0", _row(pod=f"default/p{i}"))
        rep.poll()
        assert rep.snapshots_installed == 1
        assert standby.version == primary.version
        assert len(standby.replica_rows("r0")[1]) == 12
        # incremental from here on
        primary.stage("r0", _row(pod="default/p99"))
        rep.poll()
        assert rep.snapshots_installed == 1  # no second snapshot
        assert standby.opseq == primary.opseq

    def test_retire_and_fence_state_replicate(self):
        """The promoted standby must enforce the same hub write fence
        the primary did — revoked-replica state rides the log."""
        clock, _lease, primary, standby = _ha_pair()
        rep = StandbyReplicator(standby, LocalHubClient(primary))
        primary.stage("r0", _row())
        primary.retire("r0")
        rep.poll()
        clock.advance(3.0)
        assert standby.try_promote() == 2
        with pytest.raises(AdmitConflict) as ei:
            standby.stage("r0", _row(pod="default/q"))
        assert ei.value.fenced is True
        assert standby.replica_rows("r0")[1] == ()


# -- epoch fencing ------------------------------------------------------------


class TestEpochFencing:
    def test_standby_rejects_replica_surface(self, make_lease):
        clock = FakeClock()
        _clock, _lease, _primary, standby = _ha_pair(
            clock=clock, lease=make_lease(clock)
        )
        with pytest.raises(HubDeposed):
            standby.peers_view("r0")
        with pytest.raises(HubDeposed):
            standby.stage("r0", _row())

    def test_deposed_primary_fences_writes_serves_status(
        self, make_lease
    ):
        """The partitioned-old-primary contract: after a takeover its
        replica-facing writes reject typed (and are counted — the
        chaos smoke's stale-primary proof) while the debug/read
        surface keeps serving the post-mortem."""
        clock = FakeClock()
        clock, _lease, primary, standby = _ha_pair(
            clock=clock, lease=make_lease(clock)
        )
        primary.stage("r0", _row())
        clock.advance(3.0)  # primary's lease expires unrenewed
        assert standby.try_promote() == 2
        with pytest.raises(HubDeposed):
            primary.stage("r0", _row(pod="default/q"))
        assert primary.deposed_write_rejections == 1
        assert primary.hub_status()["role"] == "deposed"
        assert primary.journal_lines() == []  # reads still serve
        # a read of the replica-facing surface is equally fenced (a
        # zombie replica must not keep resetting its staleness clock
        # against a dead hub's frozen rows) but not counted as a write
        with pytest.raises(HubDeposed):
            primary.peers_view("r0")
        assert primary.deposed_write_rejections == 1

    def test_heartbeat_self_deposes_on_lost_lease(self, make_lease):
        clock = FakeClock()
        clock, _lease, primary, standby = _ha_pair(
            clock=clock, lease=make_lease(clock)
        )
        clock.advance(3.0)
        assert standby.try_promote() == 2
        assert primary.heartbeat() is False
        assert primary.role == "deposed"

    def test_hub_deposed_maps_to_permission_denied_on_wire(self):
        """Wire half: PERMISSION_DENIED is the HubDeposed status — a
        code no other hub rejection uses, so the failover client can
        rotate on it without ambiguity."""
        import grpc

        from kubernetes_tpu.server.bulk import (
            BulkClient,
            BulkCore,
            make_grpc_server,
        )
        from kubernetes_tpu.state.cluster import ClusterState

        _clock, _lease, _primary, standby = _ha_pair()
        core = BulkCore(ClusterState(), exchange=standby)
        server, port = make_grpc_server(core, port=0)
        server.start()
        client = BulkClient(f"127.0.0.1:{port}", retries=0)
        try:
            with pytest.raises(grpc.RpcError) as ei:
                client.hub_op("peers_version", replica="r0")
            assert (
                ei.value.code() == grpc.StatusCode.PERMISSION_DENIED
            )
        finally:
            client.close()
            server.stop(grace=None)

    def test_every_reply_carries_the_epoch(self):
        from kubernetes_tpu.fleet import dispatch_hub_op

        hub = OccupancyExchange()  # standalone: permanently epoch 1
        for op in ("version", "peers_view", "hub_status"):
            assert dispatch_hub_op(hub, op, {"replica": "r0"})[
                "epoch"
            ] == 1


# -- idempotent flush (the double-apply hazard, fixed) -----------------------


class TestIdempotentFlush:
    def _remote(self, hub, replica="r0", clock=None):
        return RemoteOccupancyExchange(
            "", replica, clients=[LocalHubClient(hub)],
            clock=clock or FakeClock(), flush_client_id=f"{replica}-t",
        )

    def test_reply_loss_after_apply_does_not_double_apply(self):
        """THE regression (satellite #1): UNAVAILABLE raised AFTER the
        server-side apply used to re-land the whole buffer on retry —
        double-staged rows and double-appended journal lines. The
        sealed (client, seq) key now dedups the retry whole."""
        hub = OccupancyExchange()
        remote = self._remote(hub)
        remote.stage("r0", _row(pod="default/a"))
        remote.ship_journal("r0", ['{"line":1}'])
        hub.set_flush_fault(1)  # next apply_ops applies, reply lost
        with pytest.raises(ExchangeUnreachable):
            remote.flush()
        # server applied: the state is already there
        assert [r.pod for r in hub.replica_rows("r0")[1]] == ["default/a"]
        assert hub.journal_lines() == ['{"line":1}']
        assert remote._pending_flush() == 2  # retained client-side
        remote.flush()  # the retry — must dedup, not double-apply
        assert hub.flush_dedup_hits == 1
        assert [r.pod for r in hub.replica_rows("r0")[1]] == ["default/a"]
        assert hub.journal_lines() == ['{"line":1}']  # no double line
        assert remote._pending_flush() == 0

    def test_new_mutations_after_lost_reply_land_once_each(self):
        """Mutations buffered AFTER the lost-reply flush seal into a
        NEW batch under the next seq: the retry dedups only the old
        batch, the new one applies."""
        hub = OccupancyExchange()
        remote = self._remote(hub)
        remote.stage("r0", _row(pod="default/a"))
        hub.set_flush_fault(1)
        with pytest.raises(ExchangeUnreachable):
            remote.flush()
        remote.stage("r0", _row(pod="default/b"))
        remote.flush()
        assert hub.flush_dedup_hits == 1
        assert [r.pod for r in hub.replica_rows("r0")[1]] == [
            "default/a", "default/b",
        ]

    def test_dedup_watermark_survives_failover(self):
        """The retry of a lost-reply flush can land on the PROMOTED
        standby — the watermark replicated, so it still dedups."""
        clock, _lease, primary, standby = _ha_pair()
        rep = StandbyReplicator(standby, LocalHubClient(primary))
        remote = RemoteOccupancyExchange(
            "", "r0",
            clients=[LocalHubClient(primary), LocalHubClient(standby)],
            clock=clock, flush_client_id="r0-t",
        )
        remote.stage("r0", _row(pod="default/a"))
        remote.ship_journal("r0", ['{"line":1}'])
        primary.set_flush_fault(1)
        with pytest.raises(ExchangeUnreachable):
            remote.flush()
        rep.poll()  # the applied flush (and its watermark) replicate
        primary.set_down(True)
        clock.advance(3.0)
        assert standby.try_promote() == 2
        remote.flush()  # retried against the standby: deduped there
        assert standby.flush_dedup_hits == 1
        assert standby.journal_lines() == ['{"line":1}']
        assert [r.pod for r in standby.replica_rows("r0")[1]] == [
            "default/a"
        ]

    def test_restarted_client_is_not_mistaken_for_a_retry(self):
        """flush_client scopes the seq stream: a fresh incarnation
        starting back at seq 0 must not be dedup-dropped against the
        dead incarnation's watermark."""
        hub = OccupancyExchange()
        old = self._remote(hub)
        old.stage("r0", _row(pod="default/a"))
        old.flush()
        fresh = RemoteOccupancyExchange(
            "", "r0", clients=[LocalHubClient(hub)], clock=FakeClock(),
            flush_client_id="r0-incarnation-2",
        )
        fresh.stage("r0", _row(pod="default/b"))
        fresh.flush()  # seq 0 again, different client id: applies
        assert hub.flush_dedup_hits == 0
        assert len(hub.replica_rows("r0")[1]) == 2


# -- the endpoint-failover client --------------------------------------------


class TestFailoverClient:
    def test_rotates_to_standby_and_flags_failover(self):
        clock, _lease, primary, standby = _ha_pair()
        rep = StandbyReplicator(standby, LocalHubClient(primary))
        remote = RemoteOccupancyExchange(
            "", "r0",
            clients=[LocalHubClient(primary), LocalHubClient(standby)],
            clock=clock, flush_client_id="r0-t",
        )
        remote.publish_nodes("r0", [NodeRow("n1", "z0")])
        rep.poll()
        assert remote.consume_failover() is False
        primary.set_down(True)
        # blackout: the standby is not promoted yet — every endpoint
        # rejects, surfaced as the unreachable the PR 8 conservative
        # machinery expects
        with pytest.raises(ExchangeUnreachable):
            remote.peers_version("r0")
        clock.advance(3.0)
        assert standby.try_promote() == 2
        assert remote.peers_version("r0") == standby.version
        # the epoch advance was recorded exactly once, for the forced
        # wholesale-republish resync
        assert remote.failovers == 1
        assert remote.consume_failover() is True
        assert remote.consume_failover() is False

    def test_stale_epoch_reply_is_ignored(self):
        """A deposed primary that still answers (reads, or a lease
        check raced) is structurally ignored once a higher epoch was
        verified — the client-side half of the fence."""
        clock, _lease, primary, standby = _ha_pair()
        clock.advance(3.0)
        assert standby.try_promote() == 2

        class StaleEpochClient:
            """Answers like a pre-takeover primary that never noticed
            (the pathological case the monotone check exists for)."""

            def hub_op(self, op, **meta):
                return {"version": 0, "epoch": 1}

            def close(self):
                pass

        remote = RemoteOccupancyExchange(
            "", "r0",
            clients=[StaleEpochClient(), LocalHubClient(standby)],
            clock=clock, flush_client_id="r0-t",
        )
        # first contact lands on the stale client (epoch 1) — accepted
        # only until a higher epoch is seen
        remote.peers_version("r0")
        remote._active = 1
        assert remote.peers_version("r0") == standby.version  # epoch 2
        remote._active = 0  # force the stale endpoint first again
        assert remote.peers_version("r0") == standby.version
        assert remote._active == 1  # rotated off the stale answer

    def test_admit_conflict_never_rotates(self):
        """Semantic rejections surface immediately — a lost CAS race
        must not be retried against another endpoint (it would re-land
        the write the CAS rejected)."""
        clock, _lease, primary, standby = _ha_pair()
        calls = {"standby": 0}
        standby_client = LocalHubClient(standby)
        real = standby_client.hub_op

        def counting(op, **meta):
            calls["standby"] += 1
            return real(op, **meta)

        standby_client.hub_op = counting
        remote = RemoteOccupancyExchange(
            "", "r0",
            clients=[LocalHubClient(primary), standby_client],
            clock=clock, flush_client_id="r0-t",
        )
        primary.stage("r1", _row(pod="default/w"))  # moves the version
        with pytest.raises(AdmitConflict):
            remote.compare_and_stage("r0", _row(), 0)
        assert calls["standby"] == 0

    def test_failover_jitter_is_bounded_virtual_time(self):
        """Satellite #2's client-side twin: rotation waits are full
        jitter on the injectable clock — bounded by the doubling cap,
        non-negative, and virtual (no real sleep)."""
        clock = FakeClock()
        hub = OccupancyExchange()  # healthy second endpoint

        class DeadClient:
            def hub_op(self, op, **meta):
                raise ConnectionError("down")

            def close(self):
                pass

        remote = RemoteOccupancyExchange(
            "", "r0", clients=[DeadClient(), LocalHubClient(hub)],
            clock=clock, flush_client_id="r0-t",
        )
        t0 = clock.now()
        remote.peers_version("r0")
        waited = clock.now() - t0
        assert 0.0 <= waited < RemoteOccupancyExchange._FAILOVER_BACKOFF_S
        assert remote._active == 1

    def test_target_string_accepts_comma_list(self):
        remote = RemoteOccupancyExchange(
            "127.0.0.1:1,127.0.0.1:2", "r0", clock=FakeClock()
        )
        try:
            assert remote._targets == ["127.0.0.1:1", "127.0.0.1:2"]
            with pytest.raises(ExchangeUnreachable):
                remote.peers_version("r0")  # both dead: unreachable
        finally:
            remote.close()


class TestReviewHardening:
    def test_deposed_hub_cannot_repromote_until_caught_up(
        self, make_lease
    ):
        """Review-caught: a deposed old primary re-acquiring an
        expired lease at a HIGHER epoch while serving PRE-deposition
        state would regress the version counter behind an epoch the
        clients' monotone check must accept. Promotion stays refused
        until replication reaches lag 0 against the successor (or the
        operator overrides with allow_stale for the disaster case)."""
        clock = FakeClock()
        clock, _lease, a, b = _ha_pair(
            clock=clock, lease=make_lease(clock)
        )
        a.stage("r0", _row())
        StandbyReplicator(b, LocalHubClient(a)).poll()
        clock.advance(3.0)
        assert b.try_promote() == 2
        b.stage("r0", _row(pod="default/q"))  # B-era state A lacks
        assert a.heartbeat() is False  # A discovers its deposition
        clock.advance(3.0)  # B's lease expires unrenewed too
        assert a.try_promote() is None  # stale: refused
        rep = StandbyReplicator(a, LocalHubClient(b))
        rep.poll()  # catch up from the successor
        # a deposed hub re-joins via FULL SNAPSHOT (its own history
        # may have diverged; the successor's state REPLACES it)
        assert rep.snapshots_installed == 1
        assert a.version == b.version
        assert a.try_promote() == 3  # caught up: eligible again
        assert len(a.replica_rows("r0")[1]) == 2  # B-era row present

    def test_allow_stale_is_the_disaster_override(self, make_lease):
        clock = FakeClock()
        clock, _lease, a, b = _ha_pair(
            clock=clock, lease=make_lease(clock)
        )
        clock.advance(3.0)
        assert b.try_promote() == 2
        assert a.heartbeat() is False
        clock.advance(3.0)
        b.set_down(True)  # the successor is gone: nothing to catch
        # up from — the operator chooses stale state over no hub
        assert a.try_promote() is None
        assert a.try_promote(allow_stale=True) == 3

    def test_down_hub_answers_nothing_debug_state_bypasses(self):
        """Review-caught: degraded_replicas / journal_lines /
        pending_handoff_keys leaked through the set_down seam — a
        'killed' hub kept answering reads, so the blackout never
        exercised the degraded-read failure path a real process kill
        produces. debug_state is the harness's deliberate bypass."""
        hub = OccupancyExchange()
        hub.ship_journal("r0", ['{"a":1}'])
        hub.hand_off("r1", "default/h", 1)
        hub.set_down(True)
        for op in (
            lambda: hub.degraded_replicas(),
            lambda: hub.journal_lines(),
            lambda: hub.pending_handoff_keys(),
            lambda: hub.version,
        ):
            with pytest.raises(ExchangeUnreachable):
                op()
        state = hub.debug_state()
        assert state["pending_handoffs"] == {"default/h"}
        assert state["journal"] == ['{"a":1}']

    def test_failover_counter_ignores_renewals(self):
        """Review-caught: try_promote doubles as the serving loop's
        lease renewal — counting every same-holder re-grant made
        scheduler_hub_failover_total grow once per tick forever after
        the first failover."""
        from kubernetes_tpu import metrics

        clock, _lease, a, b = _ha_pair()
        clock.advance(3.0)
        before = metrics.hub_failover_total._value.get()
        assert b.try_promote() == 2  # the actual takeover
        for _ in range(5):
            clock.advance(1.0)
            assert b.try_promote() == 2  # renewals
        assert metrics.hub_failover_total._value.get() == before + 1

    def test_transient_self_expiry_without_standby_self_heals(
        self, make_lease
    ):
        """Review-caught: a lease expiring transiently (GC pause) with
        NO successor taking over must not wedge the only hub behind
        the needs_catchup gate — there is no successor timeline to
        diverge from, so the same-epoch re-grant heals without
        operator action."""
        clock = FakeClock()
        clock, _lease, a, _b = _ha_pair(
            clock=clock, lease=make_lease(clock)
        )
        a.stage("r0", _row())
        clock.advance(5.0)  # lease long expired; nobody acquired
        with pytest.raises(HubDeposed):
            a.stage("r0", _row(pod="default/q"))  # self-deposes
        assert a.role == "deposed" and a.needs_catchup
        assert a.try_promote() == 1  # same epoch: no takeover happened
        assert a.role == "primary" and not a.needs_catchup
        a.stage("r0", _row(pod="default/q"))  # serving again

    def test_replicator_normalizes_transport_errors(self):
        """Review-caught: a BulkClient source surfaces raw
        grpc.RpcError; poll()'s documented contract is
        ExchangeUnreachable."""
        from kubernetes_tpu.server.bulk import BulkClient

        standby = OccupancyExchange()
        rep = StandbyReplicator(
            standby, BulkClient("127.0.0.1:1", retries=0)
        )
        with pytest.raises(ExchangeUnreachable):
            rep.poll()

    def test_deposed_hub_degraded_flags_are_fenced(self):
        """Review-caught: degraded_replicas orders the fleet-wide
        handoff chain — a deposed hub's frozen flags must reject like
        peers_view, not silently serve stale routing state."""
        clock, _lease, a, b = _ha_pair()
        a.set_degraded("r0", True)
        clock.advance(3.0)
        assert b.try_promote() == 2
        with pytest.raises(HubDeposed):
            a.degraded_replicas()

    def test_deferred_retire_reissued_after_heal(self):
        """Review-caught: a retire() deferred by a mid-blackout
        unreachable hub was never retried — the dead peer's frozen
        publish stamp would age every survivor's staleness bound
        forever. maybe_resync re-issues it at the first reachable
        poll."""
        from kubernetes_tpu.fleet import FleetConfig
        from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
        from kubernetes_tpu.state.cluster import ClusterState

        clock = FakeClock()
        cluster = ClusterState(clock=clock)
        hub = OccupancyExchange(clock=clock)
        sched = Scheduler(
            cluster,
            SchedulerConfig(
                fleet=FleetConfig(
                    replica="r0", replicas=("r0", "r1"), exchange=hub
                )
            ),
            clock=clock,
        )
        hub.stage("r1", _row(pod="default/peer"))
        hub.set_down(True)
        # the membership transition observes r1 dead while the hub is
        # dark: the retire defers instead of crashing
        sched.fleet.set_alive(["r0"])
        assert "r1" in sched.fleet._pending_retires
        hub.set_down(False)
        sched.fleet.maybe_resync(sched)
        assert sched.fleet._pending_retires == set()
        assert hub.replica_rows("r1")[1] == ()  # rows retired
        assert "r1" not in hub._published_at  # stamp cleared


# -- config + debug surface ---------------------------------------------------


def test_config_hub_address_comma_list():
    from kubernetes_tpu.config.types import load

    cfg = load(
        {
            "fleet": {
                "replica": "r0",
                "hubAddress": "10.0.0.1:50051, 10.0.0.2:50051",
            }
        }
    )
    assert cfg.fleet.hub_address == "10.0.0.1:50051, 10.0.0.2:50051"
    with pytest.raises(ValueError):
        load({"fleet": {"replica": "r0", "hubAddress": "10.0.0.1:1,"}})
    with pytest.raises(ValueError):
        load({"fleet": {"replica": "r0", "hubAddress": "nocolon"}})


def test_scheduler_hub_status_debug_body():
    """Scheduler.hub_status is the GET /debug/hub body: role, epoch,
    cursors, plus the client-side view; None off-fleet."""
    from kubernetes_tpu.fleet import FleetConfig
    from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
    from kubernetes_tpu.state.cluster import ClusterState

    clock = FakeClock()
    cluster = ClusterState(clock=clock)
    solo = Scheduler(cluster, SchedulerConfig(), clock=clock)
    assert solo.hub_status() is None
    hub = OccupancyExchange(clock=clock)
    sched = Scheduler(
        cluster,
        SchedulerConfig(
            fleet=FleetConfig(replica="r0", exchange=hub)
        ),
        clock=clock,
    )
    status = sched.hub_status()
    assert status["role"] == "primary" and status["epoch"] == 1
    assert status["client"]["endpoints"] == ["in-process"]


# -- known-bad fixtures: every check_hub_failover clause ----------------------


class TestHubFailoverInvariantFixtures:
    GOOD = dict(
        promotions=1, epoch=2, deposed_write_rejections=1,
        flush_dedup_hits=1, stale_rejections=1, hub_journal_missing=0,
        old_primary_reads_ok=True,
    )

    def _run(self, **overrides):
        from kubernetes_tpu.sim.invariants import check_hub_failover

        violations = []
        check_hub_failover(0, violations, **{**self.GOOD, **overrides})
        return violations

    def test_clean_run_passes(self):
        assert self._run() == []

    @pytest.mark.parametrize(
        "overrides",
        [
            {"promotions": 0},
            {"promotions": 2},
            {"epoch": 3},
            {"deposed_write_rejections": 0},
            {"flush_dedup_hits": 0},
            {"stale_rejections": 0},
            {"hub_journal_missing": 3},
            {"old_primary_reads_ok": False},
        ],
    )
    def test_each_clause_fires(self, overrides):
        violations = self._run(**overrides)
        assert violations, f"clause never fired for {overrides}"
        assert all(v.invariant == "hub_failover" for v in violations)

    def test_dedup_clause_scoped_to_expectation(self):
        assert self._run(flush_dedup_hits=0, expect_dedup=False) == []


# -- sim acceptance -----------------------------------------------------------


def test_hub_failover_sim_heals_without_operator_action():
    """ISSUE 15 acceptance: a primary-hub kill mid-drive heals on its
    own — standby promotes at epoch 2, replicas re-attach, zero rows /
    handoffs / journal lines lost, zero double-applied flushes, the
    stale primary's writes 100% rejected — asserted by the run's
    invariants (constraint/overcommit/lost-pod/journal run every
    cycle; hub_failover clauses at the end)."""
    from kubernetes_tpu.sim.fleet import run_fleet_sim

    res = run_fleet_sim("hub_failover", seed=0, cycles=12)
    assert res.violations == []
    assert res.settled
    ha = res.summary["hub_ha"]
    assert ha["promotions"] == 1 and ha["epoch"] == 2
    assert ha["deposed_write_rejections"] >= 1
    assert ha["flush_dedup_hits"] >= 1
    assert ha["hub_journal_missing"] == 0
    assert ha["old_primary_reads_ok"] is True
    assert res.summary["stale_rejections"] >= 1  # blackout engaged


def test_hub_failover_sim_deterministic():
    from kubernetes_tpu.sim.fleet import run_fleet_sim

    a = run_fleet_sim("hub_failover", seed=3, cycles=12)
    b = run_fleet_sim("hub_failover", seed=3, cycles=12)
    assert a.journal_digests == b.journal_digests
    assert a.hub_journal_lines == b.hub_journal_lines
