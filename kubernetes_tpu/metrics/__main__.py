"""CLI: auto-generate the metrics reference from the MET001 registry.

    # render docs/METRICS.md from the registered series
    python -m kubernetes_tpu.metrics --doc

    # drift gate (the tier-1 test + CI use this): exit 1 when the
    # committed doc no longer matches the registry
    python -m kubernetes_tpu.metrics --check

The source of truth is ``kubernetes_tpu/metrics/__init__.py`` — the
same module the MET001 static-analysis pass resolves every
``metrics.<attr>`` reference against — so the committed reference can
never silently drift from what the code actually exports: adding or
renaming a series without regenerating the doc fails
``tests/test_metrics_doc.py``.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

HEADER = """\
# Metrics reference

Auto-generated from the registered series in
`kubernetes_tpu/metrics/__init__.py` (the MET001 registry) by
`python -m kubernetes_tpu.metrics --doc`. Do not edit by hand —
regenerate after adding or changing a series;
`tests/test_metrics_doc.py` asserts this file matches the registry.

| name | type | labels | help |
|---|---|---|---|
"""


def _rows() -> list[tuple[str, str, str, str]]:
    """(series name, type, labels, help) per registered metric, sorted
    by series name. Reads the live module objects, not the AST, so the
    doc reflects exactly what ``metrics.render()`` exposes."""
    from prometheus_client import Counter, Gauge, Histogram, Summary

    from kubernetes_tpu import metrics as m

    kinds = {
        Counter: "counter",
        Gauge: "gauge",
        Histogram: "histogram",
        Summary: "summary",
    }
    rows = []
    for attr in dir(m):
        obj = getattr(m, attr)
        kind = kinds.get(type(obj))
        if kind is None:
            continue
        name = obj._name
        if kind == "counter" and not name.endswith("_total"):
            # prometheus_client strips the _total suffix internally;
            # restore the exposition name dashboards key on
            exposed = name + "_total"
        else:
            exposed = name
        labels = ", ".join(obj._labelnames) if obj._labelnames else "-"
        help_text = " ".join(obj._documentation.split())
        rows.append((exposed, kind, labels, help_text))
    rows.sort()
    return rows


def render_doc() -> str:
    lines = [HEADER.rstrip("\n")]
    for name, kind, labels, help_text in _rows():
        help_md = help_text.replace("|", "\\|")
        lines.append(f"| `{name}` | {kind} | {labels} | {help_md} |")
    return "\n".join(lines) + "\n"


def doc_path() -> Path:
    return (
        Path(__file__).resolve().parents[2] / "docs" / "METRICS.md"
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.metrics",
        description="Metrics registry tools (doc generation + drift gate).",
    )
    parser.add_argument(
        "--doc", action="store_true",
        help="write docs/METRICS.md from the registered series",
    )
    parser.add_argument(
        "--check", action="store_true",
        help="exit 1 when docs/METRICS.md no longer matches the registry",
    )
    parser.add_argument(
        "--stdout", action="store_true",
        help="print the rendered doc instead of writing the file",
    )
    args = parser.parse_args(argv)
    doc = render_doc()
    if args.stdout:
        sys.stdout.write(doc)
        return 0
    path = doc_path()
    if args.check:
        committed = path.read_text() if path.exists() else ""
        if committed != doc:
            print(
                f"{path}: stale — regenerate with "
                "`python -m kubernetes_tpu.metrics --doc`",
                file=sys.stderr,
            )
            return 1
        print(f"{path}: matches the registry")
        return 0
    if args.doc:
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(doc)
        print(f"wrote {path} ({len(doc.splitlines())} lines)")
        return 0
    parser.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
