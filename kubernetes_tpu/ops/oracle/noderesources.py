"""NumPy/scalar oracle for the noderesources plugins — a direct transcription
of the reference semantics, used as ground truth by parity tests
(SURVEY.md §8.6: "the sanitizer that matters here").

Reference:
- Filter: pkg/scheduler/framework/plugins/noderesources/fit.go#fitsRequest
- LeastAllocated: noderesources/least_allocated.go#leastResourceScorer
  (integer arithmetic: (alloc-req)*100/alloc with truncating int64 division)
- MostAllocated: noderesources/most_allocated.go
- BalancedAllocation: noderesources/balanced_allocation.go
  #balancedResourceScorer (float64; |f0-f1|/2 for exactly 2 resources,
  population std otherwise; final int64 truncation)

The oracle works on plain dicts/objects — deliberately the dumbest possible
implementation, never vectorized, so it can't share bugs with the kernels.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Mapping, Sequence

from ...api.objects import RESOURCE_CPU, RESOURCE_MEMORY, Pod

MAX_NODE_SCORE = 100

# Default scoring resources/weights: noderesources/fit.go defaultResources
DEFAULT_RESOURCES = ({"name": RESOURCE_CPU, "weight": 1}, {"name": RESOURCE_MEMORY, "weight": 1})


@dataclass
class NodeState:
    """Scalar mirror of NodeInfo for the oracle scheduler."""

    name: str
    allocatable: dict[str, int]
    max_pods: int
    used: dict[str, int] = field(default_factory=dict)
    nonzero_used_cpu: int = 0
    nonzero_used_mem: int = 0
    pod_count: int = 0
    schedulable: bool = True

    def add_pod(self, pod: Pod) -> None:
        for k, v in pod.resource_request().items():
            self.used[k] = self.used.get(k, 0) + v
        nz_cpu, nz_mem = pod.non_zero_request()
        self.nonzero_used_cpu += nz_cpu
        self.nonzero_used_mem += nz_mem
        self.pod_count += 1


def fit_filter(pod: Pod, node: NodeState) -> list[str]:
    """Returns the list of insufficient resources (empty = fits).
    fit.go#fitsRequest."""
    failures: list[str] = []
    if node.pod_count + 1 > node.max_pods:
        failures.append("pods")
    req = pod.resource_request()
    # fast path in the reference: a pod requesting nothing only needs the
    # pod-count check
    for r, v in sorted(req.items()):
        if v == 0:
            continue
        if node.used.get(r, 0) + v > node.allocatable.get(r, 0):
            failures.append(r)
    return failures


def _allocatable_and_requested(pod: Pod, node: NodeState, resource: str) -> tuple[int, int]:
    """resource_allocation.go#calculateResourceAllocatableRequest: scoring
    uses NonZeroRequested for cpu/memory, plain Requested for extended."""
    nz_cpu, nz_mem = pod.non_zero_request()
    if resource == RESOURCE_CPU:
        return node.allocatable.get(resource, 0), node.nonzero_used_cpu + nz_cpu
    if resource == RESOURCE_MEMORY:
        return node.allocatable.get(resource, 0), node.nonzero_used_mem + nz_mem
    return (
        node.allocatable.get(resource, 0),
        node.used.get(resource, 0) + pod.resource_request().get(resource, 0),
    )


def least_allocated_score(
    pod: Pod, node: NodeState, resources: Sequence[Mapping] = DEFAULT_RESOURCES
) -> int:
    """least_allocated.go#leastResourceScorer — all-int64 arithmetic."""
    node_score = 0
    weight_sum = 0
    for res in resources:
        alloc, requested = _allocatable_and_requested(pod, node, res["name"])
        if alloc == 0:
            score = 0
        elif requested > alloc:
            score = 0
        else:
            score = (alloc - requested) * MAX_NODE_SCORE // alloc
        node_score += score * res["weight"]
        weight_sum += res["weight"]
    if weight_sum == 0:
        return 0
    return node_score // weight_sum


def most_allocated_score(
    pod: Pod, node: NodeState, resources: Sequence[Mapping] = DEFAULT_RESOURCES
) -> int:
    """most_allocated.go#mostResourceScorer."""
    node_score = 0
    weight_sum = 0
    for res in resources:
        alloc, requested = _allocatable_and_requested(pod, node, res["name"])
        if alloc == 0 or requested > alloc:
            score = 0
        else:
            score = requested * MAX_NODE_SCORE // alloc
        node_score += score * res["weight"]
        weight_sum += res["weight"]
    if weight_sum == 0:
        return 0
    return node_score // weight_sum


def requested_to_capacity_ratio_score(
    pod: Pod,
    node: NodeState,
    shape: Sequence[tuple[int, int]],
    resources: Sequence[Mapping] = DEFAULT_RESOURCES,
) -> int:
    """requested_to_capacity_ratio.go: piecewise-linear over utilization.

    shape: [(utilization_0..100, score_0..10)] ascending; scores scaled by
    10 to MaxNodeScore internally (maxUtilization=100, maxScore via
    helper.BuildBrokerFunction equivalent).
    """
    node_score = 0
    weight_sum = 0
    for res in resources:
        alloc, requested = _allocatable_and_requested(pod, node, res["name"])
        if alloc == 0:
            score = 0
        else:
            if requested > alloc:
                utilization = 100
            else:
                utilization = requested * 100 // alloc
            score = _piecewise(shape, utilization) * (MAX_NODE_SCORE // 10)
        node_score += score * res["weight"]
        weight_sum += res["weight"]
    if weight_sum == 0:
        return 0
    return node_score // weight_sum


def _trunc_div(a: int, b: int) -> int:
    """Go int64 division truncates toward zero; Python // floors. They differ
    exactly when the quotient is negative and inexact."""
    q = abs(a) // abs(b)
    return q if (a >= 0) == (b >= 0) else -q


def _piecewise(shape: Sequence[tuple[int, int]], x: int) -> int:
    """helper/shape_score.go#buildBrokerFunction: linear interpolation between
    shape points, Go-truncating integer math (decreasing segments produce
    negative numerators — floor division would score one point low)."""
    if x < shape[0][0]:
        return shape[0][1]
    for i in range(1, len(shape)):
        if x < shape[i][0]:
            x0, y0 = shape[i - 1]
            x1, y1 = shape[i]
            return y0 + _trunc_div((y1 - y0) * (x - x0), x1 - x0)
    return shape[-1][1]


def balanced_allocation_score(
    pod: Pod,
    node: NodeState,
    resources: Sequence[str] = (RESOURCE_CPU, RESOURCE_MEMORY),
) -> int:
    """balanced_allocation.go#balancedResourceScorer — float64 math."""
    fractions: list[float] = []
    for r in resources:
        alloc, requested = _allocatable_and_requested(pod, node, r)
        if alloc == 0:
            fraction = 1.0  # guard: balanced_allocation skips nodes w/o resource
        else:
            fraction = requested / alloc
        if fraction > 1.0:
            fraction = 1.0
        fractions.append(fraction)
    if len(fractions) == 2:
        std = abs(fractions[0] - fractions[1]) / 2.0
    elif len(fractions) > 2:
        mean = sum(fractions) / len(fractions)
        var = sum((f - mean) ** 2 for f in fractions) / len(fractions)
        std = math.sqrt(var)
    else:
        std = 0.0
    return int((1.0 - std) * MAX_NODE_SCORE)
