"""Structured logging for the scheduler and the extender server.

The reference component logs through klog with `--logging-format=json`
as the structured option (component-base/logs); this is the stdlib
analog: one ``setup()`` call configures the ``kubernetes_tpu`` logger
tree with either a human ``text`` formatter or a ``json`` formatter
that emits one JSON object per line.

The JSON formatter carries **correlation ids**: any extra attributes a
log call passes (``extra={"step": 12, "pod": "ns/name"}``) serialize as
top-level fields — the scheduler passes its span/batch id (``step``,
the ``Scheduler._trace_step`` counter shared with obs spans and the
jax-profiler step annotation) so log lines join against the span stream
and the decision journal on the same key.

No global side effects at import: ``setup()`` is called by ``cli.py
serve --log-format ...`` (and tests); library users who never call it
keep logging's default behavior (messages propagate to the root
logger / stay silent without handlers).
"""

from __future__ import annotations

import json
import logging
import sys

# LogRecord attributes that are plumbing, not payload — anything else
# found on a record came from ``extra=`` and is emitted as a field
_RESERVED = frozenset(
    {
        "name", "msg", "args", "levelname", "levelno", "pathname",
        "filename", "module", "exc_info", "exc_text", "stack_info",
        "lineno", "funcName", "created", "msecs", "relativeCreated",
        "thread", "threadName", "processName", "process", "taskName",
        "message", "asctime",
    }
)

ROOT_LOGGER = "kubernetes_tpu"


class JsonLineFormatter(logging.Formatter):
    """One JSON object per line: ts, level, logger, msg, plus every
    ``extra=`` attribute (span/batch ids ride here)."""

    def format(self, record: logging.LogRecord) -> str:
        out: dict = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        for key, value in record.__dict__.items():
            if key in _RESERVED or key.startswith("_"):
                continue
            try:
                json.dumps(value)
            except (TypeError, ValueError):
                value = repr(value)
            out[key] = value
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, sort_keys=True, separators=(",", ":"))


class TextFormatter(logging.Formatter):
    """klog-ish single-line text with the extras appended as k=v."""

    def __init__(self) -> None:
        super().__init__("%(asctime)s %(levelname)s %(name)s: %(message)s")

    def format(self, record: logging.LogRecord) -> str:
        base = super().format(record)
        extras = " ".join(
            f"{k}={record.__dict__[k]!r}"
            for k in sorted(record.__dict__)
            if k not in _RESERVED and not k.startswith("_")
        )
        return f"{base} {extras}" if extras else base


def setup(
    log_format: str = "text",
    level: int = logging.INFO,
    stream=None,
    logger_name: str = ROOT_LOGGER,
) -> logging.Logger:
    """Configure the package logger tree. Idempotent: re-running
    replaces the previously-installed handler instead of stacking a
    duplicate (serve retries / tests)."""
    if log_format not in ("text", "json"):
        raise ValueError(f"unknown log format {log_format!r}")
    logger = logging.getLogger(logger_name)
    logger.setLevel(level)
    formatter: logging.Formatter = (
        JsonLineFormatter() if log_format == "json" else TextFormatter()
    )
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.set_name(f"{logger_name}.structured")
    handler.setFormatter(formatter)
    for h in list(logger.handlers):
        if h.get_name() == handler.get_name():
            logger.removeHandler(h)
    logger.addHandler(handler)
    logger.propagate = False
    return logger
