"""FENCE001 — epoch/role fence discipline (project-wide).

PR 15's hub HA contract: after a failover, a deposed primary must
never serve replica-facing traffic from its (possibly diverged)
replicated state. The enforcement pattern is a *fence check* — the
``OccupancyExchange._ensure_primary_locked`` idiom: verify role and
lease epoch, raise ``HubDeposed`` otherwise — run at the top of every
method that touches replicated state.

Review passes hand-caught violations of this in three consecutive PRs;
this pass makes the contract structural:

- ``# ktpu: replicated`` trailing an attribute assignment in
  ``__init__`` registers hub-replicated state;
- ``# ktpu: fence-check`` marks the checker method(s);
- every OTHER method of that class touching a replicated attribute
  must *reach* a fence check — directly or through helpers, resolved
  over the cross-module call graph, so wrapping the checks in an
  ``_admit_gate()`` helper (or inheriting them from a base class)
  still satisfies the rule;
- ``# ktpu: fenced-by-caller`` exempts ``_locked``-suffix helpers
  whose public callers already ran the checks;
- ``# ktpu: fence-exempt(reason)`` records the deliberate bypasses —
  the replication apply path (a standby MUST write unfenced), debug
  and post-mortem surfaces — with a mandatory reason; a reasonless
  exemption is itself a finding.
"""

from __future__ import annotations

import ast

from ..callgraph import own_nodes
from ..core import AnalysisContext, Finding
from ..project import ProjectGraph, ProjectPass

# receiver-method calls that mutate a container in place: touching
# replicated state through these is a WRITE for the message text
_MUTATORS = {
    "append", "add", "pop", "popleft", "remove", "discard", "clear",
    "extend", "update", "setdefault", "insert",
}


class FencePass(ProjectPass):
    rule = "FENCE001"
    title = "epoch/role fence discipline"

    def run_project(
        self, project: ProjectGraph, ctx: AnalysisContext
    ) -> list:
        checks = set()
        for rel in sorted(project.graphs):
            graph = project.graphs[rel]
            m = project.modules[rel]
            for qual, finfo in graph.functions.items():
                if m.is_fence_check(finfo.node):
                    checks.add((rel, qual))
        satisfied = project.reaches(checks) if checks else set()

        findings: list[Finding] = []
        for key in sorted(project.classes):
            cinfo = project.classes[key]
            if not cinfo.replicated:
                continue
            rel = cinfo.rel
            m = project.modules[rel]
            graph = project.graphs[rel]
            for qual in sorted(graph.functions):
                finfo = graph.functions[qual]
                if finfo.cls != cinfo.name or finfo.parent:
                    continue
                name = finfo.node.name
                if name == "__init__":
                    continue  # construction precedes any role
                if m.is_fence_check(finfo.node):
                    continue
                if m.is_fenced_by_caller(finfo.node):
                    continue
                exempt = m.fence_exempt(finfo.node)
                if exempt is not None:
                    if not exempt:
                        findings.append(
                            Finding(
                                rule=self.rule,
                                path=m.path,
                                line=finfo.node.lineno,
                                message=(
                                    f"fence-exempt on '{qual}' has no "
                                    "reason"
                                ),
                                hint=(
                                    "write '# ktpu: fence-exempt(<why "
                                    "this surface may skip the fence>)'"
                                ),
                            )
                        )
                    continue
                if (rel, qual) in satisfied:
                    continue
                touch = self._first_touch(finfo.node, cinfo.replicated)
                if touch is None:
                    continue
                line, attr, wrote = touch
                verb = "writes" if wrote else "reads"
                findings.append(
                    Finding(
                        rule=self.rule,
                        path=m.path,
                        line=line,
                        message=(
                            f"'{qual}' {verb} replicated state "
                            f"'self.{attr}' without a role/epoch fence "
                            "check on any path"
                        ),
                        hint=(
                            "call the fence-check helper first (e.g. "
                            "_ensure_primary_locked), or annotate the "
                            "method: fenced-by-caller for _locked "
                            "helpers, fence-exempt(reason) for the "
                            "replication/debug surfaces"
                        ),
                    )
                )
        return findings

    def _first_touch(self, fnode, replicated) -> tuple | None:
        """(line, attr, wrote) of the first replicated-state access in
        the method's own statements; writes win over reads on a line."""
        best: tuple | None = None
        for node in own_nodes(fnode):
            hit = None
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in replicated
            ):
                wrote = isinstance(node.ctx, (ast.Store, ast.Del))
                hit = (node.lineno, node.attr, wrote)
            elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, (ast.Store, ast.Del)
            ):
                # self._rows[k] = v stores through the Subscript; the
                # inner Attribute is only a Load
                base = node.value
                while isinstance(base, ast.Subscript):
                    base = base.value
                if (
                    isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"
                    and base.attr in replicated
                ):
                    hit = (node.lineno, base.attr, True)
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS
            ):
                base = node.func.value
                # self._journal.append(...) / self._rows[k].pop(...)
                while isinstance(base, ast.Subscript):
                    base = base.value
                if (
                    isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"
                    and base.attr in replicated
                ):
                    hit = (node.lineno, base.attr, True)
            if hit is not None and (
                best is None
                or hit[0] < best[0]
                or (hit[0] == best[0] and hit[2] and not best[2])
            ):
                best = hit
        return best
