"""Scheduler-extender webhook server — the delivery boundary of SURVEY.md
§8.2: a kube-scheduler configured with this extender sends its
filter/prioritize/preempt/bind verbs here and the TPU framework answers.

Wire shapes are byte-compatible with
staging/src/k8s.io/kube-scheduler/extender/v1/types.go:
- POST /filter     ExtenderArgs{pod, nodes|nodenames} ->
                   ExtenderFilterResult{nodes|nodenames, failedNodes,
                   failedAndUnresolvableNodes, error}
- POST /prioritize ExtenderArgs -> HostPriorityList [{host, score 0..10}]
                   (MaxExtenderPriority; the caller multiplies by the
                   extender weight and rescales vs MaxNodeScore)
- POST /preempt    ExtenderPreemptionArgs{pod, nodeNameToVictims|
                   nodeNameToMetaVictims} -> ExtenderPreemptionResult
                   {nodeNameToMetaVictims: {node: {pods: [{uid}],
                   numPDBViolations}}}
- POST /bind       ExtenderBindingArgs{podName, podNamespace, podUID, node}
                   -> ExtenderBindingResult{error}
- GET  /metrics    prometheus exposition (reference names)
- GET  /healthz /livez /readyz

Handlers are pure dict->dict functions (golden-JSON testable, SURVEY §8.6)
wrapped by a thin aiohttp app. The server holds a ClusterState for the pod
side of NodeInfo (an extender keeps its own watch-fed view in the reference
deployment; ExtenderArgs only carries Node objects). nodeCacheCapable mode
accepts/returns bare node names resolved against that state.
"""

from __future__ import annotations

from typing import Mapping

from ..api.objects import Node, Pod
from ..ops.oracle import preemption as opr
from ..ops.oracle.profile import FullOracle, make_oracle_nodes
from ..state.cluster import ApiError, ClusterState
from .. import metrics

MAX_EXTENDER_PRIORITY = 10


class ExtenderCore:
    """Verb implementations as pure dict->dict handlers."""

    def __init__(self, cluster: ClusterState, node_cache_capable: bool = False):
        self.cluster = cluster
        self.node_cache_capable = node_cache_capable

    # -- helpers --

    def _pods_by_node(self) -> dict[str, list[Pod]]:
        out: dict[str, list[Pod]] = {}
        for p in self.cluster.list_pods():
            if p.node_name:
                out.setdefault(p.node_name, []).append(p)
        return out

    def _resolve_nodes(self, args: Mapping) -> tuple[list[Node], bool, list[str]]:
        """(nodes, by_name, unknown_names): honor nodes vs nodenames
        (nodeCacheCapable). Unknown names fail per-node, not per-request —
        the extender's watch-fed view may lag the scheduler's."""
        if args.get("nodenames") is not None:
            nodes, unknown = [], []
            for n in args["nodenames"]:
                try:
                    nodes.append(self.cluster.get_node(n))
                except ApiError:
                    unknown.append(n)
            return nodes, True, unknown
        items = (args.get("nodes") or {}).get("items") or []
        return [Node.from_dict(d) for d in items], False, []

    def _oracle(self, nodes: list[Node]) -> FullOracle:
        pods_by_node = self._pods_by_node()
        return FullOracle(make_oracle_nodes(nodes, pods_by_node))

    # -- verbs --

    def filter(self, args: Mapping) -> dict:
        try:
            pod = Pod.from_dict(args["pod"])
            nodes, by_name, unknown = self._resolve_nodes(args)
        except KeyError as e:
            return {"error": str(e)}
        oracle = self._oracle(nodes)
        feasible = set(oracle.feasible_set(pod))
        passed: list[Node] = []
        failed: dict[str, str] = {}
        for i, on in enumerate(oracle.nodes):
            if i in feasible:
                passed.append(on.node)
            else:
                failed[on.node.name] = "node did not satisfy filters"
        unresolvable = {n: "node not found" for n in unknown}
        out: dict = {
            "failedNodes": failed,
            "failedAndUnresolvableNodes": unresolvable,
        }
        if by_name:
            out["nodenames"] = [n.name for n in passed]
        else:
            out["nodes"] = {"items": [n.to_dict() for n in passed]}
        return out

    def prioritize(self, args: Mapping) -> list[dict]:
        """HostPriorityList: full-pipeline totals rescaled into the 0..10
        extender score range (MaxExtenderPriority). Decode errors raise —
        the HTTP layer turns them into a 500 so the caller sees the failure
        instead of silently dropping this extender's scores."""
        pod = Pod.from_dict(args["pod"])
        nodes, _, _ = self._resolve_nodes(args)
        oracle = self._oracle(nodes)
        feasible = oracle.feasible_set(pod)
        scores: dict[str, int] = {}
        if feasible:
            totals = oracle.score_totals(pod, feasible)
            mx = max(totals.values(), default=0)
            for i, t in totals.items():
                name = oracle.nodes[i].node.name
                scores[name] = (
                    MAX_EXTENDER_PRIORITY * t // mx if mx > 0 else 0
                )
        return [
            {"host": n.name, "score": scores.get(n.name, 0)} for n in nodes
        ]

    def preempt(self, args: Mapping) -> dict:
        try:
            pod = Pod.from_dict(args["pod"])
        except KeyError as e:
            return {"error": str(e)}
        from ..ops.oracle import plugins as opl

        pods_by_node = self._pods_by_node()
        pdbs = self.cluster.list_pdbs()
        candidates = args.get("nodeNameToVictims") or args.get(
            "nodeNameToMetaVictims"
        ) or {}
        out: dict[str, dict] = {}
        for node_name in candidates:
            try:
                node = self.cluster.get_node(node_name)
            except ApiError:
                continue
            # static gate: preemption cannot resolve taints/affinity/
            # nodeName/unschedulable failures (select_victims_on_node is
            # fit-only; see its docstring) — never offer such nodes
            if not (
                opl.node_name_filter(pod, node)
                and opl.node_unschedulable_filter(pod, node)
                and opl.taint_toleration_filter(pod, node)
                and opl.node_affinity_filter(pod, node)
            ):
                continue
            nv = opr.select_victims_on_node(
                pod,
                node.allocatable,
                node.allowed_pod_number,
                pods_by_node.get(node_name, []),
                pdbs,
            )
            if nv is None:
                continue  # node dropped from the result = not a candidate
            if self.node_cache_capable:
                out[node_name] = {
                    "pods": [{"uid": v.uid or v.key} for v in nv.victims],
                    "numPDBViolations": nv.num_violating,
                }
            else:
                out[node_name] = {
                    "pods": [v.to_dict() for v in nv.victims],
                    "numPDBViolations": nv.num_violating,
                }
        # extender.go#ProcessPreemption reads NodeNameToMetaVictims only for
        # nodeCacheCapable extenders, NodeNameToVictims (full pods) otherwise
        if self.node_cache_capable:
            return {"nodeNameToMetaVictims": out}
        return {"nodeNameToVictims": out}

    def bind(self, args: Mapping) -> dict:
        try:
            self.cluster.bind(
                args.get("podNamespace") or "default",
                args["podName"],
                args["node"],
            )
            return {}
        except (KeyError, ApiError) as e:
            return {"error": str(e)}


def make_app(core: ExtenderCore):
    """aiohttp application wiring the pure handlers to the wire."""
    from aiohttp import web

    async def _json(request):
        return await request.json()

    async def filter_(request):
        return web.json_response(core.filter(await _json(request)))

    async def prioritize(request):
        try:
            return web.json_response(core.prioritize(await _json(request)))
        except Exception as e:
            return web.json_response({"error": str(e)}, status=500)

    async def preempt(request):
        return web.json_response(core.preempt(await _json(request)))

    async def bind(request):
        return web.json_response(core.bind(await _json(request)))

    async def metrics_(request):
        return web.Response(
            body=metrics.render(), content_type="text/plain"
        )

    async def healthz(request):
        return web.Response(text="ok")

    app = web.Application()
    app.router.add_post("/filter", filter_)
    app.router.add_post("/prioritize", prioritize)
    app.router.add_post("/preempt", preempt)
    app.router.add_post("/bind", bind)
    app.router.add_get("/metrics", metrics_)
    for route in ("/healthz", "/livez", "/readyz"):
        app.router.add_get(route, healthz)
    return app


def run_server(
    cluster: ClusterState,
    host: str = "127.0.0.1",
    port: int = 10259,
    node_cache_capable: bool = False,
) -> None:
    """Blocking server entry (the cmd/kube-scheduler#Run analog serves
    healthz+metrics on 10259)."""
    from aiohttp import web

    app = make_app(ExtenderCore(cluster, node_cache_capable))
    web.run_app(app, host=host, port=port)
