"""Scalar oracle for the volume plugin family ([BOUNDARY], SURVEY.md §3.2):

- volumebinding (static F-stage of volumebinding/volume_binding.go#Filter):
  per PVC of the pod:
    * bound claim (volumeName set): the PV must exist and its zone labels /
      nodeAffinity must admit the node;
    * unbound + WaitForFirstConsumer class: defer — passes Filter (binding
      happens at Reserve/PreBind, out of static scope);
    * unbound immediate class: some AVAILABLE PV must match (class, size,
      access mode) AND admit the node (find_matching_pv);
  dynamic provisioning is stubbed: no matching PV and not WFFC => fail.
- volumezone (volumezone/volume_zone.go): the zone-label check above.
- volumerestrictions (volumerestrictions/volume_restrictions.go): a
  ReadWriteOnce PV already attached on node m pins every other pod using
  the same claim to m (GCE-PD/EBS single-attach semantics).
- nodevolumelimits (nodevolumelimits/csi.go): count of CSI volumes (per
  driver) on the node + the pod's new ones must stay within the node's
  attachable limit, read from allocatable "attachable-volumes-csi-<driver>".

The VolumeContext aggregates what the reference's informers/CSINode objects
provide; the tensorizer compiles the same checks into the per-class static
mask (volumerestrictions contributes per-node state like ports).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Sequence

from ...api.objects import (
    ACCESS_RWO,
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
)


@dataclass
class VolumeContext:
    pvs: dict[str, PersistentVolume] = field(default_factory=dict)
    pvcs: dict[str, PersistentVolumeClaim] = field(default_factory=dict)
    # pv name -> node name currently holding an attached RWO claimant
    rwo_attached: dict[str, str] = field(default_factory=dict)
    # node -> csi driver -> attached UNIQUE volume names (upstream
    # nodevolumelimits counts distinct volume handles: two pods sharing one
    # PV consume ONE attachment slot, csi.go#filterAttachableVolumes)
    node_csi_volumes: dict[str, dict[str, set]] = field(default_factory=dict)

    def csi_count(self, node_name: str, driver: str) -> int:
        return len(self.node_csi_volumes.get(node_name, {}).get(driver, ()))

    @staticmethod
    def build(
        pvs: Sequence[PersistentVolume],
        pvcs: Sequence[PersistentVolumeClaim],
        pods_by_node: Mapping[str, Sequence[Pod]],
    ) -> "VolumeContext":
        ctx = VolumeContext(
            pvs={pv.name: pv for pv in pvs},
            pvcs={pvc.key: pvc for pvc in pvcs},
        )
        for node_name, pods in pods_by_node.items():
            for pod in pods:
                for claim in pod.pvc_names:
                    pvc = ctx.pvcs.get(f"{pod.namespace}/{claim}")
                    if pvc is None or not pvc.volume_name:
                        continue
                    pv = ctx.pvs.get(pvc.volume_name)
                    if pv is None:
                        continue
                    if ACCESS_RWO in pv.access_modes:
                        ctx.rwo_attached[pv.name] = node_name
                    if pv.csi_driver:
                        drv = ctx.node_csi_volumes.setdefault(node_name, {})
                        drv.setdefault(pv.csi_driver, set()).add(pv.name)
        return ctx


def find_matching_pv(
    ctx: VolumeContext, pvc: PersistentVolumeClaim, node: Node
) -> PersistentVolume | None:
    """volumebinding binder.go#findMatchingVolume, static slice: available,
    class matches, big enough, access mode present, admits the node."""
    best: PersistentVolume | None = None
    for pv in ctx.pvs.values():
        if pv.claim_ref and pv.claim_ref != pvc.key:
            continue
        if pv.storage_class != pvc.storage_class:
            continue
        if pv.capacity_bytes < pvc.request_bytes:
            continue
        if not set(pvc.access_modes) <= set(pv.access_modes):
            continue
        if not pv.matches_node(node):
            continue
        # smallest adequate volume wins (binder's preference)
        if best is None or pv.capacity_bytes < best.capacity_bytes:
            best = pv
    return best


def csi_limit_key(driver: str) -> str:
    return f"attachable-volumes-csi-{driver}"


def volume_filter(pod: Pod, node: Node, ctx: VolumeContext) -> bool:
    """All four volume plugins' Filter stages, fused."""
    new_csi: dict[str, set] = {}  # driver -> new unique volume names
    for claim in pod.pvc_names:
        pvc = ctx.pvcs.get(f"{pod.namespace}/{claim}")
        if pvc is None:
            return False  # missing claim: UnschedulableAndUnresolvable
        if pvc.volume_name:
            pv = ctx.pvs.get(pvc.volume_name)
            if pv is None:
                return False
            # volumezone + PV nodeAffinity
            if not pv.matches_node(node):
                return False
            # volumerestrictions: RWO single-attach follows the holder
            holder = ctx.rwo_attached.get(pv.name)
            if (
                holder is not None
                and holder != node.name
                and ACCESS_RWO in pv.access_modes
            ):
                return False
            if pv.csi_driver:
                new_csi.setdefault(pv.csi_driver, set()).add(pv.name)
        elif pvc.wait_for_first_consumer:
            continue  # defer to Reserve/PreBind
        else:
            pv = find_matching_pv(ctx, pvc, node)
            if pv is None:
                return False  # no static match, no dynamic provisioning
            if pv.csi_driver:
                new_csi.setdefault(pv.csi_driver, set()).add(pv.name)

    # nodevolumelimits: unique existing + unique NEW volumes per driver must
    # stay within the allocatable limit; a volume already attached on this
    # node consumes no extra slot (csi.go counts distinct volume handles)
    if new_csi:
        attached = ctx.node_csi_volumes.get(node.name, {})
        for driver, names in new_csi.items():
            limit = node.allocatable.get(csi_limit_key(driver))
            if limit is None:
                continue  # no limit advertised
            have = attached.get(driver, set())
            if len(have | names) > limit:
                return False
    return True
