"""Scalar oracle for PodTopologySpread (Filter + Score).

Transcription of pkg/scheduler/framework/plugins/podtopologyspread/
{common,filtering,scoring}.go (SURVEY.md §3.2). Because the reference mount
is empty, formulas follow upstream from domain knowledge; the testable
invariant is kernel ≡ this oracle. Key semantics:

Filter (whenUnsatisfiable=DoNotSchedule constraints):
- effective selector = labelSelector + matchLabelKeys (values taken from the
  incoming pod's own labels, ANDed in as In-requirements).
- counting eligibility (common.go#calPreFilterState): a node is counted iff
  it carries ALL hard-constraint topology keys, passes the pod's
  nodeSelector/required node affinity when nodeAffinityPolicy=Honor
  (default), and its NoSchedule/NoExecute taints are tolerated when
  nodeTaintsPolicy=Honor (default Ignore).
- matchNum(v) = #existing pods (same namespace) matching the selector on
  counted nodes with topology value v.
- minMatchNum = min over registered domains (filtering.go#minMatchNum);
  empty -> +inf (constraint passes); minDomains > #domains -> 0.
- node fails a constraint iff it lacks the key
  (UnschedulableAndUnresolvable) or matchNum(v)+selfMatch-minMatchNum >
  maxSkew, selfMatch = selector matches the incoming pod's own labels.

Score (ScheduleAnyway constraints; scoring.go):
- counting eligibility: node has ALL soft keys + nodeAffinityPolicy (Honor)
  + nodeTaintsPolicy (default Ignore).
- per feasible node: Σ_c scoreForCount = cnt_c·log(size_c+2) + (maxSkew-1),
  where cnt_c = domain count for the node's value (hostname topology: count
  on the node itself), size_c = #registered domains (hostname: #feasible
  nodes). Nodes missing any soft key are "ignored" (score 0).
- NormalizeScore: ignored -> 0; maxScore==0 -> MaxNodeScore; else
  100*(max+min-score)/max (ints after math.Round of the float sum).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Mapping, Sequence

from ...api.labels import IN, Requirement, Selector
from ...api.objects import Node, Pod, TopologySpreadConstraint
from .plugins import taint_toleration_filter
from .plugins import node_affinity_filter

MAX_NODE_SCORE = 100
HOSTNAME_KEY = "kubernetes.io/hostname"


@dataclass(frozen=True)
class EffectiveConstraint:
    topology_key: str
    max_skew: int
    selector: Selector | None  # None matches nothing
    min_domains: int | None
    node_affinity_policy: str  # Honor | Ignore
    node_taints_policy: str  # Honor | Ignore


# defaults.go#systemDefaultConstraints: soft zone/hostname spreading applied
# when defaultingType=System and the pod declares no constraints of its own
SYSTEM_DEFAULT_CONSTRAINTS = (
    ("topology.kubernetes.io/zone", 3),
    ("kubernetes.io/hostname", 5),
)


def default_selector(pod: Pod, services) -> Selector | None:
    """helper/spread.go#DefaultSelector restricted to Services (RS/SS owner
    lookup is [CONTEXT]): union of matchLabels of every service selecting
    the pod; None when no service matches (upstream: empty selector =>
    buildDefaultConstraints returns nothing)."""
    merged: dict = {}
    found = False
    for svc in services or ():
        if svc.selects(pod):
            merged.update(svc.selector)
            found = True
    if not found:
        return None
    from ...api.labels import selector_from_match_labels

    return selector_from_match_labels(merged)


def default_selector_key(pod: Pod, services) -> tuple | None:
    """Canonical identity of the pod's service-derived default selector —
    pods with different keys must not share a scheduling class (their
    System default constraints differ). None = no service selects the pod."""
    merged: dict = {}
    found = False
    for svc in services or ():
        if svc.selects(pod):
            merged.update(svc.selector)
            found = True
    if not found:
        return None
    return (pod.namespace, tuple(sorted(merged.items())))


def system_default_constraints(pod: Pod, services) -> list[EffectiveConstraint]:
    """common.go#buildDefaultConstraints for defaultingType=System: two soft
    constraints (zone maxSkew 3, hostname maxSkew 5) with the service-derived
    selector; empty when the pod has its own constraints or no service
    selects it."""
    if pod.topology_spread_constraints:
        return []
    sel = default_selector(pod, services)
    if sel is None:
        return []
    return [
        EffectiveConstraint(
            topology_key=key,
            max_skew=skew,
            selector=sel,
            min_domains=None,
            node_affinity_policy="Honor",
            node_taints_policy="Ignore",
        )
        for key, skew in SYSTEM_DEFAULT_CONSTRAINTS
    ]


def effective_constraints(
    pod: Pod, hard: bool, defaults: Sequence[EffectiveConstraint] = ()
) -> list[EffectiveConstraint]:
    """``defaults`` (from system_default_constraints) apply only when the
    pod declares no constraints; system defaults are ScheduleAnyway, so the
    hard path never sees them."""
    if not pod.topology_spread_constraints:
        return [] if hard else list(defaults)
    want = "DoNotSchedule" if hard else "ScheduleAnyway"
    out = []
    for c in pod.topology_spread_constraints:
        if c.when_unsatisfiable != want:
            continue
        sel = c.label_selector
        if c.match_label_keys and sel is not None:
            extra = tuple(
                Requirement(k, IN, (pod.labels[k],))
                for k in c.match_label_keys
                if k in pod.labels
            )
            sel = Selector(sel.requirements + extra, sel.match_labels)
        out.append(
            EffectiveConstraint(
                topology_key=c.topology_key,
                max_skew=c.max_skew,
                selector=sel,
                min_domains=c.min_domains,
                node_affinity_policy=c.node_affinity_policy,
                node_taints_policy=c.node_taints_policy,
            )
        )
    return out


def _sel_matches(sel: Selector | None, labels: Mapping[str, str]) -> bool:
    return sel is not None and sel.matches(labels)


def _node_counted(
    pod: Pod,
    node: Node,
    constraints: Sequence[EffectiveConstraint],
) -> bool:
    """common.go#calPreFilterState node eligibility for domain counting."""
    if any(c.topology_key not in node.labels for c in constraints):
        return False
    # policies are per-constraint in the API but upstream evaluates them
    # per-node against the pod once (all default constraints share policies);
    # honor a policy if ANY constraint requests it
    if any(c.node_affinity_policy == "Honor" for c in constraints):
        if not node_affinity_filter(pod, node):
            return False
    if any(c.node_taints_policy == "Honor" for c in constraints):
        if not taint_toleration_filter(pod, node):
            return False
    return True


def _domain_counts(
    pod: Pod,
    constraint: EffectiveConstraint,
    counted_nodes: Sequence[tuple[Node, Sequence[Pod]]],
) -> dict[str, int]:
    """topology value -> #matching existing pods over counted nodes."""
    counts: dict[str, int] = {}
    for node, pods in counted_nodes:
        v = node.labels.get(constraint.topology_key)
        if v is None:
            continue
        counts.setdefault(v, 0)
        for p in pods:
            if p.namespace == pod.namespace and _sel_matches(
                constraint.selector, p.labels
            ):
                counts[v] += 1
    return counts


@dataclass
class SpreadFilterState:
    """Pod-level precomputation (filtering.go#preFilterState): domain counts,
    global minimum, and selfMatch per hard constraint — built ONCE per pod,
    then checked per candidate node in O(#constraints)."""

    constraints: list[EffectiveConstraint]
    counts: list[dict[str, int]]  # per constraint: domain value -> matchNum
    min_match: list[int | None]  # None = empty domain set (passes)
    self_match: list[int]

    def check(self, node: Node) -> bool:
        for c, counts, mn, sm in zip(
            self.constraints, self.counts, self.min_match, self.self_match
        ):
            v = node.labels.get(c.topology_key)
            if v is None:
                return False  # UnschedulableAndUnresolvable
            if mn is None:
                continue
            if counts.get(v, 0) + sm - mn > c.max_skew:
                return False
        return True


def build_filter_state(
    pod: Pod, all_nodes: Sequence[tuple[Node, Sequence[Pod]]]
) -> SpreadFilterState | None:
    """None = pod has no hard constraints (PreFilter Skip)."""
    constraints = effective_constraints(pod, hard=True)
    if not constraints:
        return None
    counted = [
        (n, ps) for n, ps in all_nodes if _node_counted(pod, n, constraints)
    ]
    counts_l: list[dict[str, int]] = []
    min_l: list[int | None] = []
    self_l: list[int] = []
    for c in constraints:
        counts = _domain_counts(pod, c, counted)
        if counts:
            min_match: int | None = min(counts.values())
        else:
            min_match = None  # empty critical paths -> constraint passes
        if c.min_domains is not None and len(counts) < c.min_domains:
            min_match = 0
        counts_l.append(counts)
        min_l.append(min_match)
        self_l.append(1 if _sel_matches(c.selector, pod.labels) else 0)
    return SpreadFilterState(constraints, counts_l, min_l, self_l)


def spread_filter(
    pod: Pod,
    node: Node,
    all_nodes: Sequence[tuple[Node, Sequence[Pod]]],
) -> bool:
    """Filter for one candidate node. all_nodes: (node, pods-on-node)."""
    state = build_filter_state(pod, all_nodes)
    return state is None or state.check(node)


def spread_scores(
    pod: Pod,
    feasible: Sequence[tuple[Node, Sequence[Pod]]],
    all_nodes: Sequence[tuple[Node, Sequence[Pod]]],
    defaults: Sequence[EffectiveConstraint] = (),
) -> list[int]:
    """Normalized 0-100 PodTopologySpread score for each feasible node."""
    constraints = effective_constraints(pod, hard=False, defaults=defaults)
    if not constraints:
        return [0 for _ in feasible]
    counted = [
        (n, ps) for n, ps in all_nodes if _node_counted(pod, n, constraints)
    ]
    per_c_counts = [_domain_counts(pod, c, counted) for c in constraints]

    raw: list[int | None] = []  # None = ignored node
    for node, pods in feasible:
        if any(c.topology_key not in node.labels for c in constraints):
            raw.append(None)
            continue
        score = 0.0
        for c, counts in zip(constraints, per_c_counts):
            v = node.labels[c.topology_key]
            if c.topology_key == HOSTNAME_KEY:
                cnt = sum(
                    1
                    for p in pods
                    if p.namespace == pod.namespace
                    and _sel_matches(c.selector, p.labels)
                )
                size = len(feasible)
            else:
                cnt = counts.get(v, 0)
                size = len(counts)
            score += cnt * math.log(size + 2) + (c.max_skew - 1)
        raw.append(int(round(score)))

    considered = [s for s in raw if s is not None]
    if not considered:
        return [0 for _ in raw]
    mx, mn = max(considered), min(considered)
    out = []
    for s in raw:
        if s is None:
            out.append(0)
        elif mx == 0:
            out.append(MAX_NODE_SCORE)
        else:
            out.append(MAX_NODE_SCORE * (mx + mn - s) // mx)
    return out
