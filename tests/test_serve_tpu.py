"""The served TPU solve (VERDICT r2 #2): device-backed extender verbs,
micro-batching, the ingest surface, scheduler mode, and the bulk tensor
gRPC path (SURVEY §8.2, §6.8)."""

import asyncio
import json

import numpy as np
import pytest

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.server.bulk import BulkClient, BulkCore, make_grpc_server
from kubernetes_tpu.server.extender import (
    ExtenderCore,
    MicroBatcher,
    _load_state_file,
    make_app,
)
from kubernetes_tpu.server import tensorcodec
from kubernetes_tpu.state.cluster import ClusterState


def make_cluster(n=6):
    cs = ClusterState()
    for i in range(n):
        b = (
            MakeNode()
            .name(f"node-{i}")
            .capacity({"cpu": "8", "memory": "32Gi", "pods": "20"})
            .label("zone", f"z{i % 2}")
            .label("kubernetes.io/hostname", f"node-{i}")
        )
        cs.create_node(b.obj())
    cs.create_pod(
        MakePod().name("existing").node("node-0").req({"cpu": "7"}).obj()
    )
    return cs


def node_list(cs):
    return {"items": [n.to_dict() for n in cs.list_nodes()]}


# -- device backend == oracle backend on the wire --------------------------


def test_device_filter_matches_oracle():
    cs = make_cluster()
    dev = ExtenderCore(cs, backend="device")
    orc = ExtenderCore(cs, backend="oracle")
    for pod in (
        MakePod().name("p").req({"cpu": "4"}).obj(),
        MakePod().name("z").obj(),  # zero-request
        MakePod().name("a").req({"cpu": "1"}).node_affinity_in(
            "zone", ["z1"]
        ).obj(),
    ):
        args = {"pod": pod.to_dict(), "nodes": node_list(cs)}
        got, want = dev.filter(args), orc.filter(args)
        assert [n["metadata"]["name"] for n in got["nodes"]["items"]] == [
            n["metadata"]["name"] for n in want["nodes"]["items"]
        ]
        assert got["failedNodes"] == want["failedNodes"]
        json.dumps(got)


def test_device_prioritize_matches_oracle():
    cs = make_cluster()
    dev = ExtenderCore(cs, backend="device")
    orc = ExtenderCore(cs, backend="oracle")
    pod = MakePod().name("p").req({"cpu": "2", "memory": "4Gi"}).obj()
    args = {"pod": pod.to_dict(), "nodes": node_list(cs)}
    assert dev.prioritize(args) == orc.prioritize(args)


def test_run_many_shares_one_evaluation():
    """Pods sharing a node list group into one device call and keep
    request order."""
    cs = make_cluster()
    core = ExtenderCore(cs, backend="device")
    pods = [
        MakePod().name(f"p{i}").req({"cpu": str(i + 1)}).obj() for i in range(4)
    ]
    reqs = [
        ("prioritize", {"pod": p.to_dict(), "nodes": node_list(cs)})
        for p in pods
    ]
    reqs.append(
        ("filter", {"pod": pods[0].to_dict(), "nodes": node_list(cs)})
    )
    outs = core.run_many(reqs)
    for i, p in enumerate(pods):
        solo = core.prioritize({"pod": p.to_dict(), "nodes": node_list(cs)})
        assert outs[i] == solo
    assert "failedNodes" in outs[4]


def test_run_many_isolates_bad_request():
    """A malformed request inside a micro-batch must not poison its
    batch-mates (per-request error results instead)."""
    from kubernetes_tpu.server.extender import DecodeError

    cs = make_cluster()
    core = ExtenderCore(cs, backend="device")
    good = MakePod().name("p").req({"cpu": "1"}).obj()
    outs = core.run_many(
        [
            ("prioritize", {"nodes": node_list(cs)}),  # no pod key
            ("filter", {"nodes": node_list(cs)}),  # no pod key
            ("prioritize", {"pod": good.to_dict(), "nodes": node_list(cs)}),
        ]
    )
    assert isinstance(outs[0], DecodeError)
    assert "error" in outs[1]
    assert isinstance(outs[2], list) and outs[2]  # healthy HostPriorityList


def test_run_many_does_not_share_across_different_payloads():
    """Same node names, different capacities: requests must not share one
    evaluation; nodeCacheCapable unknown-name lists stay per-request."""
    cs = make_cluster()
    core = ExtenderCore(cs, backend="device")
    pod = MakePod().name("p").req({"cpu": "4"}).obj()
    small = [
        MakeNode().name("n").capacity({"cpu": "2", "memory": "4Gi", "pods": "5"}).obj().to_dict()
    ]
    big = [
        MakeNode().name("n").capacity({"cpu": "16", "memory": "64Gi", "pods": "5"}).obj().to_dict()
    ]
    outs = core.run_many(
        [
            ("filter", {"pod": pod.to_dict(), "nodes": {"items": small}}),
            ("filter", {"pod": pod.to_dict(), "nodes": {"items": big}}),
            ("filter", {"pod": pod.to_dict(), "nodenames": ["node-1", "ghost"]}),
            ("filter", {"pod": pod.to_dict(), "nodenames": ["node-1"]}),
        ]
    )
    assert outs[0]["nodes"]["items"] == []  # 4 cpu doesn't fit 2-cpu node
    assert [n["metadata"]["name"] for n in outs[1]["nodes"]["items"]] == ["n"]
    assert outs[2]["failedAndUnresolvableNodes"] == {"ghost": "node not found"}
    assert outs[3]["failedAndUnresolvableNodes"] == {}


def test_micro_batcher_no_lost_wakeup():
    """A request arriving while a drain is mid-flight must still resolve
    (the round-2 class of silent liveness break, caught in review)."""
    import threading
    import time as _time

    cs = make_cluster()
    core = ExtenderCore(cs, backend="device")
    release = threading.Event()
    orig = core.run_many

    def slow(requests):
        release.wait(5.0)
        return orig(requests)

    core.run_many = slow
    batcher = MicroBatcher(core, window=0.005)
    pod = MakePod().name("p").req({"cpu": "1"}).obj()
    args = {"pod": pod.to_dict(), "nodes": node_list(cs)}

    async def go():
        first = asyncio.create_task(batcher.submit("prioritize", args))
        await asyncio.sleep(0.05)  # first drain is now blocked in slow()
        second = asyncio.create_task(batcher.submit("prioritize", args))
        await asyncio.sleep(0.01)
        release.set()
        return await asyncio.wait_for(
            asyncio.gather(first, second), timeout=5.0
        )

    outs = asyncio.run(go())
    assert outs[0] == outs[1] and outs[0]


def test_micro_batcher_coalesces():
    cs = make_cluster()
    core = ExtenderCore(cs, backend="device")
    calls = []
    orig = core.run_many

    def spy(requests):
        calls.append(len(requests))
        return orig(requests)

    core.run_many = spy
    batcher = MicroBatcher(core, window=0.01)
    pod = MakePod().name("p").req({"cpu": "1"}).obj()

    async def go():
        args = {"pod": pod.to_dict(), "nodes": node_list(cs)}
        return await asyncio.gather(
            *[batcher.submit("prioritize", args) for _ in range(5)]
        )

    outs = asyncio.run(go())
    assert len(outs) == 5 and all(o == outs[0] for o in outs)
    assert calls and max(calls) >= 2  # at least some coalescing happened


# -- ingest + scheduler mode over HTTP --------------------------------------


async def _http_roundtrip(app, reqs):
    from aiohttp.test_utils import TestClient, TestServer

    async with TestClient(TestServer(app)) as client:
        out = []
        for method, path, payload in reqs:
            resp = await client.request(method, path, json=payload)
            body = await resp.json() if resp.content_type == "application/json" else None
            out.append((resp.status, body))
        return out


def test_ingest_endpoints():
    cs = ClusterState()
    core = ExtenderCore(cs, backend="oracle")
    app = make_app(core)
    nodes = [
        MakeNode().name(f"n{i}").capacity({"cpu": "4", "memory": "8Gi", "pods": "10"}).obj().to_dict()
        for i in range(3)
    ]
    results = asyncio.run(
        _http_roundtrip(
            app,
            [
                ("POST", "/api/nodes", {"items": nodes}),
                ("POST", "/api/pods", MakePod().name("w").req({"cpu": "1"}).obj().to_dict()),
                ("GET", "/api/state", None),
                ("DELETE", "/api/nodes/n2", None),
                ("DELETE", "/api/nodes/nope", None),
                ("GET", "/api/state", None),
            ],
        )
    )
    assert results[0] == (200, {"applied": 3})
    assert results[1] == (200, {"applied": 1})
    assert results[2][1]["nodes"] == 3 and results[2][1]["unscheduled"] == 1
    assert results[3][0] == 200
    assert results[4][0] == 404
    assert results[5][1]["nodes"] == 2


def test_scheduler_mode_binds_ingested_pods():
    """serve --mode scheduler: pods POSTed to the ingest surface get bound
    by device solves with no external kube-scheduler."""
    from kubernetes_tpu.scheduler import Scheduler

    cs = ClusterState()
    for i in range(4):
        cs.create_node(
            MakeNode().name(f"n{i}").capacity(
                {"cpu": "8", "memory": "16Gi", "pods": "20"}
            ).obj()
        )
    sched = Scheduler(cs)
    core = ExtenderCore(cs, backend="oracle")
    app = make_app(core, scheduler=sched)

    async def go():
        from aiohttp.test_utils import TestClient, TestServer

        async with TestClient(TestServer(app)) as client:
            pods = {
                "items": [
                    MakePod().name(f"p{i}").req({"cpu": "1"}).obj().to_dict()
                    for i in range(8)
                ]
            }
            resp = await client.post("/api/pods", json=pods)
            assert resp.status == 200
            for _ in range(100):
                resp = await client.get("/api/state")
                body = await resp.json()
                if body["unscheduled"] == 0:
                    return body
                await asyncio.sleep(0.05)
            return body

    body = asyncio.run(go())
    assert body["unscheduled"] == 0
    assert all(p.node_name for p in cs.list_pods())


def test_state_file_loading(tmp_path):
    doc = {
        "nodes": [
            MakeNode().name("n0").capacity({"cpu": "4", "pods": "10"}).obj().to_dict()
        ],
        "pods": [MakePod().name("p0").req({"cpu": "1"}).obj().to_dict()],
    }
    f = tmp_path / "state.json"
    f.write_text(json.dumps(doc))
    cs = ClusterState()
    _load_state_file(cs, str(f))
    assert len(cs.list_nodes()) == 1 and len(cs.list_pods()) == 1


# -- tensor codec + bulk gRPC ------------------------------------------------


def test_tensorcodec_roundtrip():
    meta = {"mode": "exact", "names": ["a", "b"]}
    arrays = {
        "x": np.arange(6, dtype=np.int64).reshape(2, 3),
        "y": np.asarray([True, False]),
    }
    m2, a2 = tensorcodec.decode(tensorcodec.encode(meta, arrays))
    assert m2 == meta
    assert np.array_equal(a2["x"], arrays["x"])
    assert np.array_equal(a2["y"], arrays["y"])


def test_tensorcodec_rejects_bad_shapes():
    data = tensorcodec.encode({"a": 1}, {"x": np.zeros(4, dtype=np.int32)})
    # corrupt the declared shape
    import struct

    (hlen,) = struct.unpack_from("<I", data, 0)
    hdr = json.loads(data[4 : 4 + hlen])
    hdr["arrays"][0]["shape"] = [999]
    bad_hdr = json.dumps(hdr).encode()
    bad = struct.pack("<I", len(bad_hdr)) + bad_hdr + data[4 + hlen :]
    with pytest.raises(ValueError):
        tensorcodec.decode(bad)


def test_bulk_core_solve_matches_direct():
    """BulkCore.solve == a direct ExactSolver run over the same state."""
    cs = make_cluster(4)
    core = BulkCore(cs)
    cpu = np.full(8, 1000, dtype=np.int64)
    mem = np.full(8, 1 << 30, dtype=np.int64)
    reply = core.solve(
        tensorcodec.encode(
            {"mode": "exact"}, {"cpu_milli": cpu, "mem_bytes": mem}
        )
    )
    meta, arrays = tensorcodec.decode(reply)
    asg = arrays["assignments"]
    assert asg.shape == (8,)
    assert (asg >= 0).all()
    # node-0 has 7/8 cpu used: can hold at most one more 1-cpu pod
    node0 = sum(1 for a in asg if meta["nodes"][a] == "node-0")
    assert node0 <= 1


def test_bulk_core_single_shot_and_commit():
    cs = make_cluster(4)
    core = BulkCore(cs)
    cpu = np.full(6, 500, dtype=np.int64)
    mem = np.full(6, 1 << 29, dtype=np.int64)
    names = [f"default/bulk-{i}" for i in range(6)]
    reply = core.solve(
        tensorcodec.encode(
            {"mode": "single_shot", "commit": True, "names": names},
            {"cpu_milli": cpu, "mem_bytes": mem},
        )
    )
    meta, arrays = tensorcodec.decode(reply)
    placed = int((arrays["assignments"] >= 0).sum())
    assert placed == 6
    bound = [p for p in cs.list_pods() if p.name.startswith("bulk-")]
    assert len(bound) == 6 and all(p.node_name for p in bound)


def test_bulk_grpc_socket_roundtrip():
    """Full wire: gRPC server + client, SyncNodes -> Solve -> Evaluate."""
    cs = ClusterState()
    core = BulkCore(cs)
    server, port = make_grpc_server(core, port=0)
    server.start()
    try:
        client = BulkClient(f"127.0.0.1:{port}")
        out = client.sync_nodes(
            names=[f"n{i}" for i in range(5)],
            cpu_milli=[8000] * 5,
            mem_bytes=[32 << 30] * 5,
            max_pods=[20] * 5,
        )
        assert out == {"applied": 5}
        meta, arrays = client.solve(
            cpu_milli=[1000] * 10, mem_bytes=[1 << 30] * 10
        )
        assert (arrays["assignments"] >= 0).all()
        meta, arrays = client.evaluate(
            cpu_milli=[1000, 64000], mem_bytes=[1 << 30, 1 << 30]
        )
        assert arrays["scores"].shape == (2, 5)
        assert (arrays["scores"][0] >= 0).all()  # fits everywhere
        assert (arrays["scores"][1] < 0).all()  # 64 cpu fits nowhere
        client.close()
    finally:
        server.stop(grace=None)


def test_bulk_commit_honors_namespaced_keys():
    """ADVICE r3: 'ns/name'-shaped commit keys bind into THEIR namespace;
    bare names fall back to the request's meta namespace."""
    cs = make_cluster(4)
    core = BulkCore(cs)
    cpu = np.full(3, 500, dtype=np.int64)
    mem = np.full(3, 1 << 29, dtype=np.int64)
    names = ["team-a/web", "team-b/web", "bare"]
    reply = core.solve(
        tensorcodec.encode(
            {
                "mode": "single_shot", "commit": True, "names": names,
                "namespace": "fallback-ns",
            },
            {"cpu_milli": cpu, "mem_bytes": mem},
        )
    )
    meta, arrays = tensorcodec.decode(reply)
    assert int((arrays["assignments"] >= 0).sum()) == 3
    assert cs.get_pod("team-a", "web").node_name
    assert cs.get_pod("team-b", "web").node_name
    assert cs.get_pod("fallback-ns", "bare").node_name
