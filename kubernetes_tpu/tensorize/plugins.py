"""Plugin tensorizer: compile the static plugin semantics (taints, node
affinity, nodeName, unschedulable, image locality) and the node-ports state
into per-batch device tensors (SURVEY.md §8.1).

The key idea is **pod scheduling classes**: pods whose scheduling-relevant
spec (tolerations, nodeSelector/affinity, nodeName, images) is identical
share one row of the [C, N] static tensors. Real workloads come from
deployments/jobs, so C << P — the host evaluates each distinct spec once per
node instead of once per pod (the reference evaluates every (pod, node) pair
from scratch inside the goroutine parallel-for; the class dedup is the
TPU-native restructuring that makes the host prep O(C·N) and the device work
a gather).

Static per-class tensors (filter mask + raw score inputs; normalization
happens in-scan because DefaultNormalizeScore normalizes over the FEASIBLE
set, which depends on solve state):
- mask[C, N]       : NodeName ∧ NodeUnschedulable ∧ TaintToleration(Filter)
                     ∧ NodeAffinity(Filter)
- taint_cnt[C, N]  : # intolerable PreferNoSchedule taints (Score, reverse)
- nodeaff_pref[C,N]: Σ weights of matching preferred terms (Score)
- image_score[C,N] : ImageLocality final 0-100 (no normalize step upstream)

NodePorts is state-dependent (placed pods occupy ports) so it tensorizes as
a (hostIP, protocol, hostPort) vocabulary:
- used[V, N]        : occupancy counts from already-placed pods
- pod_conflict[P, V]: vocab entries that clash with the pod's wanted ports
                      (HostPortInfo.CheckConflict wildcard-IP semantics
                      precompiled host-side)
- pod_takes[P, V]   : vocab counts the pod adds when placed (the in-scan
                      scatter that replaces cache.AssumePod's port tracking)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Sequence

import numpy as np

from ..api.objects import Node, Pod
from ..ops.oracle import plugins as opl
from ..ops.oracle import volumes as ovol
from .schema import PodBatch, bucket_pow2

CLASS_PAD = 8  # pad the class axis to multiples of this (sublane-ish quantum)
PORT_PAD = 8


def _class_key(pod: Pod, with_images: bool):
    """Everything the static plugins read from the pod spec. Image names only
    matter when some node reports images (image_score is their sole
    consumer); excluding them otherwise keeps C small for image-diverse
    batches. PodTopologySpread reads the pod's own labels (selfMatch,
    matchLabelKeys) and namespace, so those join the key only for pods that
    carry spread constraints."""
    na = pod.affinity.node_affinity if pod.affinity else None
    spread = (
        (
            pod.topology_spread_constraints,
            pod.namespace,
            tuple(sorted(pod.labels.items())),
        )
        if pod.topology_spread_constraints
        else ()
    )
    # InterPodAffinity: the incoming-term set depends on the pod's affinity
    # spec AND its namespace/labels (term namespaces default to the pod's
    # own; matchLabelKeys and the first-pod self-match read its labels)
    pa = pod.affinity.pod_affinity if pod.affinity else None
    paa = pod.affinity.pod_anti_affinity if pod.affinity else None
    interpod = (
        (pa, paa, pod.namespace, tuple(sorted(pod.labels.items())))
        if (pa is not None or paa is not None)
        else ()
    )
    return (
        pod.node_name,
        tuple(sorted(pod.node_selector.items())),
        na,
        pod.tolerations,
        tuple(tuple(c.images) for c in pod.containers) if with_images else (),
        len(pod.containers) if with_images else 0,
        spread,
        interpod,
        # volume plugins resolve PVCs by (namespace, claim name)
        (pod.namespace, pod.pvc_names) if pod.pvc_names else (),
    )


@dataclass
class StaticPluginTensors:
    num_classes: int
    class_of: np.ndarray  # [Pp] int32
    mask: np.ndarray  # [Cp, Np] bool
    taint_cnt: np.ndarray  # [Cp, Np] int32
    nodeaff_pref: np.ndarray  # [Cp, Np] int32
    image_score: np.ndarray  # [Cp, Np] int32
    # representative pod per class, for downstream per-class tensorizers
    # (spread, interpod affinity); not shipped to device
    reps: list = None
    # out-of-tree ScorePlugin contributions, weight-premultiplied
    # (framework/runtime.py#fold_out_of_tree); None = no custom plugins
    extra_score: np.ndarray | None = None  # [Cp, Np] int32

    @property
    def c_pad(self) -> int:
        return self.mask.shape[0]

    def device_arrays(self) -> dict[str, np.ndarray]:
        return {
            "class_of": self.class_of,
            "mask": self.mask,
            "taint_cnt": self.taint_cnt,
            "nodeaff_pref": self.nodeaff_pref,
            "image_score": self.image_score,
        }


def trivial_static_tensors(pbatch: PodBatch, padded_n: int, schedulable: np.ndarray) -> StaticPluginTensors:
    """One all-pods class whose mask is just the node schedulable bit —
    the pre-plugin behavior, used when a caller has only resource data."""
    mask = np.zeros((CLASS_PAD, padded_n), dtype=bool)
    mask[0] = schedulable[:padded_n]
    z = np.zeros((CLASS_PAD, padded_n), dtype=np.int32)
    return StaticPluginTensors(
        num_classes=1,
        class_of=np.zeros(pbatch.padded, dtype=np.int32),
        mask=mask,
        taint_cnt=z,
        nodeaff_pref=z.copy(),
        image_score=z.copy(),
        reps=[],
    )


VOLUME_PLUGINS = frozenset(
    {"VolumeBinding", "VolumeZone", "VolumeRestrictions", "NodeVolumeLimits"}
)


def build_static_tensors(
    pods: Sequence[Pod],
    pbatch: PodBatch,
    slot_nodes: Sequence[Node | None],
    padded_n: int,
    volume_ctx=None,
    disabled: frozenset = frozenset(),
    added_affinity=None,
    class_key_extra=None,
) -> StaticPluginTensors:
    """slot_nodes: Node per snapshot slot (None = free/invalid slot), so the
    class tensors share the solver's node index space. ``volume_ctx`` (an
    ops.oracle.volumes.VolumeContext) folds the volume plugin family's
    static checks into the mask.

    ``disabled``: filter-point plugin names disabled by the profile
    (runtime/framework.go honors plugins.filter.disabled); the volume
    family is fused, so disabling any one of its four names disables the
    fused check (the config loader warns about the coarseness).
    ``added_affinity``: NodeAffinityArgs.addedAffinity — required terms AND
    into every class mask, preferred weights add to the NodeAffinity score.
    ``class_key_extra``: optional callable(pod) mixed into the class key —
    used for identity the base key cannot see (e.g. the service-derived
    System spread-default selector).
    """
    live_nodes = [n for n in slot_nodes if n is not None]
    image_states = opl.build_image_states(live_nodes)
    total_nodes = len(live_nodes)
    any_images = bool(image_states)

    class_of = np.zeros(pbatch.padded, dtype=np.int32)
    reps: list[Pod] = []
    index: dict = {}
    for i, pod in enumerate(pods):
        key = _class_key(pod, with_images=any_images)
        if class_key_extra is not None:
            key = (key, class_key_extra(pod))
        c = index.get(key)
        if c is None:
            c = len(reps)
            index[key] = c
            reps.append(pod)
        class_of[i] = c

    c_pad = bucket_pow2(max(len(reps), 1), floor=CLASS_PAD)
    mask = np.zeros((c_pad, padded_n), dtype=bool)
    taint_cnt = np.zeros((c_pad, padded_n), dtype=np.int32)
    nodeaff_pref = np.zeros((c_pad, padded_n), dtype=np.int32)
    image_score = np.zeros((c_pad, padded_n), dtype=np.int32)

    for c, rep in enumerate(reps):
        for j, node in enumerate(slot_nodes):
            if node is None or j >= padded_n:
                continue
            ok = (
                ("NodeName" in disabled or opl.node_name_filter(rep, node))
                and (
                    "NodeUnschedulable" in disabled
                    or opl.node_unschedulable_filter(rep, node)
                )
                and (
                    "TaintToleration" in disabled
                    or opl.taint_toleration_filter(rep, node)
                )
                and (
                    "NodeAffinity" in disabled
                    or (
                        opl.node_affinity_filter(rep, node)
                        and opl.added_affinity_filter(added_affinity, node)
                    )
                )
                and (
                    volume_ctx is None
                    or not rep.pvc_names
                    or bool(VOLUME_PLUGINS & disabled)
                    or ovol.volume_filter(rep, node, volume_ctx)
                )
            )
            mask[c, j] = ok
            if not ok:
                continue  # score rows are only read where mask holds
            if node.taints:
                taint_cnt[c, j] = opl.taint_toleration_score(rep, node)
            aff = rep.affinity.node_affinity if rep.affinity else None
            if aff is not None and aff.preferred:
                nodeaff_pref[c, j] = opl.node_affinity_score(rep, node)
            if added_affinity is not None and added_affinity.preferred:
                nodeaff_pref[c, j] += opl.added_affinity_score(
                    added_affinity, node
                )
            if any_images:
                image_score[c, j] = opl.image_locality_score(
                    rep, node, image_states, total_nodes
                )

    return StaticPluginTensors(
        num_classes=len(reps),
        class_of=class_of,
        mask=mask,
        taint_cnt=taint_cnt,
        nodeaff_pref=nodeaff_pref,
        image_score=image_score,
        reps=reps,
    )


@dataclass
class PortTensors:
    num_ports: int
    vocab: list[tuple[str, str, int]]
    used: np.ndarray  # [Vp, Np] int32
    pod_conflict: np.ndarray  # [Pp, Vp] bool
    pod_takes: np.ndarray  # [Pp, Vp] int32

    def device_arrays(self) -> dict[str, np.ndarray]:
        return {
            "used": self.used,
            "pod_conflict": self.pod_conflict,
            "pod_takes": self.pod_takes,
        }


def _conflicts_as_used(want: tuple[str, str, int], entry: tuple[str, str, int]) -> bool:
    """Would occupancy of vocab ``entry`` block a pod wanting ``want``?
    Delegates to the oracle's CheckConflict transcription so kernel and
    oracle can't diverge."""
    return opl.port_conflicts(want, [entry])


class PortStaging:
    """Reusable host-prep staging for the port-occupancy half of
    ``build_port_tensors`` (the streaming dispatcher's tensorize
    micro-opt): the vocab and the ``used`` occupancy matrix depend only
    on PLACED pods and the node slot layout, so consecutive batches
    against an unchanged cache (the streaming burst window: no applies,
    no watch events between tensorizes) can reuse them instead of
    re-scanning every placed pod per batch. Validity is fingerprinted
    by ``key`` — the caller passes (cache generation, padded_n), so any
    cache mutation (the dirty-node/dirty-pod check) or slot-layout
    change rebuilds from scratch. Batch wants may EXTEND a staged vocab
    (new entries have zero placed occupancy by construction — the
    staged scan already interned every placed port), growing ``used``
    only when the pow2 pad actually grows."""

    def __init__(self) -> None:
        self.key: tuple | None = None
        self.vocab: list[tuple[str, str, int]] | None = None
        self.vocab_index: dict[tuple[str, str, int], int] | None = None
        self.used: np.ndarray | None = None
        self.hits = 0
        self.misses = 0


def build_port_tensors(
    pods: Sequence[Pod],
    pbatch: PodBatch,
    slot_nodes: Sequence[Node | None],
    placed_by_slot: Mapping[int, Sequence[Pod]],
    padded_n: int,
    nominated: Sequence[tuple[Pod, int]] = (),
    staging: PortStaging | None = None,
    staging_key: tuple | None = None,
) -> PortTensors:
    """``nominated`` (pod, slot) pairs contribute their hostPorts to the
    vocab so build_nominated_tensors can encode their occupancy rows in
    this batch's port space (NominatedTensors.port_takes).

    ``staging``/``staging_key``: see PortStaging — a matching key skips
    the placed-pod occupancy scan and reuses the staged vocab + used
    matrix (the returned arrays are never mutated downstream: ``used``
    is copied into the bstate upload, so sharing one array across
    consecutive batches is safe)."""
    reuse = (
        staging is not None
        and staging_key is not None
        and staging.key == staging_key
    )
    if reuse:
        staging.hits += 1
        # copies, not the staged objects: a prior batch's PortTensors
        # holds the previous list, and interning THIS batch's wants into
        # it would retroactively grow a vocab that batch's pod_conflict
        # width was sized for (journal attribution reads it at apply)
        vocab = list(staging.vocab)
        vocab_index = dict(staging.vocab_index)
    else:
        if staging is not None:
            staging.misses += 1
        vocab_index = {}
        vocab = []

    def intern(t: tuple[str, str, int]) -> int:
        v = vocab_index.get(t)
        if v is None:
            v = len(vocab)
            vocab_index[t] = v
            vocab.append(t)
        return v

    wants: list[tuple[tuple[str, str, int], ...]] = []
    for pod in pods:
        w = pod.host_ports()
        wants.append(w)
        for t in w:
            intern(t)
    if not reuse:
        used_entries: dict[int, list[int]] = {}
        for slot, placed in placed_by_slot.items():
            lst = used_entries.setdefault(slot, [])
            for p in placed:
                for t in p.host_ports():
                    lst.append(intern(t))
    for p, _slot in nominated:
        for t in p.host_ports():
            intern(t)

    v_pad = bucket_pow2(max(len(vocab), 1), floor=PORT_PAD)
    if reuse:
        used = staging.used
        if used.shape[0] < v_pad:
            # batch wants extended the vocab past the staged pad: grow
            # with zero rows (new entries cannot have placed occupancy)
            grown = np.zeros((v_pad, padded_n), dtype=np.int32)
            grown[: used.shape[0]] = used
            used = grown
    else:
        used = np.zeros((v_pad, padded_n), dtype=np.int32)
        for slot, entries in used_entries.items():
            if slot >= padded_n:
                continue
            for v in entries:
                used[v, slot] += 1
    if staging is not None and staging_key is not None:
        staging.key = staging_key
        staging.vocab = vocab
        staging.vocab_index = vocab_index
        staging.used = used

    pod_conflict = np.zeros((pbatch.padded, v_pad), dtype=bool)
    pod_takes = np.zeros((pbatch.padded, v_pad), dtype=np.int32)
    for i, w in enumerate(wants):
        if not w:
            continue
        for t in w:
            pod_takes[i, vocab_index[t]] += 1
        for v, entry in enumerate(vocab):
            if any(_conflicts_as_used(want, entry) for want in w):
                pod_conflict[i, v] = True

    return PortTensors(
        num_ports=len(vocab),
        vocab=vocab,
        used=used,
        pod_conflict=pod_conflict,
        pod_takes=pod_takes,
    )


def trivial_port_tensors(pbatch: PodBatch, padded_n: int) -> PortTensors:
    return PortTensors(
        num_ports=0,
        vocab=[],
        used=np.zeros((PORT_PAD, padded_n), dtype=np.int32),
        pod_conflict=np.zeros((pbatch.padded, PORT_PAD), dtype=bool),
        pod_takes=np.zeros((pbatch.padded, PORT_PAD), dtype=np.int32),
    )
