"""CLI — the cmd/kube-scheduler analog (app/server.go#Setup/#Run shape):
load + validate ComponentConfig, then run one of:

  serve   extender webhook + healthz/livez/readyz + /metrics (port 10259,
          the reference's secure serving port)
  perf    scheduler_perf-compatible YAML workloads
  config  parse/validate a KubeSchedulerConfiguration and print the
          resolved settings + warnings

Leader election is [CONTEXT] (single-process; SURVEY §3.3) — the flag is
accepted and ignored with a warning for config compatibility.
"""

from __future__ import annotations

import argparse
import json
import sys

from .config import types as config_types


def _load_config(path: str | None) -> config_types.KubeSchedulerConfiguration:
    if path:
        return config_types.load_file(path)
    return config_types.KubeSchedulerConfiguration()


def _feature_gates(args):
    """Parse --feature-gates (component-base/featuregate syntax); parse
    errors exit 1 like the reference's flag validation."""
    from .utils.featuregate import FeatureGates

    try:
        fg = FeatureGates.parse(getattr(args, "feature_gates", None))
    except ValueError as e:
        print(f"error: --feature-gates: {e}", file=sys.stderr)
        raise SystemExit(1)
    for w in fg.warnings:
        print(f"warning: {w}", file=sys.stderr)
    return fg


def cmd_config(args) -> int:
    cfg = _load_config(args.config)
    _feature_gates(args)  # validate the flag here too (exit 1 on error)
    # building the runtime config runs the per-profile solver validation
    # (scoring strategy shapes, disableable filters, resource weights) so
    # its warnings surface here too, not only at serve/perf time
    config_types.scheduler_config(cfg)
    out = {
        "profiles": [
            {
                "schedulerName": p.scheduler_name,
                "scoreWeights": p.score_weights,
                "scoringStrategy": p.scoring_strategy.type,
                "hardPodAffinityWeight": p.hard_pod_affinity_weight,
            }
            for p in cfg.profiles
        ],
        "extenders": len(cfg.extenders),
        "tpuSolver": {
            "batchSize": cfg.tpu_solver.batch_size,
            "tieBreak": cfg.tpu_solver.tie_break,
            "enablePreemption": cfg.tpu_solver.enable_preemption,
            "groupSize": cfg.tpu_solver.group_size,
            "meshDevices": cfg.tpu_solver.mesh_devices,
            "streamDepth": cfg.tpu_solver.stream_depth,
            "pipelineSplit": cfg.tpu_solver.pipeline_split,
            "backlogChunkPods": cfg.tpu_solver.backlog_chunk_pods,
            "pallas": cfg.tpu_solver.pallas,
        },
        "rebalance": {
            "enabled": cfg.rebalance.enabled,
            "intervalSeconds": cfg.rebalance.interval_seconds,
            "maxMovesPerCycle": cfg.rebalance.max_moves_per_cycle,
            "minPackingUtilization": cfg.rebalance.min_packing_utilization,
            "minGainPoints": cfg.rebalance.min_gain_points,
            "nominate": cfg.rebalance.nominate,
        },
        "fleet": {
            "replica": cfg.fleet.replica,
            "replicas": cfg.fleet.replicas,
            "hubAddress": cfg.fleet.hub_address,
            "meshSlice": (
                f"{cfg.fleet.mesh_slice[0]}/{cfg.fleet.mesh_slice[1]}"
                if cfg.fleet.mesh_slice is not None
                else None
            ),
            "maxRowAgeSeconds": cfg.fleet.max_row_age_seconds,
            "flushBatch": cfg.fleet.flush_batch,
        },
        "gang": {
            "enabled": cfg.gang.enabled,
            "minMemberTimeoutSeconds": cfg.gang.min_member_timeout_seconds,
            "quarantineAfter": cfg.gang.quarantine_after,
            "throughputWeight": cfg.gang.throughput_weight,
            "classThroughputWorkloads": sorted(cfg.gang.class_throughput),
            "classThroughputPath": cfg.gang.class_throughput_path,
        },
        "tuning": {
            "enabled": cfg.tuning.enabled,
            "evalBatches": cfg.tuning.eval_batches,
            "hysteresis": cfg.tuning.hysteresis,
            "settleAfter": cfg.tuning.settle_after,
            "maxProbes": cfg.tuning.max_probes,
            "shiftThreshold": cfg.tuning.shift_threshold,
            "knobs": cfg.tuning.knobs,
        },
        "warnings": cfg.warnings,
    }
    print(json.dumps(out, indent=2))
    return 0


def cmd_serve(args) -> int:
    from .server.extender import run_server
    from .state.cluster import ClusterState
    from .utils import logging as structured_logging

    # component-base logs analog (--logging-format): one JSON object per
    # line carrying the scheduler's span/batch ids, or klog-ish text
    structured_logging.setup(args.log_format)
    cfg = _load_config(args.config)
    for w in cfg.warnings:
        print(f"warning: {w}", file=sys.stderr)
    cluster = ClusterState()
    sched_cfg = config_types.scheduler_config(cfg)
    sched_cfg.feature_gates = _feature_gates(args)
    telemetry_on = bool(args.telemetry or args.bundle_dir)
    if (
        args.obs or args.obs_journal or args.obs_dump or args.slo
        or telemetry_on
    ):
        from .obs import ObsConfig, SentinelConfig, SloConfig

        sched_cfg.obs = ObsConfig(
            spans=bool(args.obs or args.obs_journal or args.obs_dump),
            journal=bool(
                args.obs or args.obs_journal or args.obs_dump
                or telemetry_on
            ),
            journal_path=args.obs_journal,
            dump_path=args.obs_dump,
            # a serving process runs indefinitely: bound the in-memory
            # journal and rely on --obs-journal streaming for history
            journal_capacity=65536,
            # live SLO engine (GET /debug/slo + scheduler_slo_*):
            # --slo OBJECTIVE enables it with that per-pod latency
            # objective in seconds. --telemetry implies it: the
            # sentinel's p99 signal reads off the SLO engine.
            slo=(
                SloConfig(latency_objective_s=args.slo)
                if args.slo
                else (SloConfig() if telemetry_on else None)
            ),
            # always-on flight telemetry (GET /debug/profile +
            # scheduler_profile_* / scheduler_anomaly_*): continuous
            # per-stage profiler, anomaly sentinel with production-
            # sized windows, capture-on-anomaly replay bundles under
            # --bundle-dir (which implies --telemetry)
            profile=telemetry_on,
            sentinel=SentinelConfig() if telemetry_on else None,
            bundle_dir=args.bundle_dir,
        )
    if args.leader_elect:
        # client-go leaderelection.RunOrDie semantics over the state
        # service's Lease store: block serving until the lease is held;
        # renew in the background; exit the process on loss (the
        # reference's OnStoppedLeading is fatal). NOTE: exclusion spans
        # electors sharing THIS ClusterState (embedded schedulers); a
        # second standalone process has its own store and self-elects —
        # the --leader-elect help documents this scope honestly.
        import os
        import socket
        import threading

        from .utils.leaderelection import LeaderElector

        elector = LeaderElector(
            cluster, identity=f"{socket.gethostname()}_{os.getpid()}"
        )
        acquired = threading.Event()

        def lost():
            print(
                "error: leader lease lost; exiting", file=sys.stderr
            )
            os._exit(1)

        t = threading.Thread(
            target=elector.run,
            args=(threading.Event(),),
            kwargs=dict(
                on_started_leading=acquired.set, on_stopped_leading=lost
            ),
            daemon=True,
        )
        t.start()
        acquired.wait()
        print(
            f"leader election: acquired lease as {elector.identity}",
            file=sys.stderr,
        )
    run_server(
        cluster,
        host=args.host,
        port=args.port,
        node_cache_capable=args.node_cache_capable,
        mode=args.mode,
        state_file=args.state,
        solver_config=sched_cfg.solver,
        grpc_port=args.grpc_port,
        scheduler_config=sched_cfg,
    )
    return 0


def cmd_perf(args) -> int:
    from .perf.runner import PerfRunner

    cfg = _load_config(args.config)
    sched_cfg = config_types.scheduler_config(cfg)
    sched_cfg.feature_gates = _feature_gates(args)
    runner = PerfRunner(sched_cfg)
    results = runner.run_file(args.workload, workload_filter=args.workload_name)
    failed = 0
    for r in results:
        print(
            json.dumps(
                {
                    "testCase": r.test_case,
                    "workload": r.workload,
                    "scheduled": r.scheduled,
                    "unschedulable": r.unschedulable,
                    "throughput": r.throughput_summary(),
                    "podLatency": r.latency_summary(),
                    "deviceSolveSeconds": round(r.solve_seconds, 3),
                    **(
                        {"threshold": r.threshold, "passed": r.passed}
                        if r.threshold
                        else {}
                    ),
                }
            )
        )
        if not r.passed:
            failed += 1
            print(
                f"FAIL: {r.test_case}/{r.workload}: avg "
                f"{r.measured_pods / max(r.measure_seconds, 1e-9):.0f} "
                f"pods/s below threshold {r.threshold:.0f}",
                file=sys.stderr,
            )
    # scheduler_perf.go's threshold assert: a perf regression fails the run
    return 1 if failed else 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="kubernetes-tpu-scheduler",
        description="TPU-native pod->node assignment engine",
    )
    parser.add_argument("--config", help="KubeSchedulerConfiguration YAML")
    parser.add_argument(
        "--feature-gates",
        help='component-base style gate list, e.g. '
        '"SchedulerQueueingHints=false,PodSchedulingReadiness=true"',
    )
    parser.add_argument(
        "--trace-dir",
        help="write jax.profiler TensorBoard traces of device solves here "
        "(SURVEY §6.1; the --profiling analog)",
    )
    parser.add_argument(
        "--leader-elect",
        action="store_true",
        help="Lease-based active/passive leader election over the state "
        "service (client-go tools/leaderelection semantics): serve blocks "
        "until the lease is acquired and exits if it is lost. Mutual "
        "exclusion spans schedulers SHARING one state service; this "
        "binary embeds its own store, so a standalone process self-elects "
        "(the reference's lease lives in the shared apiserver)",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_serve = sub.add_parser("serve", help="run the extender webhook server")
    p_serve.add_argument("--host", default="127.0.0.1")
    p_serve.add_argument("--port", type=int, default=10259)
    p_serve.add_argument("--node-cache-capable", action="store_true")
    p_serve.add_argument(
        "--mode",
        choices=("extender", "scheduler"),
        default="extender",
        help="extender: answer webhook verbs only; scheduler: also run the "
        "batching scheduler loop over the ingested state",
    )
    p_serve.add_argument(
        "--state",
        help=(
            "initial cluster state file (JSON/YAML: nodes, pods, services, "
            "pdbs, resourceSlices, deviceClasses, resourceClaims)"
        ),
    )
    p_serve.add_argument(
        "--grpc-port",
        type=int,
        default=0,
        help="also serve the bulk tensor gRPC path on this port (0 = off)",
    )
    p_serve.add_argument(
        "--log-format",
        choices=("text", "json"),
        default="text",
        help="structured logging format (component-base --logging-format "
        "analog); json emits one object per line carrying span/batch ids",
    )
    p_serve.add_argument(
        "--obs",
        action="store_true",
        help="enable the scheduling trace layer (kubernetes_tpu/obs): "
        "spans + per-pod decision journal in a bounded flight recorder, "
        "served at /debug/flightrecorder and /debug/spans",
    )
    p_serve.add_argument(
        "--obs-journal",
        metavar="PATH",
        help="also stream per-pod decision-journal JSONL here (implies "
        "--obs); explain pods later with `python -m kubernetes_tpu.obs "
        "explain <pod> --trace PATH`",
    )
    p_serve.add_argument(
        "--obs-dump",
        metavar="PATH",
        help="flight-recorder dump target for crash and on-demand dumps "
        "(implies --obs)",
    )
    p_serve.add_argument(
        "--slo",
        type=float,
        metavar="SECONDS",
        default=0.0,
        help="enable the live SLO engine with this per-pod latency "
        "objective (first-enqueue -> bind): sliding-window p50/p99, "
        "bind throughput, multi-window error-budget burn — served at "
        "GET /debug/slo and exported as scheduler_slo_*",
    )
    p_serve.add_argument(
        "--telemetry",
        action="store_true",
        help="enable always-on flight telemetry (kubernetes_tpu/obs): "
        "continuous per-stage profiler + anomaly sentinel (implies the "
        "SLO engine for the p99 signal), served at GET /debug/profile "
        "and exported as scheduler_profile_* / scheduler_anomaly_*",
    )
    p_serve.add_argument(
        "--bundle-dir",
        metavar="DIR",
        help="write capture-on-anomaly replay bundles into this "
        "directory (implies --telemetry); replay offline with "
        "`python -m kubernetes_tpu.obs replay <bundle>`",
    )
    p_serve.set_defaults(fn=cmd_serve)

    p_perf = sub.add_parser("perf", help="run scheduler_perf YAML workloads")
    p_perf.add_argument("workload", help="performance-config.yaml path")
    p_perf.add_argument("--workload-name", help="run only this workload")
    p_perf.set_defaults(fn=cmd_perf)

    p_cfg = sub.add_parser("config", help="parse + print resolved config")
    p_cfg.set_defaults(fn=cmd_config)

    args = parser.parse_args(argv)
    if args.trace_dir:
        import atexit

        from .utils import tracing

        tracing.enable(args.trace_dir)
        atexit.register(tracing.stop)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
