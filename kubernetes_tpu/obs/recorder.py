"""Bounded in-memory flight recorder: the last-N spans and per-pod
decision records, always cheap enough to leave on, dumpable when
something goes wrong.

Triggers (mirroring aircraft FDR semantics — the recorder is only read
after an event):

- **crash**: the scheduler loops dump on an escaping exception
  (``Scheduler`` wires ``dump_path``);
- **invariant**: the simulator dumps when an invariant checker flags a
  violation (``sim/harness.py``);
- **manual**: ``GET /debug/flightrecorder`` on the extender server, or
  ``FlightRecorder.dump()`` from code.

The ring holds serialized dicts (not live Span objects) so a dump never
races a span still being mutated; ``collections.deque(maxlen=...)``
gives O(1) append with hard memory bounds. All mutation is
lock-guarded — the serve path records from the drain executor, the
event loop, and gRPC workers concurrently.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from pathlib import Path

from .. import metrics


def canonical(obj) -> str:
    """One canonical JSON encoding (sorted keys, no whitespace) so
    same-seed simulator runs dump byte-identical streams."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


class FlightRecorder:
    def __init__(
        self,
        span_capacity: int = 4096,
        decision_capacity: int = 8192,
        dump_path: str | None = None,
    ) -> None:
        self._spans: deque[dict] = deque(maxlen=span_capacity)
        self._decisions: deque[dict] = deque(maxlen=decision_capacity)
        self._lock = threading.Lock()
        # default target for crash/invariant dumps; dump() may override
        self.dump_path = dump_path
        self.dropped_spans = 0
        self.dropped_decisions = 0

    # -- ingest --

    def record_span(self, span) -> None:
        d = span.as_dict() if hasattr(span, "as_dict") else dict(span)
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped_spans += 1
            self._spans.append(d)

    def record_decision(self, rec: dict) -> None:
        with self._lock:
            if len(self._decisions) == self._decisions.maxlen:
                self.dropped_decisions += 1
            self._decisions.append(rec)

    # -- read side --

    def spans(self) -> list[dict]:
        with self._lock:
            return list(self._spans)

    def decisions(self) -> list[dict]:
        with self._lock:
            return list(self._decisions)

    def snapshot(self) -> dict:
        """Everything the /debug endpoints serve, one consistent cut."""
        with self._lock:
            return {
                "spans": list(self._spans),
                "decisions": list(self._decisions),
                "dropped_spans": self.dropped_spans,
                "dropped_decisions": self.dropped_decisions,
            }

    def lines(self, snapshot: dict | None = None) -> list[str]:
        """The JSONL dump body: decision records then spans, each one
        canonical-JSON per line (the explain CLI reads either kind).
        Pass an already-taken ``snapshot`` to serialize exactly that
        cut instead of re-reading the live ring."""
        snap = snapshot if snapshot is not None else self.snapshot()
        return [canonical(r) for r in snap["decisions"]] + [
            canonical(s) for s in snap["spans"]
        ]

    def dump(
        self,
        path: str | None = None,
        trigger: str = "manual",
        snapshot: dict | None = None,
    ) -> str | None:
        """Write the ring (or a caller-supplied ``snapshot`` of it) to
        ``path`` (or the configured dump_path) as JSONL. Returns the
        path written, or None when no target is configured. Never
        raises — a failing dump must not mask the crash that triggered
        it."""
        target = path or self.dump_path
        metrics.flight_recorder_dumps_total.labels(trigger).inc()
        if target is None:
            return None
        try:
            Path(target).write_text(
                "\n".join(self.lines(snapshot)) + "\n"
            )
        except OSError:
            return None
        return target
