"""The HBM budget model (solver/budget.py, ISSUE 12): the analytic
footprint of a (pods, nodes, vocab, mesh) drain shape, computed from
the same pad_multiple/LANE discipline the tensorizers use. Pinned here:

1. the upload-byte prediction matches the MEASURED
   scheduler_tpu_host_to_device_bytes_total delta of a real
   fresh-session solve within a documented 10% tolerance (the model is
   checkable, not decorative);
2. plan_chunk auto-splits an over-budget chunk group-aligned and
   raises the typed BudgetExceeded — never an OOM — when nothing fits;
3. assert_index_headroom accepts every shape up to (and past) the
   512k x 102k target and rejects shapes whose flattened-index
   products would wrap their container dtypes (property-tested).
"""

import dataclasses

import numpy as np
import pytest

from kubernetes_tpu import metrics
from kubernetes_tpu.solver import budget as hbm
from kubernetes_tpu.solver.budget import (
    BudgetExceeded,
    DrainShape,
    IndexWidthError,
)
from kubernetes_tpu.tensorize.schema import LANE, bucket_pow2

from _hypothesis_compat import given, settings, st


def test_node_padding_mirrors_snapshot_discipline():
    import math

    assert hbm.node_padding(1) == LANE
    assert hbm.node_padding(300) == bucket_pow2(300)
    # mesh-sharded: lcm(LANE, devices) honored past the pow2 bucket
    pad = hbm.node_padding(100_003, pad_multiple=8)
    assert pad >= 100_003
    assert pad % math.lcm(LANE, 8) == 0
    # non-pow2 device counts force the lcm rounding to matter
    pad6 = hbm.node_padding(130, pad_multiple=6)
    assert pad6 % math.lcm(LANE, 6) == 0


def test_pod_padding_grouped_vs_pow2():
    assert hbm.pod_padding(256, 64) == 256  # group-aligned: exact
    assert hbm.pod_padding(200, 64) == bucket_pow2(200)
    assert hbm.pod_padding(0, 64) == bucket_pow2(1)


def test_estimate_matches_measured_h2d_within_tolerance():
    """The checkable-model gate: predict a fresh-session solve's
    host->device bytes, run the REAL solve, compare against the
    counter delta. Tolerance: 10% (documented — the model's only
    unmirrored terms are a few dummy scalar uploads)."""
    from kubernetes_tpu.server.bulk import columnar_pod_batch
    from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig
    from kubernetes_tpu.tensorize.schema import NodeBatch, ResourceVocab

    n_nodes, n_pods, group = 300, 256, 64
    npad = hbm.node_padding(n_nodes)
    vocab = ResourceVocab(("cpu", "memory", "ephemeral-storage"))
    alloc = np.zeros((3, npad), np.int64)
    alloc[0, :n_nodes] = 16_000
    alloc[1, :n_nodes] = 64 << 30
    live = np.arange(npad) < n_nodes
    batch = NodeBatch(
        vocab=vocab,
        names=[f"n{i}" for i in range(n_nodes)],
        num_nodes=n_nodes,
        padded=npad,
        allocatable=alloc,
        used=np.zeros((3, npad), np.int64),
        nonzero_used=np.zeros((2, npad), np.int64),
        pod_count=np.zeros(npad, np.int32),
        max_pods=np.where(live, 110, 0).astype(np.int32),
        valid=live,
        schedulable=live.copy(),
    )
    pb = columnar_pod_batch(
        np.full(n_pods, 250, np.int64),
        np.full(n_pods, 512 << 20, np.int64),
        None,
        vocab,
    )
    # compact_wire off: the estimate's full-row session_upload_bytes is
    # the arm being validated (the compact path is a strict subset)
    solver = ExactSolver(
        ExactSolverConfig(
            tie_break="first", group_size=group, compact_wire=False
        )
    )
    cv = np.ones(npad, dtype=np.int64)
    h2d0 = metrics.h2d_bytes_total._value.get()
    a = solver.solve(batch, pb, col_versions=cv)
    measured = metrics.h2d_bytes_total._value.get() - h2d0
    assert int((np.asarray(a) >= 0).sum()) == n_pods

    est = hbm.estimate(
        DrainShape(nodes=n_nodes, chunk_pods=n_pods, group=group)
    )
    assert est.node_pad == npad
    assert est.pod_pad == pb.padded
    ratio = measured / est.session_upload_bytes
    assert 0.9 <= ratio <= 1.1, (
        f"measured {measured} vs estimated {est.session_upload_bytes} "
        f"(ratio {ratio:.3f}) — the byte model drifted from solve()'s "
        "wire accounting"
    )


def test_estimate_compact_and_chained_are_cheaper():
    est = hbm.estimate(DrainShape(nodes=1000, chunk_pods=1024, group=64))
    assert est.chunk_upload_bytes_compact < est.chunk_upload_bytes
    # a chained chunk additionally skips the bstate rows
    assert est.bstate_bytes > 0
    assert est.session_upload_bytes > est.chunk_upload_bytes


def test_estimate_scales_with_mesh_and_pods():
    base = DrainShape(nodes=10_000, chunk_pods=4096, group=64)
    one = hbm.estimate(base)
    mesh = hbm.estimate(dataclasses.replace(base, mesh_devices=8))
    # node-sharded residents divide across the mesh; replicated per-pod
    # arrays do not
    assert mesh.per_device_bytes < one.per_device_bytes
    small = hbm.estimate(dataclasses.replace(base, chunk_pods=512))
    assert small.per_device_bytes < one.per_device_bytes


def test_plan_chunk_auto_splits_group_aligned():
    shape = DrainShape(nodes=1000, chunk_pods=4096, group=64)
    full = hbm.estimate(shape)
    est, splits = hbm.plan_chunk(shape, full.per_device_bytes - 1)
    assert splits >= 1
    assert est.chunk_pods < 4096
    assert est.chunk_pods % 64 == 0
    assert est.per_device_bytes < full.per_device_bytes
    # a comfortable budget takes no splits
    est2, splits2 = hbm.plan_chunk(shape, full.per_device_bytes)
    assert splits2 == 0 and est2.chunk_pods == 4096


def test_plan_chunk_raises_typed_budget_exceeded():
    shape = DrainShape(nodes=1000, chunk_pods=4096, group=64)
    with pytest.raises(BudgetExceeded) as ei:
        hbm.plan_chunk(shape, 1000)
    # the exception carries the floor-chunk estimate for the operator
    assert ei.value.estimate.chunk_pods <= 64
    assert ei.value.budget_bytes == 1000


def test_index_headroom_accepts_the_10x_target_shape():
    # 512k pods x 102,400 nodes, hostname-domain d_pad, ladder group
    hbm.assert_index_headroom(
        524_288, 131_072, d_pad=131_072, group=1024
    )
    # and the auction's shape check on the same axes
    hbm.assert_index_headroom(524_288, 131_072)


def test_index_headroom_rejects_overflowing_shapes():
    with pytest.raises(IndexWidthError):
        hbm.assert_index_headroom(1 << 31, 1024)
    with pytest.raises(IndexWidthError):
        hbm.assert_index_headroom(1024, 1 << 31)
    with pytest.raises(IndexWidthError):
        # group x d_pad position product past int32
        hbm.assert_index_headroom(
            1024, 1024, d_pad=1 << 21, group=1 << 11
        )


@settings(max_examples=50, deadline=None)
@given(
    pod_pad=st.integers(min_value=1, max_value=1 << 23),
    node_pad=st.integers(min_value=LANE, max_value=1 << 21),
    d_pad=st.integers(min_value=8, max_value=1 << 21),
    group=st.integers(min_value=1, max_value=4096),
)
def test_index_headroom_property(pod_pad, node_pad, d_pad, group):
    """Any shape within an order of magnitude past the 10x target
    passes; the guard clauses fire exactly on their documented
    bounds (cheap property test — host ints only)."""
    if (group + 1) * d_pad + d_pad < (1 << 31):
        hbm.assert_index_headroom(pod_pad, node_pad, d_pad, group)
    else:
        with pytest.raises(IndexWidthError):
            hbm.assert_index_headroom(pod_pad, node_pad, d_pad, group)
