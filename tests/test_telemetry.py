"""ISSUE 18 tentpole: the flight-telemetry loop — profile -> detect ->
capture -> replay — as units (ring arithmetic, sentinel rules, capturer
lifecycle, top renderer) and end-to-end (the anomaly_storm sim writes
real bundles and every carry-clean one replays bit-identical offline).
"""

import dataclasses
import json
import subprocess
import sys

import numpy as np
import pytest

from kubernetes_tpu.obs import ObsConfig, build_telemetry
from kubernetes_tpu.obs.bundle import BundleCapturer, replay_bundle
from kubernetes_tpu.obs.profile import STAGES, StageProfiler, render_top
from kubernetes_tpu.obs.sentinel import AnomalySentinel, SentinelConfig
from kubernetes_tpu.obs.timeseries import TimeSeriesRing
from kubernetes_tpu.utils.clock import FakeClock

# -- timeseries ring --------------------------------------------------------


class TestTimeSeriesRing:
    def test_append_means_and_baseline(self):
        ring = TimeSeriesRing(8)
        for v in (10.0, 20.0, 30.0, 40.0, 50.0, 60.0):
            ring.append(t=v, batches=1, pods=1, signals={"x": v})
        assert len(ring) == 6
        assert ring.mean("x", 3) == pytest.approx(50.0)
        # baseline = the 3 windows before the trailing 3
        assert ring.mean_prev("x", 3, skip=3) == pytest.approx(20.0)
        # missing signal reads as 0.0, empty slices too
        assert ring.mean("nope", 3) == 0.0
        assert TimeSeriesRing(4).mean("x", 3) == 0.0

    def test_capacity_bound_keeps_seq_monotone(self):
        ring = TimeSeriesRing(4)
        for i in range(10):
            ring.append(t=float(i), batches=1, pods=0, signals={})
        assert len(ring) == 4
        assert ring.last().seq == 9  # seq counts evictions too

    def test_rejects_degenerate_capacity(self):
        with pytest.raises(ValueError):
            TimeSeriesRing(3)

    def test_snapshot_is_json_ready(self):
        ring = TimeSeriesRing(8)
        ring.append(t=1.23456789, batches=2, pods=5, signals={"x": 0.1})
        snap = ring.snapshot(4)
        json.dumps(snap)
        assert snap[-1]["pods"] == 5


# -- stage profiler ---------------------------------------------------------


class TestStageProfiler:
    def test_ledger_totals_and_fractions(self):
        clock = FakeClock()
        prof = StageProfiler(clock=clock)
        prof.add("tensorize", 0.25)
        prof.add("dispatch", 0.5)
        prof.add("dispatch", 0.25)
        prof.add("bind", 0.0)  # zero attribution is dropped
        clock.advance(2.0)
        entry = prof.observe_batch(step=1, pods=8)
        assert entry["stages"]["dispatch"] == pytest.approx(0.75)
        assert entry["stages"]["bind"] == 0.0
        snap = prof.snapshot()
        assert snap["batches"] == 1 and snap["pods"] == 8
        assert set(snap["stage_seconds"]) == set(STAGES)
        assert snap["stage_fraction"]["tensorize"] == pytest.approx(0.25)
        assert sum(snap["stage_fraction"].values()) == pytest.approx(1.0)

    def test_wall_is_delta_between_batches(self):
        clock = FakeClock()
        prof = StageProfiler(clock=clock)
        assert prof.observe_batch(step=1, pods=1)["wall_s"] == 0.0
        clock.advance(1.5)
        assert prof.observe_batch(step=2, pods=1)["wall_s"] == (
            pytest.approx(1.5)
        )

    def test_ledger_is_bounded(self):
        prof = StageProfiler(clock=FakeClock(), capacity=16)
        for i in range(40):
            prof.observe_batch(step=i, pods=1)
        snap = prof.snapshot(recent=100)
        assert len(snap["recent"]) == 16
        assert snap["batches"] == 40  # totals outlive the ring


# -- anomaly sentinel -------------------------------------------------------


def _small_cfg(**kw) -> SentinelConfig:
    base = dict(
        window_batches=1, fast_windows=1, slow_windows=3, spike_ratio=2.0,
        drift_ratio=1.5, hysteresis=1, cooldown_windows=4, min_windows=3,
        min_events=1.0, recover_windows=2,
    )
    base.update(kw)
    return SentinelConfig(**base)


def _window(sent, **signals):
    sample = sent.ring.append(
        t=float(len(sent.fired) + len(sent.ring)), batches=1, pods=0,
        signals=signals,
    )
    return sent.observe_window(sample)


class TestAnomalySentinel:
    def test_warmup_silence_then_spike_on_collapse(self):
        sent = AnomalySentinel(_small_cfg())
        for _ in range(4):
            assert _window(sent, pods_per_sec=1000.0) == []
        fired = _window(sent, pods_per_sec=100.0)
        assert [a.kind for a in fired] == ["spike"]
        assert fired[0].signal == "pods_per_sec"
        assert sent.degraded

    def test_hysteresis_needs_consecutive_regressions(self):
        sent = AnomalySentinel(_small_cfg(hysteresis=2))
        for _ in range(4):
            _window(sent, pods_per_sec=1000.0)
        assert _window(sent, pods_per_sec=100.0) == []  # streak 1
        fired = _window(sent, pods_per_sec=100.0)  # streak 2 -> fires
        assert [a.kind for a in fired] == ["spike"]

    def test_cooldown_silences_refire(self):
        sent = AnomalySentinel(_small_cfg())
        for _ in range(4):
            _window(sent, pods_per_sec=1000.0)
        assert _window(sent, pods_per_sec=100.0)
        # still collapsed: the signal is cooling down, not re-firing
        assert _window(sent, pods_per_sec=100.0) == []
        assert sent.fired_total == 1

    def test_degraded_clears_after_clean_recovery_windows(self):
        sent = AnomalySentinel(_small_cfg())
        for _ in range(4):
            _window(sent, pods_per_sec=1000.0)
        _window(sent, pods_per_sec=100.0)
        assert sent.degraded
        _window(sent, pods_per_sec=1000.0)
        assert sent.degraded  # 1 of recover_windows=2
        _window(sent, pods_per_sec=1000.0)
        assert not sent.degraded

    def test_breaker_edge_fires_even_under_tuner_suppression(self):
        sent = AnomalySentinel(_small_cfg())
        sample = sent.ring.append(
            t=0.0, batches=1, pods=0,
            signals={"breaker": 1.0, "pods_per_sec": 0.0},
        )
        fired = sent.observe_window(sample, suppress=True)
        assert [a.kind for a in fired] == ["edge"]
        assert sent.suppressed_windows == 1

    def test_event_floor_gates_near_zero_baseline_rates(self):
        sent = AnomalySentinel(_small_cfg(min_events=3.0))
        for _ in range(4):
            _window(sent, discard_rate=0.0)
        # regressed by ratio but under the absolute floor: noise
        assert _window(sent, discard_rate=2.0) == []
        fired = _window(sent, discard_rate=5.0)
        assert [a.signal for a in fired] == ["discard_rate"]

    def test_drift_catches_slow_degradation_spike_misses(self):
        sent = AnomalySentinel(_small_cfg())
        for v in (1000.0, 1000.0, 1000.0, 650.0, 650.0):
            assert _window(sent, pods_per_sec=v) == []
        # ring now holds 2x slow_windows; slow=650 vs prev slow=1000
        fired = _window(sent, pods_per_sec=650.0)
        assert [a.kind for a in fired] == ["drift"]

    def test_snapshot_schema(self):
        sent = AnomalySentinel(_small_cfg())
        for _ in range(4):
            _window(sent, pods_per_sec=1000.0)
        _window(sent, pods_per_sec=100.0)
        snap = sent.snapshot()
        json.dumps(snap)
        assert snap["fired_total"] == 1
        a = snap["recent_anomalies"][-1]
        assert a["signal"] == "pods_per_sec" and a["kind"] == "spike"

    def test_config_validation(self):
        with pytest.raises(ValueError):
            SentinelConfig(fast_windows=5, slow_windows=3).validate()
        with pytest.raises(ValueError):
            SentinelConfig(spike_ratio=1.0).validate()


# -- bundle capturer lifecycle ---------------------------------------------


@dataclasses.dataclass
class _FakePods:
    """Stands in for PodBatch on the in-memory lifecycle paths (the
    capturer only reads ``num_pods`` and copies ndarray fields there;
    real-schema encode/decode is proven by the e2e replay below)."""

    num_pods: int
    cpu: np.ndarray


def _solve_payload(n=3):
    return dict(
        pods=_FakePods(n, np.arange(n)), step_count=5, split=1,
        session=False, allow_heal=True, chain_occupancy=False,
    )


class TestBundleCapturer:
    def test_arm_capture_complete_record_counts_without_dir(self):
        cap = BundleCapturer(None)
        cap.arm(7, profile="t")
        cap.on_solve_input(**_solve_payload())
        cap.note_assignments(7, 0, [0, 1, 2])
        assert cap.capture("manual", note="x") is None  # no out_dir
        snap = cap.snapshot()
        assert snap["captures"] == 1 and snap["missed"] == 0
        assert snap["by_trigger"] == {"manual": 1}
        assert snap["written"] == []

    def test_trigger_with_nothing_complete_is_a_miss(self):
        cap = BundleCapturer(None)
        assert cap.capture("sentinel") is None
        assert cap.snapshot()["missed"] == 1

    def test_partial_coverage_keeps_record_pending(self):
        cap = BundleCapturer(None)
        cap.arm(9)
        cap.on_solve_input(**_solve_payload(n=3))
        cap.note_assignments(9, 0, [0, 1])
        assert cap.snapshot()["pending"] == 1
        cap.note_assignments(9, 2, [2])
        assert cap.snapshot()["ring_complete"] == 1

    def test_drop_kills_the_armed_record(self):
        cap = BundleCapturer(None)
        cap.arm(4)
        cap.drop(4)
        cap.on_solve_input(**_solve_payload())  # disarmed: ignored
        cap.note_assignments(4, 0, [0, 1, 2])
        assert cap.capture("sentinel") is None
        assert cap.snapshot()["missed"] == 1

    def test_unarmed_solve_input_is_ignored(self):
        cap = BundleCapturer(None)
        cap.on_solve_input(**_solve_payload())
        assert cap.snapshot()["pending"] == 0

    def test_carry_clean_tag(self):
        cap = BundleCapturer(None)
        cap.arm(1)
        cap.on_solve_input(
            **{**_solve_payload(), "session": True, "allow_heal": False}
        )
        cap.note_assignments(1, 0, [0, 1, 2])
        rec = cap._ring[-1]
        assert rec["payload"]["carry_clean"] is False


# -- build_telemetry gating -------------------------------------------------


class TestBuildTelemetry:
    def test_everything_off_returns_none(self):
        assert build_telemetry(None) is None
        assert build_telemetry(ObsConfig(spans=True, journal=True)) is None

    def test_profile_only(self):
        tel = build_telemetry(ObsConfig(profile=True))
        assert tel.profiler is not None
        assert tel.sentinel is None and tel.bundles is None
        assert tel.snapshot() == {
            "enabled": True, "profile": tel.profiler.snapshot(),
        }

    def test_sentinel_implies_profiler_and_memory_capturer(self):
        tel = build_telemetry(ObsConfig(sentinel=SentinelConfig()))
        assert tel.profiler is not None
        assert tel.bundles is not None and tel.bundles.out_dir is None
        assert tel.capture("manual") is None  # counts, writes nothing
        assert tel.bundles.snapshot()["missed"] == 1


# -- obs top renderer -------------------------------------------------------


class TestRenderTop:
    def _snapshot(self):
        return {
            "enabled": True,
            "profile": {
                "batches": 4, "pods": 32,
                "stage_seconds": {s: 0.1 for s in STAGES},
                "stage_fraction": {s: 1.0 / len(STAGES) for s in STAGES},
                "recent": [
                    {"step": 9, "pods": 8, "wall_s": 0.5,
                     "h2d_bytes": 1024.0, "d2h_bytes": 64.0}
                ],
            },
            "sentinel": {
                "degraded": True, "fired_total": 2,
                "suppressed_windows": 1,
                "recent_anomalies": [
                    {"signal": "pods_per_sec", "kind": "spike",
                     "value": 100.0, "baseline": 1000.0, "window": 7}
                ],
            },
            "bundles": {
                "captures": 2, "missed": 0,
                "by_trigger": {"sentinel": 1, "manual": 1},
                "written": ["/tmp/b/bundle-00000-sentinel",
                            "/tmp/b/bundle-00001-manual"],
            },
        }

    def test_full_snapshot_renders_every_section(self):
        out = render_top(self._snapshot())
        assert "flight telemetry — 4 batches, 32 pods" in out
        for s in STAGES:
            assert s in out
        assert "last batch: step=9" in out
        assert "degraded=True fired_total=2" in out
        assert "pods_per_sec (spike)" in out
        # written is a PATH LIST in the snapshot — rendered as a count
        assert "written=2" in out
        assert "manual=1,sentinel=1" in out

    def test_tolerates_partially_enabled_telemetry(self):
        out = render_top({"enabled": True, "profile": {
            "batches": 0, "pods": 0, "stage_seconds": {},
            "stage_fraction": {}, "recent": [],
        }})
        assert "0 batches" in out
        assert "sentinel" not in out and "bundles" not in out

    def test_obs_top_cli_renders_snapshot_file(self, tmp_path):
        f = tmp_path / "snap.json"
        f.write_text(json.dumps(self._snapshot()))
        out = subprocess.run(
            [sys.executable, "-m", "kubernetes_tpu.obs", "top",
             "--snapshot", str(f)],
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert "flight telemetry — 4 batches" in out.stdout


# -- end-to-end: the forensic loop over a real sim --------------------------


def test_anomaly_storm_forensic_loop(tmp_path):
    """The tentpole's closed loop, tier-1: anomaly_storm drives the
    sentinel (solver faults trip the breaker + collapse pods/s), every
    fire captures a bundle to disk, and each carry-clean bundle
    replays offline to BIT-IDENTICAL assignments. A tampered bundle
    must diverge — the comparison has teeth."""
    from kubernetes_tpu.sim.harness import run_sim

    r = run_sim(
        "anomaly_storm", seed=0, cycles=12, bundle_dir=str(tmp_path)
    )
    assert r.violations == []
    tel = r.summary["telemetry"]
    assert tel["anomalies"] >= 1
    assert "breaker" in tel["anomaly_signals"]
    assert tel["bundles_captured"] >= 1
    assert sum(tel["bundle_triggers"].values()) == tel["bundles_captured"]

    bundles = sorted(str(p) for p in tmp_path.glob("bundle-*"))
    assert bundles, "sentinel fired but nothing hit disk"
    replayed = []
    for b in bundles:
        rep = replay_bundle(b)
        if rep["replayable"]:
            assert rep["ok"], f"{b}: {rep['detail']}"
            replayed.append(b)
    assert replayed, "no carry-clean bundle — the loop never closed"

    # tamper with the stored ground truth: replay must catch it
    mpath = tmp_path / replayed[0].rsplit("/", 1)[1] / "manifest.json"
    manifest = json.loads(mpath.read_text())
    manifest["parts"][0]["assignments"][0] += 1
    mpath.write_text(json.dumps(manifest))
    rep = replay_bundle(replayed[0])
    assert rep["replayable"] and not rep["ok"]
    assert "mismatch" in rep["detail"]
