"""Kernel ≡ oracle parity for NodeResourcesFit + BalancedAllocation, and
solver ≡ sequential-oracle parity for the exact scan solver.

This is the test strategy from SURVEY.md §8.6: the NumPy/scalar oracle is the
transcription of the reference semantics; hypothesis drives random and
adversarial pod/node populations through both implementations.
"""

import numpy as np
import pytest
from _hypothesis_compat import given, settings, st

import jax.numpy as jnp

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.ops import noderesources as nr
from kubernetes_tpu.ops.oracle import noderesources as onr
from kubernetes_tpu.ops.oracle import scheduler as osched
from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig
from kubernetes_tpu.tensorize.schema import build_node_batch, build_pod_batch


def mk_nodes(specs):
    """specs: list of (cpu_milli, mem_bytes, pods)"""
    return [
        MakeNode()
        .name(f"node-{i}")
        .capacity({"cpu": f"{c}m", "memory": str(m), "pods": str(p)})
        .obj()
        for i, (c, m, p) in enumerate(specs)
    ]


def mk_pod(i, cpu_milli, mem_bytes):
    req = {}
    if cpu_milli:
        req["cpu"] = f"{cpu_milli}m"
    if mem_bytes:
        req["memory"] = str(mem_bytes)
    mp = MakePod().name(f"pod-{i}")
    if req:
        mp = mp.req(req)
    return mp.obj()


node_spec = st.tuples(
    st.integers(min_value=0, max_value=64_000),  # cpu milli
    st.integers(min_value=0, max_value=256 * 1024**3),  # mem bytes
    st.integers(min_value=0, max_value=16),  # pods
)
pod_spec = st.tuples(
    st.integers(min_value=0, max_value=8_000),
    st.integers(min_value=0, max_value=32 * 1024**3),
)


class TestKernelVsOracle:
    @settings(max_examples=50, deadline=None)
    @given(
        nodes=st.lists(node_spec, min_size=1, max_size=8),
        placed=st.lists(pod_spec, min_size=0, max_size=6),
        pod=pod_spec,
        data=st.data(),
    )
    def test_fit_and_scores_match(self, nodes, placed, pod, data):
        node_objs = mk_nodes(nodes)
        # scatter pre-placed pods onto random nodes
        pods_by_node = {}
        placed_objs = []
        for j, (c, m) in enumerate(placed):
            tgt = data.draw(st.integers(0, len(node_objs) - 1))
            po = mk_pod(1000 + j, c, m)
            pods_by_node.setdefault(node_objs[tgt].name, []).append(po)
            placed_objs.append(po)

        batch = build_node_batch(node_objs, pods_by_node)
        states = osched.make_node_states(node_objs, pods_by_node)
        p = mk_pod(0, *pod)

        req = jnp.asarray(batch.vocab.vectorize(p.resource_request()))
        rmask = req > 0
        mask = np.asarray(
            nr.fit_mask(
                req,
                rmask,
                jnp.asarray(batch.allocatable),
                jnp.asarray(batch.used),
                jnp.asarray(batch.pod_count),
                jnp.asarray(batch.max_pods),
            )
        )
        nz = jnp.asarray(np.array(p.non_zero_request(), dtype=np.int64))
        requested = nr.scoring_requested(nz, jnp.asarray(batch.nonzero_used))
        alloc2 = jnp.asarray(batch.allocatable[:2])
        w2 = jnp.ones(2, dtype=jnp.int64)
        least = np.asarray(nr.least_allocated_score(requested, alloc2, w2))
        most = np.asarray(nr.most_allocated_score(requested, alloc2, w2))
        bal = np.asarray(
            nr.balanced_allocation_score(requested, alloc2, fdtype=jnp.float64)
        )

        for i, stt in enumerate(states):
            assert mask[i] == (not onr.fit_filter(p, stt)), f"fit node {i}"
            assert least[i] == onr.least_allocated_score(p, stt), f"least node {i}"
            assert most[i] == onr.most_allocated_score(p, stt), f"most node {i}"
            assert bal[i] == onr.balanced_allocation_score(p, stt), f"balanced node {i}"

    def test_padded_lanes_never_fit(self):
        node_objs = mk_nodes([(4000, 8 * 1024**3, 10)])
        batch = build_node_batch(node_objs)  # padded to 128
        p = mk_pod(0, 100, 1024**2)
        req = jnp.asarray(batch.vocab.vectorize(p.resource_request()))
        mask = np.asarray(
            nr.fit_mask(
                req,
                req > 0,
                jnp.asarray(batch.allocatable),
                jnp.asarray(batch.used),
                jnp.asarray(batch.pod_count),
                jnp.asarray(batch.max_pods),
            )
        ) & np.asarray(batch.valid)
        assert mask[0]
        assert not mask[1:].any()

    def test_rtc_shape_matches_oracle(self):
        # default shape: 0 util -> 10, 100 util -> 0 (least-allocated-like)
        shape = [(0, 10), (100, 0)]
        node_objs = mk_nodes([(4000, 8 * 1024**3, 10), (2000, 4 * 1024**3, 10)])
        pods_by_node = {"node-0": [mk_pod(9, 1000, 1024**3)]}
        batch = build_node_batch(node_objs, pods_by_node)
        states = osched.make_node_states(node_objs, pods_by_node)
        p = mk_pod(0, 500, 2 * 1024**3)
        nz = jnp.asarray(np.array(p.non_zero_request(), dtype=np.int64))
        requested = nr.scoring_requested(nz, jnp.asarray(batch.nonzero_used))
        got = np.asarray(
            nr.rtc_score(
                requested,
                jnp.asarray(batch.allocatable[:2]),
                jnp.ones(2, dtype=jnp.int64),
                jnp.asarray([0, 100]),
                jnp.asarray([10, 0]),
            )
        )
        for i, stt in enumerate(states):
            assert got[i] == onr.requested_to_capacity_ratio_score(p, stt, shape)


class TestSolverVsOracle:
    def _run(self, node_specs, pod_specs, tie="first"):
        node_objs = mk_nodes(node_specs)
        pod_objs = [mk_pod(i, c, m) for i, (c, m) in enumerate(pod_specs)]
        batch = build_node_batch(node_objs)
        pbatch = build_pod_batch(pod_objs, batch.vocab)
        solver = ExactSolver(
            ExactSolverConfig(tie_break=tie, balanced_fdtype="float64")
        )
        got = solver.solve(batch, pbatch)
        return node_objs, pod_objs, got

    def test_matches_oracle_first_tiebreak(self):
        node_specs = [(4000, 8 * 1024**3, 5), (8000, 16 * 1024**3, 5), (2000, 4 * 1024**3, 5)]
        pod_specs = [(500, 1024**3), (1000, 2 * 1024**3), (0, 0), (4000, 1024**3), (500, 1024**3)]
        node_objs, pod_objs, got = self._run(node_specs, pod_specs)
        oracle = osched.schedule(pod_objs, osched.make_node_states(node_objs))
        assert list(got) == oracle.assignments

    def test_random_tiebreak_stays_in_tie_set(self):
        node_specs = [(4000, 8 * 1024**3, 10)] * 6  # identical nodes => ties
        pod_specs = [(500, 1024**3)] * 12
        node_objs, pod_objs, got = self._run(node_specs, pod_specs, tie="random")
        errors = osched.validate_assignments(
            pod_objs, osched.make_node_states(node_objs), got
        )
        assert not errors, errors

    def test_unschedulable_pods_marked(self):
        node_specs = [(1000, 1024**3, 1)]
        pod_specs = [(800, 0), (800, 0)]  # second won't fit cpu
        _, _, got = self._run(node_specs, pod_specs)
        assert got[0] == 0 and got[1] == -1

    def test_pod_count_exhaustion(self):
        node_specs = [(100_000, 1024**4, 2)]
        pod_specs = [(10, 0)] * 3
        _, _, got = self._run(node_specs, pod_specs)
        assert list(got) == [0, 0, -1]

    @settings(max_examples=20, deadline=None)
    @given(
        nodes=st.lists(node_spec, min_size=1, max_size=6),
        pods=st.lists(pod_spec, min_size=1, max_size=12),
    )
    def test_property_random_populations(self, nodes, pods):
        node_objs, pod_objs, got = self._run(nodes, pods)
        oracle = osched.schedule(pod_objs, osched.make_node_states(node_objs))
        assert list(got) == oracle.assignments

    def test_sequential_state_dependency(self):
        # first pod lands on the bigger node (least-allocated prefers it),
        # which must make the second pod see UPDATED state
        node_specs = [(2000, 4 * 1024**3, 10), (4000, 8 * 1024**3, 10)]
        pod_specs = [(1900, 3 * 1024**3)] * 3
        node_objs, pod_objs, got = self._run(node_specs, pod_specs)
        oracle = osched.schedule(pod_objs, osched.make_node_states(node_objs))
        assert list(got) == oracle.assignments
        # all three pods fit somewhere only if state tracking works
        assert (np.array(got) >= 0).sum() == 3


class TestReviewRegressions:
    def test_unknown_extended_resource_is_unschedulable(self):
        # pod requests a resource no node advertises: reference Fit fails it
        # everywhere; the vocab must not silently drop it
        node_objs = mk_nodes([(4000, 8 * 1024**3, 10)])
        batch = build_node_batch(node_objs)
        gpu_pod = (
            MakePod().name("gpu").req({"cpu": "100m", "example.com/gpu": "1"}).obj()
        )
        pbatch = build_pod_batch([gpu_pod], batch.vocab)
        assert not pbatch.feasible_static[0]
        solver = ExactSolver(ExactSolverConfig(tie_break="first"))
        got = solver.solve(batch, pbatch)
        assert got[0] == -1
        oracle = osched.schedule([gpu_pod], osched.make_node_states(node_objs))
        assert oracle.assignments == [-1]

    def test_known_extended_resource_still_works(self):
        n = (
            MakeNode()
            .name("gpu-node")
            .capacity({"cpu": "4", "memory": "8Gi", "pods": "10", "example.com/gpu": "2"})
            .obj()
        )
        batch = build_node_batch([n])
        p1 = MakePod().name("g1").req({"example.com/gpu": "2"}).obj()
        p2 = MakePod().name("g2").req({"example.com/gpu": "1"}).obj()
        pbatch = build_pod_batch([p1, p2], batch.vocab)
        solver = ExactSolver(ExactSolverConfig(tie_break="first"))
        got = solver.solve(batch, pbatch)
        assert list(got) == [0, -1]  # second pod: gpus exhausted

    def test_rtc_truncates_toward_zero_like_go(self):
        from kubernetes_tpu.ops.oracle.noderesources import _piecewise

        shape = [(0, 10), (100, 0)]
        # utilization 5: Go: 10 + trunc(-50/100) = 10; floor would give 9
        assert _piecewise(shape, 5) == 10
        assert _piecewise(shape, 95) == 1  # 10 + trunc(-950/100) = 10-9
        assert _piecewise(shape, 100) == 0

    def test_rtc_kernel_matches_trunc_semantics(self):
        node_objs = mk_nodes([(10_000, 10 * 1024**3, 10)])
        pods_by_node = {"node-0": [mk_pod(1, 500, 512 * 1024**2)]}
        batch = build_node_batch(node_objs, pods_by_node)
        states = osched.make_node_states(node_objs, pods_by_node)
        p = mk_pod(0, 1, 1)  # tiny -> low utilization -> negative-slope interp
        nz = jnp.asarray(np.array(p.non_zero_request(), dtype=np.int64))
        requested = nr.scoring_requested(nz, jnp.asarray(batch.nonzero_used))
        got = np.asarray(
            nr.rtc_score(
                requested,
                jnp.asarray(batch.allocatable[:2]),
                jnp.ones(2, dtype=jnp.int64),
                jnp.asarray([0, 100]),
                jnp.asarray([10, 0]),
            )
        )
        assert got[0] == onr.requested_to_capacity_ratio_score(p, states[0], [(0, 10), (100, 0)])

    def test_gt_int64_range_rejected(self):
        from kubernetes_tpu.api.labels import Requirement

        big = str(2**63)  # out of int64: Go ParseInt -> ErrRange -> no match
        assert not Requirement("k", "Gt", ("5",)).matches({"k": big})
        ok = str(2**63 - 1)
        assert Requirement("k", "Gt", ("5",)).matches({"k": ok})
