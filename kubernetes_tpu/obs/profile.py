"""Continuous per-stage profiler: where did each batch's wall time go?

A bounded per-batch **stage ledger** assembled host-side from numbers
the dispatch loops already compute — ``prep.tensorize_seconds``, the
dispatch span, the deferred-read wait, the locked validate/apply
region, the per-entry bind wall — plus within-batch deltas of the
transfer/decision counters (h2d/d2h bytes, sub-batch splits, stream
chains, discards). Zero new device syncs (TPU001-clean): every number
is either a ``clock.perf()`` difference the loop already took or a
host-side prometheus cell read, the CounterWindow discipline from
``tuning/window.py``.

Exported as ``scheduler_profile_stage_seconds{stage}`` (cumulative
seconds per stage — ``rate()`` it to see the live stage mix), rendered
by ``python -m kubernetes_tpu.obs top`` and ``GET /debug/profile``.

Stage taxonomy (one batch's life):

    tensorize     host: cluster state -> padded device arrays
    dispatch      host: solve dispatch (upload + jit call, async)
    fence_wait    host: work discarded to fences (stale flights)
    deferred_read device->host: blocking assignment read (the RTT)
    validate      host: assignment validation under the lock
    apply         host: assume/reserve under the lock
    bind          host: commit to the state service (api round-trip)
"""

from __future__ import annotations

import threading
from collections import deque

from .. import metrics

STAGES = (
    "tensorize",
    "dispatch",
    "fence_wait",
    "deferred_read",
    "validate",
    "apply",
    "bind",
)


def _cell(counter) -> float:
    return counter._value.get()  # prometheus_client internal, host-side


def _labeled_total(counter) -> float:
    """Sum over every child of a labeled counter (the
    ``tuning/window.py`` discipline) without materializing new labels."""
    try:
        with counter._lock:
            children = list(counter._metrics.values())
    except AttributeError:
        return 0.0
    return float(sum(c._value.get() for c in children))


# within-batch deltas folded into each ledger entry: transfer volume
# and the chain/split/discard decisions the loops tick. All host-side
# cells (the device never syncs to serve a read here).
_DELTA_READERS = {
    "h2d_bytes": lambda: _cell(metrics.h2d_bytes_total),
    "d2h_bytes": lambda: _cell(metrics.d2h_bytes_total),
    "subbatches": lambda: _cell(metrics.pipeline_subbatches_total),
    "solve_discards": lambda: _cell(metrics.solves_discarded_total),
    "slot_discards": lambda: _cell(metrics.stream_slot_discard_total),
    "unhidden_reads": lambda: _cell(metrics.stream_unhidden_reads_total),
}


class StageProfiler:
    """Always-on per-batch stage attribution.

    The loops call :meth:`add` at the seams they already time and
    :meth:`observe_batch` once per applied batch (next to the SLO
    tick in ``_commit_all``); readers call :meth:`snapshot` from any
    thread. ``capacity`` bounds the ledger — a serving process retains
    the recent history, never the run.
    """

    def __init__(self, clock=None, capacity: int = 512) -> None:
        import time as _time

        self._perf = clock.perf if clock is not None else _time.perf_counter
        self._ledger: deque[dict] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        # stages accumulated since the last observe_batch (the loops'
        # add() calls between two commits belong to the batch closing)
        self._pending: dict[str, float] = {}
        self._totals = {s: 0.0 for s in STAGES}
        self._counters = {k: r() for k, r in _DELTA_READERS.items()}
        self._last_t: float | None = None
        self.batches = 0
        self.pods = 0
        self._stage_cells = {
            s: metrics.profile_stage_seconds.labels(s) for s in STAGES
        }

    # -- driver-thread writes --

    def add(self, stage: str, seconds: float) -> None:
        """Attribute ``seconds`` of already-measured wall time to a
        stage of the batch currently in flight."""
        if seconds <= 0.0:
            return
        self._pending[stage] = self._pending.get(stage, 0.0) + seconds

    def observe_batch(self, *, step: int, pods: int) -> dict:
        """Close the in-flight batch's ledger entry: fold the pending
        stage seconds and the counter deltas since the previous batch,
        tick the stage metrics, append to the bounded ledger."""
        now = self._perf()
        wall = 0.0 if self._last_t is None else max(now - self._last_t, 0.0)
        self._last_t = now
        stages = {s: self._pending.get(s, 0.0) for s in STAGES}
        self._pending.clear()
        deltas = {}
        for k, read in _DELTA_READERS.items():
            cur = read()
            deltas[k] = cur - self._counters[k]
            self._counters[k] = cur
        entry = {
            "step": step,
            "pods": pods,
            "wall_s": round(wall, 6),
            "stages": {k: round(v, 6) for k, v in stages.items()},
            **{k: round(v, 1) for k, v in deltas.items()},
        }
        with self._lock:
            self._ledger.append(entry)
            self.batches += 1
            self.pods += pods
            for s, v in stages.items():
                if v > 0.0:
                    self._totals[s] += v
                    self._stage_cells[s].inc(v)
        return entry

    # -- any-thread reads --

    def snapshot(self, recent: int = 32) -> dict:
        """JSON-ready profile state: cumulative stage seconds, the
        stage mix, and the trailing ``recent`` ledger entries."""
        with self._lock:
            totals = dict(self._totals)
            tail = list(self._ledger)[-recent:]
            batches, pods = self.batches, self.pods
        accounted = sum(totals.values())
        return {
            "batches": batches,
            "pods": pods,
            "stage_seconds": {
                s: round(totals[s], 6) for s in STAGES
            },
            "stage_fraction": {
                s: round(totals[s] / accounted, 4) if accounted else 0.0
                for s in STAGES
            },
            "recent": tail,
        }


def render_top(snapshot: dict) -> str:
    """Terminal rendering of a ``Telemetry.snapshot()`` document (the
    ``python -m kubernetes_tpu.obs top`` view — same doc GET
    /debug/profile serves). Pure string formatting, separately
    unit-tested; tolerant of partially-enabled telemetry (profiler
    without sentinel, sentinel without bundles)."""
    lines: list[str] = []
    prof = snapshot.get("profile") or {}
    batches = prof.get("batches", 0)
    pods = prof.get("pods", 0)
    lines.append(f"flight telemetry — {batches} batches, {pods} pods")
    if prof:
        totals = prof.get("stage_seconds", {})
        fracs = prof.get("stage_fraction", {})
        lines.append(
            f"  {'stage':<14} {'total_s':>10} {'frac':>7} "
            f"{'per_batch_ms':>13}"
        )
        for s in STAGES:
            tot = float(totals.get(s, 0.0))
            per_batch_ms = (tot / batches * 1000.0) if batches else 0.0
            lines.append(
                f"  {s:<14} {tot:>10.4f} "
                f"{float(fracs.get(s, 0.0)) * 100.0:>6.1f}% "
                f"{per_batch_ms:>13.3f}"
            )
        recent = prof.get("recent") or []
        if recent:
            last = recent[-1]
            lines.append(
                f"  last batch: step={last.get('step')} "
                f"pods={last.get('pods')} wall_s={last.get('wall_s')} "
                f"h2d={last.get('h2d_bytes', 0):.0f}B "
                f"d2h={last.get('d2h_bytes', 0):.0f}B"
            )
    sent = snapshot.get("sentinel")
    if sent:
        lines.append(
            f"  sentinel: degraded={sent.get('degraded', False)} "
            f"fired_total={sent.get('fired_total', 0)} "
            f"suppressed_windows={sent.get('suppressed_windows', 0)}"
        )
        for a in (sent.get("recent_anomalies") or [])[-4:]:
            lines.append(
                f"    anomaly[{a.get('window')}] {a.get('signal')} "
                f"({a.get('kind')}): value={a.get('value')} "
                f"baseline={a.get('baseline')}"
            )
    bundles = snapshot.get("bundles")
    if bundles:
        trig = ",".join(
            f"{k}={v}"
            for k, v in sorted((bundles.get("by_trigger") or {}).items())
        )
        written = bundles.get("written") or ()
        n_written = (
            len(written) if isinstance(written, (list, tuple)) else written
        )
        lines.append(
            f"  bundles: captures={bundles.get('captures', 0)} "
            f"written={n_written} "
            f"missed={bundles.get('missed', 0)} "
            f"triggers=[{trig or '-'}]"
        )
    return "\n".join(lines)
