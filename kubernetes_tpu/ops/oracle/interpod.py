"""Scalar oracle for InterPodAffinity (Filter + Score).

Transcription of pkg/scheduler/framework/plugins/interpodaffinity/
{plugin,filtering,scoring}.go (SURVEY.md §3.2). The four cross-products:

Filter on candidate node n for incoming pod p:
1. EXISTING pods' required anti-affinity vs p (symmetry,
   filtering.go#satisfyExistingPodsAntiAffinity): for every existing pod q
   with required anti-affinity, each of q's terms whose selector matches p
   (namespace rule evaluated from q's perspective) "occupies" the domain
   (term.topologyKey -> q's node's value). n fails if it sits in any
   occupied domain.
2. p's required anti-affinity vs existing pods
   (#satisfyPodAntiAffinity): no existing pod matching a term may sit in
   n's domain for that term (n lacking the key => count 0 => passes).
3. p's required affinity (#satisfyPodAffinity): every term must have a
   matching existing pod in n's domain (n must have the key), EXCEPT the
   first-pod case: no matching pod exists anywhere for ANY term and p's own
   labels satisfy every term (allows bootstrapping a self-affine group).

Score (scoring.go#PreScore/#Score/#NormalizeScore):
  per existing pod q on node m, contributions keyed by q's domains:
  + w·matches for p's preferred affinity terms (q matches term selector)
  - w·matches for p's preferred anti-affinity terms
  + w_q·(q's preferred affinity terms matching p)        [symmetry]
  - w_q·(q's preferred anti-affinity terms matching p)   [symmetry]
  + hardPodAffinityWeight per required-affinity term of q matching p
  candidate n sums the entries of its own domains; NormalizeScore is
  max-min: 100*(score-min)/(max-min), 0 when max==min.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Sequence

from ...api.objects import Node, Pod, PodAffinityTerm

MAX_NODE_SCORE = 100
DEFAULT_HARD_POD_AFFINITY_WEIGHT = 1


def effective_term(term: PodAffinityTerm, owner: Pod) -> PodAffinityTerm:
    """Apply matchLabelKeys: each listed key takes the OWNER pod's label
    value and is ANDed into the selector as an In-requirement
    (framework/types.go#GetAffinityTerms + the MatchLabelKeysInPodAffinity
    merge). Terms without matchLabelKeys pass through unchanged."""
    if not term.match_label_keys or term.label_selector is None:
        return term
    from ...api.labels import IN, Requirement, Selector

    extra = tuple(
        Requirement(k, IN, (owner.labels[k],))
        for k in term.match_label_keys
        if k in owner.labels
    )
    if not extra:
        return term
    sel = term.label_selector
    return PodAffinityTerm(
        label_selector=Selector(sel.requirements + extra, sel.match_labels),
        topology_key=term.topology_key,
        namespaces=term.namespaces,
        namespace_selector=term.namespace_selector,
        match_label_keys=(),
    )


def term_matches_pod(
    term: PodAffinityTerm, owner: Pod, target: Pod
) -> bool:
    """Does ``term`` (owned by ``owner``) select ``target``?
    framework/types.go#AffinityTerm.Matches: namespace rule from the owner's
    perspective + label selector (with matchLabelKeys merged) on the
    target's labels."""
    if not term.matches_namespace(owner.namespace, target.namespace):
        return False
    t = effective_term(term, owner)
    return t.label_selector is not None and t.label_selector.matches(
        target.labels
    )


def _required_anti_terms(p: Pod) -> tuple[PodAffinityTerm, ...]:
    a = p.affinity.pod_anti_affinity if p.affinity else None
    return a.required if a else ()


def _required_aff_terms(p: Pod) -> tuple[PodAffinityTerm, ...]:
    a = p.affinity.pod_affinity if p.affinity else None
    return a.required if a else ()


def _preferred_terms(p: Pod, anti: bool):
    a = (
        (p.affinity.pod_anti_affinity if anti else p.affinity.pod_affinity)
        if p.affinity
        else None
    )
    return a.preferred if a else ()


@dataclass
class InterpodFilterState:
    """Pod-level precomputation (filtering.go#preFilterState): the
    topologyToMatchedTermCount maps reduced to domain sets — built ONCE per
    pod, then checked per candidate node in O(#terms)."""

    # (topologyKey, value) pairs occupied by existing pods whose required
    # anti-affinity selects the incoming pod (symmetry)
    existing_anti_pairs: set
    # per incoming required-anti term: occupied domain values
    anti_terms: list[tuple[PodAffinityTerm, set]]
    # per incoming required-aff term: domain values with >=1 matching pod
    aff_terms: list[tuple[PodAffinityTerm, set]]
    # first-pod special case inputs
    any_aff_match_anywhere: bool
    self_matches_all: bool

    def check(self, node: Node) -> bool:
        labels = node.labels
        for key, v in self.existing_anti_pairs:
            if labels.get(key) == v:
                return False
        for t, occupied in self.anti_terms:
            v = labels.get(t.topology_key)
            if v is not None and v in occupied:
                return False
        if self.aff_terms:
            # all topology keys must exist on the node — even the first-pod
            # special case cannot admit a keyless node
            # (filtering.go#satisfyPodAffinity)
            if any(
                labels.get(t.topology_key) is None for t, _ in self.aff_terms
            ):
                return False
            all_satisfied = all(
                labels[t.topology_key] in matched
                for t, matched in self.aff_terms
            )
            if not all_satisfied:
                if self.any_aff_match_anywhere or not self.self_matches_all:
                    return False
        return True


def build_interpod_state(
    pod: Pod, all_nodes: Sequence[tuple[Node, Sequence[Pod]]]
) -> InterpodFilterState:
    existing_anti_pairs: set = set()
    anti = _required_anti_terms(pod)
    aff = _required_aff_terms(pod)
    anti_occ: list[set] = [set() for _ in anti]
    aff_matched: list[set] = [set() for _ in aff]
    any_aff_anywhere = False

    for m, pods_on_m in all_nodes:
        for q in pods_on_m:
            # symmetry: q's required anti-affinity vs incoming pod
            for t in _required_anti_terms(q):
                v_owner = m.labels.get(t.topology_key)
                if v_owner is not None and term_matches_pod(t, q, pod):
                    existing_anti_pairs.add((t.topology_key, v_owner))
            # incoming terms vs q
            for i, t in enumerate(anti):
                v = m.labels.get(t.topology_key)
                if v is not None and term_matches_pod(t, pod, q):
                    anti_occ[i].add(v)
            for i, t in enumerate(aff):
                v = m.labels.get(t.topology_key)
                if v is not None and term_matches_pod(t, pod, q):
                    aff_matched[i].add(v)
                    any_aff_anywhere = True

    return InterpodFilterState(
        existing_anti_pairs=existing_anti_pairs,
        anti_terms=list(zip(anti, anti_occ)),
        aff_terms=list(zip(aff, aff_matched)),
        any_aff_match_anywhere=any_aff_anywhere,
        self_matches_all=all(term_matches_pod(t, pod, pod) for t in aff),
    )


def interpod_filter(
    pod: Pod,
    node: Node,
    all_nodes: Sequence[tuple[Node, Sequence[Pod]]],
) -> bool:
    """Single-node probe; hot paths build the state once via
    build_interpod_state and call .check per node."""
    return build_interpod_state(pod, all_nodes).check(node)


def interpod_raw_scores(
    pod: Pod,
    candidates: Sequence[Node],
    all_nodes: Sequence[tuple[Node, Sequence[Pod]]],
    hard_pod_affinity_weight: int = DEFAULT_HARD_POD_AFFINITY_WEIGHT,
) -> list[int]:
    """Unnormalized per-candidate scores (scoring.go topologyScore sums)."""
    # contributions keyed by (topologyKey, value)
    pair_score: dict[tuple[str, str], int] = {}

    def add(key: str, owner_node: Node, w: int) -> None:
        v = owner_node.labels.get(key)
        if v is None or w == 0:
            return
        pair_score[(key, v)] = pair_score.get((key, v), 0) + w

    pref_aff = _preferred_terms(pod, anti=False)
    pref_anti = _preferred_terms(pod, anti=True)
    for m, pods_on_m in all_nodes:
        for q in pods_on_m:
            for wt in pref_aff:
                if term_matches_pod(wt.term, pod, q):
                    add(wt.term.topology_key, m, wt.weight)
            for wt in pref_anti:
                if term_matches_pod(wt.term, pod, q):
                    add(wt.term.topology_key, m, -wt.weight)
            # symmetry: q's preferred terms vs incoming pod
            for wt in _preferred_terms(q, anti=False):
                if term_matches_pod(wt.term, q, pod):
                    add(wt.term.topology_key, m, wt.weight)
            for wt in _preferred_terms(q, anti=True):
                if term_matches_pod(wt.term, q, pod):
                    add(wt.term.topology_key, m, -wt.weight)
            # symmetry: q's REQUIRED affinity terms, weighted by config
            if hard_pod_affinity_weight:
                for t in _required_aff_terms(q):
                    if term_matches_pod(t, q, pod):
                        add(t.topology_key, m, hard_pod_affinity_weight)

    out = []
    for n in candidates:
        s = 0
        for (key, v), w in pair_score.items():
            if n.labels.get(key) == v:
                s += w
        out.append(s)
    return out


def normalize_scores(raw: Sequence[int]) -> list[int]:
    """scoring.go#NormalizeScore: max-min scaling to 0..100."""
    if not raw:
        return []
    mx, mn = max(raw), min(raw)
    if mx == mn:
        return [0 for _ in raw]
    return [MAX_NODE_SCORE * (s - mn) // (mx - mn) for s in raw]


def interpod_scores(
    pod: Pod,
    candidates: Sequence[Node],
    all_nodes: Sequence[tuple[Node, Sequence[Pod]]],
    hard_pod_affinity_weight: int = DEFAULT_HARD_POD_AFFINITY_WEIGHT,
) -> list[int]:
    return normalize_scores(
        interpod_raw_scores(pod, candidates, all_nodes, hard_pod_affinity_weight)
    )
