"""Gang scheduling (kubernetes_tpu/gang): all-or-nothing pod groups
end to end — tracker bookkeeping, config parsing, the atomic
``bind_gang`` store commit, the scheduler's assembly gate
(park / timeout / quarantine / TTL re-admit), the atomicity edges the
ISSUE names (mid-gang fence discard, crash between stage and commit,
cross-shard gangs under injected AdmitConflict), and the
heterogeneity-aware effective-throughput objective."""

import json

import pytest

from kubernetes_tpu import metrics
from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.gang import (
    ACCEL_CLASS_LABEL,
    GANG_LABEL,
    MIN_MEMBER_ANNOTATION,
    WORKLOAD_CLASS_LABEL,
    GangConfig,
    GangTracker,
)
from kubernetes_tpu.obs import ObsConfig
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ApiError, ClusterState
from kubernetes_tpu.utils.clock import FakeClock


def _ctr(c) -> float:
    return c._value.get()  # prometheus_client internal, test-style read


def _cluster(n_nodes=4, cpu="4", clock=None):
    cs = ClusterState(clock=clock)
    for i in range(n_nodes):
        cs.create_node(
            MakeNode()
            .name(f"n{i}")
            .capacity({"cpu": cpu, "memory": "8Gi", "pods": "20"})
            .obj()
        )
    return cs


def _member(name, group="train", min_member=3, cpu="1", wc=""):
    b = (
        MakePod()
        .name(name)
        .req({"cpu": cpu, "memory": "256Mi"})
        .label(GANG_LABEL, group)
        .annotation(MIN_MEMBER_ANNOTATION, str(min_member))
    )
    if wc:
        b = b.label(WORKLOAD_CLASS_LABEL, wc)
    return b.obj()


def _cfg(**kw):
    kw.setdefault("solver", ExactSolverConfig(tie_break="first"))
    kw.setdefault("gang", GangConfig())
    kw.setdefault("batch_size", 64)
    return SchedulerConfig(**kw)


def _outcomes(sched, key):
    return [
        r["outcome"]
        for r in (json.loads(line) for line in sched.journal.lines)
        if r["pod"] == key
    ]


# -- tracker -----------------------------------------------------------------


def test_tracker_gang_of_and_min_member():
    plain = MakePod().name("p").req({"cpu": "1"}).obj()
    assert GangTracker.gang_of(plain) is None
    m = _member("m", group="job-a", min_member=4)
    assert GangTracker.gang_of(m) == "default/job-a"
    assert GangTracker.min_member(m) == 4
    # malformed / missing quorum degrades to a singleton gang, not a wedge
    bad = (
        MakePod().name("b").req({"cpu": "1"})
        .label(GANG_LABEL, "g").annotation(MIN_MEMBER_ANNOTATION, "soon")
        .obj()
    )
    assert GangTracker.min_member(bad) == 1
    nolabel = (
        MakePod().name("z").req({"cpu": "1"})
        .annotation(MIN_MEMBER_ANNOTATION, "3").obj()
    )
    assert GangTracker.gang_of(nolabel) is None
    zero = (
        MakePod().name("zz").req({"cpu": "1"})
        .label(GANG_LABEL, "g").annotation(MIN_MEMBER_ANNOTATION, "0")
        .obj()
    )
    assert GangTracker.min_member(zero) == 1


def test_tracker_round_bookkeeping():
    t = GangTracker(GangConfig())
    assert t.note_seen("default/g", 10.0) == 10.0
    assert t.note_seen("default/g", 99.0) == 10.0  # first-seen sticks
    assert t.incomplete_rounds("default/g") == 0
    assert t.note_incomplete("default/g") == 1
    assert t.note_incomplete("default/g") == 2
    # a full commit resets failure state and returns the assembly start
    assert t.note_complete("default/g") == 10.0
    assert t.incomplete_rounds("default/g") == 0
    assert t.first_seen("default/g") is None
    # quarantine clears everything too: TTL re-admit starts fresh
    t.note_seen("default/h", 5.0)
    t.note_incomplete("default/h")
    t.note_quarantined("default/h")
    assert t.first_seen("default/h") is None
    assert t.incomplete_rounds("default/h") == 0


# -- config ------------------------------------------------------------------


def test_gang_config_section_parses_and_wires():
    from kubernetes_tpu.config import types as config_types

    cfg = config_types.load(
        {
            "apiVersion": "kubescheduler.config.k8s.io/v1",
            "kind": "KubeSchedulerConfiguration",
            "gang": {
                "enabled": True,
                "minMemberTimeoutSeconds": 12.5,
                "quarantineAfter": 2,
                "throughputWeight": 5,
                "classThroughput": {"transformer": {"tpu-v4": 1.0}},
            },
        }
    )
    assert cfg.gang.enabled
    assert cfg.gang.min_member_timeout_seconds == 12.5
    sched_cfg = config_types.scheduler_config(cfg)
    assert isinstance(sched_cfg.gang, GangConfig)
    assert sched_cfg.gang.quarantine_after == 2
    assert sched_cfg.gang.class_throughput == {"transformer": {"tpu-v4": 1.0}}
    # explicit nulls fall back to defaults (_nn), and a disabled (or
    # absent) section wires no GangConfig at all
    cfg2 = config_types.load(
        {"gang": {"enabled": None, "quarantineAfter": None}}
    )
    assert not cfg2.gang.enabled
    assert cfg2.gang.quarantine_after == 3
    assert config_types.scheduler_config(cfg2).gang is None


def test_gang_config_section_rejects_bad_values():
    from kubernetes_tpu.config import types as config_types

    with pytest.raises(ValueError, match="minMemberTimeoutSeconds"):
        config_types.load({"gang": {"minMemberTimeoutSeconds": 0}})
    with pytest.raises(ValueError, match="quarantineAfter"):
        config_types.load({"gang": {"quarantineAfter": 0}})
    with pytest.raises(ValueError, match="throughputWeight"):
        config_types.load({"gang": {"throughputWeight": -1}})
    with pytest.raises(ValueError, match="mutually exclusive"):
        config_types.load(
            {
                "gang": {
                    "classThroughput": {"a": {"b": 1.0}},
                    "classThroughputPath": "/tmp/t.json",
                }
            }
        )
    with pytest.raises(ValueError, match="classThroughput"):
        config_types.load(
            {"gang": {"classThroughput": {"a": {"b": -2.0}}}}
        )


def test_cli_config_dump_includes_gang_section(tmp_path, capsys):
    import argparse

    from kubernetes_tpu import cli

    p = tmp_path / "cfg.yaml"
    p.write_text(
        "gang:\n"
        "  enabled: true\n"
        "  quarantineAfter: 4\n"
        "  classThroughput:\n"
        "    resnet: {gpu-a100: 1.0}\n"
    )
    args = argparse.Namespace(config=str(p), feature_gates=None)
    assert cli.cmd_config(args) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["gang"]["enabled"] is True
    assert out["gang"]["quarantineAfter"] == 4
    assert out["gang"]["classThroughputWorkloads"] == ["resnet"]


# -- ClusterState.bind_gang --------------------------------------------------


def test_bind_gang_validates_everything_before_mutating():
    cs = _cluster(2)
    for n in ("a", "b", "c"):
        cs.create_pod(_member(n))
    # a missing node anywhere in the gang binds NOTHING
    with pytest.raises(ApiError, match="ghost"):
        cs.bind_gang(
            [
                ("default", "a", "n0"),
                ("default", "b", "ghost"),
                ("default", "c", "n1"),
            ]
        )
    assert all(p.node_name == "" for p in cs.list_pods())
    # an already-bound member anywhere rejects the whole gang
    cs.bind("default", "c", "n1")
    rv_before = {p.key: p.resource_version for p in cs.list_pods()}
    with pytest.raises(ApiError, match="already bound"):
        cs.bind_gang(
            [("default", "a", "n0"), ("default", "c", "n0")]
        )
    assert cs.get_pod("default", "a").node_name == ""
    assert {
        p.key: p.resource_version for p in cs.list_pods()
    } == rv_before  # byte-identical store on rejection
    # the clean path commits every member
    cs.bind_gang([("default", "a", "n0"), ("default", "b", "n1")])
    assert cs.get_pod("default", "a").node_name == "n0"
    assert cs.get_pod("default", "b").node_name == "n1"


def test_bind_gang_fence_rejection_binds_nothing():
    cs = _cluster(2)
    for n in ("a", "b"):
        cs.create_pod(_member(n, min_member=2))
    token = cs.grant_fence("sched", holder="inc-1")
    cs.grant_fence("sched", holder="inc-2")  # revokes inc-1's token
    with pytest.raises(ApiError) as ei:
        cs.bind_gang(
            [("default", "a", "n0"), ("default", "b", "n1")],
            fence=("sched", token),
        )
    assert ei.value.fenced
    assert all(p.node_name == "" for p in cs.list_pods())
    assert cs.fence_rejections["sched"] == 1


# -- scheduler gate: assembly, park, atomic commit ---------------------------


def test_gang_parks_short_then_binds_atomically_when_assembled():
    clock = FakeClock()
    cs = _cluster(4, clock=clock)
    sched = Scheduler(
        cs,
        _cfg(
            obs=ObsConfig(journal=True),
            gang=GangConfig(min_member_timeout=600.0),
        ),
        clock=clock,
    )
    commits0 = _ctr(metrics.gang_commits_total)
    bound0 = _ctr(metrics.gang_bound_pods_total)
    cs.create_pod(_member("m0"))
    cs.create_pod(_member("m1"))
    sched.run_until_settled()
    # short of quorum: every present member parks, none binds
    assert all(p.node_name == "" for p in cs.list_pods())
    assert _outcomes(sched, "default/m0")[-1] == "gang_incomplete"
    assert "2/3 members present" in json.loads(sched.journal.lines[-1])["reason"]
    # the last member arrives: its pop drags the parked members out of
    # the unschedulable store (take_for_gang) and the gang lands whole
    cs.create_pod(_member("m2"))
    results = sched.run_until_settled()
    scheduled = [k for r in results for k, _ in r.scheduled]
    assert sorted(scheduled) == ["default/m0", "default/m1", "default/m2"]
    assert all(p.node_name for p in cs.list_pods())
    assert _ctr(metrics.gang_commits_total) == commits0 + 1
    assert _ctr(metrics.gang_bound_pods_total) == bound0 + 3
    for m in ("m0", "m1", "m2"):
        assert _outcomes(sched, f"default/{m}")[-1] == "bound"


def test_gang_capacity_shortfall_releases_all_then_quarantines():
    clock = FakeClock()
    cs = _cluster(1, cpu="2", clock=clock)  # fits 2 of the 3 members
    sched = Scheduler(
        cs,
        _cfg(
            obs=ObsConfig(journal=True),
            gang=GangConfig(quarantine_after=1, min_member_timeout=600.0),
        ),
        clock=clock,
    )
    quar0 = _ctr(metrics.gang_quarantined_total)
    inc0 = _ctr(metrics.gang_incomplete_total)
    for n in ("m0", "m1", "m2"):
        cs.create_pod(_member(n))
    res = sched.run_until_settled()
    # the round released: placeable members rolled back with the
    # unplaceable one — zero partial binds
    assert all(p.node_name == "" for p in cs.list_pods())
    released = [k for r in res for k in r.gang_released]
    assert len(released) == 2
    assert _ctr(metrics.gang_incomplete_total) == inc0 + 1
    # the leftover flush re-pops the gang; one failed round is the
    # configured limit, so the gate quarantines the WHOLE group
    clock.advance(301.0)
    sched.queue.flush_backoff_completed()
    sched.run_until_settled()
    assert all(p.node_name == "" for p in cs.list_pods())
    assert _ctr(metrics.gang_quarantined_total) == quar0 + 1
    for m in ("m0", "m1", "m2"):
        assert _outcomes(sched, f"default/{m}")[-1] == "quarantined"
    # out of every queue, parked in quarantine as a unit (pending still
    # counts them: the drain loop must keep ticking toward the TTL)
    assert sorted(sched._quarantine) == [
        "default/m0", "default/m1", "default/m2",
    ]
    assert sched.pending == 3


def test_gang_assembly_timeout_quarantines_and_ttl_readmit_completes():
    clock = FakeClock()
    cs = _cluster(4, clock=clock)
    sched = Scheduler(
        cs,
        _cfg(
            obs=ObsConfig(journal=True),
            gang=GangConfig(min_member_timeout=5.0),
        ),
        clock=clock,
    )
    cs.create_pod(_member("m0"))
    cs.create_pod(_member("m1"))
    sched.run_until_settled()  # 2/3: parked inside the assembly window
    clock.advance(301.0)  # past min_member_timeout; leftover flush fires
    sched.queue.flush_backoff_completed()
    sched.run_until_settled()
    assert _outcomes(sched, "default/m0")[-1] == "quarantined"
    assert _outcomes(sched, "default/m1")[-1] == "quarantined"
    # the missing member finally arrives: alone it parks (1/3 present —
    # quarantine cleared the gang's assembly clock, so it waits fresh)
    cs.create_pod(_member("m2"))
    sched.run_until_settled()
    assert _outcomes(sched, "default/m2")[-1] == "gang_incomplete"
    # TTL elapses: _release_quarantine re-admits the quarantined
    # members, the gate reassembles the gang whole and it binds
    clock.advance(61.0)  # past ResilienceConfig.quarantine_ttl (60s)
    sched.queue.flush_backoff_completed()
    sched.run_until_settled()
    assert all(p.node_name for p in cs.list_pods())
    for m in ("m0", "m1", "m2"):
        assert _outcomes(sched, f"default/{m}")[-1] == "bound"


# -- atomicity edges ---------------------------------------------------------


def test_mid_gang_fence_revocation_binds_nothing():
    clock = FakeClock()
    cs = _cluster(4, clock=clock)
    sched = Scheduler(
        cs,
        _cfg(obs=ObsConfig(journal=True), fence_role="sched"),
        clock=clock,
    )
    fenced0 = _ctr(metrics.commit_fenced_total)
    for n in ("m0", "m1", "m2"):
        cs.create_pod(_member(n))
    # the seam fires after every member staged but before the atomic
    # commit — exactly where a superseding incarnation's fence grant
    # lands in a real takeover
    sched._pre_commit_hook = lambda pending: cs.grant_fence(
        "sched", holder="usurper"
    )
    sched.schedule_batch()
    assert all(p.node_name == "" for p in cs.list_pods())
    assert _ctr(metrics.commit_fenced_total) == fenced0 + 1
    for m in ("m0", "m1", "m2"):
        o = _outcomes(sched, f"default/{m}")
        assert o[-1] == "gang_incomplete"
    assert cs.fence_rejections["sched"] >= 1


def test_crash_between_stage_and_commit_recovers_whole_gang():
    class _Crash(RuntimeError):
        pass

    clock = FakeClock()
    cs = _cluster(4, clock=clock)
    s1 = Scheduler(cs, _cfg(), clock=clock)

    def _die(pending):
        raise _Crash("killed between stage and commit")

    s1._pre_commit_hook = _die
    for n in ("m0", "m1", "m2"):
        cs.create_pod(_member(n))
    with pytest.raises(_Crash):
        s1.schedule_batch()
    # the crash window: members assumed + staged, NOTHING committed
    assert all(p.node_name == "" for p in cs.list_pods())
    # a fresh incarnation re-adopts the orphans and the gang binds whole
    clock.advance(30.0)
    s2 = Scheduler(cs, _cfg(incarnation=2), clock=clock)
    s2.run_until_settled()
    assert all(p.node_name for p in cs.list_pods())


def test_restart_rolls_back_partially_bound_gang():
    """A predecessor that died between a fleet stage and the gang
    commit can leave a STRICT SUBSET bound in truth: the restart
    recovery pass must evict the stranded members so the gang
    reassembles atomically."""
    clock = FakeClock()
    cs = _cluster(4, clock=clock)
    for n in ("m0", "m1", "m2"):
        cs.create_pod(_member(n))
    cs.bind("default", "m0", "n0")  # the wreck: 1/3 bound
    s2 = Scheduler(cs, _cfg(incarnation=2), clock=clock)
    # rollback ran inside _recover, before adoption: the stranded
    # member is Pending again under its own identity
    assert cs.get_pod("default", "m0").node_name == ""
    s2.run_until_settled()
    assert all(p.node_name for p in cs.list_pods())
    # a COMPLETE gang at restart is legitimate occupancy — never touched
    clock.advance(30.0)
    s3 = Scheduler(cs, _cfg(incarnation=3), clock=clock)
    assert all(p.node_name for p in cs.list_pods())
    del s3


def test_cross_shard_gang_admit_conflict_never_partially_binds():
    """Fleet mode: every gang member stages through the hub's fenced
    CAS; injected AdmitConflict on ANY member must fail the WHOLE
    round (zero binds), and the gang lands whole once the hub heals."""
    from kubernetes_tpu.fleet import (
        AdmitConflict,
        FleetConfig,
        OccupancyExchange,
    )

    ZONE = "topology.kubernetes.io/zone"
    clock = FakeClock()
    cs = ClusterState(clock=clock)
    for i in range(4):
        cs.create_node(
            MakeNode()
            .name(f"n{i}")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"})
            .label(ZONE, f"z{i % 2}")
            .obj()
        )
    ex = OccupancyExchange()
    gang_cfg = GangConfig(quarantine_after=99, min_member_timeout=1e6)
    scheds = [
        Scheduler(
            cs,
            SchedulerConfig(
                batch_size=16,
                mesh_devices=1,
                solver=ExactSolverConfig(tie_break="first"),
                gang=gang_cfg,
                fleet=FleetConfig(
                    replica=rid,
                    replicas=("r0", "r1"),
                    exchange=ex,
                    # this test exercises CAS conflicts, not staleness:
                    # keep the 301s leftover-flush advances below from
                    # tripping the conservative-admission bound
                    max_row_age_s=1e6,
                ),
            ),
            clock=clock,
        )
        for rid in ("r0", "r1")
    ]
    orig_cas = ex.compare_and_stage
    calls = {"n": 0}

    def _conflict(*a, **kw):
        calls["n"] += 1
        raise AdmitConflict("injected CAS contention")

    ex.compare_and_stage = _conflict
    for n in ("m0", "m1"):
        cs.create_pod(_member(n, min_member=2))

    def _drive():
        for s in scheds:
            s.run_until_settled()
        bound = [p for p in cs.list_pods() if p.node_name]
        assert len(bound) in (0, 2), f"partial gang bound: {bound}"
        return len(bound)

    for _ in range(3):
        assert _drive() == 0  # every round: whole-gang release, 0 binds
        clock.advance(301.0)
        for s in scheds:
            s.queue.flush_backoff_completed()
    assert calls["n"] > 0  # the CAS seam actually gated the rounds
    ex.compare_and_stage = orig_cas  # hub heals
    for _ in range(3):
        if _drive() == 2:
            break
        clock.advance(301.0)
        for s in scheds:
            s.queue.flush_backoff_completed()
    assert all(p.node_name for p in cs.list_pods())


# -- heterogeneity objective -------------------------------------------------


def test_throughput_objective_steers_gang_to_fast_accelerator():
    clock = FakeClock()
    cs = ClusterState(clock=clock)
    # identical capacity; the slow class sorts FIRST so the default
    # first-tiebreak would pick it without the objective
    for name, accel in (("n0", "gpu-a100"), ("n1", "tpu-v4")):
        cs.create_node(
            MakeNode()
            .name(name)
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"})
            .label(ACCEL_CLASS_LABEL, accel)
            .obj()
        )
    table = {"transformer": {"tpu-v4": 1.0, "gpu-a100": 0.25}}
    sched = Scheduler(
        cs,
        _cfg(
            gang=GangConfig(
                throughput_weight=100, class_throughput=table
            )
        ),
        clock=clock,
    )
    for n in ("m0", "m1"):
        cs.create_pod(_member(n, min_member=2, wc="transformer"))
    sched.run_until_settled()
    assert {p.node_name for p in cs.list_pods()} == {"n1"}


def test_throughput_objective_off_without_weight():
    clock = FakeClock()
    cs = ClusterState(clock=clock)
    for name, accel in (("n0", "gpu-a100"), ("n1", "tpu-v4")):
        cs.create_node(
            MakeNode()
            .name(name)
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"})
            .label(ACCEL_CLASS_LABEL, accel)
            .obj()
        )
    table = {"transformer": {"tpu-v4": 1.0, "gpu-a100": 0.25}}
    sched = Scheduler(
        cs,
        _cfg(
            gang=GangConfig(throughput_weight=0, class_throughput=table)
        ),
        clock=clock,
    )
    for n in ("m0", "m1"):
        cs.create_pod(_member(n, min_member=2, wc="transformer"))
    sched.run_until_settled()
    # weight 0 = objective off: both nodes score equal, packing wins
    assert all(p.node_name for p in cs.list_pods())
