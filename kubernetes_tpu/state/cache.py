"""Scheduler cache: the host shadow of cluster state that scheduling reads.

Reference semantics (pkg/scheduler/backend/cache/cache.go#cacheImpl):
- truth = scheduled pods (observed via watch) + **assumed** pods (optimistic
  placements made before the API bind lands, so the next pod's cycle sees
  them — the mechanism that makes overlapping bind goroutines safe);
- AssumePod / ForgetPod / FinishBinding(+TTL expiry): an assumed pod whose
  bind confirmation never arrives expires after ``assume_ttl`` and its
  resources are released (crash/requeue safety, SURVEY §6.3);
- per-node **generation** counters: every mutation bumps the node's
  generation from a global monotonic counter; snapshot updates copy only
  nodes whose generation is newer than the snapshot's (cache.go#UpdateSnapshot
  incremental O(changed) contract — here it becomes a dirty-column scatter
  into the device tensors, state/snapshot.py).

HostNodeInfo mirrors framework/types.go#NodeInfo's running sums (Requested /
NonZeroRequested / pod count) so column refreshes are O(K), not O(pods).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..api.objects import Node, Pod
from ..utils.clock import Clock


class CacheError(Exception):
    pass


@dataclass
class HostNodeInfo:
    node: Node | None  # None => node deleted but assumed/bound pods remain
    generation: int
    pods: dict[str, Pod] = field(default_factory=dict)
    used: dict[str, int] = field(default_factory=dict)
    nonzero_cpu: int = 0
    nonzero_mem: int = 0
    pods_with_affinity: int = 0
    pods_with_required_anti_affinity: int = 0

    def add_pod(self, pod: Pod) -> None:
        self.pods[pod.key] = pod
        for k, v in pod.resource_request().items():
            self.used[k] = self.used.get(k, 0) + v
        nz_cpu, nz_mem = pod.non_zero_request()
        self.nonzero_cpu += nz_cpu
        self.nonzero_mem += nz_mem
        aff = pod.affinity
        if aff and (aff.pod_affinity or aff.pod_anti_affinity):
            self.pods_with_affinity += 1
        if aff and aff.pod_anti_affinity and aff.pod_anti_affinity.required:
            self.pods_with_required_anti_affinity += 1

    def remove_pod(self, pod_key: str) -> Pod:
        pod = self.pods.pop(pod_key)
        for k, v in pod.resource_request().items():
            self.used[k] = self.used.get(k, 0) - v
        nz_cpu, nz_mem = pod.non_zero_request()
        self.nonzero_cpu -= nz_cpu
        self.nonzero_mem -= nz_mem
        aff = pod.affinity
        if aff and (aff.pod_affinity or aff.pod_anti_affinity):
            self.pods_with_affinity -= 1
        if aff and aff.pod_anti_affinity and aff.pod_anti_affinity.required:
            self.pods_with_required_anti_affinity -= 1
        return pod


@dataclass
class _AssumedInfo:
    node_name: str
    binding_finished: bool = False
    deadline: float | None = None  # set by FinishBinding
    assumed_at: float = 0.0  # when the assume landed (unfinished reap)


class SchedulerCache:
    def __init__(self, clock: Clock | None = None, assume_ttl: float = 30.0):
        self._clock = clock or Clock()
        self._ttl = assume_ttl
        self._generation = 0
        self.nodes: dict[str, HostNodeInfo] = {}
        self._assumed: dict[str, _AssumedInfo] = {}
        # where each cached pod currently lives (node name), incl. assumed
        self._pod_node: dict[str, str] = {}

    # -- generation --

    def _bump(self, info: HostNodeInfo) -> None:
        self._generation += 1
        info.generation = self._generation

    @property
    def generation(self) -> int:
        return self._generation

    # -- assume / forget / confirm (schedule_one.go#assume + cache protocol) --

    def assume_pod(self, pod: Pod, node_name: str) -> None:
        if pod.key in self._pod_node:
            raise CacheError(f"pod {pod.key} already assumed/added")
        info = self.nodes.get(node_name)
        if info is None or info.node is None:
            raise CacheError(f"assume on unknown node {node_name}")
        info.add_pod(pod)
        self._bump(info)
        self._pod_node[pod.key] = node_name
        self._assumed[pod.key] = _AssumedInfo(
            node_name, assumed_at=self._clock.now()
        )

    def forget_pod(self, pod_key: str) -> None:
        """Bind failed: release the optimistic placement."""
        assumed = self._assumed.pop(pod_key, None)
        if assumed is None:
            raise CacheError(f"pod {pod_key} not assumed")
        self._remove_from_node(pod_key)

    def finish_binding(self, pod_key: str) -> None:
        a = self._assumed.get(pod_key)
        if a is not None:
            a.binding_finished = True
            a.deadline = self._clock.now() + self._ttl

    def is_assumed(self, pod_key: str) -> bool:
        return pod_key in self._assumed

    def cleanup_expired(self, protected: frozenset = frozenset()) -> list[str]:
        """Expire assumed pods whose bind confirmation never arrived
        (cache.go#cleanupAssumedPods). Returns expired pod keys.

        Two populations expire:

        - **finished** assumes (FinishBinding ran) past their deadline —
          the bind landed but the confirming watch event never arrived;
        - **unfinished** assumes older than the TTL — the binding cycle
          died between assume and finish (a crashed commit thread, an
          unwound exception path): without this arm the leaked assume
          holds phantom occupancy forever (pre-PR-8 gap: this reap both
          didn't cover them and was never even called by the
          scheduler). ``protected`` exempts pods legitimately parked
          assumed-unfinished across cycles — the Permit WaitingPods map
          — whose rollback deadline is the permit timeout, not the
          assume TTL."""
        now = self._clock.now()
        expired = [
            k
            for k, a in self._assumed.items()
            if (
                a.binding_finished
                and a.deadline is not None
                and a.deadline <= now
            )
            or (
                not a.binding_finished
                and k not in protected
                and now - a.assumed_at > self._ttl
            )
        ]
        for k in expired:
            self._assumed.pop(k)
            self._remove_from_node(k)
        return expired

    # -- watch-event handlers (eventhandlers.go semantics) --

    def add_pod(self, pod: Pod) -> None:
        """An assigned pod appeared (or bind confirmation arrived)."""
        key = pod.key
        if key in self._assumed:
            assumed_node = self._assumed[key].node_name
            self._assumed.pop(key)
            if assumed_node != pod.node_name:
                # scheduled somewhere else than we assumed: move it
                self._remove_from_node(key)
                self._add_to_node(pod)
            else:
                # confirm: swap the stored object for the API one (same sums)
                info = self.nodes[pod.node_name]
                info.pods[key] = pod
                self._bump(info)
        elif key in self._pod_node:
            raise CacheError(f"pod {key} added twice")
        else:
            self._add_to_node(pod)

    def pod_node(self, pod_key: str) -> str | None:
        """Node the cache currently holds this assigned pod on (None if
        unknown) — lets event handlers compare the cached object against
        an incoming update without reaching into node internals."""
        return self._pod_node.get(pod_key)

    def update_pod(self, pod: Pod) -> None:
        old_node = self._pod_node.get(pod.key)
        if old_node is None:
            self.add_pod(pod)
            return
        self._remove_from_node(pod.key)
        self._add_to_node(pod)

    def remove_pod(self, pod_key: str) -> None:
        self._assumed.pop(pod_key, None)
        if pod_key in self._pod_node:
            self._remove_from_node(pod_key)

    def _add_to_node(self, pod: Pod) -> None:
        name = pod.node_name
        info = self.nodes.get(name)
        if info is None:
            # pod observed before its node (reference tolerates this with an
            # imaginary node entry that materializes when the node arrives)
            info = HostNodeInfo(node=None, generation=0)
            self.nodes[name] = info
        info.add_pod(pod)
        self._bump(info)
        self._pod_node[pod.key] = name

    def _remove_from_node(self, pod_key: str) -> None:
        name = self._pod_node.pop(pod_key)
        info = self.nodes[name]
        info.remove_pod(pod_key)
        self._bump(info)
        if info.node is None and not info.pods:
            del self.nodes[name]

    def add_node(self, node: Node) -> None:
        info = self.nodes.get(node.name)
        if info is None:
            info = HostNodeInfo(node=node, generation=0)
            self.nodes[node.name] = info
        else:
            info.node = node
        self._bump(info)

    def update_node(self, node: Node) -> None:
        self.add_node(node)

    def remove_node(self, name: str) -> None:
        info = self.nodes.get(name)
        if info is None:
            return
        if info.pods:
            info.node = None  # keep resource bookkeeping for remaining pods
            self._bump(info)
        else:
            del self.nodes[name]
