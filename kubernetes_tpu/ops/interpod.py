"""Device kernels for InterPodAffinity (the in-scan pieces).

The reference's topologyToMatchedTermCount hash maps
(interpodaffinity/filtering.go) become one flattened segment-sum over
(term, domain) pairs per step: per-node owner/match counts [T, N] aggregate
to [T, D] domain totals, then gather back per node. All four directions
(incoming aff/anti, existing-anti symmetry, scored preferred/hard symmetry)
read those two aggregates; the per-pod "does existing term u concern pod p"
bits arrive as dense rows (m_anti / m_w), so the inner product over the
existing-term axis is a masked matvec.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import ops as jops

MAX_NODE_SCORE = 100
INF = jnp.int32(2**30)


# traced-region kernel, called from exact.py's jit scope: ktpu: hot
def domain_counts(
    dom, cnt, d_pad: int, ident: bool = False, pallas: bool = False
):
    """dom, cnt: [T, N] -> (per-node domain totals [T, N], has_key [T, N]).

    ``ident=True`` (static): every valid node has a UNIQUE domain in every
    row — the hostname-topology case, verified numerically by the
    tensorizer — so the per-node total IS the per-node count and no
    aggregation runs at all. This matters: the flattened segment_sum costs
    ~0.8 ms per scan step at N=5k (measured), and hostname anti-affinity
    is the canonical interpod workload (scheduler_perf
    SchedulingPodAntiAffinity).

    Otherwise one segment_sum over T*d_pad flattened segments replaces T
    hash maps — unless ``pallas=True`` (static; config
    ``tpuSolver.pallas``), which routes the [T, D] aggregation through
    the MXU one-hot-contraction kernel
    (ops/pallas_kernels.domain_counts_padded) and gathers back per node.
    Bit-identical to the segment_sum (integer adds in both); off by
    default per the measured negative results in pallas_kernels.py."""
    t, n = dom.shape
    hk = dom >= 0
    if ident:
        return jnp.where(hk, cnt, 0), hk
    dd = jnp.where(hk, dom, 0)
    if pallas:
        from .pallas_kernels import domain_counts_padded

        seg = domain_counts_padded(dom, cnt, d_pad)
    else:
        seg_ids = (
            dd + jnp.arange(t, dtype=jnp.int32)[:, None] * d_pad
        ).reshape(-1)
        seg = jops.segment_sum(
            jnp.where(hk, cnt, 0).reshape(-1),
            seg_ids,
            num_segments=t * d_pad,
        ).reshape(t, d_pad)
    node_counts = jnp.take_along_axis(seg, dd, axis=1)
    return node_counts, hk


# traced-region kernel, called from exact.py's jit scope: ktpu: hot
def filter_and_score(
    ipa, in_cnt, ex_cnt, cls, x, d_pad: int, node_valid,
    ident: bool = False, score: bool = True, pallas: bool = False,
):
    """Returns (allowed [N] bool, raw_score [N] int32).

    ipa: table dict; in_cnt/ex_cnt: carried [T, N] counts; cls: pod class;
    x: per-pod xs dict (ipa_m_anti, ipa_m_w, ipa_self_aff). Raw scores are
    returned unnormalized — normalization runs over the FINAL feasible mask
    (which includes this function's `allowed`). ``ident``: unique-domain
    fast path (see domain_counts). ``score=False`` (static): the batch has
    no preferred terms and no symmetry weights — skip the scoring section
    (raw is all-zero then anyway)."""
    in_counts, in_hk = domain_counts(
        ipa["in_dom"], in_cnt, d_pad, ident, pallas
    )
    ex_counts, ex_hk = domain_counts(
        ipa["ex_dom"], ex_cnt, d_pad, ident, pallas
    )
    n = in_counts.shape[1]

    # 1. existing pods' required anti-affinity vs this pod (symmetry)
    concerns = ipa["ex_anti"] & x["ipa_m_anti"]  # [Te]
    blocked = jnp.any(concerns[:, None] & ex_hk & (ex_counts > 0), axis=0)

    # 2. incoming required anti-affinity (missing key -> passes)
    viol = jnp.zeros(n, dtype=bool)
    sb = ipa["cls_req_anti"].shape[1]
    for s in range(sb):
        j = ipa["cls_req_anti"][cls, s]
        active = j >= 0
        jj = jnp.maximum(j, 0)
        viol = viol | (active & in_hk[jj] & (in_counts[jj] > 0))

    # 3. incoming required affinity + first-pod special case
    sa = ipa["cls_req_aff"].shape[1]
    all_ok = jnp.ones(n, dtype=bool)
    has_all_keys = jnp.ones(n, dtype=bool)
    total_any = jnp.int32(0)
    has_aff = ipa["cls_req_aff"][cls, 0] >= 0
    for s in range(sa):
        j = ipa["cls_req_aff"][cls, s]
        active = j >= 0
        jj = jnp.maximum(j, 0)
        ok_t = in_hk[jj] & (in_counts[jj] > 0)
        all_ok = all_ok & jnp.where(active, ok_t, True)
        has_all_keys = has_all_keys & jnp.where(active, in_hk[jj], True)
        total_any = total_any + jnp.where(
            active,
            jnp.sum(jnp.where(in_hk[jj] & node_valid, in_cnt[jj], 0)),
            0,
        )
    # first-pod special case never admits a node missing a topology key
    # (filtering.go#satisfyPodAffinity)
    first_pod = (total_any == 0) & x["ipa_self_aff"] & has_all_keys
    aff_ok = jnp.where(has_aff, all_ok | first_pod, True)

    allowed = ~blocked & ~viol & aff_ok

    # score: incoming preferred terms + existing-side symmetry matvec
    raw = jnp.zeros(n, dtype=jnp.int32)
    if score:
        sp = ipa["cls_pref"].shape[1]
        for s in range(sp):
            j = ipa["cls_pref"][cls, s]
            active = j >= 0
            jj = jnp.maximum(j, 0)
            w = ipa["in_pref_w"][jj]
            raw = raw + jnp.where(active & in_hk[jj], w * in_counts[jj], 0)
        raw = raw + x["ipa_m_w"] @ jnp.where(ex_hk, ex_counts, 0)
    return allowed, raw


# traced-region kernel, called from exact.py's jit scope: ktpu: hot
def normalize(raw, mask):
    """scoring.go#NormalizeScore: 100*(s-min)/(max-min) over the feasible
    set; all-equal -> 0."""
    mx = jnp.max(jnp.where(mask, raw, -INF))
    mn = jnp.min(jnp.where(mask, raw, INF))
    diff = mx - mn
    norm = MAX_NODE_SCORE * (raw - mn) // jnp.maximum(diff, 1)
    return jnp.where(mask & (diff > 0), norm, 0)
