"""Scheduler.drain_backlog (ISSUE 12 tentpole): the mega-backlog drain
through the streaming ring in HBM-budget-planned, chunk-aligned
sub-batches. Pinned here at tier-1 scale:

1. a uniform hard-shape (zone-spread) backlog drains completely in
   chunk-sized batches with cross-batch occupancy chaining ENGAGED on
   nearly every chunk (the resident-carry path, not a silent per-chunk
   drain-and-retensorize), with a valid end state;
2. a deliberately tight budget triggers the planner's auto-split
   (smaller chunk, budget_splits counted) and the drain still lands
   the same bindings; an impossible budget raises the typed
   BudgetExceeded BEFORE anything dispatches — the queue is intact;
3. drain-chunk attribution: journal records written during the drain
   carry the drain_chunk id (obs explain's chunk join) and the tag is
   gone after the pass;
4. the scheduler_backlog_* metrics move, and the estimated-vs-measured
   h2d gauge pair is populated.
"""

import numpy as np
import pytest

from kubernetes_tpu import metrics
from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.obs import ObsConfig
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver import budget as hbm
from kubernetes_tpu.solver.budget import BudgetExceeded
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState

ZONE = "topology.kubernetes.io/zone"


def mk_cluster(n_nodes=12):
    cs = ClusterState()
    for i in range(n_nodes):
        cs.create_node(
            MakeNode()
            .name(f"n{i:03}")
            .capacity({"cpu": "16", "memory": "64Gi", "pods": "110"})
            .label(ZONE, f"z{i % 3}")
            .label("kubernetes.io/hostname", f"n{i:03}")
            .obj()
        )
    return cs


def mk_sched(cs, batch=16, group=8, journal=False, **cfg):
    return Scheduler(
        cs,
        SchedulerConfig(
            batch_size=batch,
            solver=ExactSolverConfig(tie_break="first", group_size=group),
            obs=ObsConfig(journal=True) if journal else None,
            **cfg,
        ),
    )


def spread_pod(i):
    return (
        MakePod()
        .name(f"pod-{i:04}")
        .label("app", "drain")
        .req({"cpu": "100m", "memory": "256Mi"})
        .spread_constraint(1, ZONE, "DoNotSchedule", {"app": "drain"})
        .obj()
    )


def seed_backlog(cs, n):
    for i in range(n):
        cs.create_pod(spread_pod(i))


def test_drain_chains_across_chunks_and_places_everything():
    cs = mk_cluster()
    sched = mk_sched(cs)
    seed_backlog(cs, 96)
    report = sched.drain_backlog(chunk_pods=16)
    assert report.pods == 96
    assert report.drained == 96
    assert report.chunk_pods == 16
    assert report.chunks == 96 // 16
    assert report.budget_splits == 0
    # the resident-carry path, not per-chunk retensorize: every chunk
    # after the first chains on the device-resident occupancy carry
    assert report.stream_chained_batches >= report.chunks - 2
    assert report.chain_fraction >= 0.6
    assert report.measured_h2d_bytes > 0
    assert report.estimated_per_device_bytes > 0
    # end state: everything bound, zone skew holds (hard maxSkew=1)
    zones = {}
    for p in cs.list_pods():
        assert p.node_name, f"{p.name} unbound after drain"
        z = int(p.node_name[1:]) % 3
        zones[z] = zones.get(z, 0) + 1
    assert max(zones.values()) - min(zones.values()) <= 1


def test_drain_budget_auto_split_same_bindings():
    # arm A: comfortable budget
    cs_a = mk_cluster()
    sched_a = mk_sched(cs_a)
    seed_backlog(cs_a, 64)
    rep_a = sched_a.drain_backlog(chunk_pods=16)
    assert rep_a.budget_splits == 0

    # arm B: one byte under the base chunk's own estimate — the
    # planner must halve (auto-split instead of OOM) and still drain
    cs_b = mk_cluster()
    sched_b = mk_sched(cs_b)
    seed_backlog(cs_b, 64)
    shape = sched_b.drain_shape(16)
    tight = hbm.estimate(shape).per_device_bytes - 1
    splits0 = metrics.backlog_budget_splits_total._value.get()
    rep_b = sched_b.drain_backlog(chunk_pods=16, budget_bytes=tight)
    assert rep_b.budget_splits >= 1
    assert rep_b.chunk_pods < 16
    assert rep_b.chunk_pods % 8 == 0  # group-aligned halving
    assert rep_b.drained == 64
    assert (
        metrics.backlog_budget_splits_total._value.get() - splits0
        == rep_b.budget_splits
    )

    # identical end-state bindings: the chunk size is a performance
    # knob, not a semantic one (tie_break="first" is deterministic)
    def bindings(cs):
        return sorted((p.name, p.node_name) for p in cs.list_pods())

    assert bindings(cs_a) == bindings(cs_b)


def test_drain_impossible_budget_raises_typed_before_dispatch():
    cs = mk_cluster()
    sched = mk_sched(cs)
    seed_backlog(cs, 32)
    pending0 = sched.pending
    with pytest.raises(BudgetExceeded):
        sched.drain_backlog(chunk_pods=16, budget_bytes=1)
    # nothing dispatched, nothing lost: the queue is intact and a
    # follow-up drain with a sane budget lands everything
    assert sched.pending == pending0
    assert sched.config.batch_size == 16  # restored (never mutated)
    report = sched.drain_backlog(chunk_pods=16)
    assert report.drained == 32


def test_drain_chunk_ids_reach_the_journal_then_clear():
    cs = mk_cluster()
    sched = mk_sched(cs, journal=True)
    seed_backlog(cs, 48)
    report = sched.drain_backlog(chunk_pods=16)
    assert report.drained == 48
    import json

    recs = [json.loads(line) for line in sched.journal.lines]
    bound = [r for r in recs if r["outcome"] == "bound"]
    assert bound and all("drain_chunk" in r for r in bound)
    # every chunk id is a small ordinal, and distinct chunks appear
    chunk_ids = {r["drain_chunk"] for r in bound}
    assert len(chunk_ids) == report.chunks
    assert min(chunk_ids) >= 1
    # the tag is popped at drain end: post-drain records are untagged
    assert "drain_chunk" not in sched.journal.tags
    cs.create_pod(spread_pod(999))
    for r in sched.run_streaming():
        pass
    post = [
        json.loads(line)
        for line in sched.journal.lines
        if "pod-0999" in line
    ]
    assert post and all("drain_chunk" not in r for r in post)


def test_drain_metrics_and_gauge_pair_move():
    chunks0 = metrics.backlog_chunks_total._value.get()
    cs = mk_cluster()
    sched = mk_sched(cs)
    seed_backlog(cs, 32)
    report = sched.drain_backlog(chunk_pods=16)
    assert (
        metrics.backlog_chunks_total._value.get() - chunks0
        == report.chunks
    )
    assert (
        metrics.backlog_hbm_estimated_bytes._value.get()
        == report.estimated_h2d_bytes
    )
    assert (
        metrics.backlog_hbm_measured_bytes._value.get()
        == report.measured_h2d_bytes
    )
    # the model and the counters agree on order of magnitude even with
    # the compact wire engaged (the estimate picks the compact arm
    # when the solver config enables it)
    assert report.measured_h2d_bytes <= report.estimated_h2d_bytes * 3
    assert report.estimated_h2d_bytes <= report.measured_h2d_bytes * 10


def test_empty_queue_drain_is_a_noop():
    cs = mk_cluster()
    sched = mk_sched(cs)
    report = sched.drain_backlog()
    assert report.pods == 0
    assert report.chunks == 0
    assert report.results == []


def test_backlog_drain_sim_profile_deterministic():
    """The backlog_drain sim profile drives drain_backlog at cycle 0
    (budget split forced) and is byte-deterministic across runs."""
    from kubernetes_tpu.sim import run_sim

    a = run_sim("backlog_drain", seed=3, cycles=3)
    b = run_sim("backlog_drain", seed=3, cycles=3)
    assert a.ok, [str(v) for v in a.violations]
    assert a.summary["backlog"] is not None
    assert a.summary["backlog"]["budget_splits"] >= 1
    assert a.summary["backlog"]["chunks"] >= 2
    assert a.trace.digest() == b.trace.digest()
    assert a.summary["journal_digest"] == b.summary["journal_digest"]
