"""Pallas kernel parity vs the jax.lax reference (interpret mode on CPU;
the compiled TPU path is exercised by scripts/pallas_smoke.py)."""

import numpy as np
import pytest

from kubernetes_tpu.ops.pallas_kernels import (
    N_TILE,
    domain_counts_pallas,
    domain_counts_reference,
)


@pytest.mark.parametrize("t,n_tiles,d_pad", [(8, 1, 8), (8, 2, 16), (16, 4, 32)])
def test_domain_counts_parity(t, n_tiles, d_pad):
    rng = np.random.default_rng(42 + t)
    n = n_tiles * N_TILE
    dom = rng.integers(-1, d_pad, size=(t, n)).astype(np.int32)
    cnt = rng.integers(0, 5, size=(t, n)).astype(np.int32)
    got = np.asarray(domain_counts_pallas(dom, cnt, d_pad, interpret=True))
    want = np.asarray(domain_counts_reference(dom, cnt, d_pad))
    np.testing.assert_array_equal(got, want)


def test_domain_counts_excludes_missing_key():
    dom = np.full((8, N_TILE), -1, dtype=np.int32)
    cnt = np.ones((8, N_TILE), dtype=np.int32)
    out = np.asarray(domain_counts_pallas(dom, cnt, 8, interpret=True))
    assert out.sum() == 0
