"""Device-tier fleet scale-out (ISSUE 11): the cross-process occupancy
hub — fenced compare-and-stage atomic admit, the HubOp gRPC transport
(RemoteOccupancyExchange), per-replica mesh slices, and the two-process
race the CAS exists to decide."""

import multiprocessing

import pytest

from kubernetes_tpu.fleet import (
    AdmitConflict,
    ExchangeUnreachable,
    FleetConfig,
    NodeRow,
    OccupancyExchange,
    PENDING,
    PodRow,
    RemoteOccupancyExchange,
)
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.server.bulk import BulkClient, BulkCore, make_grpc_server
from kubernetes_tpu.sim.generators import make_node, make_pod
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState
from kubernetes_tpu.utils.clock import FakeClock

ZONE = "topology.kubernetes.io/zone"


def _row(pod="default/p", node="n1", zone="z0", labels=(("app", "x"),)):
    return PodRow(
        pod=pod, node=node, zone=zone, namespace="default",
        labels=labels, state=PENDING,
    )


# -- hub-side fenced compare-and-stage ---------------------------------------


class TestCompareAndStage:
    def test_cas_lands_at_expected_version(self):
        ex = OccupancyExchange()
        v = ex.version
        new = ex.compare_and_stage("r0", _row(), v)
        assert new == v + 1
        assert ex.peers_view("r1").pod_rows == (_row(),)

    def test_cas_rejects_moved_version_typed(self):
        """Two replicas admitted against the same view: the hub
        serializes their CAS calls — the first lands, the second gets
        a typed (non-fenced) AdmitConflict carrying the moved
        version."""
        ex = OccupancyExchange()
        v = ex.version
        ex.compare_and_stage("r0", _row(pod="default/a"), v)
        with pytest.raises(AdmitConflict) as ei:
            ex.compare_and_stage("r1", _row(pod="default/b"), v)
        assert ei.value.fenced is False
        assert ei.value.version == v + 1
        # only the winner's row is on the hub
        assert [r.pod for r in ex.peers_view("rx").pod_rows] == [
            "default/a"
        ]

    def test_cas_any_mutation_moves_the_version(self):
        """A plain stage (or withdraw, handoff, ...) between view and
        CAS also conflicts — the loser's view may hide that row."""
        ex = OccupancyExchange()
        v = ex.version
        ex.stage("r2", _row(pod="default/plain"))
        with pytest.raises(AdmitConflict):
            ex.compare_and_stage("r0", _row(), v)

    def test_retire_fences_hub_writes_until_reregistration(self):
        """The PR 8 fencing-token discipline at the hub: retire revokes
        write privilege — stage/CAS/commit/set_degraded/hand_off all
        reject typed fenced — and a wholesale republish (the healed
        incarnation's forced resync) re-registers."""
        ex = OccupancyExchange()
        ex.stage("r0", _row())
        ex.retire("r0")
        for op in (
            lambda: ex.stage("r0", _row()),
            lambda: ex.compare_and_stage("r0", _row(), ex.version),
            lambda: ex.commit("r0", "default/p"),
            lambda: ex.withdraw("r0", "default/p"),
            lambda: ex.set_degraded("r0", True),
            lambda: ex.hand_off("r1", "default/p", 1, from_replica="r0"),
        ):
            with pytest.raises(AdmitConflict) as ei:
                op()
            assert ei.value.fenced is True
        # reads stay open (a zombie reading is harmless)
        ex.peers_view("r0")
        # wholesale republish = re-registration
        ex.replace_pod_rows("r0", [_row()])
        ex.stage("r0", _row(pod="default/q"))
        ex.withdraw("r0", "default/q")


# -- FleetRuntime CAS admit: the in-process race -----------------------------


def _mk_fleet(n_nodes=8, zones=2, universe=("r0", "r1"), exchange=None):
    clock = FakeClock()
    cluster = ClusterState(clock=clock)
    for i in range(n_nodes):
        cluster.create_node(
            make_node(f"n{i}", "8", "32Gi", labels={ZONE: f"z{i % zones}"})
        )
    ex = exchange if exchange is not None else OccupancyExchange()
    scheds = [
        Scheduler(
            cluster,
            SchedulerConfig(
                batch_size=16,
                mesh_devices=1,
                solver=ExactSolverConfig(tie_break="first"),
                fleet=FleetConfig(
                    replica=rid, replicas=universe, exchange=ex
                ),
            ),
            clock=clock,
        )
        for rid in universe
    ]
    return cluster, scheds, ex, clock


def test_admit_cas_loser_rechecks_and_rejects():
    """The racing interleave, reproduced deterministically: r0's
    host-side recheck passes, then — before its CAS lands — a peer
    stages a conflicting spread row. The CAS must reject, the re-check
    against the fresh rows must now see the peer's row, and the admit
    must return a rejection reason (the pod requeues)."""
    from kubernetes_tpu import metrics

    cluster, scheds, ex, clock = _mk_fleet()
    r0 = scheds[0]
    # a hard zone-spread pod routed to r0's shard
    pod = make_pod("race", "250m", shape="spread")
    cluster.create_pod(pod)
    node = sorted(r0.cache.nodes)[0]
    zone = r0.cache.nodes[node].node.labels[ZONE]
    peer_zone = "z1" if zone == "z0" else "z0"
    real_cas = ex.compare_and_stage
    fired = {"n": 0}

    def interleaved(replica, row, expected_version, **kw):
        if not fired["n"]:
            fired["n"] += 1
            # the peer wins the race: maxSkew=1 means r0's placement
            # in `zone` on top of a peer row in the SAME zone (with the
            # other zone empty) would skew 2-0
            ex.stage(
                "r1",
                PodRow(
                    pod="default/peer", node="n9", zone=zone,
                    namespace="default", labels=(("app", "spread"),),
                ),
            )
        return real_cas(replica, row, expected_version, **kw)

    ex.compare_and_stage = interleaved
    before = metrics.fleet_admit_cas_conflict_total.labels(
        "version"
    )._value.get()
    why = r0.fleet.admit(pod, node, r0.cache)
    ex.compare_and_stage = real_cas
    assert why is not None and "spread" in why
    assert fired["n"] == 1
    assert (
        metrics.fleet_admit_cas_conflict_total.labels(
            "version"
        )._value.get()
        == before + 1
    )
    assert r0.fleet.cas_conflicts == 1
    # only the peer's row landed — exactly one winner
    assert [r.pod for r in ex.peers_view("rx").pod_rows] == [
        "default/peer"
    ]
    _ = peer_zone  # zone bookkeeping above documents the skew shape


def test_admit_cas_retries_through_benign_version_churn():
    """A version bump that does NOT change the constraint picture (a
    label-bearing row in a namespace the selector never matches) costs
    one CAS round trip and then lands — contention is a retry, not a
    rejection."""
    cluster, scheds, ex, clock = _mk_fleet()
    r0 = scheds[0]
    pod = make_pod("ok", "250m", shape="spread")
    cluster.create_pod(pod)
    node = sorted(r0.cache.nodes)[0]
    real_cas = ex.compare_and_stage
    fired = {"n": 0}

    def benign(replica, row, expected_version, **kw):
        if not fired["n"]:
            fired["n"] += 1
            ex.stage(
                "r1",
                PodRow(
                    pod="other/unrelated", node="n9", zone="z0",
                    namespace="other", labels=(("tier", "db"),),
                ),
            )
        return real_cas(replica, row, expected_version, **kw)

    ex.compare_and_stage = benign
    why = r0.fleet.admit(pod, node, r0.cache)
    ex.compare_and_stage = real_cas
    assert why is None
    assert fired["n"] == 1 and r0.fleet.cas_conflicts == 1
    # the row landed under CAS and the apply-phase stage() must not
    # re-send it
    assert pod.key in r0.fleet._cas_staged
    r0.fleet.stage(pod, node, r0.cache)
    assert pod.key not in r0.fleet._cas_staged
    staged = [
        r.pod for r in ex.peers_view("rx").pod_rows if r.pod == pod.key
    ]
    assert staged == [pod.key]


def test_fleet_race_exactly_one_winner_end_to_end():
    """Two replicas, one last hard-spread slot: drive both schedulers
    and assert the fleet lands a legal outcome — the CAS admits are
    what keep the losing replica from double-placing into the same
    zone when both solved against the same peer view."""
    cluster, scheds, ex, clock = _mk_fleet()
    for i in range(6):
        cluster.create_pod(make_pod(f"s{i}", "250m", shape="spread"))
    bound = []
    for _ in range(10):
        for s in scheds:
            for r in s.run_until_settled():
                bound.extend(r.scheduled)
        clock.advance(11.0)
    assert len(bound) == 6
    zones: dict = {}
    for p in cluster.list_pods():
        z = f"z{int(p.node_name[1:]) % 2}"
        zones[z] = zones.get(z, 0) + 1
    assert zones == {"z0": 3, "z1": 3}


# -- RemoteOccupancyExchange: the wire adapter -------------------------------


@pytest.fixture()
def hub_server():
    hub = OccupancyExchange()
    core = BulkCore(ClusterState(), exchange=hub)
    server, port = make_grpc_server(core, port=0)
    server.start()
    yield hub, f"127.0.0.1:{port}"
    server.stop(grace=None)


def test_remote_exchange_mirrors_in_process_semantics(hub_server):
    """The same op sequence against the in-process hub and through the
    wire produces identical views, versions, and conflicts."""
    hub, addr = hub_server
    local = OccupancyExchange()
    remote0 = RemoteOccupancyExchange(addr, "r0")
    remote1 = RemoteOccupancyExchange(addr, "r1")
    try:
        for ex0, ex1 in ((local, local), (remote0, remote1)):
            ex0.publish_nodes("r0", [NodeRow("n1", "z0")])
            v = ex0.peers_version("r0")
            ex0.compare_and_stage("r0", _row(), v)
            with pytest.raises(AdmitConflict):
                ex1.compare_and_stage("r1", _row(pod="default/q"), v)
            ex0.commit("r0", "default/p")
            ex1.hand_off(
                "r0", "default/h", 1, from_replica="r1",
                trace="r1-1:2:default/h",
            )
            assert ex0.claim_handoffs("r0") == [
                ("default/h", 1, "r1-1:2:default/h")
            ]
            ex1.set_degraded("r1", True)
            assert ex0.degraded_replicas() == frozenset({"r1"})
        lv = local.peers_view("r1")
        rv = remote1.peers_view("r1")
        assert lv.version == rv.version
        assert lv.node_rows == rv.node_rows
        assert lv.pod_rows == rv.pod_rows
        assert [r for r, _a in lv.peer_ages] == [
            r for r, _a in rv.peer_ages
        ]
    finally:
        remote0.close()
        remote1.close()


def test_remote_exchange_partition_maps_to_unreachable(hub_server):
    """The sim's partition seam crosses the wire as UNAVAILABLE and
    surfaces as ExchangeUnreachable — the PR 8 staleness machinery
    needs exactly that type. Buffered stage rows survive the
    partition client-side and land at the first reachable flush."""
    hub, addr = hub_server
    remote = RemoteOccupancyExchange(addr, "r1")
    try:
        remote.publish_nodes("r1", [])
        hub.set_partitioned("r1", True)
        remote.stage("r1", _row())  # buffers client-side, no raise yet
        with pytest.raises(ExchangeUnreachable):
            remote.peers_view("r1")  # flush-before-read surfaces it
        # retained for retry (sealed under its flush_seq), not lost
        assert remote._pending_flush() == 1
        hub.set_partitioned("r1", False)
        remote.peers_view("r1")  # flush succeeds on heal
        assert remote._pending_flush() == 0
        assert [r.pod for r in hub.peers_view("rx").pod_rows] == [
            "default/p"
        ]
    finally:
        remote.close()


def test_remote_exchange_server_down_is_unreachable():
    remote = RemoteOccupancyExchange("127.0.0.1:1", "r0")
    try:
        with pytest.raises(ExchangeUnreachable):
            remote.peers_version("r0")
    finally:
        remote.close()


def test_remote_exchange_fence_maps_typed(hub_server):
    """A fenced CAS surfaces typed over the wire; a fenced write-
    behind flush silently DROPS its buffer (a retired replica's rows
    must not land — its healed incarnation re-registers wholesale)."""
    hub, addr = hub_server
    remote = RemoteOccupancyExchange(addr, "r0")
    try:
        remote.stage("r0", _row())
        remote.peers_version("r0")  # flush
        hub.retire("r0")
        with pytest.raises(AdmitConflict) as ei:
            remote.compare_and_stage(
                "r0", _row(pod="default/q"), hub.version
            )
        assert ei.value.fenced is True
        remote.stage("r0", _row(pod="default/z"))  # buffers
        remote.peers_version("r0")  # flush: fenced -> dropped, no raise
        assert not remote._buffer
        assert hub.peers_view("rx").pod_rows == ()  # nothing landed
        # the observed fence is sticky and surfaces TYPED at the next
        # mutation, so FleetRuntime flags the re-registering resync
        # exactly like the in-process path (review-caught: silently
        # succeeding would discard every later row forever)
        with pytest.raises(AdmitConflict) as ei2:
            remote.stage("r0", _row(pod="default/zz"))
        assert ei2.value.fenced is True
        remote.replace_pod_rows("r0", [_row()])  # re-registration
        remote.stage("r0", _row(pod="default/q"))
        remote.peers_version("r0")
        assert len(hub.peers_view("rx").pod_rows) == 2
    finally:
        remote.close()


def test_remote_exchange_write_behind_buffer(hub_server):
    """Plain stage/commit/withdraw buffer client-side and land as ONE
    apply_ops RPC at the next read — per-row unary RPCs were a
    measured ~4x throughput loss on the ladder #8 fleet arm — while
    the CAS path always flushes first so admission ordering holds."""
    hub, addr = hub_server
    remote = RemoteOccupancyExchange(addr, "r0")
    calls: list = []
    real = remote._client.hub_op
    remote._client.hub_op = lambda op, **m: (
        calls.append(op),
        real(op, **m),
    )[1]
    try:
        v0 = hub.version
        remote.stage("r0", _row(pod="default/a"))
        remote.stage("r0", _row(pod="default/b"))
        remote.commit("r0", "default/a")
        remote.withdraw("r0", "default/b")
        assert hub.version == v0  # nothing on the wire yet
        assert calls == []
        view_from_peer = remote.peers_view("r1")  # flush + read
        rows = {r.pod: r.state for r in view_from_peer.pod_rows}
        assert rows == {"default/a": "committed"}  # b staged+withdrawn
        # the whole 4-mutation buffer was ONE apply_ops RPC
        assert calls == ["apply_ops", "peers_view"]
    finally:
        remote.close()


def test_bulk_client_never_retries_cas_conflict(hub_server):
    """Satellite: a hub CAS conflict is a SEMANTIC rejection — it must
    surface immediately, never retry like UNAVAILABLE (the
    committing-Solve rule). A retried lost race would re-land the
    write the compare-and-stage exists to reject."""
    import grpc

    from kubernetes_tpu import metrics
    from kubernetes_tpu.fleet.occupancy import pod_row_to_list

    hub, addr = hub_server
    sleeps = []

    class SpyClock:
        def sleep(self, s):
            sleeps.append(s)

        def now(self):
            return 0.0

    client = BulkClient(addr, retries=3, clock=SpyClock())
    try:
        v = hub.version
        hub.stage("r1", _row(pod="default/winner"))  # moves the version
        before = metrics.bulk_retry_total.labels("HubOp")._value.get()
        with pytest.raises(grpc.RpcError) as ei:
            client.hub_op(
                "cas_stage", replica="r0",
                row=pod_row_to_list(_row()), expect=v,
            )
        assert ei.value.code() == grpc.StatusCode.ABORTED
        assert sleeps == []  # zero backoff sleeps = zero retries
        assert (
            metrics.bulk_retry_total.labels("HubOp")._value.get()
            == before
        )
        # fenced rejections are equally non-retryable
        hub.retire("r0")
        with pytest.raises(grpc.RpcError) as ei:
            client.hub_op(
                "stage", replica="r0", row=pod_row_to_list(_row())
            )
        assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION
        assert sleeps == []
    finally:
        client.close()


def test_bulk_client_retries_transient_hub_op(monkeypatch):
    """The flip side: UNAVAILABLE from a flaky channel still retries
    with FULL-JITTER backoff (hub ops get the same transient hygiene
    as every bulk RPC when the caller opts into retries): each wait is
    uniform over [0, base * 2^attempt) so N clients losing the same
    server never re-arrive in lockstep."""
    import random

    import grpc

    class FakeErr(grpc.RpcError):
        def code(self):
            return grpc.StatusCode.UNAVAILABLE

    sleeps = []

    class SpyClock:
        def sleep(self, s):
            sleeps.append(s)

        def now(self):
            return 0.0

    client = BulkClient.__new__(BulkClient)
    client._grpc = grpc
    client.retries = 2
    client.deadline_s = 1.0
    client.backoff_base_s = 0.01
    client._clock = SpyClock()
    client._backoff_rng = random.Random(0)
    calls = {"n": 0}

    from kubernetes_tpu.server import tensorcodec

    def flaky(payload, timeout):
        calls["n"] += 1
        if calls["n"] < 3:
            raise FakeErr()
        return tensorcodec.encode({"version": 7})

    client._hub_op = flaky
    assert client.hub_op("version") == {"version": 7}
    assert calls["n"] == 3 and len(sleeps) == 2
    # full jitter: draws land inside the doubling caps and match the
    # injected stream exactly (deterministic given the seeded rng)
    rng = random.Random(0)
    assert sleeps == [rng.uniform(0.0, 0.01), rng.uniform(0.0, 0.02)]
    assert 0.0 <= sleeps[0] < 0.01 and 0.0 <= sleeps[1] < 0.02


# -- the two-process race (acceptance) ---------------------------------------


def _race_worker(addr, rid, barrier, out_q):
    # deliberately light imports: the race worker needs only the hub
    # client surface, not jax
    from kubernetes_tpu.fleet import (
        AdmitConflict,
        PodRow,
        RemoteOccupancyExchange,
    )

    remote = RemoteOccupancyExchange(addr, rid)
    try:
        # both processes admit against the SAME view version, exactly
        # the racing-replicas interleave
        view = remote.peers_view(rid)
        barrier.wait(timeout=30)
        row = PodRow(
            pod=f"default/{rid}", node=f"{rid}-node", zone="z0",
            namespace="default", labels=(("app", "spread"),),
        )
        try:
            remote.compare_and_stage(rid, row, view.version)
            out_q.put((rid, "won", None))
        except AdmitConflict as e:
            out_q.put((rid, "conflict", bool(e.fenced)))
    finally:
        remote.close()


def test_two_process_race_exactly_one_winner():
    """ISSUE 11 acceptance: two OS processes race a hard-spread
    placement through the real gRPC hub — both pass their host-side
    check against the same view; the hub's fenced compare-and-swap
    lets exactly ONE land and hands the loser a typed conflict (the
    loser's scheduler requeues it through the ordinary machinery)."""
    hub = OccupancyExchange()
    core = BulkCore(ClusterState(), exchange=hub)
    server, port = make_grpc_server(core, port=0)
    server.start()
    ctx = multiprocessing.get_context("spawn")
    barrier = ctx.Barrier(2)
    out_q = ctx.Queue()
    procs = [
        ctx.Process(
            target=_race_worker,
            args=(f"127.0.0.1:{port}", rid, barrier, out_q),
        )
        for rid in ("r0", "r1")
    ]
    try:
        for p in procs:
            p.start()
        results = [out_q.get(timeout=60) for _ in procs]
        outcomes = sorted(o for _rid, o, _f in results)
        assert outcomes == ["conflict", "won"], results
        # the loser's conflict was the version race, not a fence
        fenced = [f for _rid, o, f in results if o == "conflict"]
        assert fenced == [False]
        # exactly one pending row landed at the hub
        rows = hub.peers_view("observer").pod_rows
        winner = [rid for rid, o, _f in results if o == "won"][0]
        assert [r.pod for r in rows] == [f"default/{winner}"]
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
        server.stop(grace=None)


# -- gRPC-hub fleet sim equivalence ------------------------------------------


def test_fleet_sim_grpc_hub_clean_and_deterministic():
    """The whole fleet drive through the wire-backed hub settles clean
    under every invariant (overcommit/constraints/journal/lost-pod)
    and is byte-deterministic run-to-run: RPC wall time never enters
    the virtual clock. (It is deliberately NOT byte-compared against
    the in-process drive — the client's write-behind buffer re-times
    hub version bumps, which re-times conflict-parked wakeups; the
    cross-transport contract is the invariants.)"""
    from kubernetes_tpu.sim.fleet import run_fleet_sim

    wired = run_fleet_sim(
        "fleet_mixed", seed=3, cycles=6, replicas=2, grpc_hub=True
    )
    again = run_fleet_sim(
        "fleet_mixed", seed=3, cycles=6, replicas=2, grpc_hub=True
    )
    assert wired.ok and again.ok
    assert wired.summary["hub"] == "grpc"
    assert wired.journal_digests == again.journal_digests
    assert wired.bindings == again.bindings
    # the drive actually exercised the wire-side fleet machinery
    assert sum(wired.summary["binds_by_replica"].values()) > 0


# -- per-replica mesh slices -------------------------------------------------


class TestMeshSlices:
    def test_slices_are_disjoint_and_contiguous(self):
        from kubernetes_tpu.parallel.sharding import resolve_mesh

        seen: list = []
        for rank in range(4):
            mesh = resolve_mesh(0, (rank, 4))
            ids = [d.id for d in mesh.devices.flat]
            assert len(ids) == 2  # 8 conftest devices / 4 slices
            assert ids == sorted(ids)
            seen.extend(ids)
        assert sorted(seen) == list(range(8))  # disjoint cover

    def test_single_device_slice_still_pins_a_mesh(self):
        """A 1-device slice must return a 1-way Mesh — falling back to
        the default device would stack every replica on device 0, the
        sharing violation the slice exists to prevent."""
        from kubernetes_tpu.parallel.sharding import resolve_mesh

        mesh = resolve_mesh(0, (5, 8))
        assert mesh is not None and int(mesh.size) == 1
        assert [d.id for d in mesh.devices.flat] == [5]

    def test_mesh_devices_applies_within_slice(self):
        from kubernetes_tpu.parallel.sharding import resolve_mesh

        mesh = resolve_mesh(1, (1, 2))
        assert [d.id for d in mesh.devices.flat] == [4]

    def test_slice_validation(self):
        from kubernetes_tpu.parallel.sharding import resolve_mesh

        with pytest.raises(ValueError):
            resolve_mesh(0, (4, 4))
        with pytest.raises(ValueError):
            resolve_mesh(0, (0, 16))  # only 8 visible

    def test_scheduler_on_slice_binds_identically(self):
        """End to end: a scheduler pinned to slice (1, 4) produces the
        same bindings as the default full-mesh scheduler (the PR 5
        device-count-invariance contract extended to slices), and the
        mesh-slice gauge reports the slice size."""
        from kubernetes_tpu import metrics

        def run(mesh_slice):
            clock = FakeClock()
            cluster = ClusterState(clock=clock)
            for i in range(6):
                cluster.create_node(
                    make_node(
                        f"n{i}", "8", "32Gi", labels={ZONE: f"z{i % 2}"}
                    )
                )
            sched = Scheduler(
                cluster,
                SchedulerConfig(
                    batch_size=16,
                    mesh_slice=mesh_slice,
                    solver=ExactSolverConfig(tie_break="first"),
                ),
                clock=clock,
            )
            for i in range(10):
                cluster.create_pod(make_pod(f"p{i}", "500m"))
            for _ in range(4):
                sched.run_streaming()
                clock.advance(11.0)
            return {
                p.key: p.node_name
                for p in cluster.list_pods()
                if p.node_name
            }

        full = run(None)
        sliced = run((1, 4))
        assert len(full) == 10
        assert full == sliced
        assert metrics.fleet_mesh_slice_devices._value.get() == 2
