"""Scheduling queue: activeQ / podBackoffQ / unschedulablePods with the
reference's ordering and retry semantics, plus batch-pop for the TPU solver.

Reference: pkg/scheduler/backend/queue/scheduling_queue.go#PriorityQueue.
- activeQ heap ordered by the queueSort plugin — PrioritySort.Less: higher
  .spec.priority first, earlier queue timestamp within a priority
  (plugins/queuesort/priority_sort.go);
- podBackoffQ heap by backoff expiry; backoff = initial 1s doubling per
  attempt, capped at 10s (#calculateBackoffDuration); flushed every 1s
  (#flushBackoffQCompleted);
- unschedulablePods map; pods parked there move back on cluster events
  (#MoveAllToActiveOrBackoffQueue) or after the 5-minute forced flush
  (#flushUnschedulablePodsLeftover);
- schedulingCycle / moveRequestCycle bookkeeping closes the lost-wakeup race:
  a pod rejected in cycle C goes straight to backoff/active (not the
  unschedulable map) if a move request happened at cycle >= C, because the
  event that would have woken it may have fired mid-cycle;
- PreEnqueue gating (plugins/schedulinggates): pods with schedulingGates wait
  in a gated map and enter the queue only when gates clear.

Divergence from the reference, by design: Pop() becomes pop_batch(K) — the
solver schedules K pods per device solve. Ordering inside the batch is
exactly the heap order, and the exact solver preserves it (lax.scan in batch
order), so batching is observationally equivalent to K sequential Pops.
QueueingHintFn is simplified to "move everything" for now (hint functions
land with the plugin kernels that register them).
"""

from __future__ import annotations

import heapq
import itertools
from dataclasses import dataclass, field

from .. import metrics
from ..api.objects import Pod
from ..utils.clock import Clock


class _SortKey:
    """Heap key adapter for a custom QueueSort comparator
    (interface.go#QueueSortPlugin.Less). __eq__ reports comparator ties
    so tuple comparison falls through to the FIFO seq tiebreaker."""

    __slots__ = ("info", "less")

    def __init__(self, info: "QueuedPodInfo", less) -> None:
        self.info = info
        self.less = less

    def __lt__(self, other: "_SortKey") -> bool:
        return self.less(self.info, other.info)

    def __eq__(self, other) -> bool:
        return not self.less(self.info, other.info) and not self.less(
            other.info, self.info
        )

    __hash__ = None

DEFAULT_POD_INITIAL_BACKOFF = 1.0
DEFAULT_POD_MAX_BACKOFF = 10.0
UNSCHEDULABLE_FLUSH_INTERVAL = 30.0
MAX_UNSCHEDULABLE_DURATION = 300.0  # 5 min forced re-activation


@dataclass
class QueuedPodInfo:
    pod: Pod
    timestamp: float  # time (re-)entered the queue — PrioritySort tiebreak
    initial_attempt_timestamp: float
    attempts: int = 0
    unschedulable_since: float | None = None
    gated: bool = False

    @property
    def key(self) -> str:
        return self.pod.key


class PriorityQueue:
    def __init__(
        self,
        clock: Clock | None = None,
        pod_initial_backoff: float = DEFAULT_POD_INITIAL_BACKOFF,
        pod_max_backoff: float = DEFAULT_POD_MAX_BACKOFF,
        honor_scheduling_gates: bool = True,
        pre_enqueue=None,
        less=None,
    ):
        self._clock = clock or Clock()
        self._initial_backoff = pod_initial_backoff
        self._max_backoff = pod_max_backoff
        # PodSchedulingReadiness feature gate: when off, schedulingGates
        # are ignored (pre-1.26 behavior) and nothing parks as gated
        self._honor_gates = honor_scheduling_gates
        # out-of-tree PreEnqueue point (interface.go#PreEnqueuePlugin):
        # pod -> bool; False parks the pod as gated exactly like
        # schedulingGates, re-evaluated on pod update
        self._pre_enqueue = pre_enqueue
        # out-of-tree QueueSort point: QueuedPodInfo x2 -> bool ("pops
        # first"); replaces the default PrioritySort heap key
        self._less = less
        self._seq = itertools.count()

        self._active: list[tuple[int, float, int, str]] = []  # (-prio, ts, seq, key)
        self._backoff: list[tuple[float, int, str]] = []  # (ready_at, seq, key)
        self._unschedulable: dict[str, QueuedPodInfo] = {}
        self._gated: dict[str, QueuedPodInfo] = {}
        self._info: dict[str, QueuedPodInfo] = {}
        # which structure a pod key lives in: active|backoff|unsched|gated
        self._where: dict[str, str] = {}
        # incremental per-structure sizes so pending_counts is O(1) — the
        # scheduler refreshes the pending_pods gauge on every queue
        # transition, which must not cost an O(pods) scan per watch event
        self._counts = {"active": 0, "backoff": 0, "unsched": 0, "gated": 0}

        self.scheduling_cycle = 0
        self._move_request_cycle = -1

    # -- helpers --

    def __len__(self) -> int:
        return len(self._info)

    def _set_where(self, key: str, where: str) -> None:
        old = self._where.get(key)
        if old is not None:
            self._counts[old] -= 1
        self._counts[where] += 1
        self._where[key] = where

    def _unset_where(self, key: str) -> None:
        old = self._where.pop(key, None)
        if old is not None:
            self._counts[old] -= 1

    def pending_counts(self) -> dict[str, int]:
        """pending_pods{queue=...} metric shape (O(1): incrementally
        maintained by the _set_where/_unset_where transitions)."""
        c = self._counts
        return {
            "active": c["active"],
            "backoff": c["backoff"],
            "unschedulable": c["unsched"],
            "gated": c["gated"],
        }

    def entries(self) -> dict[str, str]:
        """Pod key -> structure it currently lives in (``active`` |
        ``backoff`` | ``unsched`` | ``gated``). Read-only snapshot for
        observers (the sim's lost-pod invariant checker accounts every
        unbound pod against this map plus the scheduler's in-flight and
        waiting sets) — never a mutation surface."""
        return dict(self._where)

    def active_pods(self) -> list[Pod]:
        """Live activeQ pods, unordered snapshot — the mega-planner's
        warm-start reads the POPULATION to plan over (heap order is
        what ``reorder_active`` is about to rewrite anyway)."""
        return [
            self._info[key].pod
            for key, where in self._where.items()
            if where == "active"
        ]

    def reorder_active(self, rank: dict[str, int]) -> int:
        """Warm-start reorder (ISSUE 19): re-key the activeQ heap's
        tiebreak slot with an externally computed rank so pods the
        mega-planner expects to co-locate pop adjacently and the drain
        chunks pack against pre-fitted capacity. PRIORITY STAYS THE
        PRIMARY KEY — PrioritySort's contract is untouched; the rank
        only permutes pods WITHIN a priority band (it replaces the
        queue-timestamp tiebreak, which carries no cross-pod semantics
        beyond FIFO fairness). Unranked pods keep popping after ranked
        ones in their band, FIFO among themselves via the seq slot.
        No-op (returns 0) under a custom QueueSort ``less`` — an
        out-of-tree comparator owns the full key and must not be
        second-guessed. Returns the number of live entries re-keyed."""
        if self._less is not None or not self._active:
            return 0
        fresh: list[tuple[int, float, int, str]] = []
        rekeyed = 0
        for neg_prio, _ts, seq, key in self._active:
            if self._where.get(key) != "active":
                continue  # stale entry: drop during the rebuild
            r = rank.get(key)
            if r is None:
                fresh.append((neg_prio, float("inf"), seq, key))
            else:
                fresh.append((neg_prio, float(r), seq, key))
                rekeyed += 1
        heapq.heapify(fresh)
        self._active = fresh
        return rekeyed

    def _push_active(self, info: QueuedPodInfo) -> None:
        if self._less is not None:
            key0 = _SortKey(info, self._less)
            heapq.heappush(
                self._active, (key0, 0.0, next(self._seq), info.key)
            )
        else:
            heapq.heappush(
                self._active,
                (
                    -info.pod.effective_priority,
                    info.timestamp,
                    next(self._seq),
                    info.key,
                ),
            )
        self._set_where(info.key, "active")

    def _gate(self, pod: Pod) -> bool:
        """PreEnqueue verdict: True = park as gated. The in-tree
        schedulinggates check and any out-of-tree PreEnqueue plugin both
        gate here (scheduling_queue.go#runPreEnqueuePlugins)."""
        if pod.scheduling_gates and self._honor_gates:
            return True
        return self._pre_enqueue is not None and not self._pre_enqueue(pod)

    def _activate(self, info: QueuedPodInfo) -> bool:
        """EVERY path into the active heap funnels through the PreEnqueue
        gate (scheduling_queue.go#moveToActiveQ): a mutable out-of-tree
        PreEnqueue plugin may have closed since the pod last entered, and
        unlike schedulingGates (which are never re-added) that verdict is
        not monotone. Returns False when the pod parked as gated."""
        if self._gate(info.pod):
            info.gated = True
            self._gated[info.key] = info
            self._info[info.key] = info
            self._set_where(info.key, "gated")
            return False
        info.gated = False
        self._push_active(info)
        return True

    def _backoff_duration(self, attempts: int) -> float:
        """#calculateBackoffDuration: 1s doubling per prior attempt, capped."""
        d = self._initial_backoff
        for _ in range(attempts - 1):
            d *= 2
            if d >= self._max_backoff:
                return self._max_backoff
        return min(d, self._max_backoff)

    def _backoff_ready_at(self, info: QueuedPodInfo) -> float:
        return info.timestamp + self._backoff_duration(max(info.attempts, 1))

    def _push_backoff(self, info: QueuedPodInfo) -> None:
        heapq.heappush(
            self._backoff, (self._backoff_ready_at(info), next(self._seq), info.key)
        )
        self._set_where(info.key, "backoff")

    # -- add / update / delete (informer handlers) --

    def add(self, pod: Pod) -> None:
        now = self._clock.now()
        info = QueuedPodInfo(
            pod=pod, timestamp=now, initial_attempt_timestamp=now
        )
        if self._gate(pod):
            # PreEnqueue rejection (schedulinggates or out-of-tree plugin)
            info.gated = True
            self._gated[pod.key] = info
            self._info[pod.key] = info
            self._set_where(pod.key, "gated")
            metrics.queue_incoming_pods_total.labels("gated", "PodAdd").inc()
            return
        self._info[pod.key] = info
        self._push_active(info)
        metrics.queue_incoming_pods_total.labels("active", "PodAdd").inc()

    def update(self, pod: Pod) -> None:
        info = self._info.get(pod.key)
        if info is None:
            self.add(pod)
            return
        info.pod = pod
        where = self._where[pod.key]
        if where == "gated" and not self._gate(pod):
            info.gated = False
            del self._gated[pod.key]
            info.timestamp = self._clock.now()
            self._push_active(info)
        elif where == "unsched":
            # spec update may make it schedulable: move to active/backoff
            # (reference: isPodUpdated => move)
            self._move_one(info)

    def delete(self, pod_key: str) -> None:
        self._info.pop(pod_key, None)
        self._gated.pop(pod_key, None)
        self._unschedulable.pop(pod_key, None)
        self._unset_where(pod_key)
        # lazy deletion for heap entries: popping skips stale keys

    # -- pop --

    def pop_batch(self, max_pods: int) -> list[QueuedPodInfo]:
        """K sequential Pops worth of pods, in exact heap order."""
        self.flush_backoff_completed()
        out: list[QueuedPodInfo] = []
        while len(out) < max_pods and self._active:
            _, _, _, key = heapq.heappop(self._active)
            if self._where.get(key) != "active":
                continue  # stale entry
            info = self._info[key]
            info.attempts += 1
            self.scheduling_cycle += 1
            self._unset_where(key)
            del self._info[key]
            out.append(info)
        return out

    def take_for_gang(self, matches, exclude=frozenset()) -> list[QueuedPodInfo]:
        """Pop every queued pod for which ``matches(pod)`` is true out
        of the active/backoff/unschedulable structures, with exactly
        ``pop_batch``'s per-pod bookkeeping (attempt charge +
        scheduling-cycle advance). The scheduler's gang gate uses this
        to pull the rest of a ready pod group into the batch
        regardless of heap position or backoff state — a gang pops as
        a UNIT. Gated pods stay put (their PreEnqueue gates have not
        cleared, and a gang cannot be ready while a member is gated).
        Heap entries for taken pods go stale and are skipped by the
        lazy-deletion discipline every pop already applies."""
        out: list[QueuedPodInfo] = []
        for key in sorted(self._where):
            if key in exclude or self._where.get(key) == "gated":
                continue
            info = self._info.get(key)
            if info is None or not matches(info.pod):
                continue
            info.attempts += 1
            self.scheduling_cycle += 1
            self._unschedulable.pop(key, None)
            self._unset_where(key)
            del self._info[key]
            out.append(info)
        return out

    # -- failure / retry paths --

    def requeue_popped(self, info: QueuedPodInfo) -> None:
        """Return a popped pod to the active queue as if the pop had not
        happened: the attempt is uncharged and the original queue
        timestamp keeps its PrioritySort/FIFO position. Used when a
        dispatched device solve is DISCARDED by the pipelined loop's
        fence (Scheduler.run_pipelined) — the failure is the solve's, not
        the pod's, so no backoff applies. The PreEnqueue gate still runs
        (_activate), matching every other path into the active heap."""
        info.attempts = max(info.attempts - 1, 0)
        self._info[info.key] = info
        self._activate(info)
        metrics.queue_incoming_pods_total.labels(
            self._where[info.key], "SolveDiscarded"
        ).inc()

    def add_unschedulable(self, info: QueuedPodInfo, pod_scheduling_cycle: int) -> None:
        """#AddUnschedulableIfNotPresent."""
        now = self._clock.now()
        info.timestamp = now
        info.unschedulable_since = now
        self._info[info.key] = info
        if self._move_request_cycle >= pod_scheduling_cycle:
            # an event fired while this pod was in flight: don't park it
            self._push_backoff(info)
            metrics.queue_incoming_pods_total.labels(
                "backoff", "ScheduleAttemptFailure"
            ).inc()
        else:
            self._unschedulable[info.key] = info
            self._set_where(info.key, "unsched")
            metrics.queue_incoming_pods_total.labels(
                "unschedulable", "ScheduleAttemptFailure"
            ).inc()

    def _move_one(self, info: QueuedPodInfo) -> None:
        self._unschedulable.pop(info.key, None)
        now = self._clock.now()
        if self._backoff_ready_at(info) > now:
            self._push_backoff(info)
        else:
            info.timestamp = now
            self._activate(info)

    def move_all_to_active_or_backoff(self, event: str = "", worth=None) -> None:
        """#MoveAllToActiveOrBackoffQueue with QueueingHints: ``worth`` is
        the isPodWorthRequeuing gate (scheduling_queue.go) — a predicate
        over QueuedPodInfo built by the event handler from what actually
        changed (e.g. "does this pod fit the updated node's new free
        capacity"). Pods failing the hint STAY parked; ``worth=None``
        moves everything (events with no registered hint — safe,
        strictly more wakeups than the reference)."""
        self._move_request_cycle = self.scheduling_cycle
        for info in list(self._unschedulable.values()):
            if worth is None or worth(info):
                self._move_one(info)
                metrics.queue_incoming_pods_total.labels(
                    self._where[info.key], event or "ClusterEvent"
                ).inc()

    def flush_backoff_completed(self) -> None:
        """#flushBackoffQCompleted (reference runs this every 1s; we run it
        on every pop_batch as well)."""
        now = self._clock.now()
        while self._backoff:
            ready_at, _, key = self._backoff[0]
            if self._where.get(key) != "backoff":
                heapq.heappop(self._backoff)
                continue
            if ready_at > now:
                break
            heapq.heappop(self._backoff)
            info = self._info[key]
            info.timestamp = now
            self._activate(info)
            metrics.queue_incoming_pods_total.labels(
                self._where[key], "BackoffComplete"
            ).inc()

    def flush_unschedulable_leftover(self) -> None:
        """#flushUnschedulablePodsLeftover: pods stuck > 5 min forced back."""
        now = self._clock.now()
        for info in list(self._unschedulable.values()):
            if (
                info.unschedulable_since is not None
                and now - info.unschedulable_since > MAX_UNSCHEDULABLE_DURATION
            ):
                self._move_one(info)
