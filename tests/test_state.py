"""State plane: cluster service (rv/conflicts/watch/binding), scheduler cache
(assume/forget/expire/generations), snapshot incrementality, queue ordering
and backoff — semantics from cache.go / scheduling_queue.go, with fake
clocks as in the reference's queue tests."""

import pytest

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.state.cache import CacheError, SchedulerCache
from kubernetes_tpu.state.cluster import ApiError, ClusterState
from kubernetes_tpu.state.queue import PriorityQueue
from kubernetes_tpu.state.snapshot import Snapshot
from kubernetes_tpu.utils.clock import FakeClock


def node(name, cpu="4", mem="8Gi", pods="10"):
    return MakeNode().name(name).capacity({"cpu": cpu, "memory": mem, "pods": pods}).obj()


def pod(name, cpu="100m", prio=None, ns="default"):
    mp = MakePod().name(name).namespace(ns).req({"cpu": cpu})
    if prio is not None:
        mp = mp.priority(prio)
    return mp.obj()


class TestClusterState:
    def test_crud_and_rv_monotonic(self):
        cs = ClusterState()
        cs.create_node(node("n1"))
        p = cs.create_pod(pod("p1"))
        rv1 = p.resource_version
        cs.bind("default", "p1", "n1")
        assert cs.get_pod("default", "p1").node_name == "n1"
        assert cs.get_pod("default", "p1").resource_version > rv1

    def test_bind_rejects_double_and_missing_node(self):
        cs = ClusterState()
        cs.create_node(node("n1"))
        cs.create_pod(pod("p1"))
        cs.bind("default", "p1", "n1")
        with pytest.raises(ApiError) as e:
            cs.bind("default", "p1", "n1")
        assert e.value.reason == "Conflict"
        cs.create_pod(pod("p2"))
        with pytest.raises(ApiError) as e:
            cs.bind("default", "p2", "ghost")
        assert e.value.reason == "NotFound"

    def test_optimistic_concurrency(self):
        cs = ClusterState()
        n = cs.create_node(node("n1"))
        stale = n.resource_version
        cs.update_node(n)  # bumps rv
        with pytest.raises(ApiError) as e:
            cs.update_node(n, expect_rv=stale)
        assert e.value.reason == "Conflict"

    def test_watch_order(self):
        cs = ClusterState()
        seen = []
        cs.subscribe(lambda ev: seen.append((ev.type, ev.kind)))
        cs.create_node(node("n1"))
        cs.create_pod(pod("p1"))
        cs.bind("default", "p1", "n1")
        cs.delete_pod("default", "p1")
        assert seen == [
            ("ADDED", "Node"),
            ("ADDED", "Pod"),
            ("MODIFIED", "Pod"),
            ("DELETED", "Pod"),
        ]

    def test_bind_fault_injection(self):
        cs = ClusterState()
        cs.create_node(node("n1"))
        cs.create_pod(pod("p1"))

        def boom(pod_, node_name):
            raise ApiError("Conflict", "injected")

        cs.bind_fault = boom
        with pytest.raises(ApiError):
            cs.bind("default", "p1", "n1")
        assert cs.get_pod("default", "p1").node_name == ""


class TestEviction:
    """The pods/{name}/eviction subresource analog (ClusterState.evict):
    fencing first, then existence, optimistic concurrency, the PDB gate
    (429 TooManyRequests at disruptionsAllowed == 0), and the collapsed
    delete+recreate that returns the pod to Pending under its own
    identity — the API the continuous rebalancer moves pods through."""

    def _bound(self, labels=None, claim=None):
        cs = ClusterState()
        cs.create_node(node("n1"))
        cs.create_node(node("n2"))
        mp = MakePod().name("p1").req({"cpu": "100m"})
        for k, v in (labels or {}).items():
            mp = mp.label(k, v)
        if claim:
            mp = mp.resource_claim(claim)
        cs.create_pod(mp.obj())
        cs.bind("default", "p1", "n1")
        return cs

    def test_evict_returns_pod_to_pending_with_nomination(self):
        cs = self._bound()
        seen = []
        cs.subscribe(
            lambda ev: seen.append((ev.type, bool(ev.obj.node_name)))
            if ev.kind == "Pod"
            else None
        )
        rv_before = cs.get_pod("default", "p1").resource_version
        p = cs.evict("default", "p1", nominated_node="n2")
        assert p.node_name == ""
        assert p.phase == "Pending"
        assert p.nominated_node_name == "n2"
        assert p.resource_version > rv_before
        # the watch collapse every subscriber already handles: an
        # assigned-pod DELETED (nodeName still set) then an unbound
        # ADDED re-admitting the same identity
        assert seen == [("DELETED", True), ("ADDED", False)]
        evs = [e for e in cs.list_events() if e.reason == "Evicted"]
        assert len(evs) == 1
        assert "n2" in evs[0].note  # the nomination is recorded

    def test_evict_deleted_event_snapshot_survives_recreate(self):
        # events carry their object by reference and a delayed watch
        # bus delivers them AFTER evict() has mutated the live pod for
        # the recreate half: the DELETED must be a snapshot that still
        # reads as bound at pump time, or every buffered consumer
        # takes the unbound-delete branch and leaks source occupancy
        cs = self._bound()
        buffered = []
        cs.subscribe(
            lambda ev: buffered.append(ev)
            if ev.kind == "Pod"
            else None
        )
        cs.evict("default", "p1", nominated_node="n2")
        deleted = [e for e in buffered if e.type == "DELETED"]
        assert len(deleted) == 1
        assert deleted[0].obj.node_name == "n1"  # deferred read
        assert cs.get_pod("default", "p1").node_name == ""

    def test_evict_unbound_pod_invalid(self):
        cs = ClusterState()
        cs.create_pod(pod("p1"))
        with pytest.raises(ApiError) as e:
            cs.evict("default", "p1")
        assert e.value.reason == "Invalid"

    def test_evict_missing_pod_not_found(self):
        cs = ClusterState()
        with pytest.raises(ApiError) as e:
            cs.evict("default", "ghost")
        assert e.value.reason == "NotFound"

    def test_evict_stale_rv_conflict(self):
        cs = self._bound()
        stale = cs.get_pod("default", "p1").resource_version
        cs.patch_pod_status("default", "p1", nominated_node_name="n2")
        with pytest.raises(ApiError) as e:
            cs.evict("default", "p1", expect_rv=stale)
        assert e.value.reason == "Conflict"
        assert cs.get_pod("default", "p1").node_name == "n1"  # untouched

    def test_evict_pdb_exhausted_rejects_with_429(self):
        from kubernetes_tpu.api.labels import (
            Selector,
            requirements_from_match_labels,
        )
        from kubernetes_tpu.api.objects import PodDisruptionBudget

        cs = self._bound(labels={"app": "db"})
        cs.create_pdb(
            PodDisruptionBudget(
                name="db-pdb",
                selector=Selector(
                    requirements=requirements_from_match_labels(
                        {"app": "db"}
                    )
                ),
                disruptions_allowed=0,
            )
        )
        with pytest.raises(ApiError) as e:
            cs.evict("default", "p1")
        assert e.value.reason == "TooManyRequests"
        # the eviction did NOT happen and the allowance did not go
        # further negative
        assert cs.get_pod("default", "p1").node_name == "n1"
        (pdb,) = cs.list_pdbs()
        assert pdb.disruptions_allowed == 0

    def test_evict_decrements_pdb_allowance(self):
        from kubernetes_tpu.api.labels import (
            Selector,
            requirements_from_match_labels,
        )
        from kubernetes_tpu.api.objects import PodDisruptionBudget

        cs = self._bound(labels={"app": "db"})
        mp2 = MakePod().name("p2").req({"cpu": "100m"}).label("app", "db")
        cs.create_pod(mp2.obj())
        cs.bind("default", "p2", "n2")
        cs.create_pdb(
            PodDisruptionBudget(
                name="db-pdb",
                selector=Selector(
                    requirements=requirements_from_match_labels(
                        {"app": "db"}
                    )
                ),
                disruptions_allowed=1,
            )
        )
        cs.evict("default", "p1")  # spends the one allowance
        (pdb,) = cs.list_pdbs()
        assert pdb.disruptions_allowed == 0
        with pytest.raises(ApiError) as e:
            cs.evict("default", "p2")
        assert e.value.reason == "TooManyRequests"
        assert cs.get_pod("default", "p2").node_name == "n2"

    def test_evict_fenced_zombie_rejected_before_anything(self):
        cs = self._bound()
        old = cs.grant_fence("leader")
        fresh = cs.grant_fence("leader")  # supersedes: old is a zombie
        with pytest.raises(ApiError) as e:
            cs.evict("default", "p1", fence=("leader", old))
        assert e.value.reason == "Conflict"
        assert e.value.fenced  # typed flag, not a message contract
        assert cs.fence_rejections["leader"] == 1
        assert cs.get_pod("default", "p1").node_name == "n1"
        # the current holder moves pods fine
        p = cs.evict("default", "p1", fence=("leader", fresh))
        assert p.node_name == ""

    def test_evict_fence_checked_before_existence(self):
        # order mirrors the registry: a zombie probing a deleted pod
        # learns it is fenced, not that the pod is gone
        cs = ClusterState()
        old = cs.grant_fence("leader")
        cs.grant_fence("leader")
        with pytest.raises(ApiError) as e:
            cs.evict("default", "ghost", fence=("leader", old))
        assert e.value.fenced

    def test_evict_releases_resource_claims(self):
        from kubernetes_tpu.api.dra import DeviceRequest, ResourceClaim

        cs = self._bound(claim="train")
        c = cs.create_resource_claim(
            ResourceClaim(
                name="train",
                requests=(
                    DeviceRequest(name="g", device_class_name="gpu"),
                ),
            )
        )
        c.reserved_for = ("default/p1",)
        c.allocated_node = "n1"
        gen = cs.dra_generation
        cs.evict("default", "p1")
        claim = cs.get_resource_claim("default", "train")
        # the deallocating-controller stand-in ran: nobody reserves the
        # claim, so its allocation is released for the re-bind
        assert claim.reserved_for == ()
        assert claim.allocated_node == ""
        assert cs.dra_generation > gen


class TestSchedulerCache:
    def test_assume_confirm_flow(self):
        clock = FakeClock()
        c = SchedulerCache(clock)
        c.add_node(node("n1"))
        p = pod("p1")
        c.assume_pod(p, "n1")
        assert c.is_assumed("default/p1")
        assert c.nodes["n1"].used["cpu"] == 100
        c.finish_binding("default/p1")
        bound = pod("p1")
        bound.node_name = "n1"
        c.add_pod(bound)  # watch confirmation
        assert not c.is_assumed("default/p1")
        assert c.nodes["n1"].used["cpu"] == 100  # not double-counted

    def test_forget_releases(self):
        c = SchedulerCache(FakeClock())
        c.add_node(node("n1"))
        c.assume_pod(pod("p1"), "n1")
        c.forget_pod("default/p1")
        assert c.nodes["n1"].used.get("cpu", 0) == 0
        assert c.nodes["n1"].pod_count if hasattr(c.nodes["n1"], "pod_count") else True

    def test_assume_expiry(self):
        clock = FakeClock()
        c = SchedulerCache(clock, assume_ttl=30)
        c.add_node(node("n1"))
        c.assume_pod(pod("p1"), "n1")
        c.finish_binding("default/p1")
        clock.advance(31)
        expired = c.cleanup_expired()
        assert expired == ["default/p1"]
        assert c.nodes["n1"].used.get("cpu", 0) == 0

    def test_unfinished_assume_expires_after_ttl(self):
        # pre-PR-8 discrepancy: an assume whose binding cycle died
        # before finish_binding was NEVER reaped, leaking phantom
        # occupancy forever. It now expires after the assume TTL and
        # releases its occupancy (the restart-recovery pass leans on
        # the same release semantics).
        clock = FakeClock()
        c = SchedulerCache(clock, assume_ttl=30)
        c.add_node(node("n1"))
        c.assume_pod(pod("p1"), "n1")
        clock.advance(29)
        assert c.cleanup_expired() == []  # binding still in flight
        clock.advance(2)
        assert c.cleanup_expired() == ["default/p1"]
        assert c.nodes["n1"].used.get("cpu", 0) == 0  # occupancy released
        assert not c.is_assumed("default/p1")

    def test_protected_unfinished_assume_survives_ttl(self):
        # Permit-parked pods legitimately sit assumed-unfinished across
        # cycles: the WaitingPods map protects them from the unfinished
        # reap (their rollback deadline is the permit timeout)
        clock = FakeClock()
        c = SchedulerCache(clock, assume_ttl=30)
        c.add_node(node("n1"))
        c.assume_pod(pod("p1"), "n1")
        clock.advance(300)
        assert c.cleanup_expired(protected=frozenset({"default/p1"})) == []
        assert c.is_assumed("default/p1")

    def test_double_assume_rejected(self):
        c = SchedulerCache(FakeClock())
        c.add_node(node("n1"))
        c.assume_pod(pod("p1"), "n1")
        with pytest.raises(CacheError):
            c.assume_pod(pod("p1"), "n1")

    def test_node_removed_with_pods_keeps_ghost(self):
        c = SchedulerCache(FakeClock())
        c.add_node(node("n1"))
        bound = pod("p1")
        bound.node_name = "n1"
        c.add_pod(bound)
        c.remove_node("n1")
        assert c.nodes["n1"].node is None  # ghost holding the pod
        c.remove_pod("default/p1")
        assert "n1" not in c.nodes


class TestSnapshot:
    def test_incremental_update(self):
        c = SchedulerCache(FakeClock())
        for i in range(3):
            c.add_node(node(f"n{i}"))
        snap = Snapshot()
        b = snap.update(c)
        assert b.num_nodes == 3
        assert b.valid.sum() == 3
        # place a pod; only that column should change
        bound = pod("p1", cpu="500m")
        bound.node_name = "n1"
        c.add_pod(bound)
        i1 = snap.slot_of("n1")
        before = b.used.copy()
        b2 = snap.update(c)
        assert b2.used[0, i1] == 500
        unchanged = [snap.slot_of("n0"), snap.slot_of("n2")]
        for j in unchanged:
            assert (b2.used[:, j] == before[:, j]).all()

    def test_node_remove_and_slot_reuse(self):
        c = SchedulerCache(FakeClock())
        for i in range(3):
            c.add_node(node(f"n{i}"))
        snap = Snapshot()
        snap.update(c)
        slot = snap.slot_of("n1")
        c.remove_node("n1")
        b = snap.update(c)
        assert not b.valid[slot]
        c.add_node(node("n9"))
        b = snap.update(c)
        assert snap.slot_of("n9") == slot  # reused
        assert b.valid[slot]

    def test_high_freed_slot_with_multiple_adds_no_collision(self):
        """Regression (sim-caught overcommit): removing a HIGH slot and
        adding more nodes than _free holds in ONE update used to
        double-assign the freed slot — max+1 fresh-slot counting walked
        back up into a slot _free had already handed out, two nodes
        shared a column, and the second write erased the first node's
        usage (the solver then overcommitted against understated
        tables)."""
        c = SchedulerCache(FakeClock())
        for i in range(9):
            c.add_node(node(f"n{i}"))
        snap = Snapshot()
        snap.update(c)
        # free a LOW slot, then a HIGH slot, then add three nodes in one
        # update: free=[low, high] pops high first, and the fresh-slot
        # path must not re-issue it
        c.remove_node("n7")
        snap.update(c)
        c.remove_node("n8")
        for i in range(9, 12):
            c.add_node(node(f"n{i}"))
        b = snap.update(c)
        slots = [snap.slot_of(f"n{i}") for i in (0, 1, 2, 3, 4, 5, 6, 9, 10, 11)]
        assert len(set(slots)) == len(slots), slots
        # every column carries ITS node's tables (no silent overwrite)
        for i in (9, 10, 11):
            s = snap.slot_of(f"n{i}")
            assert b.valid[s]
            assert b.allocatable[0, s] == 4000
            assert b.used[0, s] == 0

    def test_capacity_growth_preserves_slots(self):
        c = SchedulerCache(FakeClock())
        for i in range(100):
            c.add_node(node(f"n{i:03}"))
        snap = Snapshot()
        b = snap.update(c)
        assert b.padded == 128
        s50 = snap.slot_of("n050")
        for i in range(100, 200):
            c.add_node(node(f"n{i:03}"))
        b = snap.update(c)
        assert b.padded == 256
        assert snap.slot_of("n050") == s50
        assert b.allocatable[0, s50] == 4000


class TestPriorityQueue:
    def test_priority_then_fifo_order(self):
        clock = FakeClock()
        q = PriorityQueue(clock)
        q.add(pod("low1", prio=1))
        clock.advance(1)
        q.add(pod("high", prio=10))
        clock.advance(1)
        q.add(pod("low2", prio=1))
        got = [i.pod.name for i in q.pop_batch(10)]
        assert got == ["high", "low1", "low2"]

    def test_unschedulable_parks_until_move(self):
        clock = FakeClock()
        q = PriorityQueue(clock)
        q.add(pod("p1"))
        (info,) = q.pop_batch(1)
        cycle = q.scheduling_cycle
        q.add_unschedulable(info, cycle)
        assert q.pop_batch(1) == []
        clock.advance(60)  # well past any backoff
        q.move_all_to_active_or_backoff("NodeAdd")
        got = q.pop_batch(1)
        assert [i.pod.name for i in got] == ["p1"]

    def test_backoff_grows_and_caps(self):
        clock = FakeClock()
        q = PriorityQueue(clock)
        q.add(pod("p1"))
        # attempt 1 -> backoff 1s
        (info,) = q.pop_batch(1)
        q.add_unschedulable(info, q.scheduling_cycle)
        q.move_all_to_active_or_backoff()
        assert q.pop_batch(1) == []  # still backing off
        clock.advance(1.01)
        (info,) = q.pop_batch(1)
        # attempt 2 -> 2s
        q.add_unschedulable(info, q.scheduling_cycle)
        q.move_all_to_active_or_backoff()
        clock.advance(1.01)
        assert q.pop_batch(1) == []
        clock.advance(1.0)
        (info,) = q.pop_batch(1)
        assert info.attempts == 3

    def test_move_request_cycle_prevents_lost_wakeup(self):
        clock = FakeClock()
        q = PriorityQueue(clock)
        q.add(pod("p1"))
        (info,) = q.pop_batch(1)
        cycle = q.scheduling_cycle
        # event fires while the pod is mid-cycle
        q.move_all_to_active_or_backoff("NodeAdd")
        q.add_unschedulable(info, cycle)
        # pod must NOT be parked: it goes to backoff and becomes ready
        clock.advance(1.01)
        assert [i.pod.name for i in q.pop_batch(1)] == ["p1"]

    def test_five_minute_flush(self):
        clock = FakeClock()
        q = PriorityQueue(clock)
        q.add(pod("p1"))
        (info,) = q.pop_batch(1)
        q.add_unschedulable(info, q.scheduling_cycle)
        clock.advance(301)
        q.flush_unschedulable_leftover()
        assert [i.pod.name for i in q.pop_batch(1)] == ["p1"]

    def test_scheduling_gates(self):
        clock = FakeClock()
        q = PriorityQueue(clock)
        gated = MakePod().name("g").scheduling_gates(["wait"]).obj()
        q.add(gated)
        assert q.pop_batch(1) == []
        ungated = MakePod().name("g").obj()
        q.update(ungated)
        assert [i.pod.name for i in q.pop_batch(1)] == ["g"]

    def test_delete_pending(self):
        q = PriorityQueue(FakeClock())
        q.add(pod("p1"))
        q.delete("default/p1")
        assert q.pop_batch(1) == []


def test_event_store_ttl_prunes_old_records():
    """Events expire after event_ttl (the reference apiserver's 1h TTL)
    instead of accumulating forever — and a count-bumped OLD record with
    a fresh last_timestamp must not block the sweep (review-caught: the
    sweep scans the whole store, not just the insertion-order head)."""
    from kubernetes_tpu.api.wrappers import MakeNode

    cs = ClusterState()
    n = cs.create_node(MakeNode().name("n1").capacity({"cpu": "1"}).obj())
    cs.event_ttl = 100.0
    cs._events_sweep_at = 3  # sweep once the store holds 3 records
    cs.record_event(n, "HotHead", "recurring", timestamp=0.0)
    cs.record_event(n, "Old", "stale note", timestamp=10.0)
    # the head record keeps recurring within its TTL: fresh
    # last_timestamp, oldest insertion slot
    cs.record_event(n, "HotHead", "recurring", timestamp=95.0)
    cs.record_event(n, "Newer", "fresh note", timestamp=195.0)
    cs.record_event(n, "Latest", "now", timestamp=200.0)
    reasons = {e.reason for e in cs.list_events()}
    assert "Old" not in reasons, "expired record behind a hot head"
    assert {"HotHead", "Newer", "Latest"} <= reasons
    assert cs.list_events(regarding_name="n1")[0].count >= 2


def test_event_store_ttl_small_store_still_prunes():
    """A store below the size-sweep threshold still expires records once
    a full TTL elapses since the last sweep (review-caught: the size-only
    trigger never fired for small stores)."""
    from kubernetes_tpu.api.wrappers import MakeNode

    cs = ClusterState()
    n = cs.create_node(MakeNode().name("n1").capacity({"cpu": "1"}).obj())
    cs.event_ttl = 100.0  # default sweep threshold (256) untouched
    cs.record_event(n, "Old", "stale", timestamp=0.0)
    cs.record_event(n, "Fresh", "new", timestamp=150.0)
    reasons = {e.reason for e in cs.list_events()}
    assert "Old" not in reasons and "Fresh" in reasons


def test_fit_hint_ignores_capacity_shrink_that_still_fits():
    """VERDICT r3 weak #8: a resource-only NodeUpdate that SHRINKS
    allocatable must not wake parked pods that already fit the old
    capacity — the change cannot have unblocked them."""
    from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
    from kubernetes_tpu.solver.exact import ExactSolverConfig

    clock = FakeClock()
    cs = ClusterState()
    n1 = node("n1", cpu="8")
    cs.create_node(n1)
    sched = Scheduler(
        cs,
        SchedulerConfig(solver=ExactSolverConfig(tie_break="first")),
        clock=clock,
    )
    # park two pods as unschedulable: one that always fit n1's resources
    # (rejected elsewhere) and one genuinely resource-blocked
    cs.create_pod(pod("small", cpu="100m"))
    cs.create_pod(pod("big", cpu="6000m"))
    infos = sched.queue.pop_batch(2)
    for info in infos:
        sched.queue.add_unschedulable(info, sched.queue.scheduling_cycle)
    assert sched.queue.pending_counts()["unschedulable"] == 2
    # shrink allocatable 8 -> 4 cpu: small still fits old AND new (the
    # change cannot have unblocked it), big fits neither -> no wakeups
    shrunk = node("n1", cpu="4")
    shrunk.resource_version = cs.get_node("n1").resource_version
    cs.update_node(shrunk)
    assert sched.queue.pending_counts()["unschedulable"] == 2, (
        "a shrink that changes no verdict must wake nothing"
    )
    # grow 4 -> 16 cpu: big fits new but NOT old -> exactly it wakes
    grown = node("n1", cpu="16")
    grown.resource_version = cs.get_node("n1").resource_version
    cs.update_node(grown)
    counts = sched.queue.pending_counts()
    assert counts["unschedulable"] == 1  # small stays parked
    clock.advance(1.1)  # let the moved pod clear its backoff window
    woken = [i.pod.name for i in sched.queue.pop_batch(10)]
    assert woken == ["big"]
