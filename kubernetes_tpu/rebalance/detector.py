"""Fragmentation detection from the live snapshot tensors.

Everything here is host-side numpy over the ``NodeBatch`` arrays the
scheduler's ``Snapshot`` already maintains (``allocatable``/``used``/
``pod_count``/``valid``/``schedulable``) — no device reads, no new sync
points (TPU001-clean by construction, same contract as the decision
journal's attribution).

The signals:

- **packed utilization** — the dominant-resource fill of the nodes that
  actually host pods: ``max(cpu, mem)`` of ``sum(used) / sum(alloc)``
  over non-empty schedulable nodes. A perfectly consolidated cluster
  runs its in-use nodes near full on their binding resource; a
  fragmented one spreads the same load thin. Dominant-resource (max,
  not mean) so a cpu-bound node counts as full even with memory spare —
  using the mean would make well-packed cpu-bound clusters look
  permanently fragmented and the rebalancer would chase an unreachable
  threshold forever.
- **bin-packing lower bound** — the fewest nodes the current load could
  occupy (total used / largest per-node allocatable, per resource, take
  the max). ``nodes_in_use`` far above it means consolidation headroom.
- **stranded capacity** — the fraction of total free capacity that
  hides on partly-used nodes (free slivers between resident pods)
  rather than on empty nodes, dominant-resource like packing (per
  resource, take the max). High stranding is what makes large pods
  unschedulable on a cluster whose aggregate free capacity is ample.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..tensorize.schema import CPU_IDX, MEM_IDX


@dataclass(frozen=True)
class FragmentationReport:
    nodes_total: int  # schedulable nodes in the snapshot
    nodes_in_use: int  # schedulable nodes hosting >= 1 pod
    ideal_nodes: int  # bin-packing lower bound for the current load
    packed_utilization: float  # dominant-resource fill of in-use nodes
    stranded_fraction: float  # free capacity hiding on partly-used nodes
    fragmented: bool  # packed_utilization < threshold with headroom
    # pending pods whose priority exceeds the lowest bound priority — a
    # signal that re-packing could seat them (advisory; the planner
    # itself only consolidates)
    priority_inversions: int = 0


def detect(
    batch,
    *,
    min_packing: float = 0.7,
    priority_inversions: int = 0,
) -> FragmentationReport:
    """Compute the fragmentation report for one snapshot ``NodeBatch``.

    ``fragmented`` is True when the in-use nodes run below
    ``min_packing`` on their dominant resource AND the load could
    provably fit on fewer nodes (``nodes_in_use > ideal_nodes``) — the
    second clause keeps a sparse-but-unconsolidatable cluster (one pod
    per node, each pod near node-sized) from triggering pointless plan
    solves every interval.
    """
    live = np.asarray(batch.valid) & np.asarray(batch.schedulable)
    pod_count = np.asarray(batch.pod_count)
    nonempty = live & (pod_count > 0)
    nodes_total = int(live.sum())
    nodes_in_use = int(nonempty.sum())

    cpu_a = np.asarray(batch.allocatable[CPU_IDX], dtype=np.float64)
    mem_a = np.asarray(batch.allocatable[MEM_IDX], dtype=np.float64)
    cpu_u = np.asarray(batch.used[CPU_IDX], dtype=np.float64)
    mem_u = np.asarray(batch.used[MEM_IDX], dtype=np.float64)

    if nodes_in_use == 0:
        return FragmentationReport(
            nodes_total=nodes_total,
            nodes_in_use=0,
            ideal_nodes=0,
            packed_utilization=1.0,
            stranded_fraction=0.0,
            fragmented=False,
            priority_inversions=priority_inversions,
        )

    def frac(used, alloc, mask) -> float:
        denom = float(alloc[mask].sum())
        return float(used[mask].sum()) / denom if denom > 0 else 0.0

    packed = max(
        frac(cpu_u, cpu_a, nonempty), frac(mem_u, mem_a, nonempty)
    )

    # bin-packing lower bound: per resource, total load over the
    # LARGEST single node's capacity (a true lower bound even on
    # heterogeneous clusters); dominant resource decides
    ideal = 0
    for used, alloc in ((cpu_u, cpu_a), (mem_u, mem_a)):
        cap = float(alloc[live].max()) if nodes_total else 0.0
        if cap > 0:
            ideal = max(
                ideal, int(np.ceil(float(used[live].sum()) / cap))
            )

    # dominant-resource, like packing: a memory-fragmented cluster
    # (cpu free concentrated on empty nodes, memory free scattered as
    # slivers) must still report high stranding
    stranded = 0.0
    for used, alloc in ((cpu_u, cpu_a), (mem_u, mem_a)):
        free = np.maximum(alloc - used, 0.0)
        total_free = float(free[live].sum())
        if total_free > 0:
            stranded = max(
                stranded, float(free[nonempty].sum()) / total_free
            )

    return FragmentationReport(
        nodes_total=nodes_total,
        nodes_in_use=nodes_in_use,
        ideal_nodes=ideal,
        packed_utilization=packed,
        stranded_fraction=stranded,
        fragmented=packed < min_packing and nodes_in_use > max(ideal, 1),
        priority_inversions=priority_inversions,
    )


def packing_score(batch, slot: int, extra_used=None) -> int:
    """Integer dominant-resource fill of one snapshot slot, in percent
    points — the planner's per-move gain currency (integer so move
    selection is exactly deterministic). ``extra_used`` (a [K] vector)
    adjusts the slot's usage, e.g. minus the candidate pod's own request
    on its source node."""
    cpu_a = float(batch.allocatable[CPU_IDX, slot])
    mem_a = float(batch.allocatable[MEM_IDX, slot])
    cpu_u = float(batch.used[CPU_IDX, slot])
    mem_u = float(batch.used[MEM_IDX, slot])
    if extra_used is not None:
        cpu_u += float(extra_used[CPU_IDX])
        mem_u += float(extra_used[MEM_IDX])
    cpu_f = cpu_u / cpu_a if cpu_a > 0 else 0.0
    mem_f = mem_u / mem_a if mem_a > 0 else 0.0
    return int(100.0 * max(min(cpu_f, 1.0), min(mem_f, 1.0), 0.0))
