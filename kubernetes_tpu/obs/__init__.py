"""kubernetes_tpu.obs — the end-to-end scheduling trace layer.

Three cooperating pieces, all zero-dep and virtual-time-clean:

- **spans** (``span.py``): OTel-shaped host-side spans threaded through
  both scheduler loops (enqueue → snapshot → tensorize → fold/extender
  → dispatch → fence → apply → bind) and the extender server's
  micro-batcher; exported as JSONL and into the flight recorder.
- **per-pod decision journal** (``journal.py``): one record per pod per
  solved batch — outcome plus per-plugin filter attribution pulled from
  the host-materialized solve tensors, so "why is pod X pending" has a
  concrete answer ("NodeResourcesFit rejected 14/16 nodes, ...").
- **flight recorder** (``recorder.py``): bounded ring of recent spans +
  decisions, dumped on crash, on sim invariant violation, and on demand
  via ``GET /debug/flightrecorder`` / ``/debug/spans``.

``python -m kubernetes_tpu.obs explain <pod> [--trace FILE | --url U]``
reconstructs a pod's history from any of those sources (``explain.py``).

Everything is OFF by default: ``build_obs(None, clock)`` returns a
disabled tracer and no journal/recorder, and the scheduler's hot path
then pays one attribute check per would-be span — no allocation, no
host↔device syncs (TPU001 stays clean; verified by the analyzer gate).
"""

from __future__ import annotations

from dataclasses import dataclass

from .. import metrics
from ..utils.clock import Clock
from .explain import (
    Explanation,
    explain_pod,
    merge_fleet_records,
    parse_stream,
)
from .journal import (
    OUTCOMES,
    TERMINAL_OUTCOMES,
    PodDecisionJournal,
    attribute_failure,
    fleet_merge_key,
    summarize_plugins,
    validate_line,
    validate_lines,
)
from .bundle import BundleCapturer, load_bundle, replay_bundle
from .profile import StageProfiler
from .recorder import FlightRecorder, canonical
from .sentinel import AnomalySentinel, SentinelConfig, SyntheticPod
from .slo import SloConfig, SloEngine
from .span import Span, Tracer

__all__ = [
    "ObsConfig",
    "build_obs",
    "build_telemetry",
    "Telemetry",
    "Tracer",
    "Span",
    "PodDecisionJournal",
    "FlightRecorder",
    "Explanation",
    "SloConfig",
    "SloEngine",
    "StageProfiler",
    "AnomalySentinel",
    "SentinelConfig",
    "SyntheticPod",
    "BundleCapturer",
    "load_bundle",
    "replay_bundle",
    "explain_pod",
    "merge_fleet_records",
    "parse_stream",
    "attribute_failure",
    "fleet_merge_key",
    "summarize_plugins",
    "validate_line",
    "validate_lines",
    "canonical",
    "OUTCOMES",
    "TERMINAL_OUTCOMES",
]


@dataclass
class ObsConfig:
    """Observability knobs carried on SchedulerConfig.obs (None = all
    off, the production default)."""

    spans: bool = False  # emit spans from the scheduler loops
    journal: bool = False  # per-pod decision journal
    span_capacity: int = 4096  # flight-recorder ring sizes
    decision_capacity: int = 8192
    # in-memory journal line retention: None = unbounded (the sim needs
    # the full history); serve passes a bound and streams to
    # journal_path for durability
    journal_capacity: int | None = None
    # streaming JSONL sinks (append-mode files); None = in-memory only
    spans_path: str | None = None
    journal_path: str | None = None
    # crash / invariant-violation dump target for the flight recorder
    dump_path: str | None = None
    # live SLO engine (obs/slo.py): an SloConfig enabling the sliding-
    # window p50/p99 latency, bind throughput, and multi-window error-
    # budget burn computation (scheduler_slo_* metrics + GET
    # /debug/slo + the degraded-health signal). None = off. Independent
    # of spans/journal — the engine reads only BatchResult numbers the
    # loops already compute.
    slo: SloConfig | None = None
    # deterministic 1-in-N sampling for the PER-WATCH-EVENT enqueue
    # span — the one span family whose volume scales with event rate
    # (tens of thousands/s at sustained-stream scale) rather than with
    # batches. The first event is always sampled and the counter is
    # deterministic, so same-seed sim runs stay byte-identical. 1 =
    # span every event (the PR 3 behavior). Batch-level spans
    # (schedule_batch/dispatch/apply/bind/...) are never sampled: they
    # are the trace's structure. The shipped default keeps the whole
    # obs layer inside the <= 5% sustained-throughput budget bench
    # ladder #13 asserts.
    enqueue_span_sample_n: int = 64
    # deterministic 1-in-N sampling for the PER-POD bind span (the
    # other per-pod-volume family). The decision JOURNAL stays
    # complete — one record per pod per batch, never sampled; the bind
    # span only adds the commit's wall duration, which N-sampling
    # preserves statistically. First bind always sampled; 1 = every
    # bind (PR 3 behavior).
    bind_span_sample_n: int = 8
    # -- flight telemetry (profile -> detect -> capture -> replay) --
    # continuous per-stage profiler (obs/profile.py): the bounded
    # per-batch stage ledger + scheduler_profile_stage_seconds{stage}
    profile: bool = False
    # anomaly sentinel over the windowed health ring (obs/sentinel.py);
    # a SentinelConfig enables it (sentinel implies the profiler's
    # batch tick: the sentinel windows ride the same commit seam)
    sentinel: "SentinelConfig | None" = None
    # capture-on-anomaly replay bundles (obs/bundle.py): directory the
    # bundles are written to. None with sentinel set = captures COUNT
    # (and the in-memory record ring runs) but nothing hits disk —
    # what the sim's determinism selfcheck re-run uses.
    bundle_dir: str | None = None
    # complete solve records retained in memory (the capture ring)
    bundle_keep: int = 4
    # bundle directories one process may write (forensics, not a log)
    bundle_max: int = 8


class _FileSink:
    """Append-mode JSONL line writer (flushed per line: a crash must
    not lose the records explaining it)."""

    def __init__(self, path: str) -> None:
        self._f = open(path, "a")

    def __call__(self, rec: dict) -> None:
        self._f.write(canonical(rec) + "\n")
        self._f.flush()


def build_obs(
    cfg: ObsConfig | None, clock: Clock | None = None
) -> tuple[Tracer, PodDecisionJournal | None, FlightRecorder | None]:
    """(tracer, journal, flight recorder) for one Scheduler. With cfg
    None or everything disabled: a disabled Tracer and two Nones."""
    if cfg is None or not (cfg.spans or cfg.journal):
        return Tracer(clock=clock, enabled=False), None, None
    recorder = FlightRecorder(
        span_capacity=cfg.span_capacity,
        decision_capacity=cfg.decision_capacity,
        dump_path=cfg.dump_path,
    )
    tracer = Tracer(
        clock=clock,
        enabled=cfg.spans,
        recorder=recorder,
        sink=_FileSink(cfg.spans_path) if cfg.spans_path else None,
    )
    journal = None
    if cfg.journal:
        journal = PodDecisionJournal(
            clock=clock,
            recorder=recorder,
            sink=_FileSink(cfg.journal_path) if cfg.journal_path else None,
            capacity=cfg.journal_capacity,
        )
    return tracer, journal, recorder


class Telemetry:
    """The flight-telemetry coordinator: one object on the scheduler
    holding the profiler, the sentinel (+ its health ring), and the
    bundle capturer, driven from the commit seam both loops share.

    The scheduler's hot path pays one ``is not None`` check when
    telemetry is off; when on, every write here is host-side arithmetic
    over numbers the loops already computed (TPU001-clean — the whole
    layer rides inside bench ladder #13's <= 5% obs budget)."""

    def __init__(
        self,
        *,
        clock: Clock | None = None,
        profiler: StageProfiler | None = None,
        sentinel: AnomalySentinel | None = None,
        bundles: BundleCapturer | None = None,
        journal: PodDecisionJournal | None = None,
        recorder: FlightRecorder | None = None,
    ) -> None:
        self.clock = clock or Clock()
        self.profiler = profiler
        self.sentinel = sentinel
        self.bundles = bundles
        self.journal = journal
        self.recorder = recorder
        self.anomalies: list = []  # every Anomaly fired, for surfaces
        # window accumulation state (driver thread only)
        self._win_batches = 0
        self._win_pods = 0
        self._win_t0: float | None = None
        self._last = {
            "chained": 0.0,
            "discards": 0.0,
            "cas": 0.0,
            "gang": 0.0,
            "trips": 0.0,
        }

    # -- stage attribution passthrough (scheduler seams) --

    def add_stage(self, stage: str, seconds: float) -> None:
        if self.profiler is not None:
            self.profiler.add(stage, seconds)

    # -- the per-batch tick (commit seam, next to the SLO engine) --

    def observe_batch(self, scheduler, *, step: int, pods: int) -> None:
        """Close the batch's profile ledger entry; every
        ``sentinel.config.window_batches`` batches, aggregate a window
        sample and run the sentinel's regression rules."""
        if self.profiler is not None:
            self.profiler.observe_batch(step=step, pods=pods)
        if self.sentinel is None:
            return
        now = self.clock.perf()
        if self._win_t0 is None:
            self._win_t0 = now
        self._win_batches += 1
        self._win_pods += pods
        if self._win_batches < self.sentinel.config.window_batches:
            return
        wall = max(now - self._win_t0, 1e-9)
        signals = self._window_signals(scheduler, wall)
        sample = self.sentinel.ring.append(
            t=now,
            batches=self._win_batches,
            pods=self._win_pods,
            signals=signals,
        )
        self._win_batches = 0
        self._win_pods = 0
        self._win_t0 = now
        # PR 13's rate-signature discipline: a probing tuner moves
        # knobs on purpose — its self-inflicted swings must not fire
        tuner = getattr(scheduler, "tuner", None)
        suppress = (
            tuner is not None
            and not getattr(tuner, "frozen", False)
            and not tuner.settled()
        )
        fired = self.sentinel.observe_window(sample, suppress=suppress)
        for a in fired:
            self.anomalies.append(a)
            if self.journal is not None:
                self.journal.record(
                    step,
                    getattr(scheduler.queue, "scheduling_cycle", 0),
                    SyntheticPod(key=f"telemetry/{a.signal}"),
                    "telemetry_anomaly",
                    reason=a.describe(),
                )
            self.capture("sentinel", note=a.describe())

    def _window_signals(self, scheduler, wall: float) -> dict:
        """One window's health-signal values, every one a host-side
        delta or an SLO-engine read (the CounterWindow discipline).
        The event-rate signals are raw per-window event counts — the
        sentinel's ``min_events`` floor is defined over them."""
        from .profile import _cell, _labeled_total

        chained = 0.0
        for s in getattr(scheduler, "solvers", {}).values():
            chained += s.dispatch_counts.get("stream_chained", 0)
        discards = _cell(metrics.solves_discarded_total) + _cell(
            metrics.stream_slot_discard_total
        )
        cas = _labeled_total(metrics.fleet_admit_cas_conflict_total)
        gang = _cell(metrics.gang_incomplete_total)
        resilience = getattr(scheduler, "resilience", None)
        trips = (
            float(resilience.summary().get("trips", 0))
            if resilience is not None
            else 0.0
        )
        deltas = {}
        for key, cur in (
            ("chained", chained),
            ("discards", discards),
            ("cas", cas),
            ("gang", gang),
            ("trips", trips),
        ):
            deltas[key] = max(cur - self._last[key], 0.0)
            self._last[key] = cur
        slo = getattr(scheduler, "slo", None)
        p99 = slo.latency_quantiles()[1] if slo is not None else 0.0
        n = max(self._win_batches, 1)
        return {
            "pods_per_sec": self._win_pods / wall,
            "p99_latency_s": float(p99 or 0.0),
            "chain_fraction": min(deltas["chained"] / n, 1.0),
            "discard_rate": deltas["discards"],
            "cas_conflict_rate": deltas["cas"],
            "gang_incomplete_rate": deltas["gang"],
            "breaker": 1.0 if deltas["trips"] > 0 else 0.0,
        }

    # -- the capture trigger (any telemetry-relevant event funnels here) --

    def capture(self, trigger: str, note: str = "") -> str | None:
        """Snapshot the newest complete solve record into a bundle.
        Safe no-op without a capturer; the journal tail, flight slice,
        and metrics snapshot ride along when available."""
        if self.bundles is None:
            return None
        tail: list[str] = []
        if self.journal is not None:
            tail = list(self.journal.lines)[-200:]
        flight: list[str] = []
        if self.recorder is not None:
            flight = self.recorder.lines()
        return self.bundles.capture(
            trigger.split(":", 1)[0] if ":" in trigger else trigger,
            note=note or trigger,
            journal_tail=tail,
            flight_lines=flight,
            metrics_text=metrics.render(),
        )

    @property
    def degraded(self) -> bool:
        return self.sentinel is not None and self.sentinel.degraded

    def snapshot(self) -> dict:
        """The ``GET /debug/profile`` body: profile + sentinel + bundle
        state, one JSON-ready dict (each piece locks internally)."""
        out: dict = {"enabled": True}
        if self.profiler is not None:
            out["profile"] = self.profiler.snapshot()
        if self.sentinel is not None:
            out["sentinel"] = self.sentinel.snapshot()
        if self.bundles is not None:
            out["bundles"] = self.bundles.snapshot()
        return out


def build_telemetry(
    cfg: ObsConfig | None,
    clock: Clock | None = None,
    *,
    journal: PodDecisionJournal | None = None,
    recorder: FlightRecorder | None = None,
) -> Telemetry | None:
    """The telemetry stack for one Scheduler, or None when every piece
    is off (the production default — the hot path then pays a single
    attribute check)."""
    if cfg is None or not (
        cfg.profile or cfg.sentinel is not None or cfg.bundle_dir
    ):
        return None
    profiler = (
        StageProfiler(clock=clock)
        if (cfg.profile or cfg.sentinel is not None)
        else None
    )
    sentinel = (
        AnomalySentinel(cfg.sentinel) if cfg.sentinel is not None else None
    )
    bundles = None
    if cfg.bundle_dir is not None or cfg.sentinel is not None:
        bundles = BundleCapturer(
            cfg.bundle_dir, keep=cfg.bundle_keep, max_bundles=cfg.bundle_max
        )
    return Telemetry(
        clock=clock,
        profiler=profiler,
        sentinel=sentinel,
        bundles=bundles,
        journal=journal,
        recorder=recorder,
    )
