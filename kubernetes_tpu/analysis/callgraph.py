"""Intra-module call graph + hot/traced scope computation.

Scope rules (analysis/README.md §TPU001):

- *jit roots*: functions wrapped by ``jax.jit`` — as a decorator
  (``@jax.jit``, ``@partial(jax.jit, ...)``), or by a module-level
  assignment ``g = jax.jit(f, ...)``.
- *hot roots*: functions marked ``# ktpu: hot`` — host-side functions on
  the per-batch critical path (the pipelined apply path, the sanctioned
  device-read boundary).
- Scope propagates through the intra-module call graph: plain-name calls
  to module-level functions and ``self.method(...)`` calls to methods of
  the same class. Nested ``def``\\ s inherit their parent's scope (a scan
  body is part of the traced computation).
- Propagation STOPS at functions marked ``# ktpu: cold`` (explicitly
  off-hot-path: error diagnosis, preemption aftermath) and at whitelisted
  sanctioned sync points (the audited device-read boundary).

The graph is intentionally intra-module and name-based: cross-module
calls (``nr.rtc_score``) are not followed — cover those modules with
their own jit/hot roots. Precision over recall inside one file; the
fixture tests pin the exact contract.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .core import SourceModule


@dataclass
class FunctionInfo:
    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    cls: str | None  # enclosing class name, if a method
    parent: str | None  # enclosing function qualname, if nested
    calls: set = field(default_factory=set)  # callee qualnames (resolved)


def _is_jit_expr(node: ast.expr) -> bool:
    """``jax.jit`` / ``jit`` / ``partial(jax.jit, ...)`` /
    ``functools.partial(jax.jit, ...)``."""
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return True
    if isinstance(node, ast.Name) and node.id == "jit":
        return True
    if isinstance(node, ast.Call):
        f = node.func
        is_partial = (isinstance(f, ast.Name) and f.id == "partial") or (
            isinstance(f, ast.Attribute) and f.attr == "partial"
        )
        if is_partial and node.args:
            return _is_jit_expr(node.args[0])
        # jax.jit(f, ...) used as a decorator-factory is already matched
        # by the Attribute case above when it IS the decorator; a direct
        # call jax.jit(f) is handled by the assignment scan
    return False


class ModuleGraph:
    """Function index + call edges for one module."""

    def __init__(self, module: SourceModule):
        self.module = module
        self.functions: dict[str, FunctionInfo] = {}
        self._class_methods: dict[str, set] = {}
        self._module_level: set = set()
        self._jit_roots: set = set()
        self._hot_roots: set = set()
        self._build()

    # -- construction ------------------------------------------------------

    def _build(self) -> None:
        self._index(self.tree_body(), cls=None, parent=None)
        self._scan_jit_assignments()
        for info in self.functions.values():
            self._resolve_calls(info)
            if self.module.is_hot(info.node):
                self._hot_roots.add(info.qualname)
            for deco in getattr(info.node, "decorator_list", ()):
                if _is_jit_expr(deco):
                    self._jit_roots.add(info.qualname)

    def tree_body(self) -> list[ast.stmt]:
        return self.module.tree.body

    def _index(self, body, cls, parent) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{parent}.{stmt.name}" if parent else (
                    f"{cls}.{stmt.name}" if cls else stmt.name
                )
                info = FunctionInfo(qual, stmt, cls, parent)
                self.functions[qual] = info
                if cls and not parent:
                    self._class_methods.setdefault(cls, set()).add(stmt.name)
                if cls is None and parent is None:
                    self._module_level.add(stmt.name)
                self._index(stmt.body, cls=cls, parent=qual)
            elif isinstance(stmt, ast.ClassDef):
                self._index(stmt.body, cls=stmt.name, parent=None)
            elif isinstance(
                stmt,
                (
                    ast.If, ast.Try, ast.With, ast.For, ast.While,
                    ast.AsyncWith, ast.AsyncFor, ast.Match,
                    ast.ExceptHandler, ast.match_case,
                ),
            ):
                # descend through compound statements INCLUDING the
                # non-stmt containers (except handlers, match cases) so a
                # def inside an error-recovery path is still indexed
                for sub in ast.iter_child_nodes(stmt):
                    if isinstance(sub, (ast.stmt, ast.ExceptHandler, ast.match_case)):
                        self._index([sub], cls=cls, parent=parent)

    def _scan_jit_assignments(self) -> None:
        """``g = jax.jit(f, ...)`` at module level marks ``f`` a root."""
        for stmt in self.tree_body():
            value = getattr(stmt, "value", None)
            if not isinstance(value, ast.Call):
                continue
            if _is_jit_expr(value.func) and value.args:
                arg = value.args[0]
                if isinstance(arg, ast.Name) and arg.id in self.functions:
                    self._jit_roots.add(arg.id)

    def _resolve_calls(self, info: FunctionInfo) -> None:
        """Collect callee qualnames from this function's OWN statements
        (nested defs resolve their own calls)."""
        for node in own_nodes(info.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name):
                name = f.id
                # nested function in an enclosing FUNCTION scope wins,
                # then module level. The walk must stop BEFORE the class
                # prefix: a bare name inside a method never resolves to a
                # sibling method (that needs `self.`), and pairing it with
                # one would shadow a same-named module-level function
                # (review-caught false negative)
                scope = info.qualname
                while scope and scope != info.cls:
                    cand = f"{scope}.{name}"
                    if cand in self.functions:
                        info.calls.add(cand)
                        break
                    scope = scope.rpartition(".")[0]
                else:
                    if name in self._module_level:
                        info.calls.add(name)
            elif (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "self"
                and info.cls
                and f.attr in self._class_methods.get(info.cls, ())
            ):
                info.calls.add(f"{info.cls}.{f.attr}")

    # -- scope -------------------------------------------------------------

    def _expand(self, roots: set, barrier) -> set:
        """BFS through call edges + nested defs, stopping at barriers."""
        seen: set = set()
        work = [q for q in roots if not barrier(q)]
        while work:
            q = work.pop()
            if q in seen:
                continue
            seen.add(q)
            info = self.functions.get(q)
            if info is None:
                continue
            nxt = set(info.calls)
            # nested defs inherit the parent's scope
            for other, oinfo in self.functions.items():
                if oinfo.parent == q:
                    nxt.add(other)
            for callee in nxt:
                if callee not in seen and not barrier(callee):
                    work.append(callee)
        return seen

    def scopes(self, ctx) -> tuple[set, set]:
        """(traced, hot) qualname sets after propagation; whitelisted and
        cold functions are excluded (they are the barriers)."""

        def barrier(qual: str) -> bool:
            info = self.functions.get(qual)
            if info is None:
                return False
            if self.module.is_cold(info.node):
                return True
            return ctx.is_sanctioned(self.module.rel, qual)

        traced = self._expand(set(self._jit_roots), barrier)
        hot = self._expand(set(self._hot_roots), barrier)
        return traced, hot


def scoped_graph(module: SourceModule, ctx) -> tuple["ModuleGraph", set, set]:
    """(graph, traced, hot) for a module, memoized on the module object —
    graph construction and scope BFS are the analyzer's expensive steps
    and every scope-driven pass needs the same result."""
    cache = getattr(module, "_scope_cache", None)
    if cache is not None and cache[0] is ctx:
        return cache[1], cache[2], cache[3]
    graph = ModuleGraph(module)
    traced, hot = graph.scopes(ctx)
    module._scope_cache = (ctx, graph, traced, hot)
    return graph, traced, hot


def own_nodes(func: ast.AST):
    """Walk a function's own statements, NOT descending into nested
    function/class definitions (those are separate scope entries)."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))
