"""Label selectors: parse + host-side evaluation.

Reference semantics:
- staging/src/k8s.io/apimachinery/pkg/labels/selector.go#Requirement.Matches
- staging/src/k8s.io/apimachinery/pkg/apis/meta/v1/types.go#LabelSelector
  (matchLabels AND matchExpressions, all requirements ANDed)
- NodeSelectorRequirement operators (In/NotIn/Exists/DoesNotExist/Gt/Lt) from
  staging/src/k8s.io/api/core/v1/types.go#NodeSelectorOperator, evaluated in
  k8s.io/component-helpers/scheduling/corev1/nodeaffinity/nodeaffinity.go.

Matching rules (same as reference):
- In:            key present and value in values
- NotIn:         key absent OR value not in values
- Exists:        key present
- DoesNotExist:  key absent
- Gt / Lt:       key present, label value parses as integer, int(label) >/< int(values[0])

An empty LabelSelector ({}) matches everything; a nil selector matches nothing
(callers encode that by passing None).

These evaluate host-side; the tensorizer (kubernetes_tpu/tensorize) compiles
the same requirements into bitset index programs for on-device evaluation.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Iterable, Mapping

# Gt/Lt label values must parse like Go's strconv.ParseInt: ASCII digits with
# optional sign — no underscores, no unicode digits (int() is too lenient).
_GO_INT_RE = re.compile(r"^[+-]?[0-9]+$")


def _parse_go_int(s: str) -> int | None:
    if not _GO_INT_RE.match(s):
        return None
    v = int(s)
    # strconv.ParseInt(..., 10, 64) fails with ErrRange outside int64
    if v > (1 << 63) - 1 or v < -(1 << 63):
        return None
    return v

IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"

# metav1.LabelSelector only admits these (apimachinery#LabelSelectorAsSelector
# returns an error for anything else); NodeSelectorRequirement additionally
# admits Gt/Lt (core/v1#NodeSelectorOperator).
_LABEL_SELECTOR_OPS = {IN, NOT_IN, EXISTS, DOES_NOT_EXIST}
_NODE_SELECTOR_OPS = {IN, NOT_IN, EXISTS, DOES_NOT_EXIST, GT, LT}


@dataclass(frozen=True)
class Requirement:
    """One selector requirement: key <op> values."""

    key: str
    operator: str
    values: tuple[str, ...] = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        present = self.key in labels
        if self.operator == IN:
            return present and labels[self.key] in self.values
        if self.operator == NOT_IN:
            return (not present) or labels[self.key] not in self.values
        if self.operator == EXISTS:
            return present
        if self.operator == DOES_NOT_EXIST:
            return not present
        if self.operator in (GT, LT):
            if not present or len(self.values) != 1:
                return False
            lhs = _parse_go_int(labels[self.key])
            rhs = _parse_go_int(self.values[0])
            if lhs is None or rhs is None:
                return False
            return lhs > rhs if self.operator == GT else lhs < rhs
        raise ValueError(f"unknown selector operator {self.operator!r}")


@dataclass(frozen=True)
class Selector:
    """AND of requirements. ``Selector(())`` matches everything.

    ``match_labels`` records which leading requirements came from a
    LabelSelector's matchLabels map so serialization reproduces the original
    wire shape (they are ALSO present in ``requirements`` as In-requirements;
    evaluation uses only ``requirements``).
    """

    requirements: tuple[Requirement, ...] = ()
    match_labels: tuple[tuple[str, str], ...] = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        return all(r.matches(labels) for r in self.requirements)

    @property
    def empty(self) -> bool:
        return not self.requirements


def selector_from_label_selector(obj: Mapping | None) -> Selector | None:
    """Build a Selector from a metav1.LabelSelector-shaped dict.

    Returns None for a nil selector (matches nothing), Selector(()) for the
    empty selector (matches everything) — mirroring
    apimachinery#LabelSelectorAsSelector.
    """
    if obj is None:
        return None
    reqs: list[Requirement] = []
    ml = tuple(sorted((obj.get("matchLabels") or {}).items()))
    for k, v in ml:
        reqs.append(Requirement(k, IN, (v,)))
    for expr in obj.get("matchExpressions") or ():
        op = expr.get("operator")
        if op not in _LABEL_SELECTOR_OPS:
            raise ValueError(f"invalid matchExpressions operator {op!r}")
        reqs.append(
            Requirement(expr["key"], op, tuple(expr.get("values") or ()))
        )
    return Selector(tuple(reqs), match_labels=ml)


def selector_from_node_selector_requirements(exprs) -> Selector:
    """Build a Selector from NodeSelectorRequirement dicts (Gt/Lt allowed)."""
    reqs: list[Requirement] = []
    for expr in exprs or ():
        op = expr.get("operator")
        if op not in _NODE_SELECTOR_OPS:
            raise ValueError(f"invalid nodeSelector operator {op!r}")
        reqs.append(Requirement(expr["key"], op, tuple(expr.get("values") or ())))
    return Selector(tuple(reqs))


def requirements_from_match_labels(match_labels: Mapping[str, str]) -> tuple[Requirement, ...]:
    return tuple(Requirement(k, IN, (v,)) for k, v in sorted(match_labels.items()))


def selector_from_match_labels(match_labels: Mapping[str, str]) -> Selector:
    """Selector equivalent to a pure matchLabels LabelSelector (wire shape
    preserved on serialization)."""
    ml = tuple(sorted(match_labels.items()))
    return Selector(requirements_from_match_labels(match_labels), match_labels=ml)


def label_selector_to_dict(sel: Selector | None) -> dict | None:
    """Inverse of selector_from_label_selector, for wire round-trips."""
    if sel is None:
        return None
    out: dict = {}
    n_ml = len(sel.match_labels)
    if n_ml:
        out["matchLabels"] = dict(sel.match_labels)
    exprs = [
        {"key": r.key, "operator": r.operator, "values": list(r.values)}
        for r in sel.requirements[n_ml:]
    ]
    if exprs:
        out["matchExpressions"] = exprs
    return out


def matches_any(selectors: Iterable[Selector], labels: Mapping[str, str]) -> bool:
    return any(s.matches(labels) for s in selectors)
