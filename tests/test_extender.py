"""Extender webhook: golden JSON round-trips of the v1 wire shapes plus a
live aiohttp socket round-trip (SURVEY.md §8.6)."""

import asyncio
import json

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.server.extender import ExtenderCore, make_app
from kubernetes_tpu.state.cluster import ClusterState


def make_cluster():
    cs = ClusterState()
    for i in range(4):
        b = (
            MakeNode()
            .name(f"node-{i}")
            .capacity({"cpu": "8", "memory": "32Gi", "pods": "20"})
            .label("zone", f"z{i % 2}")
        )
        if i == 3:
            b = b.taint("dedicated", "gpu", "NoSchedule")
        cs.create_node(b.obj())
    # an existing pod occupying node-0
    cs.create_pod(
        MakePod().name("existing").node("node-0").req({"cpu": "7"}).obj()
    )
    return cs


def node_list(cs):
    return {"items": [n.to_dict() for n in cs.list_nodes()]}


def test_filter_wire_shape():
    cs = make_cluster()
    core = ExtenderCore(cs)
    pod = MakePod().name("p").req({"cpu": "4"}).obj()
    args = {"pod": pod.to_dict(), "nodes": node_list(cs)}
    out = core.filter(args)
    # ExtenderFilterResult shape
    assert set(out) >= {"nodes", "failedNodes", "failedAndUnresolvableNodes"}
    names = [n["metadata"]["name"] for n in out["nodes"]["items"]]
    # node-0 fails resources (7+4 > 8); node-3 fails taints
    assert names == ["node-1", "node-2"]
    assert set(out["failedNodes"]) == {"node-0", "node-3"}
    # must be JSON-serializable as-is
    json.dumps(out)


def test_filter_node_cache_capable():
    cs = make_cluster()
    core = ExtenderCore(cs, node_cache_capable=True)
    pod = MakePod().name("p").req({"cpu": "4"}).obj()
    out = core.filter({"pod": pod.to_dict(), "nodenames": ["node-1", "node-0"]})
    assert out["nodenames"] == ["node-1"]
    assert "nodes" not in out


def test_prioritize_wire_shape():
    cs = make_cluster()
    core = ExtenderCore(cs)
    pod = MakePod().name("p").req({"cpu": "1"}).obj()
    out = core.prioritize({"pod": pod.to_dict(), "nodes": node_list(cs)})
    assert isinstance(out, list)
    by_host = {e["host"]: e["score"] for e in out}
    assert set(by_host) == {"node-0", "node-1", "node-2", "node-3"}
    assert all(0 <= s <= 10 for s in by_host.values())
    # empty nodes 1/2 outscore the packed node-0
    assert by_host["node-1"] > by_host["node-0"]
    json.dumps(out)


def test_bind_and_conflict():
    cs = make_cluster()
    core = ExtenderCore(cs)
    cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
    ok = core.bind(
        {"podName": "p", "podNamespace": "default", "podUID": "u1",
         "node": "node-1"}
    )
    assert ok == {}
    assert cs.get_pod("default", "p").node_name == "node-1"
    dup = core.bind(
        {"podName": "p", "podNamespace": "default", "podUID": "u1",
         "node": "node-2"}
    )
    assert "Conflict" in dup["error"]


def test_preempt_wire_shape():
    cs = make_cluster()
    core = ExtenderCore(cs)
    cs.create_pod(
        MakePod().name("low").node("node-1").req({"cpu": "8"}).priority(1)
        .uid("low-uid").obj()
    )
    vip = MakePod().name("vip").req({"cpu": "8"}).priority(100).obj()
    out = core.preempt(
        {
            "pod": vip.to_dict(),
            "nodeNameToVictims": {"node-1": {"pods": []}, "node-2": {"pods": []}},
        }
    )
    # non-nodeCacheCapable extenders answer with FULL pod objects under
    # nodeNameToVictims (extender.go#ProcessPreemption reads that field)
    assert "nodeNameToMetaVictims" not in out
    victims = out["nodeNameToVictims"]
    assert [p["metadata"]["name"] for p in victims["node-1"]["pods"]] == ["low"]
    assert victims["node-1"]["numPDBViolations"] == 0
    assert victims["node-2"]["pods"] == []
    json.dumps(out)

    # nodeCacheCapable mode: MetaVictims with bare uids
    core_nc = ExtenderCore(cs, node_cache_capable=True)
    out2 = core_nc.preempt(
        {"pod": vip.to_dict(), "nodeNameToVictims": {"node-1": {"pods": []}}}
    )
    assert out2["nodeNameToMetaVictims"]["node-1"]["pods"] == [{"uid": "low-uid"}]


def test_filter_unknown_name_fails_per_node():
    cs = make_cluster()
    core = ExtenderCore(cs, node_cache_capable=True)
    pod = MakePod().name("p").req({"cpu": "4"}).obj()
    out = core.filter(
        {"pod": pod.to_dict(), "nodenames": ["node-1", "brand-new-node"]}
    )
    assert out["nodenames"] == ["node-1"]
    assert "brand-new-node" in out["failedAndUnresolvableNodes"]
    assert "error" not in out


def test_preempt_respects_static_filters():
    # node-3 is tainted; an intolerant pod must not get it as a candidate
    # even when victims would free enough resources
    cs = make_cluster()
    core = ExtenderCore(cs)
    cs.create_pod(
        MakePod().name("low3").node("node-3").req({"cpu": "8"}).priority(1)
        .uid("low3-uid").obj()
    )
    vip = MakePod().name("vip").req({"cpu": "8"}).priority(100).obj()
    out = core.preempt(
        {"pod": vip.to_dict(), "nodeNameToVictims": {"node-3": {"pods": []}}}
    )
    assert out["nodeNameToVictims"] == {}


def test_live_http_round_trip():
    from aiohttp.test_utils import TestClient, TestServer

    cs = make_cluster()
    app = make_app(ExtenderCore(cs))
    pod = MakePod().name("p").req({"cpu": "4"}).obj()

    async def drive():
        async with TestClient(TestServer(app)) as client:
            r = await client.post(
                "/filter", json={"pod": pod.to_dict(), "nodes": node_list(cs)}
            )
            assert r.status == 200
            body = await r.json()
            assert [n["metadata"]["name"] for n in body["nodes"]["items"]] == [
                "node-1",
                "node-2",
            ]
            r2 = await client.get("/healthz")
            assert r2.status == 200
            r3 = await client.get("/metrics")
            assert r3.status == 200
            text = await r3.text()
            assert "scheduler_schedule_attempts_total" in text

    asyncio.run(drive())


def test_preempt_device_matches_oracle():
    """The device-backed /preempt (one batched dry-run over all
    candidates, VERDICT r3 #8) answers exactly like the scalar oracle
    path for the same args."""
    cs = make_cluster()
    # fill node-1/node-2 with preemptable load at different priorities
    cs.create_pod(
        MakePod().name("low1").node("node-1").priority(0).req({"cpu": "6"}).obj()
    )
    cs.create_pod(
        MakePod().name("low2").node("node-2").priority(5).req({"cpu": "4"}).obj()
    )
    vip = MakePod().name("vip").priority(100).req({"cpu": "6"}).obj()
    args = {
        "pod": vip.to_dict(),
        "nodeNameToVictims": {
            "node-0": {"pods": []},
            "node-1": {"pods": []},
            "node-2": {"pods": []},
            "node-3": {"pods": []},
        },
    }
    dev = ExtenderCore(cs, backend="device").preempt(args)
    orc = ExtenderCore(cs, backend="oracle").preempt(args)
    assert dev == orc
    assert "node-1" in dev["nodeNameToVictims"]


def test_preempt_device_sees_extended_resources():
    """A preemptor requesting an extended resource no candidate node
    advertises must get NO candidates from the device path, matching the
    oracle (review-caught: the node-only vocab silently dropped the
    request and offered infeasible nodes)."""
    cs = make_cluster()
    gpu_pod = MakePod().name("gpu").priority(100).req(
        {"cpu": "1", "example.com/gpu": "1"}
    ).obj()
    args = {
        "pod": gpu_pod.to_dict(),
        "nodeNameToVictims": {"node-1": {"pods": []}, "node-2": {"pods": []}},
    }
    dev = ExtenderCore(cs, backend="device").preempt(args)
    orc = ExtenderCore(cs, backend="oracle").preempt(args)
    assert dev == orc
    assert dev["nodeNameToVictims"] == {}
