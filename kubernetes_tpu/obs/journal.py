"""Per-pod decision journal: one JSONL record per pod per solved batch,
so "why is pod X still pending" is answerable from a file instead of a
re-run under the profiler.

Each record carries the pod's **outcome** for that batch and, for
unschedulable pods, **per-plugin filter attribution** computed from the
already-materialized host-side solve tensors (``_PreparedGroup``'s
numpy tables: pod requests, node capacities, the static class mask, the
port occupancy vocab). No device read happens here — the assignments
were already downloaded through the one sanctioned deferred-read point
(``analysis/registry.py``), and everything else lives on the host, so
journaling is TPU001-clean by construction.

Attribution granularity follows what the tensors materialize:

- ``NodeResourcesFit``   — request vs (allocatable - used) + pod count,
  from the NodeBatch/PodBatch tensors;
- ``NodeAffinity``       — the fused static-family mask row (NodeName,
  NodeUnschedulable, TaintToleration, NodeAffinity, volume plugins,
  plus any folded out-of-tree/extender/DRA verdicts), reported under
  the family's dominant member like the scheduler's per-plugin timing
  metric does;
- ``NodePorts``          — the pod's conflict vocab vs per-node port
  occupancy;
- residual rejections (nodes every host-side mask accepts but the
  solve still rejected) are attributed to the in-scan constraint the
  pod actually carries — ``PodTopologySpread`` / ``InterPodAffinity``
  — or to ``BatchCarriedUsage`` (capacity consumed by earlier pods of
  the same batch, which only exists device-side).

Determinism contract (shared with ``sim/trace.py``): records are
canonical JSON with sorted keys, timestamps come off the injectable
``Clock``, and attribution is pure numpy over deterministic inputs —
two same-seed simulator runs produce **byte-identical** journals.
"""

from __future__ import annotations

import json

import numpy as np

from .. import metrics
from ..utils.clock import Clock
from .recorder import canonical

SCHEMA_VERSION = 1

OUTCOMES = frozenset(
    {
        "bound",
        "unschedulable",
        "bind_failure",
        "permit_wait",
        "permit_rejected",
        "permit_timeout",
        "discarded",
        # a solve-boundary failure (device error / corrupt output /
        # poison batch) requeued this pod for a retry — the retry
        # history `explain <pod>` shows (non-terminal)
        "solver_error",
        # poison-batch bisection isolated the solve failure to this
        # pod: it sits out a TTL'd backoff before re-admission
        "quarantined",
        # a fresh scheduler incarnation's cold-start recovery pass
        # re-adopted this pod from cluster truth after a crash orphaned
        # it mid-flight (assumed/parked/queued state evaporated with
        # the dead process)
        "recovered",
        # the continuous rebalancer evicted this bound pod to
        # defragment (kubernetes_tpu/rebalance): node= the source,
        # nominated= the auction's target hint. Non-terminal — the pod
        # re-enters the queue and its next attempt journals the
        # migration's outcome.
        "evicted_for_rebalance",
        # the pod's gang (kubernetes_tpu/gang) did not land whole this
        # round — a member failed, the quorum never assembled, or the
        # atomic commit was released — so every staged placement was
        # rolled back and the gang requeued. Non-terminal: the gang
        # retries as a unit (a partial gang is never bound).
        "gang_incomplete",
        # the telemetry sentinel fired an anomaly (flight telemetry
        # tentpole): the "pod" is the synthetic `telemetry/<signal>`
        # carrier, never a cluster pod, so completeness invariants —
        # which iterate real pods — ignore it. Non-terminal and
        # non-retiring by construction (there is no journey to retire).
        "telemetry_anomaly",
    }
)
# a pod whose LAST journal record is one of these has a settled fate for
# the run; permit_wait, discarded, and solver_error always lead to
# another attempt. quarantined IS terminal: the pod's fate is settled
# and attributable (the re-admit after the TTL starts a new history).
# recovered IS terminal for the same cross-incarnation reason: it closes
# a history the crash left dangling (permit_wait/discarded/solver_error
# with no process left to continue it) — the adopting incarnation's own
# records then form the pod's next history.
TERMINAL_OUTCOMES = frozenset(
    {
        "bound", "unschedulable", "bind_failure", "permit_rejected",
        "permit_timeout", "quarantined", "recovered",
    }
)

# outcomes that RETIRE a pod's journey trace (obs tentpole): the pod's
# current scheduling journey is over — a later re-entry (rebalance
# migration, quarantine re-admit, a fresh incarnation's adoption)
# starts a new history with a fresh trace. Deliberately narrower than
# TERMINAL_OUTCOMES: unschedulable/bind_failure/permit verdicts retry
# the SAME journey, and a trace must survive those retries (and fleet
# handoffs between them) to render as one chain.
_TRACE_RETIRING_OUTCOMES = frozenset({"bound", "quarantined", "recovered"})

_REQUIRED_KEYS = ("k", "v", "step", "cycle", "pod", "outcome", "t")

# optional decision-record fields and their required types — the schema
# catch-up covering everything added since PR 3: journal tags
# (``replica``/``incarnation`` from the fleet/restart layers,
# ``drain_chunk``/``drain_trace`` from backlog drains), the journey
# ``trace`` id the cross-replica handoff propagates, and the per-record
# extras. ``validate_line`` is STRICT about key membership: a field
# added to the writer without a validator entry fails tier-1 (and the
# CI obs smoke) instead of silently passing validate — that is the
# drift gate.
_OPTIONAL_FIELDS: dict[str, type] = {
    "uid": str,
    "node": str,
    "reason": str,
    "profile": str,
    "nominated": str,
    "replica": str,
    "trace": str,
    "attempts": int,
    "incarnation": int,
    "drain_chunk": int,
    "drain_trace": int,
    "plugins": dict,
}
_KNOWN_KEYS = frozenset(_REQUIRED_KEYS) | frozenset(_OPTIONAL_FIELDS)

# span records: required keys plus the optional ones every emitting
# site may attach (parent/status/attrs — tuning spans, dispatch spans,
# the recover/bisect roots all stay inside this surface)
_SPAN_REQUIRED = ("name", "span", "trace", "start", "end", "dur")
_SPAN_KNOWN = frozenset(_SPAN_REQUIRED) | {
    "k", "v", "parent", "status", "attrs",
}


def fleet_merge_key(rec: dict) -> tuple:
    """The PR 8 cross-replica journal merge/tie-break key, shared
    between the fleet sim's journal-completeness invariant and
    ``obs explain --fleet``: latest virtual time wins; on a t-tie
    prefer terminal, then ``bound`` (a bind is irrevocable — a fenced
    zombie's same-instant ``bind_failure`` can never supersede the
    survivor's successful bind), then the within-replica step (steps
    are NOT comparable across replicas, so it only breaks same-replica
    ties)."""
    return (
        rec["t"],
        1 if rec["outcome"] in TERMINAL_OUTCOMES else 0,
        1 if rec["outcome"] == "bound" else 0,
        rec["step"],
    )


def attribute_failure(prep, idx: int) -> dict[str, list[int]]:
    """Per-plugin ``{name: [rejected, of]}`` for pod ``idx`` of a
    prepared group, from the group's host tensors. ``of`` is the live
    node count; families that rejected nothing are omitted."""
    slot_nodes = prep.slot_nodes
    valid = [j for j, n in enumerate(slot_nodes) if n is not None]
    total = len(valid)
    out: dict[str, list[int]] = {}
    if not total:
        return out
    vs = np.asarray(valid, dtype=np.int64)
    batch, pbatch, static = prep.batch, prep.pbatch, prep.static

    req = pbatch.req[idx]  # [K]
    free = batch.allocatable[:, vs] - batch.used[:, vs]
    fit_ok = (req[:, None] <= free).all(axis=0) & (
        batch.pod_count[vs] + 1 <= batch.max_pods[vs]
    )
    if not bool(pbatch.feasible_static[idx]):
        # requests a resource no node advertises: every node fails Fit
        fit_ok[:] = False
    n = int((~fit_ok).sum())
    if n:
        out["NodeResourcesFit"] = [n, total]

    static_ok = static.mask[int(static.class_of[idx])][vs]
    n = int((~static_ok).sum())
    if n:
        out["NodeAffinity"] = [n, total]

    ports_ok = np.ones(total, dtype=bool)
    ports = prep.ports
    if ports is not None and ports.num_ports:
        conflict_rows = np.nonzero(ports.pod_conflict[idx])[0]
        if conflict_rows.size:
            ports_ok = ~(ports.used[np.ix_(conflict_rows, vs)] > 0).any(axis=0)
            n = int((~ports_ok).sum())
            if n:
                out["NodePorts"] = [n, total]

    residual = int((fit_ok & static_ok & ports_ok).sum())
    if residual:
        pod = prep.pods[idx]
        if pod.topology_spread_constraints:
            label = "PodTopologySpread"
        elif pod.affinity is not None and (
            pod.affinity.pod_affinity is not None
            or pod.affinity.pod_anti_affinity is not None
        ):
            label = "InterPodAffinity"
        else:
            label = "BatchCarriedUsage"
        out[label] = [residual, total]
    return out


def summarize_plugins(plugins: dict[str, list[int]]) -> str:
    """Human line for a plugins dict: 'NodeResourcesFit rejected 14/16
    nodes, PodTopologySpread 2/16' (the ISSUE's explain shape)."""
    if not plugins:
        return ""
    parts = []
    for name in sorted(plugins):
        rej, of = plugins[name]
        parts.append(f"{name} rejected {rej}/{of} nodes")
    return ", ".join(parts)


class PodDecisionJournal:
    """Collects decision records in memory (``lines``), fans them out to
    the flight recorder and an optional line sink (streaming JSONL
    file). One instance per Scheduler; all writes happen on scheduler
    threads that already serialize per batch."""

    def __init__(
        self,
        clock: Clock | None = None,
        recorder=None,
        sink=None,
        capacity: int | None = None,
    ):
        self.clock = clock or Clock()
        self.recorder = recorder
        self.sink = sink
        # capacity=None keeps every line (the sim's byte-identity and
        # completeness contracts need the full history); a long-running
        # serve process passes a bound and relies on the streaming sink
        # for durability, so memory stays O(capacity).
        #
        # Serialization is LAZY: ``record`` appends the dict to a
        # pending list and the canonical-JSON encode runs at the first
        # ``lines`` read (per-cycle fleet shipping, sim finish, dump,
        # /debug) — off the per-pod hot path, where the obs-overhead
        # ladder budgets the whole layer at <= 5%. The byte contract is
        # unchanged: canonical() is deterministic whenever it runs.
        if capacity is None:
            self._lines: list[str] = []
        else:
            from collections import deque

            self._lines = deque(maxlen=capacity)
        self._pending: list[dict] = []
        # constant fields merged into every record (e.g. the fleet
        # replica identity) — set once at wiring time, before any
        # record is written, so same-seed runs stay byte-identical
        self.tags: dict = {}
        # journey-trace propagation (the cross-replica tentpole): pod
        # key -> the trace id its whole scheduling journey shares. The
        # FIRST record for a pod mints "<origin>:<step>" (origin = the
        # writing replica/incarnation identity set at wiring time);
        # every later record re-uses it, a fleet handoff ships it on
        # the handoff row so the ADOPTING replica's records continue
        # the SAME trace, and a terminal outcome retires it (a
        # post-terminal re-admit — quarantine TTL, rebalance eviction —
        # starts a fresh history with a fresh trace, the documented
        # history semantics). Deterministic: derived from the step
        # counter the records already carry.
        self.pod_traces: dict[str, str] = {}
        self.origin: str = "s-1"
        # monotone record count (never decremented by a bounded deque's
        # eviction): the fleet journal-shipping cursor reads this
        self.total_records = 0
        # per-outcome metric children resolved once, and the prometheus
        # inc BATCHED python-side (one mutex-guarded float add per
        # record is measurable at per-pod journal volume): counts
        # accumulate in a plain dict and flush to the registry at every
        # ``lines`` read / pending flush
        self._outcome_counters: dict = {}
        self._outcome_pending: dict[str, int] = {}

    def record(
        self,
        step: int,
        cycle: int,
        pod,
        outcome: str,
        *,
        node: str = "",
        reason: str = "",
        plugins: dict | None = None,
        profile: str = "",
        attempts: int = 0,
        nominated: str = "",
    ) -> dict:
        rec: dict = {
            "k": "dec",
            "v": SCHEMA_VERSION,
            "step": step,
            "cycle": cycle,
            "pod": pod.key,
            "uid": pod.uid or "",
            "outcome": outcome,
            "t": self.clock.now(),
        }
        if node:
            rec["node"] = node
        if reason:
            rec["reason"] = reason
        if plugins:
            rec["plugins"] = plugins
        if profile:
            rec["profile"] = profile
        if attempts:
            rec["attempts"] = attempts
        if nominated:
            rec["nominated"] = nominated
        trace = self.pod_traces.get(pod.key)
        if trace is None:
            # origin identity + minting step + pod key: unique per
            # journey, deterministic, and self-describing about WHERE
            # the journey started (the handoff row ships it onward)
            trace = f"{self.origin}:{step}:{pod.key}"
            self.pod_traces[pod.key] = trace
        rec["trace"] = trace
        if outcome in _TRACE_RETIRING_OUTCOMES:
            # the journey genuinely ended: bound (a later rebalance
            # eviction starts a migration journey), quarantined (the
            # TTL re-admit starts a new history — documented), or
            # recovered (the adopting incarnation's records form the
            # next history). NOT every TERMINAL outcome: unschedulable
            # / bind_failure / permit verdicts lead to retries of the
            # SAME journey, and retiring there would shatter one
            # journey into per-attempt traces.
            self.pod_traces.pop(pod.key, None)
        if self.tags:
            rec.update(self.tags)
        self.total_records += 1
        self._pending.append(rec)
        self._outcome_pending[outcome] = (
            self._outcome_pending.get(outcome, 0) + 1
        )
        if len(self._pending) >= 4096:
            # amortized flush bound: a serve process that is never
            # read must not grow the pending list without limit
            self._flush_pending()
        if self.recorder is not None:
            self.recorder.record_decision(rec)
        if self.sink is not None:
            self.sink(rec)
        return rec

    def unschedulable(
        self, step: int, cycle: int, pod, prep, idx: int, *,
        reason: str = "", nominated: str = "", attempts: int = 0,
    ) -> dict:
        """The failure-path record: outcome + per-plugin attribution
        from the group's materialized tensors."""
        return self.record(
            step, cycle, pod, "unschedulable",
            reason=reason,
            plugins=attribute_failure(prep, idx),
            profile=prep.profile,
            nominated=nominated,
            attempts=attempts,
        )

    def _flush_pending(self) -> None:
        pending, self._pending = self._pending, []
        self._lines.extend(canonical(r) for r in pending)
        counts, self._outcome_pending = self._outcome_pending, {}
        for outcome, n in counts.items():
            counter = self._outcome_counters.get(outcome)
            if counter is None:
                counter = self._outcome_counters[outcome] = (
                    metrics.journal_records_total.labels(outcome)
                )
            counter.inc(n)

    @property
    def lines(self):
        """The canonical-JSONL record lines (list for unbounded
        journals, deque for bounded ones). Flushes the lazily-held
        pending records through ``canonical`` first — every reader
        sees the complete, deterministic byte stream."""
        if self._pending:
            self._flush_pending()
        return self._lines

    def dump(self, path) -> None:
        from pathlib import Path

        Path(path).write_text("\n".join(self.lines) + "\n")

    def last_outcomes(self) -> dict[str, dict]:
        """pod key -> its most recent record (the sim's completeness
        invariant reads this)."""
        out: dict[str, dict] = {}
        for line in self.lines:
            rec = json.loads(line)
            out[rec["pod"]] = rec
        return out


def validate_line(line: str) -> str | None:
    """Schema check for one journal/flight-recorder JSONL line. Returns
    an error string, or None when valid. Span lines (``k == "span"``)
    are accepted and shallow-checked; unknown kinds are errors.

    STRICT about key membership on both kinds: a writer-side field
    added without a matching ``_OPTIONAL_FIELDS`` / ``_SPAN_KNOWN``
    entry is a validation error, so schema drift fails tier-1 (and the
    CI obs smoke, which validates a freshly recorded journal) instead
    of silently passing."""
    try:
        rec = json.loads(line)
    except ValueError as e:
        return f"not JSON: {e}"
    if not isinstance(rec, dict):
        return "not a JSON object"
    kind = rec.get("k")
    if kind == "span":
        for key in _SPAN_REQUIRED:
            if key not in rec:
                return f"span record missing {key!r}"
        for key in rec:
            if key not in _SPAN_KNOWN:
                return f"span record has unknown field {key!r}"
        if "attrs" in rec and not isinstance(rec["attrs"], dict):
            return "span attrs is not an object"
        if "status" in rec and rec["status"] not in ("ok", "error"):
            return f"span status {rec['status']!r} not ok|error"
        return None
    if kind != "dec":
        return f"unknown record kind {kind!r}"
    for key in _REQUIRED_KEYS:
        if key not in rec:
            return f"decision record missing {key!r}"
    for key in rec:
        if key not in _KNOWN_KEYS:
            return f"decision record has unknown field {key!r}"
    if rec["v"] != SCHEMA_VERSION:
        return f"unsupported schema version {rec['v']!r}"
    if not isinstance(rec["pod"], str):
        return "field 'pod' is not a string"
    for key in ("step", "cycle"):
        if not isinstance(rec[key], int) or isinstance(rec[key], bool):
            return f"field {key!r} is not an integer"
    if not isinstance(rec["t"], (int, float)) or isinstance(
        rec["t"], bool
    ):
        return "field 't' is not a number"
    if rec["outcome"] not in OUTCOMES:
        return f"unknown outcome {rec['outcome']!r}"
    for key, typ in _OPTIONAL_FIELDS.items():
        if key in rec and not isinstance(rec[key], typ):
            return (
                f"field {key!r} is {type(rec[key]).__name__}, "
                f"expected {typ.__name__}"
            )
    # int-typed fields must not be bools (bool subclasses int)
    for key in ("attempts", "incarnation", "drain_chunk", "drain_trace"):
        if key in rec and isinstance(rec[key], bool):
            return f"field {key!r} is bool, expected int"
    plugins = rec.get("plugins")
    if plugins is not None:
        for name, pair in plugins.items():
            if (
                not isinstance(pair, list)
                or len(pair) != 2
                or not all(isinstance(x, int) for x in pair)
            ):
                return f"plugins[{name!r}] is not [rejected, of]"
    return None


def validate_lines(lines) -> list[str]:
    """All schema errors across an iterable of lines (empty = valid)."""
    errors = []
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        err = validate_line(line)
        if err is not None:
            errors.append(f"line {i + 1}: {err}")
    return errors
