"""PodTopologySpread: oracle unit tests + solver-vs-oracle parity."""

import numpy as np

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.ops.oracle import spread as osp
from kubernetes_tpu.ops.oracle.profile import FullOracle, make_oracle_nodes
from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig
from kubernetes_tpu.tensorize.plugins import (
    build_port_tensors,
    build_static_tensors,
)
from kubernetes_tpu.tensorize.spread import build_spread_tensors
from kubernetes_tpu.tensorize.schema import (
    ResourceVocab,
    build_node_batch,
    build_pod_batch,
)


def zone_nodes(n, zones):
    return [
        MakeNode()
        .name(f"node-{i:03}")
        .capacity({"cpu": "8", "memory": "32Gi", "pods": "50"})
        .label("zone", f"z{i % zones}")
        .label("kubernetes.io/hostname", f"node-{i:03}")
        .obj()
        for i in range(n)
    ]


def spread_pod(i, max_skew=1, when="DoNotSchedule", key="zone"):
    return (
        MakePod()
        .name(f"p{i:03}")
        .label("app", "web")
        .req({"cpu": "100m"})
        .spread_constraint(max_skew, key, when, match_labels={"app": "web"})
        .obj()
    )


# -- oracle unit tests ------------------------------------------------------


def test_oracle_filter_skew():
    nodes = zone_nodes(4, 2)  # z0: n0,n2; z1: n1,n3
    p_on = [MakePod().name(f"e{i}").label("app", "web").node(f"node-00{i}").obj()
            for i in range(2)]  # one web pod in each zone? e0->n0 (z0), e1->n1 (z1)
    all_nodes = [
        (nodes[0], [p_on[0]]),
        (nodes[1], [p_on[1]]),
        (nodes[2], []),
        (nodes[3], []),
    ]
    pod = spread_pod(0)
    # counts: z0=1, z1=1, min=1; skew of z0 = 1+1-1 = 1 <= 1 -> ok everywhere
    for n in nodes:
        assert osp.spread_filter(pod, n, all_nodes)
    # add another web pod to z0 -> z0=2, z1=1, min=1; placing in z0: 2+1-1=2 > 1
    all_nodes[2] = (nodes[2], [MakePod().name("e2").label("app", "web").obj()])
    assert not osp.spread_filter(pod, nodes[0], all_nodes)
    assert not osp.spread_filter(pod, nodes[2], all_nodes)
    assert osp.spread_filter(pod, nodes[1], all_nodes)


def test_oracle_filter_missing_key():
    nodes = zone_nodes(2, 2)
    bare = MakeNode().name("bare").capacity({"cpu": "8", "pods": "10"}).obj()
    all_nodes = [(n, []) for n in nodes] + [(bare, [])]
    pod = spread_pod(0)
    assert not osp.spread_filter(pod, bare, all_nodes)  # node lacks zone label


def test_oracle_min_domains():
    nodes = zone_nodes(2, 2)
    all_nodes = [(n, []) for n in nodes]
    # minDomains=3 > 2 registered domains -> global min treated as 0;
    # skew = 0+1-0 = 1 <= 1 -> still passes with empty zones
    pod = (
        MakePod().name("p").label("app", "web").req({"cpu": "100m"})
        .spread_constraint(1, "zone", "DoNotSchedule",
                           match_labels={"app": "web"}, min_domains=3)
        .obj()
    )
    assert osp.spread_filter(pod, nodes[0], all_nodes)
    # now one pod in z0: placing there gives skew 1+1-0=2 > 1 -> fails there
    all_nodes[0] = (nodes[0], [MakePod().name("e").label("app", "web").obj()])
    assert not osp.spread_filter(pod, nodes[0], all_nodes)
    assert osp.spread_filter(pod, nodes[1], all_nodes)


def test_oracle_soft_scores_prefer_sparse_domains():
    nodes = zone_nodes(4, 2)
    web = MakePod().name("e").label("app", "web").obj()
    all_nodes = [(nodes[0], [web]), (nodes[1], []), (nodes[2], []), (nodes[3], [])]
    pod = spread_pod(0, when="ScheduleAnyway")
    scores = osp.spread_scores(pod, all_nodes, all_nodes)
    # z1 nodes (1, 3) should outscore z0 nodes (0, 2)
    assert scores[1] > scores[0]
    assert scores[3] > scores[2]


# -- solver parity ----------------------------------------------------------


def run_solver(nodes, pods, placed_by_node=None, tie_break="first"):
    placed_by_node = placed_by_node or {}
    all_pods = pods + [p for ps in placed_by_node.values() for p in ps]
    vocab = ResourceVocab.build(all_pods, nodes)
    nbatch = build_node_batch(nodes, placed_by_node, vocab=vocab)
    pbatch = build_pod_batch(pods, vocab)
    slot_nodes = list(nodes) + [None] * (nbatch.padded - len(nodes))
    placed_by_slot = {
        i: placed_by_node[n.name]
        for i, n in enumerate(nodes)
        if n.name in placed_by_node
    }
    static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded)
    ports = build_port_tensors(pods, pbatch, slot_nodes, placed_by_slot, nbatch.padded)
    spread = build_spread_tensors(
        pods, static.reps, pbatch, slot_nodes,
        placed_by_slot, nbatch.padded, static.c_pad,
    )
    solver = ExactSolver(ExactSolverConfig(tie_break=tie_break))
    return solver.solve(nbatch, pbatch, static, ports, spread), nbatch


def assert_parity(nodes, pods, placed_by_node=None):
    assignments, nbatch = run_solver(nodes, pods, placed_by_node)
    oracle = FullOracle(make_oracle_nodes(nodes, placed_by_node))
    names = [nbatch.names[a] if a >= 0 else None for a in assignments]
    errors = oracle.validate_assignments(pods, list(assignments), names=names)
    assert not errors, "\n".join(errors[:5])
    return assignments


def test_hard_spread_balances_zones():
    nodes = zone_nodes(6, 3)
    pods = [spread_pod(i) for i in range(9)]
    a = assert_parity(nodes, pods)
    assert all(x >= 0 for x in a)
    zone_counts = [0, 0, 0]
    for x in a:
        zone_counts[x % 3] += 1
    assert max(zone_counts) - min(zone_counts) <= 1


def test_hard_spread_marks_unschedulable_when_skew_unavoidable():
    # 2 zones but z1 nodes are full -> after z0 fills to maxSkew, pods fail
    nodes = zone_nodes(2, 2)
    blocker = MakePod().name("blk").node("node-001").req({"cpu": "8"}).obj()
    pods = [spread_pod(i) for i in range(4)]
    a = assert_parity(nodes, pods, {"node-001": [blocker]})
    # z1 has no capacity; z0 can take maxSkew=1 pod above z1's count (0)
    assert list(a).count(-1) == 3
    assert (a >= 0).sum() == 1


def test_soft_spread_steers_but_never_blocks():
    nodes = zone_nodes(4, 2)
    web = MakePod().name("w").label("app", "web").node("node-000").obj()
    pods = [spread_pod(i, when="ScheduleAnyway") for i in range(4)]
    a = assert_parity(nodes, pods, {"node-000": [web]})
    assert all(x >= 0 for x in a)


def test_hostname_spread():
    nodes = zone_nodes(4, 2)
    pods = [spread_pod(i, key="kubernetes.io/hostname", max_skew=1) for i in range(8)]
    a = assert_parity(nodes, pods)
    assert all(x >= 0 for x in a)
    # per-node counts must stay within skew 1 of each other
    counts = np.bincount(a, minlength=4)
    assert counts.max() - counts.min() <= 1


def test_mixed_hard_and_soft():
    nodes = zone_nodes(6, 3)
    pods = []
    for i in range(12):
        b = (
            MakePod()
            .name(f"m{i:03}")
            .label("app", "api")
            .req({"cpu": "200m", "memory": "512Mi"})
            .spread_constraint(2, "zone", "DoNotSchedule", match_labels={"app": "api"})
            .spread_constraint(1, "kubernetes.io/hostname", "ScheduleAnyway",
                               match_labels={"app": "api"})
        )
        pods.append(b.obj())
    a = assert_parity(nodes, pods)
    assert all(x >= 0 for x in a)


def test_min_domains_through_solver():
    # 2 zones, minDomains=3 -> min treated as 0 -> each zone holds maxSkew=1
    # matching pod; 4 pods -> only 2 place (parity-checked vs oracle)
    nodes = zone_nodes(4, 2)
    pods = [
        MakePod()
        .name(f"p{i}")
        .label("app", "web")
        .req({"cpu": "100m"})
        .spread_constraint(1, "zone", "DoNotSchedule",
                           match_labels={"app": "web"}, min_domains=3)
        .obj()
        for i in range(4)
    ]
    a = assert_parity(nodes, pods)
    assert (a >= 0).sum() == 2
    assert list(a).count(-1) == 2


def test_match_label_keys_through_solver():
    # matchLabelKeys=[group]: pods of group g spread only against group g
    from kubernetes_tpu.api.objects import TopologySpreadConstraint
    from kubernetes_tpu.api.labels import selector_from_match_labels

    nodes = zone_nodes(4, 2)
    pods = []
    for i in range(4):
        b = (
            MakePod()
            .name(f"g{i}")
            .label("app", "web")
            .label("group", f"grp{i % 2}")
            .req({"cpu": "100m"})
        )
        b._pod.topology_spread_constraints = (
            TopologySpreadConstraint(
                max_skew=1,
                topology_key="zone",
                when_unsatisfiable="DoNotSchedule",
                label_selector=selector_from_match_labels({"app": "web"}),
                match_label_keys=("group",),
            ),
        )
        pods.append(b.obj())
    a = assert_parity(nodes, pods)
    assert all(x >= 0 for x in a)
    # each group's two pods must land in different zones
    for g in range(2):
        zs = {int(a[i]) % 2 for i in range(4) if i % 2 == g}
        assert len(zs) == 2


def test_node_taints_policy_honor_through_solver():
    # nodeTaintsPolicy=Honor: tainted z1 nodes are excluded from domain
    # counting, so z1's emptiness doesn't pin the global min at 0
    from kubernetes_tpu.api.objects import TopologySpreadConstraint
    from kubernetes_tpu.api.labels import selector_from_match_labels

    nodes = zone_nodes(4, 2)
    nodes[1] = (
        MakeNode().name("node-001")
        .capacity({"cpu": "8", "memory": "32Gi", "pods": "50"})
        .label("zone", "z1").label("kubernetes.io/hostname", "node-001")
        .taint("gpu", "true", "NoSchedule").obj()
    )
    nodes[3] = (
        MakeNode().name("node-003")
        .capacity({"cpu": "8", "memory": "32Gi", "pods": "50"})
        .label("zone", "z1").label("kubernetes.io/hostname", "node-003")
        .taint("gpu", "true", "NoSchedule").obj()
    )
    pods = []
    for i in range(2):
        b = MakePod().name(f"h{i}").label("app", "web").req({"cpu": "100m"})
        b._pod.topology_spread_constraints = (
            TopologySpreadConstraint(
                max_skew=1,
                topology_key="zone",
                when_unsatisfiable="DoNotSchedule",
                label_selector=selector_from_match_labels({"app": "web"}),
                node_taints_policy="Honor",
            ),
        )
        pods.append(b.obj())
    a = assert_parity(nodes, pods)
    # both pods place in z0 (nodes 0, 2): z1 is tainted and not counted, so
    # skew vs z1 never blocks; with Ignore policy the second pod would fail
    assert all(x >= 0 and x % 2 == 0 for x in a)


def test_spread_with_existing_cluster_state():
    nodes = zone_nodes(4, 2)
    existing = {
        "node-000": [
            MakePod().name(f"e{i}").label("app", "web").node("node-000").obj()
            for i in range(2)
        ]
    }
    pods = [spread_pod(i, max_skew=2) for i in range(4)]
    assert_parity(nodes, pods, existing)
