"""Cross-shard reconciliation: the pre-commit recheck that makes
sharded solving safe for constraints whose scope crosses the node
partition.

The device solve enforces every constraint *within* a shard (its
snapshot holds only owned nodes). Two constraint families can still be
violated *between* shards:

- **PodTopologySpread** — domain counts are global: a zone's (or, for
  hostname-keyed constraints, a node's) matching-pod count includes
  pods placed by every replica, and the ``maxSkew`` bound compares
  against the global minimum domain — including peer domains this
  replica owns no node of;
- **required inter-pod anti-affinity with a non-hostname topology
  key** — a zone-scoped anti term can match a pod a peer placed in the
  same zone. (Hostname-keyed anti terms cannot cross shards: node
  ownership is disjoint, so co-residence is always intra-shard.)

``admit`` re-checks exactly these against (a) this replica's own cache
— which already counts the batch's earlier assumes — and (b) the peer
rows from the occupancy exchange. A conflicting placement is rejected
host-side and the pod retries through the ordinary
unschedulable-requeue machinery (the fleet's Conflict-on-stale
analog): no global lock, no fleet-wide barrier.

Deliberate scope (documented, mirrored in README):

- domain eligibility is not re-filtered by the pod's node affinity —
  an extra empty domain can only *lower* the observed minimum, so the
  recheck errs conservative (rejects, retries later), never unsafe;
- the symmetric direction of zone-scoped anti-affinity (an already
  placed pod whose anti term matches the incoming pod) is not checked
  across shards: peer rows carry labels, not terms. Hostname-keyed
  terms — the overwhelmingly common case, and the only kind the sim
  generates — are unaffected.
"""

from __future__ import annotations

from typing import Iterable

from ..api.objects import Pod
from .occupancy import PeerView, PodRow

HOSTNAME_KEY = "kubernetes.io/hostname"
ZONE_KEY = "topology.kubernetes.io/zone"


def _sel_matches(selector, labels: dict) -> bool:
    from ..ops.oracle import spread as osp

    return osp._sel_matches(selector, labels)


def _domain_of(topology_key: str, node_name: str, zone: str) -> str | None:
    """Map a placement's (node, zone) to its domain value under one
    topology key. Only the two well-known keys cross the wire (rows
    carry node + zone); anything else is unknowable here."""
    if topology_key == HOSTNAME_KEY:
        return node_name
    if topology_key == ZONE_KEY:
        return zone or None
    return None


class CrossShardReconciler:
    def __init__(self, self_id: str) -> None:
        self.self_id = self_id

    # -- helpers over the two occupancy sources --

    @staticmethod
    def _local_placements(cache) -> Iterable[tuple[Pod, str, str]]:
        """(pod, node, zone) for every placed/assumed pod in the
        shard-scoped cache."""
        for name in sorted(cache.nodes):
            info = cache.nodes[name]
            if info.node is None:
                continue
            zone = info.node.labels.get(ZONE_KEY, "")
            for key in sorted(info.pods):
                yield info.pods[key], name, zone

    def _spread_conflict(
        self, pod: Pod, node_name: str, node_zone: str, cache, peers: PeerView
    ) -> str | None:
        constraints = [
            c
            for c in pod.topology_spread_constraints
            if c.when_unsatisfiable == "DoNotSchedule"
            and c.topology_key in (HOSTNAME_KEY, ZONE_KEY)
        ]
        if not constraints:
            return None
        # materialize both occupancy sources once per admit
        local = list(self._local_placements(cache))
        for c in constraints:
            target = _domain_of(c.topology_key, node_name, node_zone)
            if target is None:
                continue
            counts: dict[str, int] = {}
            # domain inventory: my nodes + peer node rows
            for name in sorted(cache.nodes):
                info = cache.nodes[name]
                if info.node is None:
                    continue
                d = _domain_of(
                    c.topology_key, name,
                    info.node.labels.get(ZONE_KEY, ""),
                )
                if d is not None:
                    counts.setdefault(d, 0)
            for nr in peers.node_rows:
                d = _domain_of(c.topology_key, nr.node, nr.zone)
                if d is not None:
                    counts.setdefault(d, 0)
            if target not in counts:
                counts[target] = 0
            # matching-pod counts: my cache + peer pod rows
            for q, qnode, qzone in local:
                if q.namespace != pod.namespace:
                    continue
                if not _sel_matches(c.label_selector, q.labels):
                    continue
                d = _domain_of(c.topology_key, qnode, qzone)
                if d is not None and d in counts:
                    counts[d] += 1
            for row in peers.pod_rows:
                if row.namespace != pod.namespace:
                    continue
                if not _sel_matches(c.label_selector, dict(row.labels)):
                    continue
                d = _domain_of(c.topology_key, row.node, row.zone)
                if d is not None and d in counts:
                    counts[d] += 1
            global_min = min(counts.values())
            if counts[target] + 1 - global_min > c.max_skew:
                return (
                    "cross-shard topology spread would exceed maxSkew="
                    f"{c.max_skew} for {c.topology_key}={target} "
                    f"(count {counts[target]} vs fleet minimum {global_min})"
                )
        return None

    def _anti_conflict(
        self, pod: Pod, node_zone: str, peers: PeerView
    ) -> str | None:
        anti = pod.affinity.pod_anti_affinity if pod.affinity else None
        if anti is None or not anti.required:
            return None
        for term in anti.required:
            if term.topology_key == HOSTNAME_KEY:
                continue  # intra-shard by construction (disjoint nodes)
            if term.topology_key != ZONE_KEY or term.label_selector is None:
                continue
            for row in peers.pod_rows:
                if row.zone != node_zone or not node_zone:
                    continue
                if not term.matches_namespace(pod.namespace, row.namespace):
                    continue
                if term.label_selector.matches(dict(row.labels)):
                    return (
                        "cross-shard anti-affinity: peer pod "
                        f"{row.pod} in zone {node_zone} matches a "
                        "required anti term"
                    )
        return None

    def admit(
        self, pod: Pod, node_name: str, node_zone: str, cache, peers: PeerView
    ) -> str | None:
        """None = the placement holds fleet-wide; otherwise a reason
        string (the pod requeues and retries)."""
        why = self._spread_conflict(pod, node_name, node_zone, cache, peers)
        if why is not None:
            return why
        return self._anti_conflict(pod, node_zone, peers)
