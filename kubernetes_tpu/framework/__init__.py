from .interface import (  # noqa: F401
    MAX_NODE_SCORE,
    MIN_NODE_SCORE,
    CycleState,
    FilterPlugin,
    Plugin,
    PreFilterPlugin,
    ScorePlugin,
    Status,
    StatusCode,
)
from .runtime import Framework  # noqa: F401
