"""Dynamic Resource Allocation API objects (resource.k8s.io subset).

The reference's dynamicresources plugin
(pkg/scheduler/framework/plugins/dynamicresources/ [U], the structured-
parameters model of resource.k8s.io/v1beta1) schedules pods that reference
ResourceClaims: drivers publish per-node device inventories as
ResourceSlices, DeviceClasses name a category of devices, and a claim asks
for a count of devices of a class. The scheduler allocates concrete
devices to claims during scheduling (PreFilter/Filter candidate nodes,
Reserve assumes the allocation, PreBind writes it) and records which pods
reserve the claim.

[BOUNDARY] depth, documented divergences from the upstream wire:
- DeviceClass selectors: upstream selects devices with CEL expressions
  (``spec.selectors[].cel.expression``); this implementation supports the
  structural equivalent — an optional ``driver`` name plus exact-match
  ``matchAttributes`` — and records any CEL expression it cannot
  interpret as an opaque mismatch (the class then matches no devices,
  the conservative direction). CEL evaluation is out of scope.
- Device capacity/consumable-counter models and partitionable devices
  are out of scope: a device is allocated whole, to one claim.
- ``allocationMode: All`` and management-access requests are parsed and
  rejected at admission with a clear error rather than half-supported.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping


@dataclass
class Device:
    """One device row of a ResourceSlice (resource.k8s.io Device, basic
    shape: name + flat string attributes)."""

    name: str
    attributes: dict[str, str] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: Mapping) -> "Device":
        attrs: dict[str, str] = {}
        # upstream: attributes: {key: {"string": .., "int": .., "bool": ..,
        # "version": ..}} under .basic; accept both that and a flat map
        basic = d.get("basic") or d
        for k, v in (basic.get("attributes") or {}).items():
            if isinstance(v, Mapping):
                for typ in ("string", "int", "bool", "version"):
                    if typ in v:
                        attrs[k] = str(v[typ]).lower() if typ == "bool" else str(v[typ])
                        break
            else:
                # flat form must normalize bools the same way the typed
                # form does (str(True) is "True", not "true")
                attrs[k] = str(v).lower() if isinstance(v, bool) else str(v)
        return Device(name=d.get("name") or "", attributes=attrs)

    def to_dict(self) -> dict:
        out: dict[str, Any] = {"name": self.name}
        if self.attributes:
            out["basic"] = {
                "attributes": {k: {"string": v} for k, v in self.attributes.items()}
            }
        return out


@dataclass
class ResourceSlice:
    """resource.k8s.io ResourceSlice: one driver's device inventory on one
    node (spec.nodeName + spec.driver + spec.devices)."""

    name: str
    node_name: str = ""
    driver: str = ""
    pool: str = ""
    devices: tuple[Device, ...] = ()
    resource_version: int = 0

    @property
    def key(self) -> str:
        return self.name

    @staticmethod
    def from_dict(d: Mapping) -> "ResourceSlice":
        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        return ResourceSlice(
            name=meta.get("name") or "",
            node_name=spec.get("nodeName") or "",
            driver=spec.get("driver") or "",
            pool=(spec.get("pool") or {}).get("name") or "",
            devices=tuple(
                Device.from_dict(x) for x in spec.get("devices") or ()
            ),
            resource_version=int(meta.get("resourceVersion") or 0),
        )

    def to_dict(self) -> dict:
        spec: dict[str, Any] = {
            "nodeName": self.node_name,
            "driver": self.driver,
            "devices": [dv.to_dict() for dv in self.devices],
        }
        if self.pool:
            spec["pool"] = {"name": self.pool}
        return {
            "apiVersion": "resource.k8s.io/v1beta1",
            "kind": "ResourceSlice",
            "metadata": {"name": self.name},
            "spec": spec,
        }


@dataclass
class DeviceClass:
    """resource.k8s.io DeviceClass: a named device category. Selector
    support is structural (driver + exact attribute matches) — see the
    module docstring's CEL divergence note."""

    name: str
    driver: str = ""  # "" = any driver
    match_attributes: dict[str, str] = field(default_factory=dict)
    # a CEL expression we could not interpret: the class matches nothing
    opaque_selector: str = ""
    resource_version: int = 0

    @property
    def key(self) -> str:
        return self.name

    def matches(self, driver: str, device: Device) -> bool:
        if self.opaque_selector:
            return False
        if self.driver and driver != self.driver:
            return False
        for k, v in self.match_attributes.items():
            # device attributes are normalized strings (bools lowercase);
            # normalize the wanted value the same way so a YAML bool in
            # matchAttributes compares equal
            want = str(v).lower() if isinstance(v, bool) else str(v)
            if device.attributes.get(k) != want:
                return False
        return True

    @staticmethod
    def from_dict(d: Mapping) -> "DeviceClass":
        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        driver = spec.get("driver") or ""
        match: dict[str, str] = dict(spec.get("matchAttributes") or {})
        opaque = ""
        for sel in spec.get("selectors") or ():
            cel = (sel.get("cel") or {}).get("expression") or ""
            if not cel:
                continue
            parsed = _parse_simple_cel(cel)
            if parsed is None:
                opaque = cel  # uninterpretable: match nothing (conservative)
            else:
                kind, key, val = parsed
                if kind == "driver":
                    if driver and driver != val:
                        # contradictory conjunction: matches nothing —
                        # keep the original driver so the opaque state
                        # round-trips through to_dict/from_dict
                        opaque = cel
                    else:
                        driver = val
                elif key in match and match[key] != val:
                    # two selectors pinning one attribute to different
                    # values is an unsatisfiable AND, not last-wins
                    opaque = cel
                else:
                    match[key] = val
        return DeviceClass(
            name=meta.get("name") or "",
            driver=driver,
            match_attributes=match,
            opaque_selector=opaque,
            resource_version=int(meta.get("resourceVersion") or 0),
        )

    def to_dict(self) -> dict:
        spec: dict[str, Any] = {}
        if self.driver:
            spec["driver"] = self.driver
        if self.match_attributes:
            spec["matchAttributes"] = dict(self.match_attributes)
        if self.opaque_selector:
            spec["selectors"] = [{"cel": {"expression": self.opaque_selector}}]
        return {
            "apiVersion": "resource.k8s.io/v1beta1",
            "kind": "DeviceClass",
            "metadata": {"name": self.name},
            "spec": spec,
        }


def _parse_simple_cel(expr: str):
    """Interpret the two ubiquitous CEL selector shapes:
    ``device.driver == "x"`` and ``device.attributes["k"] == "v"``
    (whitespace-insensitive). Returns ("driver", None, value) or
    ("attr", key, value), or None when the expression is anything else.
    """
    import re

    e = expr.strip()
    m = re.fullmatch(r'device\.driver\s*==\s*"([^"]*)"', e)
    if m:
        return ("driver", None, m.group(1))
    m = re.fullmatch(
        r'device\.attributes\[\s*"([^"]*)"\s*\]\s*==\s*"([^"]*)"', e
    )
    if m:
        return ("attr", m.group(1), m.group(2))
    return None


@dataclass
class DeviceRequest:
    """One entry of claim.spec.devices.requests: count devices of a
    class."""

    name: str
    device_class_name: str
    count: int = 1

    @staticmethod
    def from_dict(d: Mapping) -> "DeviceRequest":
        mode = d.get("allocationMode") or "ExactCount"
        if mode != "ExactCount":
            raise ValueError(
                f"deviceRequest {d.get('name')!r}: allocationMode {mode!r} "
                "is out of scope (only ExactCount is supported)"
            )
        if d.get("adminAccess"):
            raise ValueError(
                f"deviceRequest {d.get('name')!r}: adminAccess is out of scope"
            )
        raw = d.get("count")
        count = 1 if raw is None else int(raw)
        if count < 1:
            raise ValueError(
                f"deviceRequest {d.get('name')!r}: count must be >= 1, "
                f"got {count}"
            )
        return DeviceRequest(
            name=d.get("name") or "",
            device_class_name=d.get("deviceClassName") or "",
            count=count,
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "deviceClassName": self.device_class_name,
            "allocationMode": "ExactCount",
            "count": self.count,
        }


@dataclass
class DeviceResult:
    """One allocated device in claim.status.allocation. Identity is
    (driver, pool, device) — per-pool device names routinely repeat."""

    request: str
    driver: str
    device: str
    pool: str = ""

    @staticmethod
    def from_dict(d: Mapping) -> "DeviceResult":
        return DeviceResult(
            request=d.get("request") or "",
            driver=d.get("driver") or "",
            device=d.get("device") or "",
            pool=d.get("pool") or "",
        )

    def to_dict(self) -> dict:
        return {
            "request": self.request,
            "driver": self.driver,
            "device": self.device,
            "pool": self.pool,
        }


@dataclass
class ResourceClaim:
    """resource.k8s.io ResourceClaim: device requests + (status) the
    allocation and the pods reserving it."""

    name: str
    namespace: str = "default"
    requests: tuple[DeviceRequest, ...] = ()
    # status.allocation (node_name "" = unallocated)
    allocated_node: str = ""
    results: tuple[DeviceResult, ...] = ()
    # status.reservedFor pod keys (ns/name)
    reserved_for: tuple[str, ...] = ()
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    @property
    def allocated(self) -> bool:
        return bool(self.allocated_node)

    @staticmethod
    def from_dict(d: Mapping) -> "ResourceClaim":
        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        alloc = status.get("allocation") or {}
        node = ""
        # upstream records the chosen node as a nodeSelector with one term;
        # accept both that and a plain nodeName
        node = alloc.get("nodeName") or ""
        if not node:
            for term in (
                (alloc.get("nodeSelector") or {}).get("nodeSelectorTerms")
                or ()
            ):
                for f in term.get("matchFields") or ():
                    if f.get("key") == "metadata.name" and f.get("values"):
                        node = f["values"][0]
        return ResourceClaim(
            name=meta.get("name") or "",
            namespace=meta.get("namespace") or "default",
            requests=tuple(
                DeviceRequest.from_dict(r)
                for r in (spec.get("devices") or {}).get("requests") or ()
            ),
            allocated_node=node,
            results=tuple(
                DeviceResult.from_dict(r)
                for r in (alloc.get("devices") or {}).get("results") or ()
            ),
            reserved_for=tuple(
                f"{r.get('namespace') or meta.get('namespace') or 'default'}"
                f"/{r.get('name')}"
                for r in status.get("reservedFor") or ()
                if r.get("name")
            ),
            resource_version=int(meta.get("resourceVersion") or 0),
        )

    def to_dict(self) -> dict:
        out: dict[str, Any] = {
            "apiVersion": "resource.k8s.io/v1beta1",
            "kind": "ResourceClaim",
            "metadata": {"name": self.name, "namespace": self.namespace},
            "spec": {
                "devices": {"requests": [r.to_dict() for r in self.requests]}
            },
        }
        status: dict[str, Any] = {}
        if self.allocated:
            status["allocation"] = {
                "nodeName": self.allocated_node,
                "nodeSelector": {
                    "nodeSelectorTerms": [
                        {
                            "matchFields": [
                                {
                                    "key": "metadata.name",
                                    "operator": "In",
                                    "values": [self.allocated_node],
                                }
                            ]
                        }
                    ]
                },
                "devices": {
                    "results": [r.to_dict() for r in self.results]
                },
            }
        if self.reserved_for:
            status["reservedFor"] = [
                {
                    "resource": "pods",
                    "namespace": k.split("/", 1)[0],
                    "name": k.split("/", 1)[1],
                }
                for k in self.reserved_for
            ]
        if status:
            out["status"] = status
        return out
