"""ResourceClaim allocator — the Reserve/PreBind/Unreserve stages of the
dynamicresources plugin (plugins/dynamicresources/dynamicresources.go
#Reserve -> claim assume, #PreBind -> allocation + reservedFor API writes,
#Unreserve [U]), shaped after this repo's VolumeBinder.

Flow inside a scheduling batch (gate: DynamicResourceAllocation):
  Reserve  : assume_pod_claims(pod, node) — resolve the pod's claims,
             greedily pick concrete free devices on the CHOSEN node
             (ops/oracle/dra.py#DraContext.pick, which also pins
             already-allocated claims to their node), and record the
             assumption. Assumed devices count as taken for later pods in
             the same batch even though nothing is written yet.
  PreBind  : bind_pod_claims(pod) — write allocation + reservedFor into
             the cluster state for every assumption.
  failure  : unreserve(pod) — roll back writes + assumptions.

Claim sharing: two pods may reference the same claim. The first Reserve
allocates it; the second pod's Reserve succeeds only on the allocation
node (otherwise it fails here and the pod requeues — the next batch's
filter mask pins it to the right node, the same assume-and-retry pattern
the reference uses for in-flight claim state).

Concurrency: Reserve runs under the cluster lock (inside schedule_batch);
PreBind/Unreserve run on the lockless binding cycle, so their claim-object
mutations take the cluster lock explicitly. The ``writing`` suppression
flag is THREAD-LOCAL: only events emitted from this thread's own
bind-write call stack are suppressed — another thread's concurrent
slice/claim event must still wake parked pods.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..api.dra import DeviceResult, ResourceClaim
from ..api.objects import Pod
from ..ops.oracle.dra import ClaimError, DraContext
from .cluster import ApiError, ClusterState


class ClaimAllocationError(Exception):
    pass


@dataclass
class _Assumption:
    claim: ResourceClaim
    node_name: str
    # the allocation this pod depends on — the freshly-picked devices when
    # this pod allocated the claim (fresh=True), or a COPY of the pinned
    # in-flight/written allocation when it joined as a sharer. Sharers
    # carrying the results means the in-flight accounting and the PreBind
    # write survive the original allocator rolling back first.
    results: tuple[DeviceResult, ...]
    fresh: bool
    # set by bind_pod_claims when THIS pod's PreBind wrote the allocation;
    # unreserve only clears an allocation this scheduler wrote (fresh or
    # wrote_alloc) — a pre-existing driver/controller allocation the pod
    # merely joined is never destroyed by our rollback
    wrote_alloc: bool = False


@dataclass
class ClaimAllocator:
    cluster: ClusterState
    # pod key -> assumptions made at Reserve
    _assumed: dict[str, list[_Assumption]] = field(default_factory=dict)
    # (dra_generation, DraContext) — the base context rebuild walks every
    # slice/class/claim, so it is cached until a DRA object changes
    _ctx_cache: tuple | None = None
    # in-flight overlay, maintained INCREMENTALLY as assumptions come and
    # go (rebuilding it from _assumed on every Reserve would be quadratic
    # across a DRA-heavy batch): per-node taken device ids and per-claim
    # pinned allocations
    _ov_taken: dict[str, set] = field(default_factory=dict)
    _ov_claims: dict[str, ResourceClaim] = field(default_factory=dict)
    _ov_dirty: bool = False
    # thread-local bind-write depth (see module docstring)
    _writing: threading.local = field(default_factory=threading.local)

    @property
    def writing(self) -> int:
        """Nonzero iff THIS thread is inside a bind-side claim write."""
        return getattr(self._writing, "n", 0)

    def _overlay_add(self, assumptions: list[_Assumption]) -> None:
        for a in assumptions:
            t = self._ov_taken.setdefault(a.node_name, set())
            for r in a.results:
                t.add((r.driver, r.pool, r.device))
            # pin the claim for later sharers while its status is unwritten
            if not a.claim.allocated:
                c = a.claim
                self._ov_claims[c.key] = ResourceClaim(
                    name=c.name,
                    namespace=c.namespace,
                    requests=c.requests,
                    allocated_node=a.node_name,
                    results=a.results,
                    reserved_for=c.reserved_for,
                    resource_version=c.resource_version,
                )

    def _rebuild_overlay(self) -> None:
        self._ov_taken = {}
        self._ov_claims = {}
        for assumptions in self._assumed.values():
            self._overlay_add(assumptions)
        self._ov_dirty = False

    def context(self) -> DraContext:
        # snapshot generation + the three lists atomically: callers run
        # outside the cluster lock (the fold section, the binding cycle),
        # and individually-locked list calls could tear against a
        # concurrent slice/claim write
        with self.cluster.lock:
            gen = getattr(self.cluster, "dra_generation", -1)
            if self._ctx_cache is None or self._ctx_cache[0] != gen:
                self._ctx_cache = (
                    gen,
                    DraContext.build(
                        self.cluster.list_resource_slices(),
                        self.cluster.list_device_classes(),
                        self.cluster.list_resource_claims(),
                    ),
                )
        base = self._ctx_cache[1]
        if self._ov_dirty:
            self._rebuild_overlay()
        # merged view: classes/by_node are immutable after build and
        # shared; claims/taken merge the in-flight overlay on top of the
        # base. Sets from ``base`` are SHARED where no overlay exists —
        # context consumers must not mutate ctx.taken (pick() uses a
        # local ``extra`` set).
        taken = dict(base.taken)
        for n, s in self._ov_taken.items():
            taken[n] = (base.taken.get(n) or set()) | s
        claims = dict(base.claims)
        for k, pinned in self._ov_claims.items():
            live = claims.get(k)
            if live is not None and not live.allocated:
                claims[k] = pinned
        return DraContext(
            classes=base.classes,
            claims=claims,
            by_node=base.by_node,
            taken=taken,
        )

    def assume_pod_claims(self, pod: Pod, node_name: str) -> bool:
        """Reserve. True if anything was assumed; False for the
        claim-free fast path. Raises ClaimAllocationError when a claim
        cannot be satisfied on the chosen node — the caller unreserves
        and requeues."""
        if not pod.resource_claim_names and not pod.claim_templates_unresolved:
            return False
        ctx = self.context()
        try:
            claims = ctx.pod_claims(pod)
        except ClaimError as e:
            raise ClaimAllocationError(str(e)) from None
        # the effective (possibly batch-assumed) claim objects
        claims = [ctx.claims[c.key] for c in claims]
        picked = ctx.pick(node_name, claims)
        if picked is None:
            raise ClaimAllocationError(
                f"cannot allocate resourceclaims on node {node_name}: "
                "devices exhausted or claim allocated elsewhere"
            )
        assumptions = []
        for c in claims:
            live = self.cluster.get_resource_claim(c.namespace, c.name)
            fresh = c.key in picked
            # sharers copy the allocation they depend on (the written one,
            # or the in-flight overlay's) so their PreBind can write it if
            # the allocating pod rolled back first
            results = (
                tuple(picked[c.key])
                if fresh
                else (ctx.claims[c.key].results or live.results)
            )
            assumptions.append(
                _Assumption(
                    claim=live,
                    node_name=node_name,
                    results=results,
                    fresh=fresh,
                )
            )
        if assumptions:
            self._assumed[pod.key] = assumptions
            self._overlay_add(assumptions)
            return True
        return False

    def bind_pod_claims(self, pod: Pod) -> None:
        """PreBind: write allocation + reservedFor for every assumption.
        A sharer writes the allocation too when the claim is (still or
        again) unallocated — the allocating pod may have failed its bind
        after this pod reserved. Runs on the lockless binding cycle, so
        the claim mutations take the cluster lock explicitly."""
        self._writing.n = getattr(self._writing, "n", 0) + 1
        try:
            with self.cluster.lock:
                for a in self._assumed.get(pod.key, ()):
                    c = a.claim
                    if not c.allocated and a.results:
                        c.allocated_node = a.node_name
                        c.results = a.results
                        a.wrote_alloc = True
                    if pod.key not in c.reserved_for:
                        c.reserved_for = c.reserved_for + (pod.key,)
                    self.cluster.update_resource_claim(c)
        finally:
            self._writing.n -= 1

    def finish(self, pod_key: str) -> None:
        """Binding succeeded: drop the assumption bookkeeping (the claim
        status is written, so the base context now carries it)."""
        if self._assumed.pop(pod_key, None) is not None:
            self._ov_dirty = True

    def unreserve(self, pod_key: str) -> None:
        """Roll back assumptions AND any PreBind writes (idempotent).
        The allocation is cleared only when no other pod reserves the
        claim AND this scheduler wrote it — a bound sharer keeps it
        alive, and a pre-existing controller allocation the pod merely
        joined is never destroyed."""
        assumptions = self._assumed.pop(pod_key, None)
        if assumptions is None:
            return
        self._ov_dirty = True
        with self.cluster.lock:
            for a in assumptions:
                c = a.claim
                changed = False
                if pod_key in c.reserved_for:
                    c.reserved_for = tuple(
                        k for k in c.reserved_for if k != pod_key
                    )
                    changed = True
                if (
                    c.allocated
                    and not c.reserved_for
                    and (a.fresh or a.wrote_alloc)
                ):
                    c.allocated_node = ""
                    c.results = ()
                    changed = True
                if changed:
                    try:
                        self.cluster.update_resource_claim(c)
                    except ApiError:
                        pass
