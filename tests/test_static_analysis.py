"""Gate + fixture tests for kubernetes_tpu.analysis.

The gate runs the analyzer in-process over the whole package and fails
on ANY unsuppressed finding — the tier-1 equivalent of scripts/lint.py.
The fixture tests prove each rule actually fires on a known-bad snippet
(a rule that never fires gates nothing), including LOCK001 catching the
pre-fix ``_apply_flight`` exception-path pattern it was built for.
"""

import textwrap

from kubernetes_tpu import analysis
from kubernetes_tpu.analysis import AnalysisContext, analyze_source
from kubernetes_tpu.analysis.passes import (
    DtypeDisciplinePass,
    HostSyncPass,
    LockDisciplinePass,
    MetricNamePass,
    TracedBranchPass,
)


def findings_for(source, passes, ctx=None, filename="snippet.py"):
    return analyze_source(
        textwrap.dedent(source), filename=filename, ctx=ctx, passes=passes
    )


def active(findings, rule=None):
    return [
        f
        for f in findings
        if not f.suppressed and (rule is None or f.rule == rule)
    ]


# -- the gate ---------------------------------------------------------------


def test_package_has_zero_unsuppressed_findings():
    """python -m kubernetes_tpu.analysis kubernetes_tpu/ must exit 0."""
    findings = analysis.run_paths()
    bad = active(findings)
    assert not bad, "unsuppressed findings:\n" + "\n".join(
        f.render() for f in bad
    )


def test_every_suppression_carries_a_reason():
    findings = analysis.run_paths()
    assert not [f for f in findings if f.rule == "KTPU000"]
    for f in findings:
        if f.suppressed:
            assert f.suppress_reason.strip()


# -- TPU001 host-sync-in-hot-path ------------------------------------------

_JIT_SYNC = """
    import jax
    import numpy as np

    def leaf(x):
        return np.asarray(x).sum()

    @jax.jit
    def solve(x):
        return leaf(x) + 1
"""


def test_tpu001_fires_on_np_asarray_reachable_from_jit():
    fs = findings_for(_JIT_SYNC, [HostSyncPass])
    assert active(fs, "TPU001"), "np.asarray reachable from jax.jit missed"
    assert any("leaf" in f.message for f in fs)


def test_tpu001_fires_on_coercion_and_block_until_ready():
    fs = findings_for(
        """
        import jax

        @jax.jit
        def f(x):
            y = x.block_until_ready()
            return int(y)
        """,
        [HostSyncPass],
    )
    msgs = [f.message for f in active(fs, "TPU001")]
    assert any("block_until_ready" in m for m in msgs)
    assert any("int() coercion" in m for m in msgs)


def test_tpu001_fires_in_registered_hot_function():
    fs = findings_for(
        """
        # the apply path: ktpu: hot
        def apply(batch):
            return batch.assignments.tolist()
        """,
        [HostSyncPass],
    )
    assert active(fs, "TPU001")


def test_tpu001_hot_scope_skips_plain_host_coercions():
    """int()/float() on host values is legitimate outside traced code."""
    fs = findings_for(
        """
        # ktpu: hot
        def apply(batch):
            return int(batch.count) + float(batch.score)
        """,
        [HostSyncPass],
    )
    assert not active(fs, "TPU001")


def test_tpu001_whitelist_exempts_sanctioned_read_point():
    src = """
        import numpy as np

        class DeferredAssignments:
            # ktpu: hot
            def get(self):
                return np.asarray(self._dev)
    """
    hit = findings_for(src, [HostSyncPass], filename="exact.py")
    assert active(hit, "TPU001"), "unwhitelisted read must be flagged"
    ctx = AnalysisContext(
        sanctioned_sync=frozenset({("exact.py", "DeferredAssignments.get")})
    )
    ok = findings_for(src, [HostSyncPass], ctx=ctx, filename="exact.py")
    assert not active(ok, "TPU001")


def test_tpu001_jit_assignment_form_is_a_root():
    """g = jax.jit(f) roots f even without a decorator."""
    fs = findings_for(
        """
        import jax
        import numpy as np

        def _scan(x):
            return np.asarray(x)

        _scan_jit = jax.jit(_scan)
        """,
        [HostSyncPass],
    )
    assert active(fs, "TPU001")


def test_tpu001_bare_name_resolves_to_module_function_not_sibling_method():
    """A bare name inside a method is the module-level function (a
    sibling method needs `self.`); scope must follow the right callee."""
    fs = findings_for(
        """
        import jax
        import numpy as np

        def helper(x):
            return np.asarray(x)

        class S:
            def helper(self, x):
                return x  # clean sibling that must NOT shadow the call

            @jax.jit
            def solve(self, x):
                return helper(x)
        """,
        [HostSyncPass],
    )
    hits = active(fs, "TPU001")
    assert hits and all("'helper'" in f.message for f in hits)


def test_tpu001_sees_functions_defined_in_except_handlers():
    fs = findings_for(
        """
        import jax
        import numpy as np

        @jax.jit
        def solve(x):
            try:
                return x
            except Exception:
                def rescue(v):
                    return np.asarray(v)

                return rescue(x)
        """,
        [HostSyncPass],
    )
    assert active(fs, "TPU001"), "def inside except handler escaped scope"


def test_cli_errors_on_nonexistent_path(tmp_path):
    """A typo'd path must not leave the gate silently green."""
    import pytest

    from kubernetes_tpu.analysis import run_paths
    from kubernetes_tpu.analysis.__main__ import main

    with pytest.raises(FileNotFoundError):
        run_paths([str(tmp_path / "no_such_dir")])
    assert main([str(tmp_path / "no_such_dir")]) == 2


def test_tpu001_suppression_with_reason_is_honored():
    fs = findings_for(
        """
        import jax

        @jax.jit
        def f(shape):
            # ktpu: ignore[TPU001]: shape is a static argname
            return int(shape[0])
        """,
        [HostSyncPass],
    )
    assert not active(fs, "TPU001")
    assert any(f.suppressed for f in fs)


def test_reasonless_suppression_is_its_own_finding():
    fs = findings_for(
        """
        import jax

        @jax.jit
        def f(shape):
            # ktpu: ignore[TPU001]
            return int(shape[0])
        """,
        [HostSyncPass],
    )
    assert active(fs, "KTPU000"), "reasonless ignore must be rejected"


# -- TPU002 traced-branch ---------------------------------------------------


def test_tpu002_fires_on_python_if_over_jnp():
    fs = findings_for(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            if jnp.any(x > 0):
                return x
            while jnp.sum(x) < 3:
                x = x + 1
            return -x
        """,
        [TracedBranchPass],
    )
    assert len(active(fs, "TPU002")) == 2


def test_tpu002_fires_in_hot_scope_as_implicit_sync():
    """if jnp.any(...) in HOST hot-path code syncs on every call."""
    fs = findings_for(
        """
        import jax.numpy as jnp

        # ktpu: hot
        def apply(rows):
            if jnp.any(rows < 0):
                return None
            return rows
        """,
        [TracedBranchPass],
    )
    hits = active(fs, "TPU002")
    assert len(hits) == 1
    assert "syncs per call" in hits[0].message


def test_tpu002_allows_static_python_branches():
    fs = findings_for(
        """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("mode",))
        def f(x, mode):
            if mode == "fast":
                return x * 2
            return x
        """,
        [TracedBranchPass],
    )
    assert not active(fs, "TPU002")


# -- TPU003 dtype discipline ------------------------------------------------

_DTYPE_CTX = AnalysisContext(dtype_paths=("",))


def test_tpu003_fires_on_missing_dtype_and_float_literal():
    fs = findings_for(
        """
        import jax.numpy as jnp

        def build(n):
            a = jnp.zeros(n)
            b = jnp.full(n, 0.5)
            c = jnp.array([True])
            return a, b, c
        """,
        [DtypeDisciplinePass],
        ctx=_DTYPE_CTX,
    )
    hits = active(fs, "TPU003")
    assert len(hits) == 3
    assert any("float literal" in f.message for f in hits)


def test_tpu003_accepts_keyword_and_positional_dtype():
    fs = findings_for(
        """
        import jax.numpy as jnp

        def build(n, x):
            a = jnp.zeros(n, jnp.int32)
            b = jnp.full(n, 0, jnp.int64)
            c = jnp.array([1], dtype=jnp.int32)
            d = jnp.zeros_like(x)
            return a, b, c, d
        """,
        [DtypeDisciplinePass],
        ctx=_DTYPE_CTX,
    )
    assert not active(fs, "TPU003")


def test_tpu003_fires_on_narrow_flattened_index():
    # the 512k x 102k audit (ISSUE 12): a pod·node flattened index
    # narrowed to int32 in the same expression wraps silently at scale
    fs = findings_for(
        """
        import jax.numpy as jnp

        def flatten(pod_ids, node_ids, n):
            a = (pod_ids * n + node_ids).astype(jnp.int32)
            b = (pod_ids * n + node_ids).astype(dtype=jnp.int32)
            return a, b
        """,
        [DtypeDisciplinePass],
        ctx=_DTYPE_CTX,
    )
    hits = active(fs, "TPU003")
    assert len(hits) == 2  # positional AND keyword dtype forms
    assert all("flattened-index" in f.message for f in hits)


def test_tpu003_narrow_flatten_accepts_int64_and_float_scores():
    fs = findings_for(
        """
        import jax.numpy as jnp

        MAX_NODE_SCORE = 100

        def ok(pod_ids, node_ids, n, frac):
            wide = (pod_ids.astype(jnp.int64) * n + node_ids)
            narrow_named = wide.astype(jnp.int32)  # named, not inline
            score = ((1.0 - frac) * MAX_NODE_SCORE).astype(jnp.int32)
            ratio = (frac * MAX_NODE_SCORE / 2).astype(jnp.int32)
            return narrow_named, score, ratio
        """,
        [DtypeDisciplinePass],
        ctx=_DTYPE_CTX,
    )
    assert not active(fs, "TPU003")


def test_tpu003_scoped_to_configured_paths():
    fs = findings_for(
        "import jax.numpy as jnp\nx = jnp.zeros(3)\n",
        [DtypeDisciplinePass],
        ctx=AnalysisContext(dtype_paths=("kubernetes_tpu/ops/",)),
        filename="elsewhere.py",
    )
    assert not active(fs, "TPU003")


# -- LOCK001 lock discipline ------------------------------------------------

# Distilled from the PRE-FIX _apply_flight/_commit_all exception path:
# guarded in-flight bookkeeping and the session-stale flag touched on the
# failure path without the lock the happy path holds (ADVICE r5 #3).
_PREFIX_APPLY_FLIGHT = """
    class Scheduler:
        def __init__(self, cluster):
            self.cluster = cluster
            self._in_flight = {}  # ktpu: guarded-by(cluster.lock)
            self._session_stale = False  # ktpu: guarded-by(cluster.lock)

        def _apply_flight(self, flight):
            try:
                with self.cluster.lock:
                    self._in_flight.update(flight.infos)
            except Exception:
                # exception path: bookkeeping torn down WITHOUT the lock
                for info in flight.infos:
                    self._in_flight.pop(info.key, None)
                self._session_stale = True
                raise
"""


def test_lock001_catches_prefix_apply_flight_exception_path():
    fs = findings_for(_PREFIX_APPLY_FLIGHT, [LockDisciplinePass])
    hits = active(fs, "LOCK001")
    assert len(hits) == 2
    assert any("_in_flight" in f.message for f in hits)
    assert any("_session_stale" in f.message for f in hits)
    # the happy path (inside the with) is NOT flagged: both hits sit in
    # the except handler, after the locked update
    locked_line = next(
        i + 1
        for i, l in enumerate(_PREFIX_APPLY_FLIGHT.splitlines())
        if "update" in l
    )
    assert all(f.line > locked_line for f in hits)


def test_lock001_accepts_with_lock_and_holds_annotation():
    fs = findings_for(
        """
        class Scheduler:
            def __init__(self):
                self._seq = 0  # ktpu: guarded-by(_lock)

            def bump(self):
                with self._lock:
                    self._seq += 1

            # watch callbacks fire under the lock: ktpu: holds(_lock)
            def on_event(self, ev):
                self._seq += 1
        """,
        [LockDisciplinePass],
    )
    assert not active(fs, "LOCK001")


def test_lock001_unannotated_attrs_are_free():
    fs = findings_for(
        """
        class Scheduler:
            def __init__(self):
                self.counter = 0

            def bump(self):
                self.counter += 1
        """,
        [LockDisciplinePass],
    )
    assert not active(fs, "LOCK001")


def test_lock001_flags_real_scheduler_gap_when_annotations_stand():
    """The shipped Scheduler class passes ONLY because the exception
    paths now lock; stripping one lock re-fires the rule (guards the
    guard)."""
    fs = findings_for(
        """
        class Scheduler:
            def __init__(self):
                self._in_flight = {}  # ktpu: guarded-by(cluster.lock)

            def _commit_all(self, infos):
                for info in infos:
                    self._in_flight.pop(info.key, None)
        """,
        [LockDisciplinePass],
    )
    assert active(fs, "LOCK001")


# -- MET001 metric names ----------------------------------------------------

_MET_CTX = AnalysisContext(
    metric_scan_paths=("",),
    metric_attrs={
        "solve_latency_seconds": "scheduler_tpu_solve_latency_seconds",
        "render": None,
    },
)


def test_met001_fires_on_unknown_attr_and_series_string():
    fs = findings_for(
        """
        from . import metrics

        def record():
            metrics.solve_latency_seconds.observe(1.0)
            metrics.solve_latency_sconds.observe(1.0)  # typo
            return "scheduler_tpu_solve_latency_secnds"  # typo
        """,
        [MetricNamePass],
        ctx=_MET_CTX,
    )
    hits = active(fs, "MET001")
    assert len(hits) == 2
    assert any("solve_latency_sconds" in f.message for f in hits)
    assert any("secnds" in f.message for f in hits)


def test_met001_shipped_registry_resolves_real_usage():
    """The real metrics module must expose every series the scheduler
    records — including the new pipeline fallback counter."""
    from kubernetes_tpu.analysis.passes.metricnames import (
        load_metric_registry,
    )

    attrs = load_metric_registry()
    assert attrs["pipeline_fallback_total"] == (
        "scheduler_pipeline_fallback_total"
    )
    assert attrs["solves_discarded_total"] == (
        "scheduler_tpu_solves_discarded_total"
    )


# ===========================================================================
# -- Analyzer v2: project-wide rules over the cross-module call graph ------
# ===========================================================================

from kubernetes_tpu.analysis import analyze_sources, build_project
from kubernetes_tpu.analysis.core import SourceModule
from kubernetes_tpu.analysis.passes import (
    CrossModuleSyncPass,
    FencePass,
    LockOrderPass,
    MetricsDocPass,
    RetryPass,
)


def project_findings(sources, project_passes, ctx=None):
    return analyze_sources(
        {name: textwrap.dedent(src) for name, src in sources.items()},
        ctx=ctx,
        project_passes=project_passes,
    )


# -- LOCK002 lock-order deadlocks -------------------------------------------

_LOCK_CYCLE = {
    "registry.py": """
        import threading
        from cache import Cache

        class Registry:
            def __init__(self):
                self._lock = threading.Lock()
                self.rows = {}

            def merge(self, cache: Cache):
                with self._lock:
                    cache.invalidate()
    """,
    "cache.py": """
        import threading
        from registry import Registry

        class Cache:
            def __init__(self, registry: Registry):
                self._lock = threading.Lock()
                self.registry = registry

            def invalidate(self):
                with self._lock:
                    pass

            def refresh(self):
                with self._lock:
                    self.registry.merge(self)
    """,
}


def test_lock002_detects_cross_module_cycle():
    """registry holds its lock and calls cache.invalidate (acquires
    cache lock); cache.refresh holds its lock and calls registry.merge
    (acquires registry lock) — opposite orders, classic deadlock."""
    fs = project_findings(_LOCK_CYCLE, [LockOrderPass])
    hits = active(fs, "LOCK002")
    assert any("cycle" in f.message for f in hits), [
        f.render() for f in fs
    ]
    cycle = next(f for f in hits if "cycle" in f.message)
    assert "Registry._lock" in cycle.message
    assert "Cache._lock" in cycle.message


def test_lock002_consistent_order_is_clean_and_proves_an_order():
    sources = {
        "registry.py": """
            import threading
            from cache import Cache

            class Registry:
                def __init__(self):
                    self._lock = threading.Lock()

                def merge(self, cache: Cache):
                    with self._lock:
                        cache.invalidate()
        """,
        "cache.py": """
            import threading

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()

                def invalidate(self):
                    with self._lock:
                        pass
        """,
    }
    fs = project_findings(sources, [LockOrderPass])
    assert not active(fs, "LOCK002")
    from kubernetes_tpu.analysis.passes.lockorder import get_analysis

    modules = [
        SourceModule.parse(n, source=textwrap.dedent(s))
        for n, s in sorted(sources.items())
    ]
    project = build_project(modules, AnalysisContext())
    analysis_result = get_analysis(project)
    assert not analysis_result.cycles()
    order = analysis_result.order()
    # registry's lock is held when cache's is acquired -> registry first
    assert order.index("registry.py::Registry._lock") < order.index(
        "cache.py::Cache._lock"
    )


def test_lock002_self_deadlock_on_nonreentrant_lock():
    fs = project_findings(
        {
            "core.py": """
                import threading

                class Core:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def outer(self):
                        with self._lock:
                            self.inner()

                    def inner(self):
                        with self._lock:
                            pass
            """
        },
        [LockOrderPass],
    )
    hits = active(fs, "LOCK002")
    assert len(hits) == 1
    assert "self-deadlock" in hits[0].message
    assert "re-acquires" in hits[0].message  # the call-path variant


def test_lock002_rlock_reentry_is_fine():
    fs = project_findings(
        {
            "core.py": """
                import threading

                class Core:
                    def __init__(self):
                        self.lock = threading.RLock()

                    def outer(self):
                        with self.lock:
                            self.inner()

                    def inner(self):
                        with self.lock:
                            pass
            """
        },
        [LockOrderPass],
    )
    assert not active(fs, "LOCK002")


def test_lock002_holds_annotation_contributes_edges():
    """A callback annotated holds(cluster.lock) that takes another lock
    creates the same edge a lexical nesting would."""
    fs = project_findings(
        {
            "a.py": """
                import threading

                class Cluster:
                    def __init__(self):
                        self.lock = threading.Lock()

                class Watcher:
                    def __init__(self, cluster: Cluster):
                        self.cluster = cluster
                        self._lock = threading.Lock()

                    # fires under the cluster lock: ktpu: holds(cluster.lock)
                    def on_event(self):
                        with self._lock:
                            pass

                    def sweep(self):
                        with self._lock:
                            with self.cluster.lock:
                                pass
            """
        },
        [LockOrderPass],
    )
    hits = active(fs, "LOCK002")
    assert any("cycle" in f.message for f in hits), [
        f.render() for f in fs
    ]


def test_lock002_artifact_is_current_at_head():
    """docs/LOCK_ORDER.md must match what the analyzer derives — the
    committed order is the provable one, and it is cycle-free."""
    from pathlib import Path

    from kubernetes_tpu.analysis import default_context, load_modules
    from kubernetes_tpu.analysis.passes.lockorder import (
        get_analysis,
        lock_order_markdown,
    )

    modules, broken = load_modules(None)
    assert not broken
    project = build_project(modules, default_context())
    assert not get_analysis(project).cycles()
    artifact = lock_order_markdown(project)
    committed = (
        Path(__file__).resolve().parents[1] / "docs" / "LOCK_ORDER.md"
    )
    assert committed.read_text() == artifact, (
        "docs/LOCK_ORDER.md drifted — regenerate: "
        "python -m kubernetes_tpu.analysis --write-lock-order"
    )


# -- FENCE001 epoch/role fence discipline -----------------------------------

_FENCE_BASE = """
    import threading

    class Hub:
        def __init__(self):
            self._lock = threading.Lock()
            self._rows = {{}}  # ktpu: replicated
            self._role = "standby"

        # ktpu: fence-check
        def _ensure_primary(self):
            if self._role != "primary":
                raise RuntimeError("deposed")

{methods}
"""


def fence_fixture(methods):
    return {
        "hub.py": _FENCE_BASE.format(
            methods=textwrap.indent(textwrap.dedent(methods), "        ")
        )
    }


def test_fence001_fires_on_unfenced_write():
    fs = project_findings(
        fence_fixture(
            """
            def stage(self, key, row):
                with self._lock:
                    self._rows[key] = row
            """
        ),
        [FencePass],
    )
    hits = active(fs, "FENCE001")
    assert len(hits) == 1
    assert "writes replicated state 'self._rows'" in hits[0].message


def test_fence001_mutator_call_counts_as_write():
    fs = project_findings(
        fence_fixture(
            """
            def wipe(self):
                self._rows.clear()
            """
        ),
        [FencePass],
    )
    hits = active(fs, "FENCE001")
    assert len(hits) == 1
    assert "writes" in hits[0].message


def test_fence001_direct_fence_call_satisfies():
    fs = project_findings(
        fence_fixture(
            """
            def stage(self, key, row):
                with self._lock:
                    self._ensure_primary()
                    self._rows[key] = row
            """
        ),
        [FencePass],
    )
    assert not active(fs, "FENCE001")


def test_fence001_fence_through_helper_satisfies():
    """The check reached through an intermediate gate helper still
    counts — resolution is interprocedural, not lexical."""
    fs = project_findings(
        fence_fixture(
            """
            def _gate(self):
                self._ensure_primary()

            def stage(self, key, row):
                with self._lock:
                    self._gate()
                    self._rows[key] = row
            """
        ),
        [FencePass],
    )
    assert not active(fs, "FENCE001")


def test_fence001_annotations_exempt_and_reasonless_exempt_fires():
    fs = project_findings(
        fence_fixture(
            """
            # ktpu: fenced-by-caller
            def _stage_locked(self, key, row):
                self._rows[key] = row

            # ktpu: fence-exempt(replication apply path)
            def install(self, rows):
                self._rows = dict(rows)

            # ktpu: fence-exempt()
            def peek(self):
                return dict(self._rows)
            """
        ),
        [FencePass],
    )
    hits = active(fs, "FENCE001")
    assert len(hits) == 1
    assert "no reason" in hits[0].message


def test_fence001_cross_module_check_resolves():
    """Fence helper inherited from a base class in ANOTHER module."""
    fs = project_findings(
        {
            "base.py": """
                class Fenced:
                    # ktpu: fence-check
                    def _ensure_primary(self):
                        raise RuntimeError
            """,
            "hub.py": """
                from base import Fenced

                class Hub(Fenced):
                    def __init__(self):
                        self._rows = {}  # ktpu: replicated

                    def stage(self, key, row):
                        self._ensure_primary()
                        self._rows[key] = row

                    def leak(self, key):
                        return self._rows.get(key)
            """,
        },
        [FencePass],
    )
    hits = active(fs, "FENCE001")
    assert len(hits) == 1
    assert "'Hub.leak' reads" in hits[0].message


# -- RETRY001 retry discipline ----------------------------------------------

def test_retry001_swallowed_nonretryable_fires():
    fs = project_findings(
        {
            "client.py": """
                class AdmitConflict(Exception):
                    pass

                def admit(op):
                    for attempt in range(5):
                        try:
                            return op()
                        except AdmitConflict:
                            continue
            """
        },
        [RetryPass],
    )
    hits = active(fs, "RETRY001")
    assert any("AdmitConflict" in f.message for f in hits)
    assert any("backoff" in f.message for f in hits)


def test_retry001_reraise_is_the_sanctioned_idiom():
    fs = project_findings(
        {
            "client.py": """
                import random
                import time

                class AdmitConflict(Exception):
                    pass

                def admit(op):
                    for attempt in range(5):
                        try:
                            return op()
                        except AdmitConflict:
                            raise
                        except IOError:
                            time.sleep(random.uniform(0, 0.1 * 2 ** attempt))
            """
        },
        [RetryPass],
    )
    assert not active(fs, "RETRY001")


def test_retry001_backoff_through_cross_module_helper():
    """sleep(uniform(...)) hidden in another module's helper still
    counts as backoff — resolved through the project graph."""
    fs = project_findings(
        {
            "backoff.py": """
                import random
                import time

                def full_jitter(attempt):
                    time.sleep(random.uniform(0, 0.1 * 2 ** attempt))
            """,
            "client.py": """
                from backoff import full_jitter

                def fetch(op):
                    for attempt in range(5):
                        try:
                            return op()
                        except IOError:
                            full_jitter(attempt)
            """,
        },
        [RetryPass],
    )
    assert not active(fs, "RETRY001")


def test_retry001_constant_sleep_is_not_backoff():
    fs = project_findings(
        {
            "client.py": """
                import time

                def fetch(op):
                    while True:
                        try:
                            return op()
                        except IOError:
                            time.sleep(1.0)
            """
        },
        [RetryPass],
    )
    hits = active(fs, "RETRY001")
    assert len(hits) == 1
    assert "backoff" in hits[0].message


def test_retry001_work_drain_loops_are_out_of_scope():
    """while <condition>: drain loops and plain iteration are NOT retry
    loops — the shape is pinned to for-range / while-True."""
    fs = project_findings(
        {
            "drain.py": """
                def flush(self):
                    while self._sealed:
                        try:
                            self._send_one()
                        except IOError:
                            self._requeue()

                def broadcast(replicas, op):
                    for replica in replicas:
                        try:
                            op(replica)
                        except IOError:
                            pass
            """
        },
        [RetryPass],
    )
    assert not active(fs, "RETRY001")


# -- TPU004 cross-module host-sync escape -----------------------------------

def test_tpu004_catches_cross_module_item():
    fs = project_findings(
        {
            "apply.py": """
                from readers import scalar_of

                # ktpu: hot
                def apply_assignments(batch):
                    return [scalar_of(x) for x in batch]
            """,
            "readers.py": """
                def scalar_of(x):
                    return x.item()
            """,
        },
        [CrossModuleSyncPass],
    )
    hits = active(fs, "TPU004")
    assert len(hits) == 1
    assert ".item() forces a host sync in 'scalar_of'" in hits[0].message
    assert "apply_assignments -> scalar_of" in hits[0].message


def test_tpu004_cold_barrier_stops_the_scope():
    fs = project_findings(
        {
            "apply.py": """
                from readers import debug_dump

                # ktpu: hot
                def apply_assignments(batch):
                    debug_dump(batch)
            """,
            "readers.py": """
                # ktpu: cold
                def debug_dump(batch):
                    return [x.item() for x in batch]
            """,
        },
        [CrossModuleSyncPass],
    )
    assert not active(fs, "TPU004")


def test_tpu004_typed_method_receiver_resolves():
    """A hot method calling a helper METHOD on a typed attribute from
    another module is still traced into."""
    fs = project_findings(
        {
            "sched.py": """
                from store import Store

                class Scheduler:
                    def __init__(self, store: Store):
                        self.store = store

                    # ktpu: hot
                    def commit(self, row):
                        self.store.put(row)
            """,
            "store.py": """
                class Store:
                    def put(self, row):
                        self.total = row.cost.item()
            """,
        },
        [CrossModuleSyncPass],
    )
    hits = active(fs, "TPU004")
    assert len(hits) == 1
    assert "'Store.put'" in hits[0].message


def test_tpu004_head_is_clean():
    """The shipped package has no cross-module sync escapes."""
    findings = analysis.run_paths()
    assert not active(findings, "TPU004")


# -- MET002 metrics registry <-> doc drift ----------------------------------

_MET2_REGISTRY = """
    from prometheus_client import Counter, Gauge

    solves = Counter("scheduler_solves", "solve batches")
    depth = Gauge("scheduler_queue_depth", "queue depth")
"""


def met2_ctx(doc_text):
    return AnalysisContext(
        metrics_module_suffix="metrics.py",
        metrics_doc_text=textwrap.dedent(doc_text),
    )


def test_met002_clean_when_registry_matches_doc():
    fs = project_findings(
        {"metrics.py": _MET2_REGISTRY},
        [MetricsDocPass],
        ctx=met2_ctx(
            """
            | metric | help |
            |--------|------|
            | `scheduler_solves_total` | solve batches |
            | `scheduler_queue_depth` | queue depth |
            """
        ),
    )
    assert not active(fs, "MET002")


def test_met002_fires_both_ways():
    fs = project_findings(
        {"metrics.py": _MET2_REGISTRY},
        [MetricsDocPass],
        ctx=met2_ctx(
            """
            | metric | help |
            |--------|------|
            | `scheduler_solves_total` | solve batches |
            | `scheduler_ghost_seconds` | never registered |
            """
        ),
    )
    hits = active(fs, "MET002")
    assert len(hits) == 2
    missing = next(f for f in hits if "queue_depth" in f.message)
    assert "missing from docs/METRICS.md" in missing.message
    assert missing.path == "metrics.py"
    stale = next(f for f in hits if "ghost" in f.message)
    assert "not registered" in stale.message
    assert stale.path == "docs/METRICS.md"


def test_met002_counter_total_suffix_normalized():
    """A Counter registered without _total is compared against its
    exposed name — same normalization as the doc generator."""
    fs = project_findings(
        {"metrics.py": _MET2_REGISTRY},
        [MetricsDocPass],
        ctx=met2_ctx(
            """
            | `scheduler_solves` | wrong: raw registration name |
            | `scheduler_queue_depth` | queue depth |
            """
        ),
    )
    hits = active(fs, "MET002")
    assert any("scheduler_solves_total" in f.message for f in hits)
    assert any("'scheduler_solves' is not registered" in f.message
               for f in hits)


def test_met002_head_registry_matches_shipped_doc():
    findings = analysis.run_paths()
    assert not active(findings, "MET002")


# -- suppression-debt ratchet -----------------------------------------------

def test_ratchet_holds_at_head():
    from kubernetes_tpu.analysis.ratchet import (
        check_ratchet,
        count_suppressions,
        load_baseline,
    )

    modules, _ = analysis.load_modules(None)
    baseline = load_baseline()
    assert baseline is not None, (
        "missing analysis/suppression_baseline.json — write one: "
        "python -m kubernetes_tpu.analysis --write-baseline"
    )
    assert not check_ratchet(count_suppressions(modules), baseline)


def test_ratchet_fails_on_growth_per_rule_and_total():
    from kubernetes_tpu.analysis.ratchet import check_ratchet

    counts = {"total": 3, "rules": {"TPU001": 2, "FENCE001": 1}}
    msgs = check_ratchet(
        counts, {"total": 3, "rules": {"TPU001": 3, "FENCE001": 0}}
    )
    assert any("FENCE001" in m for m in msgs)
    assert not any("total" in m and "grew" in m for m in msgs)
    msgs = check_ratchet(counts, {"total": 2, "rules": counts["rules"]})
    assert any("count grew" in m for m in msgs)
    assert not check_ratchet(counts, counts)


def test_missing_baseline_is_a_violation():
    from kubernetes_tpu.analysis.ratchet import check_ratchet

    assert check_ratchet({"total": 0, "rules": {}}, None)


# -- SARIF + stable output --------------------------------------------------

def test_sarif_carries_suppressions_and_all_rules():
    import json as _json

    from kubernetes_tpu.analysis.sarif import render_sarif

    findings = analysis.run_paths()
    doc = _json.loads(render_sarif(findings))
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "ktpu-analysis"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    for rid in ("LOCK002", "FENCE001", "RETRY001", "TPU004", "MET002"):
        assert rid in rule_ids
    suppressed = [
        r for r in run["results"] if r.get("suppressions")
    ]
    assert suppressed, "suppressed findings must survive into SARIF"
    for r in suppressed:
        assert r["level"] == "warning"
        assert r["suppressions"][0]["justification"].strip()
    for r in run["results"]:
        if not r.get("suppressions"):
            assert r["level"] == "error"


def test_findings_are_stable_sorted():
    findings = analysis.run_paths()
    key = [(f.path, f.line, f.rule, f.message) for f in findings]
    assert key == sorted(key)
