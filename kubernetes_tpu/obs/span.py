"""Zero-dep span tracing for the scheduling loops (the tentpole of the
trace layer, SURVEY §6.1's *host-side* complement to ``utils/tracing``'s
jax-profiler device traces).

Spans are OTel-shaped — name, span/trace/parent ids, attributes, start
and end timestamps — but carry **two** time bases from the injectable
``Clock``: ``now()`` (the scheduling clock; ``FakeClock`` virtual time
in the simulator, so recorded spans replay deterministically) and
``perf()`` (the duration clock). No OpenTelemetry dependency, no
network exporter: spans land in the in-memory flight recorder ring and,
optionally, a JSONL file.

Hot-path contract (TPU001): a *disabled* tracer's ``span()`` returns a
preallocated no-op context manager — one attribute check, no
allocation, no jax import, no host↔device sync. Enabling tracing adds
host-side dict work only; it never reads device values (the sanctioned
deferred-read points in ``analysis/registry.py`` stay the only ones).

Span ids are sequence numbers, not random — two same-seed simulator
runs emit byte-identical span streams (the sim's determinism contract
extends to observability output).
"""

from __future__ import annotations

import itertools
import threading

from .. import metrics
from ..utils.clock import Clock


class Span:
    """One timed operation. ``trace_id`` groups every span of one
    scheduling batch (the ``Scheduler._trace_step`` counter, shared
    with the jax-profiler step annotation).

    A plain ``__slots__`` class, not a dataclass: spans are created at
    per-pod volume on the bind path (and per sampled watch event), and
    the obs-overhead ladder holds the whole layer to <= 5% sustained
    throughput — instance-dict allocation is measurable there."""

    __slots__ = (
        "name", "span_id", "trace_id", "parent_id", "start_wall",
        "start_perf", "attrs", "end_wall", "end_perf", "status",
    )

    def __init__(
        self,
        name: str,
        span_id: int,
        trace_id: int,
        parent_id: "int | None",
        start_wall: float,  # Clock.now() — virtual in the simulator
        start_perf: float,  # Clock.perf() — duration base
        attrs: dict | None = None,
    ) -> None:
        self.name = name
        self.span_id = span_id
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.start_wall = start_wall
        self.start_perf = start_perf
        self.attrs = attrs if attrs is not None else {}
        self.end_wall = 0.0
        self.end_perf = 0.0
        self.status = "ok"  # ok | error

    @property
    def duration(self) -> float:
        return self.end_perf - self.start_perf

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def as_dict(self) -> dict:
        d = {
            "k": "span",
            "v": 1,
            "name": self.name,
            "span": self.span_id,
            "trace": self.trace_id,
            "parent": self.parent_id,
            "start": self.start_wall,
            "end": self.end_wall,
            "dur": self.end_perf - self.start_perf,
            "status": self.status,
        }
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class _NoopSpan:
    """Yielded by a disabled tracer: absorbs ``set()`` without work."""

    __slots__ = ()

    def set(self, **attrs) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NOOP = _NoopSpan()


class _SpanCtx:
    """Context manager for one live span: pushes itself on the tracer's
    thread-local parent stack so nested spans link automatically."""

    __slots__ = ("_tracer", "span")

    def __init__(self, tracer: "Tracer", span: Span) -> None:
        self._tracer = tracer
        self.span = span

    def set(self, **attrs) -> None:
        self.span.attrs.update(attrs)

    def __enter__(self) -> Span:
        self._tracer._stack().append(self.span)
        return self.span

    def __exit__(self, exc_type, exc, tb) -> bool:
        stack = self._tracer._stack()
        if stack and stack[-1] is self.span:
            stack.pop()
        if exc_type is not None:
            self.span.status = "error"
            self.span.attrs.setdefault("error", exc_type.__name__)
        self._tracer._finish(self.span)
        return False


class Tracer:
    """Span factory + export fan-out.

    ``recorder`` (obs.recorder.FlightRecorder) receives every finished
    span; ``sink`` is an optional callable(dict) for JSONL export (the
    CLI wires a file writer). ``enabled=False`` short-circuits to the
    shared no-op — the production default.
    """

    def __init__(
        self,
        clock: Clock | None = None,
        enabled: bool = False,
        recorder=None,
        sink=None,
    ) -> None:
        self.clock = clock or Clock()
        self.enabled = enabled
        self.recorder = recorder
        self.sink = sink
        # itertools.count: C-atomic increment — the span hot path pays
        # no lock acquire per id (span volume at sustained-stream rate
        # is thousands/s; the obs-overhead ladder budget is 5%)
        self._seq = itertools.count(1)
        self._local = threading.local()
        # current trace (batch) id; the scheduler sets it per cycle
        self.trace_id = 0
        # per-name metric children resolved once: labels() is a lock +
        # tuple-keyed dict lookup per call, measurable at per-pod span
        # volume (bind spans)
        self._span_counters: dict = {}

    # -- internals --

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _next_id(self) -> int:
        return next(self._seq)

    def _finish(self, span: Span) -> None:
        span.end_wall = self.clock.now()
        span.end_perf = self.clock.perf()
        counter = self._span_counters.get(span.name)
        if counter is None:
            counter = self._span_counters[span.name] = (
                metrics.trace_spans_total.labels(span.name)
            )
        counter.inc()
        if self.recorder is not None:
            self.recorder.record_span(span)
        if self.sink is not None:
            self.sink(span.as_dict())

    # -- public surface --

    def span(self, name: str, trace_id: int | None = None, **attrs):
        """Open a span under the current thread's innermost live span.
        Disabled tracers return the shared no-op (zero allocation)."""
        if not self.enabled:
            return _NOOP
        stack = self._stack()
        parent = stack[-1] if stack else None
        return _SpanCtx(
            self,
            Span(
                name,
                self._next_id(),
                (
                    trace_id
                    if trace_id is not None
                    else (parent.trace_id if parent else self.trace_id)
                ),
                parent.span_id if parent else None,
                self.clock.now(),
                self.clock.perf(),
                attrs,  # the **kwargs dict is already fresh
            ),
        )

    def current(self) -> Span | None:
        """The innermost live span on this thread (None when idle or
        disabled) — the structured-logging formatter reads span/trace
        ids from here."""
        stack = getattr(self._local, "stack", None)
        return stack[-1] if stack else None
