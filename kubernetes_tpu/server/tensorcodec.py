"""Columnar tensor wire format for the bulk gRPC path (SURVEY §6.8).

One message = one 4-byte little-endian header length, a JSON header, then
the raw array bytes back-to-back. The header carries request metadata plus
an array directory (name, dtype, shape, byte offset/length into the
payload). This keeps the hot 50k-pod path free of per-pod JSON — a pod
batch is three arrays, not 50k objects — while staying dependency-free
(grpcio's generic handlers carry opaque bytes; no protoc codegen needed
in this image).

Only little-endian scalar dtypes cross the wire (int8..int64, uint*,
float32/64, bool) — shapes and dtypes are validated on decode so a
malformed message errors instead of shearing memory.
"""

from __future__ import annotations

import json
import struct

import numpy as np

_ALLOWED_DTYPES = {
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64",
    "float32", "float64", "bool",
}


def encode(meta: dict, arrays: dict[str, np.ndarray] | None = None) -> bytes:
    arrays = arrays or {}
    directory = []
    chunks = []
    off = 0
    for name, arr in arrays.items():
        a = np.ascontiguousarray(arr)
        dt = a.dtype.name
        if dt not in _ALLOWED_DTYPES:
            raise ValueError(f"dtype {dt} not wire-safe for array {name!r}")
        raw = a.tobytes()
        directory.append(
            {
                "name": name,
                "dtype": dt,
                "shape": list(a.shape),
                "offset": off,
                "nbytes": len(raw),
            }
        )
        chunks.append(raw)
        off += len(raw)
    header = json.dumps({"meta": meta, "arrays": directory}).encode()
    return struct.pack("<I", len(header)) + header + b"".join(chunks)


def decode(data: bytes) -> tuple[dict, dict[str, np.ndarray]]:
    if len(data) < 4:
        raise ValueError("truncated message")
    (hlen,) = struct.unpack_from("<I", data, 0)
    if 4 + hlen > len(data):
        raise ValueError("truncated header")
    header = json.loads(data[4 : 4 + hlen].decode())
    payload = memoryview(data)[4 + hlen :]
    arrays: dict[str, np.ndarray] = {}
    for ent in header.get("arrays") or []:
        dt = ent["dtype"]
        if dt not in _ALLOWED_DTYPES:
            raise ValueError(f"dtype {dt} not wire-safe")
        shape = tuple(int(s) for s in ent["shape"])
        dtype = np.dtype(dt)
        expect = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if ent["nbytes"] != expect:
            raise ValueError(
                f"array {ent['name']!r}: {ent['nbytes']} bytes != shape {shape}"
            )
        start = int(ent["offset"])
        if start < 0 or start + expect > len(payload):
            raise ValueError(
                f"array {ent['name']!r}: offset {start} out of payload bounds"
            )
        buf = payload[start : start + expect]
        arrays[ent["name"]] = np.frombuffer(buf, dtype=dtype).reshape(shape)
    return header.get("meta") or {}, arrays
