"""Framework runtime — the host-side extension-point runner
(pkg/scheduler/framework/runtime/framework.go#frameworkImpl), built so
plugin tests read like upstream's (runtime.NewFramework over a snapshot
of nodes, then RunFilterPlugins / RunScorePlugins per pod).

The in-tree plugin pipeline itself lives in the fused device kernels (the
whole point of this framework); this runtime wraps the scalar ORACLE
pipeline for the in-tree set and runs out-of-tree Python plugins around
it, so it is both the upstream-shaped test fixture and the semantics
reference for SchedulerConfig.out_of_tree_plugins."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Sequence

from ..api.objects import Node, Pod
from .interface import (
    MAX_NODE_SCORE,
    CycleState,
    FilterPlugin,
    Registry,
    ScorePlugin,
    Status,
    StatusCode,
    run_pre_filter,
)


class WaitingPod:
    """runtime/waiting_pods_map.go#waitingPod: a pod parked at the Permit
    point. Each waiting Permit plugin holds its own timeout; the pod is
    rejected when the earliest one expires, allowed when every pending
    plugin calls allow(). allow/reject only record the verdict — the
    scheduler applies it (finishes or rolls back the binding) on its next
    cycle, the batched analog of the binding goroutine's WaitOnPermit."""

    def __init__(
        self, pod: Pod, node_name: str,
        plugin_timeouts: Mapping[str, float], now: float,
    ) -> None:
        self.pod = pod
        self.node_name = node_name
        self.deadlines = {
            name: now + timeout for name, timeout in plugin_timeouts.items()
        }
        self.pending = set(self.deadlines)
        self.rejected_by: str | None = None
        self.reject_message = ""

    def get_pending_plugins(self) -> list[str]:
        return sorted(self.pending)

    def allow(self, plugin_name: str) -> None:
        self.pending.discard(plugin_name)

    def reject(self, plugin_name: str, msg: str = "") -> None:
        self.rejected_by = plugin_name
        self.reject_message = msg

    @property
    def allowed(self) -> bool:
        return not self.pending and self.rejected_by is None

    def expired(self, now: float) -> "str | None":
        """Name of the first timed-out pending plugin, or None."""
        for name in sorted(self.pending):
            if now >= self.deadlines[name]:
                return name
        return None


@dataclass
class Framework:
    """runtime.NewFramework analog: nodes (+ resident pods) in, extension
    points runnable per pod. ``with_default_plugins`` includes the whole
    in-tree pipeline via the scalar oracle."""

    nodes: Sequence[Node]
    pods_by_node: Mapping[str, Sequence[Pod]] = field(default_factory=dict)
    registry: Registry = field(default_factory=Registry)
    with_default_plugins: bool = True

    def __post_init__(self) -> None:
        self._oracle = None
        if self.with_default_plugins:
            from ..ops.oracle.profile import FullOracle, make_oracle_nodes

            self._oracle = FullOracle(
                make_oracle_nodes(
                    list(self.nodes),
                    {k: list(v) for k, v in self.pods_by_node.items()},
                )
            )

    # -- extension points (framework.go#Run*Plugins) --

    def run_pre_filter_plugins(self, state: CycleState, pod: Pod) -> Status:
        """framework.go#RunPreFilterPlugins: statuses short-circuit;
        PreFilterResult allowlists intersect, stored in the cycle state
        under "PreFilterResult" (run_all consumes it)."""
        allow = None
        for p in self.registry.pre_filter:
            st, result = run_pre_filter(p, state, pod)
            if not st.is_success:
                return st
            if result is not None and not result.all_nodes():
                allow = (
                    result.node_names
                    if allow is None
                    else allow & result.node_names
                )
        if allow is not None:
            state.write("PreFilterResult", frozenset(allow))
        return Status.success()

    def run_filter_plugins(
        self, state: CycleState, pod: Pod, node: Node
    ) -> Status:
        """All Filter plugins for one (pod, node): in-tree pipeline first
        (when enabled), then out-of-tree plugins in registration order."""
        if self._oracle is not None:
            idx = self._node_index(node.name)
            if idx is None or not self._oracle.filter_one(
                pod, self._oracle.nodes[idx]
            ):
                return Status.unschedulable("in-tree filters")
        placed = tuple(self.pods_by_node.get(node.name, ()))
        for p in self.registry.filter:
            st = p.filter(state, pod, node, placed)
            if not st.is_success:
                return st
        return Status.success()

    def run_score_plugins(
        self, state: CycleState, pod: Pod, nodes: Sequence[Node]
    ) -> dict[str, int]:
        """Score + NormalizeScore + weight over ``nodes``
        (framework.go#RunScorePlugins' three passes), summed with the
        in-tree totals when defaults are enabled."""
        totals: dict[str, int] = {n.name: 0 for n in nodes}
        if self._oracle is not None:
            idxs = [self._node_index(n.name) for n in nodes]
            feasible = [i for i in idxs if i is not None]
            in_tree = self._oracle.score_totals(pod, feasible)
            for n, i in zip(nodes, idxs):
                if i is not None and i in in_tree:
                    totals[n.name] += in_tree[i]
        for p in self.registry.score:
            raw = {n.name: int(p.score(state, pod, n)) for n in nodes}
            norm = p.normalize_score(state, pod, raw)
            if norm is not None:
                raw = dict(norm)
            w = p.weight()
            for name, s in raw.items():
                if not 0 <= s <= MAX_NODE_SCORE:
                    raise ValueError(
                        f"plugin {p.name()} score {s} outside "
                        f"[0, {MAX_NODE_SCORE}] for node {name}"
                    )  # framework.go rejects out-of-range scores
                totals[name] += w * s
        return totals

    def run_all(
        self, pod: Pod
    ) -> tuple[list[Node], dict[str, int], Status]:
        """PreFilter -> Filter over all nodes -> Score over the feasible
        set: the schedulePod shape, for tests."""
        state = CycleState()
        st = self.run_pre_filter_plugins(state, pod)
        if not st.is_success:
            return [], {}, st
        try:
            allow = state.read("PreFilterResult")
        except KeyError:
            allow = None
        feasible = [
            n
            for n in self.nodes
            if (allow is None or n.name in allow)
            and self.run_filter_plugins(state, pod, n).is_success
        ]
        if not feasible:
            return [], {}, Status(StatusCode.UNSCHEDULABLE)
        return feasible, self.run_score_plugins(state, pod, feasible), Status.success()

    def _node_index(self, name: str):
        for i, n in enumerate(self.nodes):
            if n.name == name:
                return i
        return None


def fold_out_of_tree(
    plugins: Sequence[FilterPlugin | ScorePlugin],
    reps: Sequence[Pod],
    slot_nodes: Sequence[Node | None],
    mask,
    extra_score,
) -> None:
    """Fold out-of-tree plugins into the per-class device tables
    (SchedulerConfig.out_of_tree_plugins consumption): for every
    (scheduling-class representative, node slot), Filter rejections clear
    ``mask[c, slot]`` and Scores — after the plugin's NormalizeScore pass
    and the upstream 0..MAX_NODE_SCORE range check — accumulate weighted
    into ``extra_score[c, slot]``: the class-vectorized equivalent of
    registering the plugin in-process. Mutates the numpy tables in place.

    Semantics match Framework.run_*_plugins per scheduling CLASS: each
    class gets a fresh CycleState seeded by the PreFilter point, so
    plugins using the standard PreFilter-precompute pattern work. A
    Filter returning ERROR aborts the batch (raised), exactly as the
    reference aborts the scheduling cycle — an outage must not silently
    read as Unschedulable."""
    from .interface import PreFilterPlugin

    for c, rep in enumerate(reps):
        state = CycleState()  # per scheduling class == per cycle here
        rejected = False
        nodes = [
            (slot, node)
            for slot, node in enumerate(slot_nodes)
            if node is not None
        ]
        for p in plugins:
            if isinstance(p, PreFilterPlugin):
                st, result = run_pre_filter(p, state, rep)
                if st.code == StatusCode.ERROR:
                    raise RuntimeError(
                        f"plugin {p.name()} PreFilter error: {st.reasons}"
                    )
                if st.is_rejection:
                    # PreFilter rejection fails the pod on every node
                    # (schedule_one.go#schedulePod's early return)
                    mask[c, :] = False
                    rejected = True
                    break
                if result is not None and not result.all_nodes():
                    # PreFilterResult node-name allowlist -> static mask
                    for slot, node in nodes:
                        if node.name not in result.node_names:
                            mask[c, slot] = False
        if rejected:
            continue
        for p in plugins:
            if isinstance(p, FilterPlugin):
                for slot, node in nodes:
                    if not mask[c, slot]:
                        continue
                    st = p.filter(state, rep, node)
                    if st.code == StatusCode.ERROR:
                        raise RuntimeError(
                            f"plugin {p.name()} Filter error on "
                            f"{node.name}: {st.reasons}"
                        )
                    if not st.is_success:
                        mask[c, slot] = False
            if isinstance(p, ScorePlugin):
                raw = {
                    node.name: int(p.score(state, rep, node))
                    for slot, node in nodes
                    if mask[c, slot]
                }
                norm = p.normalize_score(state, rep, raw)
                if norm is not None:
                    raw = dict(norm)
                w = p.weight()
                for slot, node in nodes:
                    if node.name not in raw:
                        continue
                    s = raw[node.name]
                    if not 0 <= s <= MAX_NODE_SCORE:
                        raise ValueError(
                            f"plugin {p.name()} score {s} outside "
                            f"[0, {MAX_NODE_SCORE}] for node {node.name}"
                        )
                    extra_score[c, slot] += w * s
