"""Cross-shard occupancy exchange: the compact rows fleet replicas
trade before committing placements, so cross-shard
``PodTopologySpread`` / inter-pod anti-affinity stay enforceable
without a global lock.

A replica's shard-filtered cache (state/cluster.py filtered watch)
deliberately contains ONLY its own nodes and pods — peers' placements
are invisible to it. The exchange is the one channel that crosses the
partition: each replica publishes

- **node rows** — (node, zone) for every node it owns: the domain
  inventory peers need to compute global spread skew (an empty peer
  zone is a min-count domain even though no pod row mentions it);
- **pod rows** — (pod, node, zone, namespace, labels) for every
  *label-bearing* pod it has assumed (``pending``) or bound
  (``committed``) on its shard. Label-free pods can never match a
  spread selector or an (anti-)affinity term, so they stay off the
  wire — that is what keeps the rows compact.

Rows are the host-side mirror of the device-resident
``BatchCarriedUsage`` occupancy carry (solver/exact.py): the same
"placements earlier in flight count against constraints solved later"
idea, stretched across replicas instead of chained sub-batches — and
they ride the same tensorcodec wire framing over the bulk gRPC
boundary (server/bulk.py ``ExchangeOccupancy``).

Concurrency contract: the hub serializes every mutation under one lock
and bumps a monotonically increasing ``version``, and admission is
atomic AT THE HUB for every fleet shape — in-process or cross-process.
``compare_and_stage`` is a fenced compare-and-swap on pending rows:
the replica re-checks its cross-shard constraints host-side against a
peer view taken at version V, then lands the pending row only if the
hub is STILL at V (any interleaved stage/commit/withdraw by a peer
moved it). Two replicas racing a hard-spread placement therefore can
never both land it: the hub serializes the two CAS calls, the first
wins, the second gets a typed ``AdmitConflict`` and re-admits against
the fresh rows (which now include the winner's pending row). The CAS
is *fenced* with the PR 8 token discipline: ``retire`` (a membership
transition declaring the replica dead) revokes its hub write
privilege, so a zombie's CAS — or any other row mutation — rejects
with ``AdmitConflict(fenced=True)`` until the replica re-registers by
wholesale republish (``publish_nodes`` / ``replace_pod_rows``, the
resync path every heal already takes). Cross-process replicas reach
all of this over the bulk service's ``HubOp`` RPC via
``fleet.runtime.RemoteOccupancyExchange``; version conflicts map to
gRPC ABORTED and fenced conflicts to FAILED_PRECONDITION — semantic
rejections the BulkClient never retries (unlike UNAVAILABLE).

Granularity scope note: by default the CAS compares against the ONE
hub-wide version, so any interleaved write — even a row that cannot
touch the admitted pod's spread domain — costs the admit a
re-fetch/re-check round (bounded by FleetRuntime._CAS_ATTEMPTS, then
an ordinary requeue; ``scheduler_fleet_admit_cas_conflict_total`` is
the observability). The fleet backlog drain made that contention
measurable (N replicas' write-behind flushes all bump the one
version), so ``compare_and_stage(domain_scope=True)`` now offers
PER-DOMAIN versioning, keyed on what actually interferes: a
LABEL-FREE row's only cross-replica effect is capacity on its node,
and a node lives in exactly one zone — so label-free rows bump only
their zone's domain version. Label-bearing rows can shift spread
skew / anti-affinity evaluation in EVERY zone (selectors are global),
and membership mutations (publish/replace/retire/handoff) reshape the
domain inventory itself — those bump the hub-wide domain FLOOR.
Fleet-drain ledger mutations bump neither (the ledger touches no
occupancy row), which is precisely the churn the scoped CAS stops
paying for. A domain-scoped CAS conflicts iff
``max(zone_version, floor) > expected_version`` — same typed
rejection, same fence, strictly fewer spurious retries
(``FleetConfig.cas_domain`` opts a replica in).

High availability (hub HA): the hub is no longer necessarily one
process. Every mutation appends a version-keyed entry to an
append-only OP LOG; one or more STANDBY hubs replicate it (snapshot +
log catch-up on join — fleet/ha.py ``StandbyReplicator``) so a standby
holds the same versioned row state, handoff queue, and journal
aggregation deque as the primary. Hubs carry a monotone ``hub_epoch``
granted by a lease (fleet/ha.py ``HubLease``, the LeaderElector
discipline applied per-hub): only the current lease holder is PRIMARY
and may serve the replica-facing surface; a hub whose lease was taken
over (a deposed old primary, or a not-yet-promoted standby) rejects
that surface with the typed ``HubDeposed`` — the PR 8 → PR 11 fencing
ladder extended to the hub tier, so a partitioned old primary can
never accept a CAS the new primary doesn't know about (CAS version
continuity across the epoch boundary is the invariant: the standby
replicated the version counter, so the new primary continues it).
Debug/replication reads (``hub_status`` / ``journal_lines`` /
``ops_since`` / ``snapshot``) stay open on a deposed hub — a
post-mortem needs them — while ``RemoteOccupancyExchange`` verifies
the epoch on every reply is monotone, so a client that has seen the
new primary structurally ignores anything an old one still serves.
"""

from __future__ import annotations

import copy
import threading
from dataclasses import dataclass, replace
from typing import Iterable, Mapping

import numpy as np

from .. import metrics
from . import drain as fleet_drain

PENDING = "pending"
COMMITTED = "committed"


class ExchangeUnreachable(Exception):
    """The occupancy hub cannot be reached from this replica (network
    partition / hub outage). Raised by every hub operation while the
    replica is partitioned; FleetRuntime degrades to its cached peer
    view, whose growing age drives admission conservative
    (fleet/runtime.py occupancy-staleness bounds)."""


class AdmitConflict(Exception):
    """Typed hub-side rejection of a row mutation — the cross-process
    analog of the state service's fenced ``ApiError`` (a flag, not a
    message-prefix contract).

    ``fenced=False``: a ``compare_and_stage`` lost its compare — the
    hub version moved past ``expected_version`` between the caller's
    peer-view fetch and its CAS (a peer landed a row first). The caller
    re-fetches and re-admits; ``version`` carries the hub version at
    rejection time. ``fenced=True``: the caller's hub write privilege
    was revoked by ``retire`` (its membership was declared dead) — no
    mutation lands until it re-registers wholesale via resync.

    This is a SEMANTIC rejection, never a transport failure: over the
    wire it maps to gRPC ABORTED / FAILED_PRECONDITION, which the
    BulkClient deliberately does not retry (a blind retry of a lost
    race would re-land the very write the CAS exists to reject —
    the committing-Solve never-retries rule)."""

    def __init__(
        self, message: str, *, fenced: bool = False,
        version: int | None = None,
    ) -> None:
        self.fenced = fenced
        self.version = version
        super().__init__(message)


class HubDeposed(ExchangeUnreachable):
    """Typed rejection from a hub that does not hold the primary lease
    (a deposed old primary after a failover, or a standby that was
    never promoted): the replica-facing surface — reads and writes
    alike — must come from the CURRENT primary, or staleness bounds
    and the CAS fence both unravel. Over the wire this maps to gRPC
    PERMISSION_DENIED (a status no other hub rejection uses), which
    ``RemoteOccupancyExchange`` treats as "this endpoint is not the
    hub": rotate to the next endpoint, never retry here. For a fleet
    replica a deposed hub is functionally unreachable — hence the
    subclassing, so every PR 8 conservative-degradation handler
    (dirty flag, cached-view aging, staleness bounds) runs unchanged —
    but the process itself is alive: its debug/replication surface
    (hub_status / journal_lines / ops_since / snapshot) still serves,
    and the wire mapping + failover client distinguish it from a dead
    endpoint. Distinct from ``AdmitConflict``, which is a semantic
    answer about one row and is never retried anywhere."""

    def __init__(self, message: str, *, epoch: int = 0, role: str = "") -> None:
        self.epoch = epoch
        self.role = role
        super().__init__(message)


@dataclass(frozen=True)
class NodeRow:
    """Domain-inventory row: one owned node and its zone key."""

    node: str
    zone: str = ""


@dataclass(frozen=True)
class PodRow:
    """One label-bearing placement a replica holds (assumed or
    bound)."""

    pod: str  # ns/name key
    node: str
    zone: str
    namespace: str
    labels: tuple[tuple[str, str], ...]  # sorted items
    state: str = PENDING  # pending | committed

    @staticmethod
    def for_pod(pod, node: str, zone: str, state: str = PENDING) -> "PodRow":
        return PodRow(
            pod=pod.key,
            node=node,
            zone=zone,
            namespace=pod.namespace,
            labels=tuple(sorted(pod.labels.items())),
            state=state,
        )


@dataclass(frozen=True)
class PeerView:
    """One consistent snapshot of every OTHER replica's rows, plus the
    hub version it was taken at — the Conflict-on-stale fence value.
    ``peer_ages`` carries, per peer that has ever published, the
    seconds since its last successful publish at view time: a peer
    partitioned from the hub stops publishing, its age grows, and
    admission against its frozen rows turns conservative once the age
    passes the staleness bound (fleet/runtime.py)."""

    version: int
    node_rows: tuple[NodeRow, ...]
    pod_rows: tuple[PodRow, ...]
    peer_ages: tuple[tuple[str, float], ...] = ()


class OccupancyExchange:
    """The in-process hub (one per fleet; the sim's replicas share it
    directly, cross-process deployments reach it through the bulk
    service's ``ExchangeOccupancy`` RPC). All iteration is sorted so
    any serialized view is deterministic."""

    def __init__(
        self, clock=None, *, hub_id: str = "hub", lease=None,
        oplog_capacity: int = 65_536,
    ) -> None:
        from ..utils.clock import Clock

        self._lock = threading.Lock()
        self._version = 0  # ktpu: replicated
        # -- high availability (hub HA) --
        # identity + lease: a standalone hub (lease=None, every
        # deployment before HA) is permanently primary at epoch 1 —
        # zero behavior change. With a lease (fleet/ha.py HubLease)
        # the hub starts as a STANDBY and only serves the
        # replica-facing surface while it holds the lease; the lease
        # grant IS the monotone hub_epoch.
        self._hub_id = hub_id
        self._lease = lease
        self._epoch = 1 if lease is None else 0
        self._role = "primary" if lease is None else "standby"
        # set at every primary -> deposed transition: a deposed hub
        # must catch up from its successor (note_caught_up) before
        # try_promote will re-grant it — re-promoting with stale state
        # would regress the version counter behind a HIGHER epoch
        self._needs_catchup = False
        # append-only op log (replication): every mutation appends
        # (opseq, version_after, ts, kind, payload); standbys consume
        # via ops_since / snapshot. Bounded: a standby further behind
        # than the retained window re-joins via snapshot.
        from collections import deque as _deque

        self._oplog: _deque = _deque(maxlen=oplog_capacity)
        self._opseq = 0
        # idempotent client flush dedup: replica -> (client id, last
        # applied flush_seq). A retried write-behind flush whose reply
        # was lost after the server-side apply lands exactly once.
        self._flush_seen: dict[str, tuple[str, int]] = {}  # ktpu: replicated
        self.flush_dedup_hits = 0
        # fault seams + failover accounting: set_down models the whole
        # hub process dying (every op from every replica raises
        # ExchangeUnreachable); set_flush_fault injects a reply loss
        # AFTER a server-side apply_ops apply (the double-apply
        # hazard's trigger); deposed_write_rejections counts writes a
        # non-primary hub fenced off (the stale-primary proof the
        # failover sim pins).
        self._down = False
        self._flush_faults = 0
        self.deposed_write_rejections = 0
        # publish timestamps (staleness bounds): replica -> when it
        # last successfully wrote anything to the hub. Off the
        # injectable clock so the sim's virtual timeline covers row
        # aging too.
        self._clock = clock or Clock()
        self._published_at: dict[str, float] = {}
        # replicas currently partitioned from the hub (sim fault seam):
        # every operation FROM a partitioned replica raises
        # ExchangeUnreachable — its writes don't land, its reads fail,
        # and its published_at freezes, which is what peers' staleness
        # bounds key off.
        self._partitioned: set[str] = set()
        # replicas whose hub write privilege is revoked (retire()): the
        # PR 8 fencing-token discipline extended to the hub — a peer
        # observed this replica's lease stale and retired it, so its
        # row mutations must not land until it re-registers by
        # wholesale republish (publish_nodes / replace_pod_rows — the
        # path every heal's forced resync already takes). Reads stay
        # open: a zombie reading rows is harmless, a zombie WRITING
        # rows would distort every peer's admission.
        self._revoked: set[str] = set()
        # metric children resolved once: stage/commit run per placed
        # pod on the scheduler's apply path, and the label lookup is
        # measurable there (ops mirror the metric help string)
        self._m = {
            op: metrics.fleet_occupancy_rows_total.labels(op)
            for op in ("staged", "committed", "withdrawn", "retired",
                       "handoff")
        }
        self._node_rows: dict[str, dict[str, NodeRow]] = {}  # replica -> node -> row; ktpu: replicated
        self._pod_rows: dict[str, dict[str, PodRow]] = {}  # replica -> pod -> row; ktpu: replicated
        # pod handoffs: to-replica -> pod key -> (hop count, journey
        # trace id). A replica whose shard cannot legally host a routed
        # pod (persistent cross-shard conflict) releases it here for
        # the next replica in the pod's rendezvous chain
        # (fleet/runtime.py). The trace id is the PR 3 journey trace
        # threaded ACROSS the handoff: the adopting replica's journal
        # records continue the same trace, so `obs explain --fleet`
        # renders enqueue→handoff→re-admit→bind as ONE trace.
        self._handoffs: dict[str, dict[str, tuple[int, str]]] = {}  # ktpu: replicated
        # append-only journal aggregation surface (the cross-replica
        # obs tentpole): replicas ship bounded decision-journal
        # segments — piggybacked on the existing write-behind flush,
        # no new RPC cadence — and `obs explain --fleet` reads the
        # merged stream. Bounded: a long-lived hub keeps the recent
        # window, not unbounded history (replicas' own sinks are the
        # durable store).
        from collections import deque

        self._journal: deque[str] = deque(maxlen=262_144)  # ktpu: replicated
        # replicas whose solve breaker is open (degraded-mode solve
        # resilience): peers prefer them LAST in rendezvous handoff
        # chains — don't route refugees to a sick replica. The replica
        # keeps serving its own shard (the fallback ladder guarantees
        # forward progress); this flag only shapes cross-shard routing.
        self._degraded: set[str] = set()  # ktpu: replicated
        # fleet backlog drain ledger (fleet/drain.py state dict, None
        # while no drain is active): partitions, granted leases, the
        # done map, and the orphan pool. Replicated as INCREMENTAL
        # "drain" op-log entries replayed through the same pure
        # state-machine functions, so a promoted standby continues the
        # ledger without a gap — a 512k-key ledger must not be
        # re-shipped wholesale per progress report.
        self._drain: dict | None = None  # ktpu: replicated
        # per-domain CAS versions (scope note up top): zone -> hub
        # version at the last label-free row landing in it, plus the
        # hub-wide floor every globally-visible mutation advances.
        # Reset conservatively (floor = version) on snapshot install —
        # a freshly promoted standby starts strict and relaxes as new
        # writes refine the map.
        self._domain_versions: dict[str, int] = {}
        self._domain_floor = 0

    @property
    # ktpu: fence-exempt(down-gated wake-seed read; admission-relevant version reads ride peers_version, which is fenced)
    def version(self) -> int:
        # bookkeeping surface (wake-version seeding, tests), down-
        # gated but deliberately NOT role-fenced: admission-relevant
        # version reads ride peers_version/peers_view, which are.
        # A stale wake seed only delays a conflict-parked wakeup by
        # one poll.
        with self._lock:
            self._check_down_locked()
            return self._version

    @property
    def hub_epoch(self) -> int:
        with self._lock:
            return self._epoch

    @property
    def opseq(self) -> int:
        """Applied op-log cursor (replication bookkeeping)."""
        with self._lock:
            return self._opseq

    @property
    def role(self) -> str:
        with self._lock:
            return self._role

    # -- high availability: lease, roles, op log --

    def try_promote(self, *, allow_stale: bool = False) -> int | None:
        """Attempt to take (or retake) the hub lease. Returns the
        granted epoch, or None while another hub's lease is live. A
        grant past epoch 1 is a FAILOVER — the previous primary was
        deposed — and ticks ``scheduler_hub_failover_total``. The
        standby's replicated state (rows, handoffs, journal, flush
        dedup, and crucially the VERSION counter) is what it starts
        serving from, so CAS version continuity holds across the
        epoch boundary.

        A hub that was DEPOSED refuses to re-promote until it has
        caught up from the hub that superseded it
        (``note_caught_up``, set by StandbyReplicator at lag 0):
        re-acquiring an expired lease at a HIGHER epoch while serving
        PRE-deposition state would regress the version counter and
        hide the interim primary's committed rows behind an epoch the
        clients' monotone check must accept — exactly the continuity
        the fence exists for. ``allow_stale=True`` is the operator
        override for the disaster case (every caught-up hub is gone
        and stale state beats no hub)."""
        if self._lease is None:
            return None  # standalone hub: permanently primary
        with self._lock:
            if (
                self._needs_catchup
                and not allow_stale
                and self._lease.epoch != self._epoch
            ):
                # a SUCCESSOR took the lease past our epoch: our state
                # may have diverged — refuse until the snapshot
                # re-join. (Lease epoch == ours means nobody ever took
                # over — a transient self-expiry with no standby — so
                # there is no successor timeline to diverge from and
                # refusing would wedge the only hub forever.)
                return None
        granted = self._lease.try_acquire(self._hub_id)
        if granted is None:
            return None
        with self._lock:
            if (
                self._needs_catchup
                and not allow_stale
                and granted != self._epoch
            ):
                # raced a successor's expiry: the grant just advanced
                # the epoch past our (possibly stale) state — hand the
                # lease back rather than serve stale rows at an epoch
                # clients must accept
                self._lease.release(self._hub_id)
                return None
            epoch_advanced = granted != self._epoch
            became_primary = self._role != "primary"
            self._epoch = granted
            self._role = "primary"
            self._needs_catchup = False
        if epoch_advanced or became_primary:
            metrics.hub_epoch.set(granted)
        if granted > 1 and epoch_advanced:
            # an actual takeover — NOT the same-holder renewal this
            # method also serves (review-caught: counting renewals
            # made the failover counter grow once per serving-loop
            # tick forever after the first failover)
            metrics.hub_failover_total.inc()
        return granted

    def note_caught_up(self) -> None:
        """Replication reached lag 0 against the current primary
        (StandbyReplicator): a previously-deposed hub is eligible for
        promotion again."""
        with self._lock:
            self._needs_catchup = False

    @property
    def needs_catchup(self) -> bool:
        """True after a deposition, until replication catches up. A
        deposed hub's history may have DIVERGED from its successor's
        (ops it acked that never replicated), and its opseq cursor is
        meaningless against the new timeline — the replicator reads
        this flag and re-joins via FULL SNAPSHOT instead of a log
        suffix, so the successor's state replaces (never merges with)
        the stale one."""
        with self._lock:
            return self._needs_catchup

    def heartbeat(self) -> bool:
        """Primary lease renewal (the hub's liveness loop). A failed
        renewal means the lease moved on — self-depose so the stale
        incarnation fences its own replica-facing surface even before
        any peer tells it anything."""
        if self._lease is None:
            return True
        with self._lock:
            if self._role != "primary":
                return False
        if self._lease.renew(self._hub_id):
            return True
        with self._lock:
            if self._role == "primary":
                self._role = "deposed"
                self._needs_catchup = True
        return False

    def set_down(self, down: bool) -> None:
        """Fault seam: the hub process is gone (crash/kill). EVERY
        operation — any replica, reads and writes, replication —
        raises ExchangeUnreachable until the seam clears. Clearing it
        models the old process resurfacing (partitioned-zombie style:
        alive, lease long lost)."""
        with self._lock:
            self._down = down

    def set_flush_fault(self, count: int = 1) -> None:
        """Fault seam: the next ``count`` apply_ops calls apply fully
        server-side, then raise ExchangeUnreachable — the lost-reply
        window behind the write-behind double-apply hazard. The
        client's retry of the same (client, flush_seq) must dedup."""
        with self._lock:
            self._flush_faults = int(count)

    # callers hold self._lock
    def _check_down_locked(self) -> None:
        if self._down:
            raise ExchangeUnreachable(
                f"occupancy hub {self._hub_id} is down"
            )

    # callers hold self._lock
    # ktpu: fence-check
    def _ensure_primary_locked(self, *, write: bool, op: str) -> None:
        """Role fence for the replica-facing surface: only the live
        lease holder serves it. A primary whose lease silently expired
        (the deposed-zombie case) discovers it here and self-deposes;
        writes it rejected are counted — the failover sim's
        stale-primary-writes-rejected proof."""
        if self._lease is None:
            return
        if self._role == "primary" and not self._lease.valid(self._hub_id):
            self._role = "deposed"
            self._needs_catchup = True
        if self._role != "primary":
            if write:
                self.deposed_write_rejections += 1
            raise HubDeposed(
                f"hub {self._hub_id} is {self._role} at epoch "
                f"{self._epoch}: {op!r} must go to the current primary",
                epoch=self._epoch,
                role=self._role,
            )

    # callers hold self._lock; appends one replication entry. ts rides
    # the entry so a standby's publish stamps replay the PRIMARY's
    # timeline (read-only touches don't replicate — a promoted
    # standby's peer ages then read slightly OLDER than truth, which
    # errs conservative).
    # ktpu: fenced-by-caller
    def _log(self, kind: str, payload: list) -> None:
        self._opseq += 1
        self._oplog.append(
            [self._opseq, self._version, self._clock.now(), kind, payload]
        )

    # -- partition seam (hub reachability, per replica) --

    def set_partitioned(self, replica: str, partitioned: bool) -> None:
        """Sim/fault seam: model ``replica`` losing (or regaining) its
        network path to the hub. While partitioned, every hub operation
        from that replica raises ExchangeUnreachable."""
        with self._lock:
            if partitioned:
                self._partitioned.add(replica)
            else:
                self._partitioned.discard(replica)

    def _check_reachable(self, replica: str) -> None:
        # callers hold self._lock or tolerate the benign race (the
        # partition flag only ever flips between whole sim cycles)
        self._check_down_locked()
        if replica in self._partitioned:
            raise ExchangeUnreachable(
                f"replica {replica} is partitioned from the occupancy hub"
            )

    # ktpu: fenced-by-caller
    def _check_write_fence(self, replica: str) -> None:
        # callers hold self._lock
        if replica in self._revoked:
            raise AdmitConflict(
                f"replica {replica} was retired at the hub (membership "
                "declared it dead): row mutations are fenced until it "
                "re-registers by wholesale republish",
                fenced=True,
                version=self._version,
            )

    def _touch(self, replica: str) -> None:
        """Refresh ``replica``'s liveness stamp. Rows are maintained
        incrementally (every change stages/commits/withdraws
        immediately), so between changes no-news-is-good-news AS LONG
        AS the replica can still reach the hub: any successful
        reachability-gated operation — reads included — proves its
        rows are current and refreshes the stamp. Without the
        read-side touch, a healthy but IDLE peer (no pod churn) would
        age past max_row_age_s and starve every cross-shard-
        constrained pod fleet-wide (review-caught)."""
        self._published_at[replica] = self._clock.now()

    # callers hold self._lock and have ALREADY bumped self._version for
    # the mutation being recorded (domain versions store the post-bump
    # value — the version a domain-scoped CAS must not be older than).
    # Scope rule from the module docstring: a label-free row's only
    # cross-replica effect is capacity on its node, and a node lives in
    # one zone — zone-local; a label-bearing row can shift spread/anti
    # evaluation in every zone — hub-wide floor.
    # ktpu: fenced-by-caller
    def _bump_domain_row_locked(self, row: PodRow) -> None:
        if row.labels:
            self._domain_floor = self._version
        else:
            self._domain_versions[row.zone] = self._version

    # callers hold self._lock, post-bump (see above): membership-shaped
    # mutations (publish/replace/retire/handoff/claim/degraded) change
    # what EVERY domain's admission can see
    # ktpu: fenced-by-caller
    def _bump_domain_floor_locked(self) -> None:
        self._domain_floor = self._version

    def peers_version(self, replica: str) -> int:
        """The hub version as seen from ``replica`` (reachability-
        gated, unlike the raw ``version`` property)."""
        with self._lock:
            self._check_reachable(replica)
            self._ensure_primary_locked(write=False, op="peers_version")
            self._touch(replica)
            return self._version

    # -- publishing --

    def publish_nodes(self, replica: str, rows: Iterable[NodeRow]) -> None:
        """Replace ``replica``'s domain inventory (called at startup
        and on every resync — the owned set is replaced wholesale, not
        diffed, so a missed event can never leave a stale row). A
        wholesale republish is the replica re-asserting itself from
        cluster truth, so it also clears a hub write fence (the healed
        zombie's forced resync routes here)."""
        with self._lock:
            self._check_reachable(replica)
            self._ensure_primary_locked(write=True, op="publish_nodes")
            self._revoked.discard(replica)
            self._version += 1
            self._bump_domain_floor_locked()
            self._node_rows[replica] = {r.node: r for r in rows}
            self._touch(replica)
            self._log(
                "nodes",
                [replica, [[r.node, r.zone] for r in self._node_rows[replica].values()]],
            )

    def stage(self, replica: str, row: PodRow) -> None:
        with self._lock:
            self._check_reachable(replica)
            self._ensure_primary_locked(write=True, op="stage")
            self._check_write_fence(replica)
            self._stage_locked(replica, row)
        self._m["staged"].inc()

    # callers hold self._lock and have run the reachability/role/fence
    # checks (stage, compare_and_stage, apply_ops share this effect)
    # ktpu: fenced-by-caller
    def _stage_locked(self, replica: str, row: PodRow) -> None:
        self._version += 1
        self._bump_domain_row_locked(row)
        self._pod_rows.setdefault(replica, {})[row.pod] = row
        self._touch(replica)
        self._log("row", [replica, pod_row_to_list(row)])

    def compare_and_stage(
        self, replica: str, row: PodRow, expected_version: int,
        *, domain_scope: bool = False,
    ) -> int:
        """Cross-process atomic admit: land ``row`` as pending ONLY if
        the hub is still at ``expected_version`` — the version the
        caller's host-side constraint recheck ran against. Any
        interleaved mutation (a peer's stage/commit/withdraw, a
        handoff, a membership retire) moved the version, so the
        caller's view may hide a racing placement: reject with a typed
        ``AdmitConflict`` and let the caller re-fetch + re-admit.
        Returns the new hub version on success. Fenced (retired)
        replicas reject regardless of version.

        ``domain_scope=True`` narrows the compare to the row's DOMAIN
        (module-docstring scope note): conflict iff a write that could
        actually interfere — a row in the same zone, any label-bearing
        row, any membership mutation — landed past ``expected_version``.
        Interleaved writes that provably cannot touch this row's
        admission (label-free rows in OTHER zones, fleet-drain ledger
        mutations) no longer cost the caller a re-fetch round. The
        caller still passes the same fetched view version either way —
        opting in changes only which interleavings reject."""
        with self._lock:
            self._check_reachable(replica)
            self._ensure_primary_locked(write=True, op="cas_stage")
            self._check_write_fence(replica)
            if domain_scope:
                effective = max(
                    self._domain_versions.get(row.zone, 0),
                    self._domain_floor,
                )
                conflict = effective > expected_version
            else:
                effective = self._version
                conflict = self._version != expected_version
            if conflict:
                raise AdmitConflict(
                    f"hub version moved to {effective} past the "
                    f"admitted view at {expected_version}: a peer's row "
                    "landed first — re-fetch and re-admit",
                    version=self._version,
                )
            self._stage_locked(replica, row)
            version = self._version
        self._m["staged"].inc()
        return version

    def replace_pod_rows(self, replica: str, rows: Iterable[PodRow]) -> None:
        """Replace ``replica``'s pod rows wholesale (resync): rows are
        rebuilt from cluster truth whenever the partition moves, so a
        pod whose DELETE the shard filter later hides from this
        replica can never leave a ghost row behind. Clears a hub write
        fence like publish_nodes (same re-registration argument)."""
        with self._lock:
            self._check_reachable(replica)
            self._ensure_primary_locked(write=True, op="replace_pod_rows")
            self._revoked.discard(replica)
            self._version += 1
            self._bump_domain_floor_locked()
            self._pod_rows[replica] = {r.pod: r for r in rows}
            self._touch(replica)
            self._log(
                "rows",
                [replica, [pod_row_to_list(r) for r in self._pod_rows[replica].values()]],
            )

    def commit(self, replica: str, pod_key: str) -> None:
        with self._lock:
            self._check_reachable(replica)
            self._ensure_primary_locked(write=True, op="commit")
            self._check_write_fence(replica)
            if not self._commit_locked(replica, pod_key):
                return
        self._m["committed"].inc()

    # callers hold self._lock post-checks; True if the row transitioned
    # ktpu: fenced-by-caller
    def _commit_locked(self, replica: str, pod_key: str) -> bool:
        row = self._pod_rows.get(replica, {}).get(pod_key)
        if row is None or row.state == COMMITTED:
            return False
        self._version += 1
        self._bump_domain_row_locked(row)
        committed = replace(row, state=COMMITTED)
        self._pod_rows[replica][pod_key] = committed
        self._touch(replica)
        self._log("row", [replica, pod_row_to_list(committed)])
        return True

    def withdraw(self, replica: str, pod_key: str) -> None:
        with self._lock:
            self._check_reachable(replica)
            self._ensure_primary_locked(write=True, op="withdraw")
            # fenced like every other mutation: today a retired
            # replica's rows are already dropped (nil data effect),
            # but an asymmetric escape hatch is one refactor away from
            # a zombie deleting a live row (review-caught)
            self._check_write_fence(replica)
            if not self._withdraw_locked(replica, pod_key):
                return
        self._m["withdrawn"].inc()

    # callers hold self._lock post-checks; True if a row was removed
    # ktpu: fenced-by-caller
    def _withdraw_locked(self, replica: str, pod_key: str) -> bool:
        row = self._pod_rows.get(replica, {}).pop(pod_key, None)
        if row is None:
            return False
        self._version += 1
        self._bump_domain_row_locked(row)
        self._touch(replica)
        self._log("row_del", [replica, pod_key])
        return True

    def retire(self, replica: str) -> None:
        """Drop a dead replica's rows: its committed placements become
        visible to the adopting replica through its own resync re-list,
        so keeping them here would double-count. Unclaimed handoffs
        addressed to it revert to plain hash routing — the new route
        owner adopts the pod at its membership-change resync. Also
        REVOKES the replica's hub write privilege (the fencing-token
        discipline): if it is actually a zombie, its next row mutation
        (stage / CAS / commit / withdraw / handoff / degraded-flag)
        rejects with a typed fenced AdmitConflict until its healed
        incarnation re-registers wholesale."""
        with self._lock:
            self._check_down_locked()
            self._ensure_primary_locked(write=True, op="retire")
            self._revoked.add(replica)
            had = (
                bool(self._node_rows.pop(replica, None))
                | bool(self._pod_rows.pop(replica, None))
                | bool(self._handoffs.pop(replica, None))
            )
            self._degraded.discard(replica)
            # a retired replica's frozen publish stamp must not keep
            # peers' staleness bounds conservative forever
            self._published_at.pop(replica, None)
            if had:
                self._version += 1
                self._bump_domain_floor_locked()
            self._log("retire", [replica])
            # a dead replica's drain lease returns for reassignment:
            # outstanding keys (and an unclaimed base partition) become
            # orphans the next claimant adopts — no backlog pod is lost
            # to a mid-drain death. Rides retire so every death path
            # (membership change, sim kill, operator) returns it.
            if self._drain is not None:
                if fleet_drain.return_leases(self._drain, replica):
                    self._version += 1
                self._log("drain", ["return", replica])
        self._m["retired"].inc()

    # -- degraded flags (solve-resilience breaker state) --

    def set_degraded(self, replica: str, degraded: bool) -> None:
        """Publish/clear a replica's degraded flag (its solve circuit
        breaker tripped / re-closed). Bumps the version so peers'
        conflict-parked pods re-evaluate their handoff chains."""
        with self._lock:
            self._check_reachable(replica)
            self._ensure_primary_locked(write=True, op="set_degraded")
            self._check_write_fence(replica)
            if degraded == (replica in self._degraded):
                return
            if degraded:
                self._degraded.add(replica)
            else:
                self._degraded.discard(replica)
            self._version += 1
            self._bump_domain_floor_locked()
            self._touch(replica)
            self._log("degraded", [replica, bool(degraded)])

    def degraded_replicas(self) -> frozenset:
        # replica-facing like peers_view (maybe_hand_off orders the
        # fleet-wide handoff chain by these flags): a deposed hub's
        # frozen flags must not route refugees toward a peer whose
        # breaker opened during the blackout (review-caught)
        with self._lock:
            self._check_down_locked()
            self._ensure_primary_locked(write=False, op="degraded_replicas")
            return frozenset(self._degraded)

    # -- journal aggregation (obs explain --fleet's hub surface) --

    def ship_journal(self, replica: str, lines) -> None:
        """Append a replica's journal segment to the aggregation
        surface. Reachability-gated (a partitioned replica's segment
        waits out the partition with its buffered rows) but NOT
        write-fenced: journal lines are append-only observability of
        decisions that already happened — a fenced zombie's history is
        exactly what a post-mortem needs to see."""
        lines = list(lines)
        if not lines:
            return
        with self._lock:
            self._check_reachable(replica)
            self._ensure_primary_locked(write=True, op="ship_journal")
            self._touch(replica)
            self._journal.extend(lines)
            self._log("journal", [replica, lines])
        metrics.fleet_journal_segments_total.inc()
        metrics.fleet_journal_lines_total.inc(len(lines))

    # ktpu: fence-exempt(down-gated observability read; a standby's merged journal is exactly what obs explain --fleet wants)
    def journal_lines(self) -> list[str]:
        """The aggregated journal stream, in arrival order. `obs
        explain --fleet` re-orders per pod with the PR 8 merge rules,
        so arrival order only needs to be deterministic, not sorted.
        Down-gated (a dead hub answers nothing); ``debug_state`` is
        the harness's bypass."""
        with self._lock:
            self._check_down_locked()
            return list(self._journal)

    # -- pod handoffs --

    def hand_off(
        self, to_replica: str, pod_key: str, hops: int,
        from_replica: str | None = None,
        trace: str = "",
    ) -> None:
        with self._lock:
            self._check_down_locked()
            self._ensure_primary_locked(write=True, op="hand_off")
            if from_replica is not None:
                self._check_reachable(from_replica)
                self._check_write_fence(from_replica)
                self._touch(from_replica)
            self._version += 1
            self._bump_domain_floor_locked()
            self._handoffs.setdefault(to_replica, {})[pod_key] = (
                hops, trace,
            )
            self._log("handoff", [to_replica, pod_key, hops, trace])
        self._m["handoff"].inc()

    def claim_handoffs(self, replica: str) -> list[tuple[str, int, str]]:
        """Pop every handoff addressed to ``replica`` (sorted, so
        claim order is deterministic). Each claim is (pod key, hops,
        journey trace id) — the trace rode the handoff row so the
        adopting replica's journal continues the SAME trace."""
        with self._lock:
            self._check_reachable(replica)
            self._ensure_primary_locked(write=True, op="claim_handoffs")
            self._touch(replica)  # liveness: the poll proves contact
            rows = self._handoffs.pop(replica, None)
            if not rows:
                return []
            self._version += 1
            self._bump_domain_floor_locked()
            self._log("claim", [replica])
            return [
                (k, hops, trace)
                for k, (hops, trace) in sorted(rows.items())
            ]

    # ktpu: fence-exempt(down-gated sim-invariant surface; reads on a standby are harmless and never on the wire)
    def pending_handoff_keys(self) -> set[str]:
        """Pods released by one replica and not yet claimed by the
        next — the fleet lost-pod invariant counts these as tracked.
        Down-gated like every other op (set_down models the whole
        process dying — a dead hub answers nothing); the sim harness
        introspects a downed hub via ``debug_state`` instead."""
        with self._lock:
            self._check_down_locked()
            return {
                k for rows in self._handoffs.values() for k in rows
            }

    # -- fleet backlog drain (the fleet/drain.py ledger, hub-hosted) --

    def drain_init(
        self, replica: str, partitions: Mapping, residual,
        *, membership_version: int = 0,
    ) -> dict:
        """Install a fresh drain ledger: the coordinator (whoever
        hosts the hub primary) ran the global relax plan, partitioned
        the backlog by planned-node shard ownership, and registers the
        result here. Epoch-fenced like every hub write — a deposed
        coordinator's plan never lands — and rejected while a previous
        drain still has outstanding work (two concurrent global plans
        would hand the same pod to two leases)."""
        partitions = {
            str(r): [str(k) for k in ks]
            for r, ks in partitions.items()
        }
        residual = [str(k) for k in residual]
        with self._lock:
            self._check_reachable(replica)
            self._ensure_primary_locked(write=True, op="drain_init")
            self._check_write_fence(replica)
            if (
                self._drain is not None
                and not fleet_drain.status(self._drain)["complete"]
            ):
                raise AdmitConflict(
                    "a fleet backlog drain is already in progress: "
                    "its ledger must drain dry before a new global "
                    "plan may land",
                    version=self._version,
                )
            self._drain = fleet_drain.new_state(
                partitions, residual,
                epoch=self._epoch,
                membership_version=int(membership_version),
            )
            self._version += 1
            self._touch(replica)
            self._log(
                "drain",
                ["init", partitions, residual, self._epoch,
                 int(membership_version)],
            )
            st = fleet_drain.status(self._drain)
        metrics.fleet_drain_partitions.set(st["partitions"])
        metrics.fleet_drain_residual_pods.set(st["residual"])
        return st

    def drain_claim(self, replica: str) -> dict | None:
        """Grant ``replica`` its next drain lease (fleet/drain.py
        claim order: its own partition, then orphaned work, then the
        serialized residual cohort). Idempotent — a retried claim
        re-serves the granted lease verbatim. Returns None when no
        work is claimable (the replica polls again next cycle)."""
        with self._lock:
            self._check_reachable(replica)
            self._ensure_primary_locked(write=True, op="drain_claim")
            self._check_write_fence(replica)
            # liveness: the claim poll proves contact either way
            self._touch(replica)
            if self._drain is None:
                return None
            lease, reassigned = fleet_drain.claim(self._drain, replica)
            if lease is None:
                return None
            self._version += 1
            self._log("drain", ["claim", replica])
        if reassigned:
            metrics.fleet_drain_lease_reassignments_total.inc()
        return lease

    def drain_progress(self, replica: str, keys) -> int:
        """Record pods ``replica`` drained under its lease (one report
        per applied chunk). Doubles as the replica's LIVENESS refresh:
        a long chunk keeps writing nothing else to the hub, and
        without the touch here its publish stamp would age past
        ``max_row_age_s`` mid-drain and flip every peer's constrained
        admission conservative (the staleness interaction the drain
        tentpole must not regress)."""
        keys = [str(k) for k in keys]
        with self._lock:
            self._check_reachable(replica)
            self._ensure_primary_locked(write=True, op="drain_progress")
            self._check_write_fence(replica)
            self._touch(replica)
            if self._drain is None:
                return 0
            n = fleet_drain.progress(self._drain, replica, keys)
            if n:
                self._version += 1
                self._log("drain", ["progress", replica, keys])
        return n

    def drain_complete(self, replica: str, lease_id: str) -> bool:
        """Mark ``replica``'s granted lease done (its partition slice
        fully drained through its slot ring)."""
        with self._lock:
            self._check_reachable(replica)
            self._ensure_primary_locked(write=True, op="drain_complete")
            self._check_write_fence(replica)
            self._touch(replica)
            if self._drain is None:
                return False
            ok = fleet_drain.complete(
                self._drain, replica, str(lease_id)
            )
            if ok:
                self._version += 1
                self._log(
                    "drain", ["complete", replica, str(lease_id)]
                )
        return ok

    # ktpu: fence-exempt(down-gated observability read: footers, metrics, the sim's ledger introspection)
    def drain_status(self) -> dict:
        """Counts-only ledger summary (``active=False`` while no drain
        ledger is installed). Down-gated like every read; served by
        standbys too — 'how far did the drain get' is a post-failover
        question."""
        with self._lock:
            self._check_down_locked()
            if self._drain is None:
                return {"active": False}
            return dict(fleet_drain.status(self._drain), active=True)

    # ktpu: fence-exempt(down-gated sim-invariant surface, like pending_handoff_keys)
    def drain_outstanding_keys(self) -> list:
        """Backlog keys not yet drained — the fleet lost-pod invariant
        counts these as hub-tracked (mid-reassignment they sit in no
        replica's queue, exactly like an unclaimed handoff)."""
        with self._lock:
            self._check_down_locked()
            if self._drain is None:
                return []
            return fleet_drain.outstanding_keys(self._drain)

    # callers hold self._lock (apply_replicated): replay one "drain"
    # op-log entry through the SAME pure state-machine functions the
    # primary ran, so the standby's ledger is bit-identical without
    # ever shipping the 512k-key state wholesale
    # ktpu: fence-exempt(standby log replay: the replication apply path MUST write while not primary — fencing it would invert HA)
    def _apply_drain_locked(self, payload) -> None:
        sub = payload[0]
        if sub == "init":
            _sub, partitions, residual, epoch, mv = payload
            self._drain = fleet_drain.new_state(
                partitions, residual,
                epoch=int(epoch), membership_version=int(mv),
            )
            return
        if self._drain is None:
            return
        if sub == "claim":
            fleet_drain.claim(self._drain, payload[1])
        elif sub == "progress":
            fleet_drain.progress(self._drain, payload[1], payload[2])
        elif sub == "complete":
            fleet_drain.complete(self._drain, payload[1], payload[2])
        elif sub == "return":
            fleet_drain.return_leases(self._drain, payload[1])

    # ktpu: fence-exempt(post-mortem bypass: reading a dead process's last state; dispatch_hub_op never exposes it)
    def debug_state(self) -> dict:
        """Harness/post-mortem introspection that deliberately
        bypasses the down seam (reading a dead process's LAST state is
        what a post-mortem of its persisted image would do): pending
        handoff keys + journal lines. Never served over the wire —
        dispatch_hub_op does not expose it."""
        with self._lock:
            return {
                "pending_handoffs": {
                    k for rows in self._handoffs.values() for k in rows
                },
                "journal": list(self._journal),
                "degraded": sorted(self._degraded),
                "version": self._version,
                "opseq": self._opseq,
                "drain": copy.deepcopy(self._drain),
            }

    # -- reading --

    def peers_view(self, replica: str) -> PeerView:
        with self._lock:
            self._check_reachable(replica)
            self._ensure_primary_locked(write=False, op="peers_view")
            self._touch(replica)  # liveness: the fetch proves contact
            node_rows = tuple(
                self._node_rows[r][n]
                for r in sorted(self._node_rows)
                if r != replica
                for n in sorted(self._node_rows[r])
            )
            pod_rows = tuple(
                self._pod_rows[r][p]
                for r in sorted(self._pod_rows)
                if r != replica
                for p in sorted(self._pod_rows[r])
            )
            now = self._clock.now()
            peer_ages = tuple(
                (r, max(now - self._published_at[r], 0.0))
                for r in sorted(self._published_at)
                if r != replica
            )
            return PeerView(self._version, node_rows, pod_rows, peer_ages)

    # ktpu: fence-exempt(replication-verification surface: standbys and tests compare raw rows across roles; down-gated, never on the wire)
    def replica_rows(self, replica: str) -> tuple[tuple[NodeRow, ...], tuple[PodRow, ...]]:
        """Raw row export for one replica (replication verification:
        standby-vs-primary comparisons in the HA tests and sims).
        Down-gated like every read — a dead hub answers nothing;
        ``debug_state`` is the deliberate bypass."""
        with self._lock:
            self._check_down_locked()
            return (
                tuple(
                    self._node_rows.get(replica, {})[n]
                    for n in sorted(self._node_rows.get(replica, {}))
                ),
                tuple(
                    self._pod_rows.get(replica, {})[p]
                    for p in sorted(self._pod_rows.get(replica, {}))
                ),
            )

    # -- idempotent write-behind flush (the apply_ops surface) --

    _FLUSH_OP_KINDS = frozenset({"stage", "commit", "withdraw", "journal"})

    def apply_ops(
        self, replica: str, ops: list, *,
        flush_seq: int | None = None, flush_client: str = "",
    ) -> dict:
        """One write-behind flush (RemoteOccupancyExchange) applied
        ATOMICALLY under the hub lock: journal lines land first
        (append-only observability, deliberately not fence-gated — a
        fenced zombie's history is what the post-mortem needs), then
        the buffered stage/commit/withdraw row mutations, fence-
        checked as a unit.

        IDEMPOTENT on ``(replica, flush_client, flush_seq)``: the
        client seals each flush batch with a monotone sequence before
        sending, and a batch whose reply was lost AFTER the
        server-side apply (UNAVAILABLE on the wire) is retried with
        the SAME key — the hub recognizes it and drops the retry
        whole, so rows are never double-staged and journal lines never
        double-append (the latent hazard this closes: the old path
        re-landed the entire buffer). ``flush_client`` scopes the
        sequence to one client incarnation, so a restarted replica
        starting back at seq 0 is never mistaken for a stale retry.
        The dedup watermark is itself replicated (a ``flush_seen`` op
        log entry), so a retry that lands on the PROMOTED standby
        after a failover still dedups. ``flush_seq=None`` (a caller
        predating the sealed-batch client) applies without dedup —
        rows are idempotent upserts either way."""
        for kind, _arg in ops:
            if kind not in self._FLUSH_OP_KINDS:
                # validate BEFORE any effect: a partial apply that
                # died on a bogus kind would double-append its journal
                # lines on retry (the seen watermark is only recorded
                # for fully-applied batches)
                raise ValueError(f"unknown apply_ops kind {kind!r}")
        counts = {"staged": 0, "committed": 0, "withdrawn": 0}
        fenced = False
        flush_fault = False
        journal_landed = 0
        with self._lock:
            self._check_reachable(replica)
            self._ensure_primary_locked(write=True, op="apply_ops")
            if flush_seq is not None:
                seen_client, seen_seq = self._flush_seen.get(
                    replica, ("", -1)
                )
                if flush_client == seen_client and int(flush_seq) <= seen_seq:
                    self.flush_dedup_hits += 1
                    metrics.fleet_flush_dedup_total.inc()
                    return {"deduped": True}
            journal = [arg for kind, arg in ops if kind == "journal"]
            if journal:
                self._journal.extend(journal)
                self._log("journal", [replica, list(journal)])
            fenced = replica in self._revoked
            if not fenced:
                for kind, arg in ops:
                    if kind == "stage":
                        self._stage_locked(replica, pod_row_from_list(arg))
                        counts["staged"] += 1
                    elif kind == "commit":
                        counts["committed"] += self._commit_locked(
                            replica, arg
                        )
                    elif kind == "withdraw":
                        counts["withdrawn"] += self._withdraw_locked(
                            replica, arg
                        )
            if flush_seq is not None:
                self._flush_seen[replica] = (flush_client, int(flush_seq))
                self._log(
                    "flush_seen", [replica, flush_client, int(flush_seq)]
                )
            journal_landed = len(journal)
            if self._flush_faults > 0:
                self._flush_faults -= 1
                flush_fault = True
        for op_name, n in counts.items():
            if n:
                self._m[op_name].inc(n)
        if journal_landed:
            metrics.fleet_journal_segments_total.inc()
            metrics.fleet_journal_lines_total.inc(journal_landed)
        if fenced:
            raise AdmitConflict(
                f"replica {replica} is fenced at the hub: the flush's "
                "row mutations were dropped (its journal lines landed "
                "— append-only history is not fence-gated)",
                fenced=True,
            )
        if flush_fault:
            raise ExchangeUnreachable(
                "injected reply loss AFTER the server-side apply "
                "(set_flush_fault seam): the client must retry this "
                "flush under the same (client, seq) key and the hub "
                "must dedup it"
            )
        return {"applied": counts, "journal": journal_landed}

    # -- replication surface (standby catch-up; fleet/ha.py) --

    def ops_since(self, since: int):
        """Op-log entries past ``since``, plus the latest opseq.
        Returns ``(None, latest)`` when ``since`` predates the
        retained window — the standby must re-join via snapshot.
        Served regardless of role (a deposed primary can still be
        caught up FROM; replication is not the replica-facing
        surface), but not while down."""
        with self._lock:
            self._check_down_locked()
            latest = self._opseq
            if since >= latest:
                return [], latest
            floor = self._oplog[0][0] if self._oplog else self._opseq + 1
            if since < floor - 1:
                return None, latest
            return [list(e) for e in self._oplog if e[0] > since], latest

    # ktpu: fence-exempt(replication pull path: a standby joining MUST read the primary's state; down-gated)
    def snapshot(self) -> dict:
        """Full JSON-able state export for standby join (and the wire
        half of repl_sync when the log window has moved past the
        standby's cursor)."""
        with self._lock:
            self._check_down_locked()
            return {
                "opseq": self._opseq,
                "version": self._version,
                "nodes": {
                    r: [[n.node, n.zone] for _k, n in sorted(rows.items())]
                    for r, rows in self._node_rows.items()
                },
                "pods": {
                    r: [pod_row_to_list(p) for _k, p in sorted(rows.items())]
                    for r, rows in self._pod_rows.items()
                },
                "handoffs": {
                    to: [[k, h, t] for k, (h, t) in sorted(rows.items())]
                    for to, rows in self._handoffs.items()
                },
                "degraded": sorted(self._degraded),
                "revoked": sorted(self._revoked),
                "publishedAt": dict(self._published_at),
                "journal": list(self._journal),
                "flushSeen": {
                    r: [c, s] for r, (c, s) in self._flush_seen.items()
                },
                "drain": copy.deepcopy(self._drain),
            }

    # ktpu: fence-exempt(standby join: the replication apply path MUST write while not primary — fencing it would invert HA)
    def install_snapshot(self, snap: dict) -> None:
        """Replace this hub's replicated state wholesale (standby
        join). Role/epoch/lease are NOT part of the snapshot — a
        standby stays a standby until its own lease grant promotes
        it."""
        with self._lock:
            self._opseq = int(snap.get("opseq") or 0)
            self._version = int(snap.get("version") or 0)
            self._node_rows = {
                r: {n: NodeRow(node=n, zone=z) for n, z in rows}
                for r, rows in (snap.get("nodes") or {}).items()
            }
            self._pod_rows = {
                r: {
                    row.pod: row
                    for row in (pod_row_from_list(v) for v in rows)
                }
                for r, rows in (snap.get("pods") or {}).items()
            }
            self._handoffs = {
                to: {k: (int(h), str(t)) for k, h, t in rows}
                for to, rows in (snap.get("handoffs") or {}).items()
            }
            self._degraded = set(snap.get("degraded") or ())
            self._revoked = set(snap.get("revoked") or ())
            self._published_at = {
                r: float(t)
                for r, t in (snap.get("publishedAt") or {}).items()
            }
            self._journal.clear()
            self._journal.extend(snap.get("journal") or ())
            self._flush_seen = {
                r: (str(c), int(s))
                for r, (c, s) in (snap.get("flushSeen") or {}).items()
            }
            self._drain = copy.deepcopy(snap.get("drain"))
            # domain versions restart conservative: floor at the
            # installed version means a domain-scoped CAS behaves
            # hub-wide until new writes refine the per-zone map —
            # strictly MORE conflicts, never a missed one
            self._domain_versions = {}
            self._domain_floor = self._version
            self._oplog.clear()

    # ktpu: fence-exempt(standby log replay: the replication apply path MUST write while not primary — fencing it would invert HA)
    def apply_replicated(self, entry) -> None:
        """Apply one op-log entry on a STANDBY: raw state effects,
        version-keyed — no reachability/fence/role checks (those ran
        at the primary when the op first landed) and no metric ticks
        (the op was already counted where it happened). The entry is
        re-appended to this hub's own log so a healed old primary can
        later catch up FROM the promoted standby. Entries at or below
        the applied cursor are ignored (catch-up windows overlap
        harmlessly)."""
        opseq, version, ts, kind, payload = entry
        with self._lock:
            if opseq <= self._opseq:
                return
            if kind == "nodes":
                r, rows = payload
                self._node_rows[r] = {
                    n: NodeRow(node=n, zone=z) for n, z in rows
                }
                self._revoked.discard(r)
                self._published_at[r] = ts
            elif kind == "row":
                r, rowlist = payload
                row = pod_row_from_list(rowlist)
                self._pod_rows.setdefault(r, {})[row.pod] = row
                self._published_at[r] = ts
            elif kind == "rows":
                r, rows = payload
                self._pod_rows[r] = {
                    row.pod: row
                    for row in (pod_row_from_list(v) for v in rows)
                }
                self._revoked.discard(r)
                self._published_at[r] = ts
            elif kind == "row_del":
                r, pod_key = payload
                self._pod_rows.get(r, {}).pop(pod_key, None)
                self._published_at[r] = ts
            elif kind == "retire":
                (r,) = payload
                self._revoked.add(r)
                self._node_rows.pop(r, None)
                self._pod_rows.pop(r, None)
                self._handoffs.pop(r, None)
                self._degraded.discard(r)
                self._published_at.pop(r, None)
            elif kind == "degraded":
                r, flag = payload
                if flag:
                    self._degraded.add(r)
                else:
                    self._degraded.discard(r)
            elif kind == "journal":
                _r, lines = payload
                self._journal.extend(lines)
            elif kind == "handoff":
                to, pod_key, hops, trace = payload
                self._handoffs.setdefault(to, {})[pod_key] = (
                    int(hops), str(trace),
                )
            elif kind == "claim":
                (r,) = payload
                self._handoffs.pop(r, None)
                self._published_at[r] = ts
            elif kind == "flush_seen":
                r, client, seq = payload
                self._flush_seen[r] = (str(client), int(seq))
            elif kind == "drain":
                self._apply_drain_locked(payload)
            # unknown kinds are skipped (forward compatibility), but
            # the cursor still advances — the primary wrote them
            self._opseq = opseq
            self._version = version
            # replayed mutations refine the standby's domain map with
            # the same scope rule the primary applied ("row" entries
            # land in _pod_rows above; everything else that moved the
            # version is membership-shaped or ledger churn)
            if kind == "row":
                r, rowlist = payload
                self._bump_domain_row_locked(pod_row_from_list(rowlist))
            elif kind == "drain":
                pass  # ledger churn bumps no domain (the whole point)
            else:
                self._domain_floor = version
            self._oplog.append(list(entry))

    # ktpu: fence-exempt(down-gated observability read; role/epoch are part of the PAYLOAD here, not a gate)
    def hub_status(self) -> dict:
        """The ``GET /debug/hub`` body (and the failover sim's
        introspection): role, epoch, replicated-state cursors, and
        the HA counters. Deliberately served by standbys and deposed
        primaries alike — 'who do you think you are' is exactly the
        question an operator asks a suspect hub."""
        with self._lock:
            self._check_down_locked()
            return {
                "hub": self._hub_id,
                "role": self._role,
                "epoch": self._epoch,
                "needs_catchup": self._needs_catchup,
                "version": self._version,
                "opseq": self._opseq,
                "replicas": sorted(self._published_at),
                "pod_rows": sum(len(v) for v in self._pod_rows.values()),
                "pending_handoffs": sum(
                    len(v) for v in self._handoffs.values()
                ),
                "journal_lines": len(self._journal),
                "flush_dedup_hits": self.flush_dedup_hits,
                "deposed_write_rejections": self.deposed_write_rejections,
                "drain": (
                    fleet_drain.status(self._drain)
                    if self._drain is not None
                    else None
                ),
            }


# -- wire framing (server/tensorcodec.py, the BatchCarriedUsage wire) --


def pod_row_to_list(r: PodRow) -> list:
    """JSON-meta shape of one pod row for the HubOp RPC (state rides
    inline — single-row ops don't need the columnar committed array
    the bulk ExchangeOccupancy payload uses)."""
    return [
        r.pod, r.node, r.zone, r.namespace,
        [list(kv) for kv in r.labels], r.state,
    ]


def pod_row_from_list(v) -> PodRow:
    pod, node, zone, ns, labels, state = v
    return PodRow(
        pod=pod, node=node, zone=zone, namespace=ns,
        labels=tuple((k, val) for k, val in labels), state=state,
    )


def encode_rows(
    replica: str,
    version: int,
    node_rows: Iterable[NodeRow],
    pod_rows: Iterable[PodRow],
) -> bytes:
    """One occupancy payload: row identities/labels in the JSON meta,
    the numeric columns (pending/committed flags) as wire arrays —
    the same meta + column framing the bulk solve path uses."""
    from ..server import tensorcodec

    node_rows = list(node_rows)
    pod_rows = list(pod_rows)
    meta = {
        "replica": replica,
        "version": int(version),
        "nodes": [[r.node, r.zone] for r in node_rows],
        "pods": [
            [r.pod, r.node, r.zone, r.namespace, [list(kv) for kv in r.labels]]
            for r in pod_rows
        ],
    }
    committed = np.fromiter(
        (1 if r.state == COMMITTED else 0 for r in pod_rows),
        dtype=np.int8,
        count=len(pod_rows),
    )
    return tensorcodec.encode(meta, {"committed": committed})


def decode_rows(
    data: bytes,
) -> tuple[str, int, list[NodeRow], list[PodRow]]:
    from ..server import tensorcodec

    meta, arrays = tensorcodec.decode(data)
    node_rows = [NodeRow(node=n, zone=z) for n, z in meta.get("nodes") or []]
    committed = arrays.get("committed")
    pod_rows = []
    for i, (pod, node, zone, ns, labels) in enumerate(meta.get("pods") or []):
        pod_rows.append(
            PodRow(
                pod=pod,
                node=node,
                zone=zone,
                namespace=ns,
                labels=tuple((k, v) for k, v in labels),
                state=(
                    COMMITTED
                    if committed is not None and i < len(committed) and committed[i]
                    else PENDING
                ),
            )
        )
    return (
        str(meta.get("replica") or ""),
        int(meta.get("version") or 0),
        node_rows,
        pod_rows,
    )


def ingest_payload(exchange: OccupancyExchange, data: bytes) -> bytes:
    """Server half of the ``ExchangeOccupancy`` RPC: replace the
    sender's rows wholesale, reply with the hub's merged view of every
    OTHER replica (encoded the same way). Routed through the public
    replace surface so the mutations land in the replication op log
    like every other write (they used to poke hub internals, which
    would have been invisible to a standby)."""
    replica, _version, node_rows, pod_rows = decode_rows(data)
    exchange.publish_nodes(replica, node_rows)
    exchange.replace_pod_rows(replica, pod_rows)
    exchange._m["staged"].inc()
    view = exchange.peers_view(replica)
    return encode_rows("", view.version, view.node_rows, view.pod_rows)


def dispatch_hub_op(hub: OccupancyExchange, op: str, meta: Mapping) -> dict:
    """Dispatch one occupancy-hub operation by name — the ONE op
    surface behind both transports: ``server/bulk.py``'s HubOp gRPC
    method (which maps the typed exceptions to status codes) and
    ``fleet/ha.py``'s LocalHubClient (which raises them directly), so
    the failover client exercises identical semantics in-process and
    over the wire. Raises the hub's typed exceptions
    (ExchangeUnreachable / HubDeposed / AdmitConflict / ValueError);
    every successful reply carries the hub's ``epoch`` — the value
    ``RemoteOccupancyExchange`` verifies is monotone (the client-side
    half of the epoch fence)."""
    replica = str(meta.get("replica") or "")
    out: dict = {}
    if op == "version":
        out["version"] = hub.version
    elif op == "peers_version":
        out["version"] = hub.peers_version(replica)
    elif op == "publish_nodes":
        hub.publish_nodes(
            replica,
            [NodeRow(node=n, zone=z) for n, z in meta.get("nodes") or []],
        )
    elif op == "stage":
        hub.stage(replica, pod_row_from_list(meta["row"]))
    elif op == "cas_stage":
        out["version"] = hub.compare_and_stage(
            replica,
            pod_row_from_list(meta["row"]),
            int(meta["expect"]),
            domain_scope=bool(meta.get("domain_scope")),
        )
    elif op == "replace_pod_rows":
        hub.replace_pod_rows(
            replica,
            [pod_row_from_list(r) for r in meta.get("rows") or []],
        )
    elif op == "commit":
        hub.commit(replica, meta["pod"])
    elif op == "withdraw":
        hub.withdraw(replica, meta["pod"])
    elif op == "apply_ops":
        # write-behind flush: a batch of buffered stage/commit/
        # withdraw mutations plus piggybacked journal segments (kind
        # "journal"), applied atomically and deduped on the client's
        # (flush_client, flush_seq) key — see OccupancyExchange
        # .apply_ops for the idempotency contract
        seq = meta.get("flush_seq")
        out.update(
            hub.apply_ops(
                replica,
                meta.get("ops") or [],
                flush_seq=None if seq is None else int(seq),
                flush_client=str(meta.get("flush_client") or ""),
            )
        )
    elif op == "ship_journal":
        hub.ship_journal(replica, meta.get("lines") or [])
    elif op == "journal_lines":
        out["lines"] = hub.journal_lines()
    elif op == "retire":
        hub.retire(replica)
    elif op == "set_degraded":
        hub.set_degraded(replica, bool(meta.get("degraded")))
    elif op == "degraded_replicas":
        out["replicas"] = sorted(hub.degraded_replicas())
    elif op == "hand_off":
        hub.hand_off(
            meta["to"], meta["pod"], int(meta.get("hops") or 0),
            from_replica=meta.get("from") or None,
            trace=str(meta.get("trace") or ""),
        )
    elif op == "claim_handoffs":
        # (pod, hops, journey trace) — the trace context rides the
        # handoff row across the wire (cross-replica trace propagation)
        out["handoffs"] = [
            [k, h, trace] for k, h, trace in hub.claim_handoffs(replica)
        ]
    elif op == "pending_handoff_keys":
        out["keys"] = sorted(hub.pending_handoff_keys())
    elif op == "peers_view":
        view = hub.peers_view(replica)
        out = {
            "version": view.version,
            "nodes": [[r.node, r.zone] for r in view.node_rows],
            "pods": [pod_row_to_list(r) for r in view.pod_rows],
            "peerAges": [[r, a] for r, a in view.peer_ages],
        }
    elif op == "repl_sync":
        # standby catch-up (fleet/ha.py StandbyReplicator): op-log
        # entries past the standby's cursor, or the full snapshot when
        # the retained window has moved past it
        ops, latest = hub.ops_since(int(meta.get("since") or 0))
        out["latest"] = latest
        if ops is None:
            out["snapshot"] = hub.snapshot()
        else:
            out["ops"] = ops
    elif op == "drain_init":
        # the fleet backlog drain ledger (fleet/drain.py): coordinator
        # installs the global plan's partitions; replicas claim leases,
        # report per-chunk progress (their liveness refresh mid-drain),
        # and complete — all epoch-fenced hub writes
        out["status"] = hub.drain_init(
            replica,
            meta.get("partitions") or {},
            meta.get("residual") or [],
            membership_version=int(meta.get("membership_version") or 0),
        )
    elif op == "drain_claim":
        out["lease"] = hub.drain_claim(replica)
    elif op == "drain_progress":
        out["done"] = hub.drain_progress(replica, meta.get("keys") or [])
    elif op == "drain_complete":
        out["ok"] = hub.drain_complete(
            replica, str(meta.get("lease") or "")
        )
    elif op == "drain_status":
        out["status"] = hub.drain_status()
    elif op == "hub_status":
        out["status"] = hub.hub_status()
    else:
        raise ValueError(f"unknown hub op {op!r}")
    out["epoch"] = hub.hub_epoch
    return out
