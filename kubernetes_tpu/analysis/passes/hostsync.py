"""TPU001 — host-sync-in-hot-path.

The batched solve only wins while the hot path stays on-device: one
accidental ``np.asarray``/``int()`` on a traced or device value inside
the solve loop re-serializes every batch on the host<->device tunnel
(~104 ms post-first-read on the bench box, BENCH_r05).

Scope (see callgraph.ModuleGraph): functions wrapped by ``jax.jit`` and
everything reachable from them intra-module (*traced scope*), plus
functions registered hot via ``# ktpu: hot`` and their reachable set
(*hot scope*). The two sanctioned deferred-read points in
registry.SANCTIONED_SYNC_POINTS are exempt and stop propagation.

Flagged primitives:

- ``np.asarray`` / ``np.array`` / ``numpy.*`` (both scopes) — a forced
  device->host transfer when the argument is a device value; in traced
  code it is a trace-time failure or a silently baked constant.
- ``.block_until_ready()`` and ``.tolist()`` (both scopes) — explicit
  sync points.
- ``float()`` / ``int()`` / ``bool()`` on non-literal arguments (traced
  scope only) — tracer coercions. Host-side hot code coerces numpy
  scalars legitimately, so hot scope skips this sub-rule; device reads
  there must still route through the sanctioned points.
"""

from __future__ import annotations

import ast

from ..callgraph import own_nodes, scoped_graph
from ..core import Finding, Pass

_NP_BASES = {"np", "numpy", "onp"}
_NP_FUNCS = {"asarray", "array"}
_SYNC_METHODS = {"block_until_ready", "tolist"}
_COERCIONS = {"float", "int", "bool"}


def _is_np_transfer(call: ast.Call) -> bool:
    f = call.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr in _NP_FUNCS
        and isinstance(f.value, ast.Name)
        and f.value.id in _NP_BASES
    )


def _is_sync_method(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS:
        return f.attr
    return None


def _is_coercion(call: ast.Call) -> str | None:
    f = call.func
    if (
        isinstance(f, ast.Name)
        and f.id in _COERCIONS
        and call.args
        and not all(isinstance(a, ast.Constant) for a in call.args)
    ):
        return f.id
    return None


class HostSyncPass(Pass):
    rule = "TPU001"
    title = "host sync in hot path"

    def run(self, module, ctx):
        graph, traced, hot = scoped_graph(module, ctx)
        findings: list[Finding] = []
        for qual in sorted(traced | hot):
            info = graph.functions.get(qual)
            if info is None:
                continue
            in_traced = qual in traced
            where = "jit-traced" if in_traced else "hot-path"
            for node in own_nodes(info.node):
                if not isinstance(node, ast.Call):
                    continue
                if _is_np_transfer(node):
                    findings.append(
                        Finding(
                            self.rule, module.path, node.lineno,
                            f"numpy transfer ({ast.unparse(node.func)}) in "
                            f"{where} function '{qual}' forces a "
                            "device->host sync",
                            hint="keep the value on-device (jnp), or read "
                            "it through a sanctioned deferred-read point",
                        )
                    )
                    continue
                meth = _is_sync_method(node)
                if meth is not None:
                    findings.append(
                        Finding(
                            self.rule, module.path, node.lineno,
                            f".{meth}() in {where} function '{qual}' "
                            "blocks on the device",
                            hint="defer the read past the overlapped host "
                            "work, or move it off the hot path",
                        )
                    )
                    continue
                if in_traced:
                    co = _is_coercion(node)
                    if co is not None:
                        findings.append(
                            Finding(
                                self.rule, module.path, node.lineno,
                                f"{co}() coercion in jit-traced function "
                                f"'{qual}' concretizes a traced value",
                                hint="use jnp ops on the tracer; coerce "
                                "only static (Python) arguments",
                            )
                        )
        return findings
