"""Bounded in-memory time series over the scheduler's health signals.

The substrate under the anomaly sentinel (``obs/sentinel.py``) and the
``/debug/profile`` surface: a fixed-capacity ring of **windowed
samples**, each one the aggregation of ``window_batches`` applied
batches (pods/s over the window, p99 from the SLO engine, counter-delta
rates). Windowing is what makes the multi-window regression rules
cheap — the sentinel compares ring slices, never raw batches — and the
ring bound is what makes the whole layer safe to leave always-on in a
serving process.

Everything here is host-side arithmetic over numbers the loops already
tick (the CounterWindow discipline from ``tuning/window.py``): zero
device syncs, driver-thread writes, lock-guarded reads so the debug
endpoints can snapshot concurrently.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field


@dataclass(frozen=True)
class WindowSample:
    """One aggregated window of applied batches."""

    seq: int  # monotone window counter (0-based)
    t: float  # virtual/wall perf timestamp at window close
    batches: int  # batches aggregated into this window
    pods: int  # pods applied across the window
    signals: dict = field(default_factory=dict)  # name -> float


class TimeSeriesRing:
    """Fixed-capacity ring of :class:`WindowSample`.

    ``mean(signal, n)`` / ``mean_prev(signal, n)`` are the two reads the
    sentinel's fast-vs-slow rules need: the trailing ``n`` windows and
    the ``n`` windows immediately before them.
    """

    def __init__(self, capacity: int = 256) -> None:
        if capacity < 4:
            raise ValueError("timeseries capacity must be >= 4")
        self._ring: deque[WindowSample] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0

    def __len__(self) -> int:
        return len(self._ring)

    def append(
        self, *, t: float, batches: int, pods: int, signals: dict
    ) -> WindowSample:
        sample = WindowSample(
            seq=self._seq, t=t, batches=batches, pods=pods,
            signals=dict(signals),
        )
        with self._lock:
            self._ring.append(sample)
            self._seq += 1
        return sample

    def last(self) -> WindowSample | None:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def mean(self, signal: str, n: int) -> float:
        """Mean of ``signal`` over the trailing ``n`` windows (0.0 when
        the ring is empty)."""
        with self._lock:
            tail = list(self._ring)[-n:]
        if not tail:
            return 0.0
        return sum(s.signals.get(signal, 0.0) for s in tail) / len(tail)

    def mean_prev(self, signal: str, n: int, skip: int) -> float:
        """Mean of ``signal`` over the ``n`` windows immediately before
        the trailing ``skip`` windows — the baseline the spike rule
        compares the fast window against."""
        with self._lock:
            ring = list(self._ring)
        base = ring[-(skip + n): -skip] if skip else ring[-n:]
        if not base:
            return 0.0
        return sum(s.signals.get(signal, 0.0) for s in base) / len(base)

    def snapshot(self, n: int = 32) -> list[dict]:
        """The trailing ``n`` samples as JSON-ready dicts (newest last)."""
        with self._lock:
            tail = list(self._ring)[-n:]
        return [
            {
                "seq": s.seq,
                "t": round(s.t, 6),
                "batches": s.batches,
                "pods": s.pods,
                "signals": {
                    k: round(v, 6) for k, v in sorted(s.signals.items())
                },
            }
            for s in tail
        ]
