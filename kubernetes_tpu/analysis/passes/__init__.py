"""The analyzer's rule set. Each module holds one pass; ALL_PASSES is
the shipped order (cheap scoping passes first, cross-file MET001 last).
"""

from __future__ import annotations

from .hostsync import HostSyncPass
from .tracedbranch import TracedBranchPass
from .dtypes import DtypeDisciplinePass
from .locks import LockDisciplinePass
from .metricnames import MetricNamePass

ALL_PASSES = (
    HostSyncPass,
    TracedBranchPass,
    DtypeDisciplinePass,
    LockDisciplinePass,
    MetricNamePass,
)

__all__ = [
    "ALL_PASSES",
    "HostSyncPass",
    "TracedBranchPass",
    "DtypeDisciplinePass",
    "LockDisciplinePass",
    "MetricNamePass",
]
