"""Scalar oracles for the "static" in-tree plugins — TaintToleration,
NodeAffinity, NodeName, NodePorts, NodeUnschedulable, ImageLocality — plus
the shared DefaultNormalizeScore helper.

Direct transcriptions of the reference semantics (SURVEY.md §3.2); used as
ground truth by kernel parity tests. Never vectorized on purpose.

Reference:
- tainttoleration/taint_toleration.go#Filter (FindMatchingUntoleratedTaint
  over NoSchedule|NoExecute), #Score (countIntolerableTaintsPreferNoSchedule),
  #NormalizeScore (DefaultNormalizeScore reverse=true)
- nodeaffinity/node_affinity.go#Filter (GetRequiredNodeAffinity =
  spec.nodeSelector AND requiredDuringScheduling...), #Score (sum of matched
  preferred-term weights), #NormalizeScore (DefaultNormalizeScore)
- nodename/node_name.go#Filter
- nodeports/node_ports.go#Filter + framework/types.go#HostPortInfo.CheckConflict
- nodeunschedulable/node_unschedulable.go#Filter (tolerating the
  node.kubernetes.io/unschedulable:NoSchedule taint bypasses the check)
- imagelocality/image_locality.go#Score (#sumImageScores, #scaledImageScore,
  #calculatePriority, #normalizedImageName)
- plugins/helper/normalize_score.go#DefaultNormalizeScore
"""

from __future__ import annotations

from typing import Iterable, Mapping, Sequence

from ...api.objects import (
    TAINT_NO_EXECUTE,
    TAINT_NO_SCHEDULE,
    TAINT_PREFER_NO_SCHEDULE,
    Node,
    Pod,
    Taint,
)
from ...api.labels import Selector, selector_from_match_labels

MAX_NODE_SCORE = 100

# v1.TaintNodeUnschedulable
TAINT_NODE_UNSCHEDULABLE = "node.kubernetes.io/unschedulable"

MB = 1024 * 1024
# imagelocality/image_locality.go
IMAGE_MIN_THRESHOLD = 23 * MB
IMAGE_MAX_THRESHOLD = 1000 * MB


# ---------------------------------------------------------------------------
# NodeName
# ---------------------------------------------------------------------------


def node_name_filter(pod: Pod, node: Node) -> bool:
    """nodename/node_name.go#Fits."""
    return not pod.node_name or pod.node_name == node.name


# ---------------------------------------------------------------------------
# NodeUnschedulable
# ---------------------------------------------------------------------------


def node_unschedulable_filter(pod: Pod, node: Node) -> bool:
    """node_unschedulable.go#Filter: unschedulable nodes pass only for pods
    tolerating the unschedulable:NoSchedule taint."""
    if not node.unschedulable:
        return True
    probe = Taint(key=TAINT_NODE_UNSCHEDULABLE, effect=TAINT_NO_SCHEDULE)
    return any(t.tolerates(probe) for t in pod.tolerations)


# ---------------------------------------------------------------------------
# TaintToleration
# ---------------------------------------------------------------------------


def taint_toleration_filter(pod: Pod, node: Node) -> bool:
    """Every NoSchedule/NoExecute taint must be tolerated."""
    for taint in node.taints:
        if taint.effect not in (TAINT_NO_SCHEDULE, TAINT_NO_EXECUTE):
            continue
        if not any(t.tolerates(taint) for t in pod.tolerations):
            return False
    return True


def taint_toleration_score(pod: Pod, node: Node) -> int:
    """Count of intolerable PreferNoSchedule taints (raw score; normalized
    reverse so fewer = better)."""
    cnt = 0
    for taint in node.taints:
        if taint.effect != TAINT_PREFER_NO_SCHEDULE:
            continue
        if not any(t.tolerates(taint) for t in pod.tolerations):
            cnt += 1
    return cnt


# ---------------------------------------------------------------------------
# NodeAffinity
# ---------------------------------------------------------------------------


def node_affinity_filter(pod: Pod, node: Node) -> bool:
    """GetRequiredNodeAffinity: spec.nodeSelector (AND of equals) AND
    nodeAffinity.requiredDuringScheduling (OR of terms)."""
    if pod.node_selector:
        sel = selector_from_match_labels(pod.node_selector)
        if not sel.matches(node.labels):
            return False
    na = pod.affinity.node_affinity if pod.affinity else None
    if na is not None and na.required is not None:
        fields = node.field_labels()
        if not any(t.matches(node.labels, fields) for t in na.required):
            return False
    return True


def added_affinity_filter(added, node: Node) -> bool:
    """NodeAffinityArgs.addedAffinity required terms (node_affinity.go: the
    scheduler-level selector is ANDed with the pod's own)."""
    if added is None or added.required is None:
        return True
    fields = node.field_labels()
    return any(t.matches(node.labels, fields) for t in added.required)


def added_affinity_score(added, node: Node) -> int:
    """Sum of matching addedAffinity preferred-term weights."""
    if added is None:
        return 0
    fields = node.field_labels()
    return sum(
        p.weight
        for p in added.preferred
        if p.weight and p.preference.matches(node.labels, fields)
    )


def node_affinity_score(pod: Pod, node: Node) -> int:
    """Sum of weights of matching preferredDuringScheduling terms."""
    na = pod.affinity.node_affinity if pod.affinity else None
    if na is None:
        return 0
    score = 0
    fields = node.field_labels()
    for pref in na.preferred:
        if pref.weight == 0:
            continue
        if pref.preference.matches(node.labels, fields):
            score += pref.weight
    return score


# ---------------------------------------------------------------------------
# NodePorts
# ---------------------------------------------------------------------------

WILDCARD_IP = "0.0.0.0"


def port_conflicts(
    want: tuple[str, str, int], used: Iterable[tuple[str, str, int]]
) -> bool:
    """HostPortInfo.CheckConflict for one wanted (hostIP, proto, hostPort)
    against the set of used triples on a node."""
    ip, proto, port = want
    if port <= 0:
        return False
    ip = ip or WILDCARD_IP
    if ip == WILDCARD_IP:
        return any(p == proto and pt == port for (_, p, pt) in used)
    return any(
        (uip == WILDCARD_IP or uip == ip) and p == proto and pt == port
        for (uip, p, pt) in used
    )


def node_ports_filter(pod: Pod, used_ports: Iterable[tuple[str, str, int]]) -> bool:
    used = list(used_ports)
    return not any(port_conflicts(w, used) for w in pod.host_ports())


def used_host_ports(pods: Iterable[Pod]) -> list[tuple[str, str, int]]:
    out: list[tuple[str, str, int]] = []
    for p in pods:
        out.extend(p.host_ports())
    return out


# ---------------------------------------------------------------------------
# ImageLocality
# ---------------------------------------------------------------------------


def normalized_image_name(name: str) -> str:
    """image_locality.go#normalizedImageName: append :latest when the image
    has no tag/digest (':' after the last '/' counts as a tag)."""
    if name.rfind(":") <= name.rfind("/") and "@" not in name:
        name += ":latest"
    return name


def build_image_states(
    nodes: Sequence[Node],
) -> dict[str, tuple[int, int]]:
    """name -> (sizeBytes, numNodes) over the snapshot, mirroring the cache's
    imageStates summary (cache.go#createImageStateSummary)."""
    states: dict[str, tuple[int, int]] = {}
    for node in nodes:
        for img in node.images:
            for n in img.names:
                n = normalized_image_name(n)
                size, cnt = states.get(n, (img.size_bytes, 0))
                states[n] = (size, cnt + 1)
    return states


def image_locality_score(
    pod: Pod,
    node: Node,
    image_states: Mapping[str, tuple[int, int]],
    total_nodes: int,
) -> int:
    """image_locality.go#Score. Only scoring containers (not init);
    scaledImageScore = size * numNodes / totalNodes (float->int64 trunc);
    image counted only if present on THIS node."""
    node_images = {
        normalized_image_name(n) for img in node.images for n in img.names
    }
    sum_scores = 0
    num_containers = len(pod.containers)
    for c in pod.containers:
        for raw in c.images:
            name = normalized_image_name(raw)
            if name not in node_images:
                continue
            size, num_nodes = image_states.get(name, (0, 0))
            if total_nodes > 0:
                sum_scores += int(size * num_nodes / total_nodes)
    min_t = IMAGE_MIN_THRESHOLD * num_containers
    max_t = IMAGE_MAX_THRESHOLD * num_containers
    s = min(max(sum_scores, min_t), max_t)
    if max_t == min_t:
        return 0
    return MAX_NODE_SCORE * (s - min_t) // (max_t - min_t)


# ---------------------------------------------------------------------------
# DefaultNormalizeScore
# ---------------------------------------------------------------------------


def default_normalize_score(
    scores: Sequence[int], reverse: bool, max_priority: int = MAX_NODE_SCORE
) -> list[int]:
    """helper/normalize_score.go#DefaultNormalizeScore (int64 math)."""
    max_count = max(scores, default=0)
    if max_count == 0:
        if reverse:
            return [max_priority for _ in scores]
        return list(scores)
    out = []
    for s in scores:
        s = max_priority * s // max_count
        if reverse:
            s = max_priority - s
        out.append(s)
    return out
