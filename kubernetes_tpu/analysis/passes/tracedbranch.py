"""TPU002 — Python control flow on traced/device values.

Inside jit-traced code, a Python ``if``/``while`` whose condition is a
``jnp`` expression either raises a ConcretizationTypeError at trace
time or — worse, via implicit ``bool()`` on platforms that allow it —
silently syncs and bakes the branch for the traced shape. In *hot*
(host-side) scope the same shape is an implicit device->host sync on
every call — the exact per-batch round trip the pipelined loop exists
to hide. Branching on *static* Python arguments is fine and common
(the solver's ``static_argnames`` dispatch), so this pass only flags
conditions that syntactically contain a ``jnp.``-rooted expression;
name-typed data flow is out of scope (documented precision bound,
analysis/README.md).
"""

from __future__ import annotations

import ast

from ..callgraph import own_nodes, scoped_graph
from ..core import Finding, Pass

_TRACED_BASES = {"jnp", "lax"}


def _jnp_rooted(expr: ast.expr) -> bool:
    for node in ast.walk(expr):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in _TRACED_BASES
        ):
            return True
    return False


class TracedBranchPass(Pass):
    rule = "TPU002"
    title = "Python branch on traced value"

    def run(self, module, ctx):
        graph, traced, hot = scoped_graph(module, ctx)
        findings: list[Finding] = []
        for qual in sorted(traced | hot):
            info = graph.functions.get(qual)
            if info is None:
                continue
            in_traced = qual in traced
            for node in own_nodes(info.node):
                if isinstance(node, (ast.If, ast.While)) and _jnp_rooted(
                    node.test
                ):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    what = (
                        f"Python '{kind}' on a jnp expression in "
                        f"jit-traced function '{qual}'"
                        if in_traced
                        else f"Python '{kind}' on a jnp expression in "
                        f"hot-path function '{qual}' syncs per call"
                    )
                    findings.append(
                        Finding(
                            self.rule, module.path, node.lineno, what,
                            hint="use jnp.where / lax.cond / lax.while_loop"
                            " (or hoist the decision to a static arg)",
                        )
                    )
        return findings
