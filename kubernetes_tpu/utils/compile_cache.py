"""Persistent XLA compilation cache + startup warmup (SURVEY.md §6.4).

The reference scheduler is stateless and needs no checkpointing; the one
piece of solver state worth persisting across restarts is the XLA
executable cache (SURVEY.md §6.4 "Solver warm state"). Without it every
process start pays the full compile of the scan pipeline on its first
batch — the round-1 benchmark measured 108 s of p99 latency from exactly
this. With the cache on disk a restart deserializes the executable in
well under a second.

Verified against the experimental `axon` PJRT platform on this box:
first compile 2.26 s -> 0.55 s from a cold process with a warm disk cache.
"""

from __future__ import annotations

import os

_DEFAULT_CACHE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache",
)

_enabled = False


def enable_persistent_cache(cache_dir: str | None = None) -> str:
    """Idempotently point JAX's persistent compilation cache at
    ``cache_dir`` (default: ``<repo>/.jax_cache``, overridable with
    ``KUBERNETES_TPU_COMPILE_CACHE``). Returns the directory used.

    Thresholds are zeroed so even sub-second kernels persist: the solve
    pipeline is one big executable, but the tensorizers jit a handful of
    small helpers whose compiles otherwise still add up at startup.
    """
    global _enabled
    import jax

    cache_dir = (
        cache_dir
        or os.environ.get("KUBERNETES_TPU_COMPILE_CACHE")
        or _DEFAULT_CACHE_DIR
    )
    if not _enabled:
        configured = jax.config.jax_compilation_cache_dir
        if configured:
            # the embedding application already chose a cache dir — respect it
            _enabled = True
            return configured
        try:
            os.makedirs(cache_dir, exist_ok=True)
        except OSError:
            # read-only install dir and no override — run without the cache
            _enabled = True
            return cache_dir
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _enabled = True
    return cache_dir
