"""Multi-scheduler fleet drive: N REAL Schedulers sharding one REAL
ClusterState through the watch bus, on one virtual timeline.

The single-scheduler harness (sim/harness.py) validates the engine's
concurrency story for one process; this drive validates the fleet
tier's (kubernetes_tpu/fleet): every replica subscribes with its
shard filter, solves its own partition, exchanges occupancy rows
through one shared in-process hub, and hands off pods it cannot
legally host. After every cycle the fleet-wide invariants run:

- **no-global-overcommit** (the tentpole's flagship check): every
  bind each replica reported landed on a node the ring assigned to
  that replica at the time, and global per-node capacity holds across
  all replicas' commits;
- the single-scheduler checks (double-bind, constraints, monotonic
  counters) over the shared cluster state;
- **fleet lost-pod**: every unbound routed pod is tracked by SOME
  replica's queue/in-flight/waiting maps or by a pending handoff row;
- **fleet journal completeness** (at the end): each pod's merged
  journal history — across every replica it traversed — ends on a
  terminal outcome.

The ``replica_loss`` profile kills one replica mid-drive
(unsubscribe + stop driving + retire its exchange rows, exactly what
a process crash looks like to the others); the survivors' membership
flip re-owns its shard and adopts its orphaned pods.

Determinism: same contract as the single harness — one thread,
FakeClock, string-seeded RNG, sorted iteration, round-robin replica
drive order — so same seed + profile produce byte-identical
per-replica journals (the ci.sh fleet smoke byte-compares the
digests).
"""

from __future__ import annotations

import hashlib
import random
from dataclasses import dataclass, field

from .. import metrics
from ..fleet import FleetConfig, OccupancyExchange
from ..obs import ObsConfig
from ..scheduler import Scheduler, SchedulerConfig
from ..solver.exact import ExactSolverConfig
from ..state.cluster import ClusterState
from ..utils.clock import FakeClock
from .generators import ChurnGenerator, apply_event
from .invariants import (
    BindTransitionTracker,
    MonotonicCounters,
    Violation,
    _record,
    check_constraints,
    check_fleet_drain,
    check_fleet_journal_completeness,
    check_hub_failover,
    check_hub_partition,
    check_no_global_overcommit,
    check_no_partial_gangs,
)
from .harness import _GANG_COUNTERS, _counter_value, _gang_throughput_table
from .profiles import Profile, get_profile


@dataclass
class FleetSimResult:
    profile: str
    seed: int
    cycles: int
    replicas: int
    bindings: dict[str, str]  # pod key -> node (final)
    unbound: list[str]
    violations: list[Violation]
    settled: bool
    summary: dict
    # per-replica decision journals (canonical JSONL) + digests
    journals: dict[str, list[str]] = field(default_factory=dict)
    journal_digests: dict[str, str] = field(default_factory=dict)
    # the hub's append-only journal aggregation surface (obs tentpole):
    # every replica's shipped segments merged in arrival order — the
    # one-file `obs explain --fleet` source the CLI writes out
    hub_journal_lines: list[str] = field(default_factory=list)
    # per-replica flight-recorder dumps written on invariant violation
    # (path -> replica), mirroring the single harness's trigger
    flight_dumps: dict[str, str] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations and self.settled


def _digest(lines: list[str]) -> str:
    return hashlib.sha256(("\n".join(lines) + "\n").encode()).hexdigest()


class FleetSimHarness:
    def __init__(
        self,
        profile: Profile | str,
        seed: int = 0,
        cycles: int = 10,
        replicas: int | None = None,
        *,
        pipelined: bool | None = None,
        streaming: bool | None = None,
        max_settle_rounds: int = 12,
        grpc_hub: bool = False,
        flight_dump: str | None = None,
    ) -> None:
        self.flight_dump_path = flight_dump
        self.profile = (
            get_profile(profile) if isinstance(profile, str) else profile
        )
        self.profile.validate()
        if self.profile.watch_delay or self.profile.external_bind_rate:
            raise ValueError(
                f"profile {self.profile.name}: the fleet drive needs "
                "prompt delivery and no external binds (the ownership "
                "invariant and the fleet≡single equivalence both lean "
                "on it)"
            )
        self.seed = seed
        self.cycles = cycles
        self.n = replicas or self.profile.fleet_replicas or 2
        self.pipelined = (
            self.profile.pipelined if pipelined is None else pipelined
        )
        # streaming dispatcher drive per replica (run_streaming)
        self.streaming = (
            self.profile.streaming if streaming is None else streaming
        )
        self.max_settle_rounds = max_settle_rounds
        # the same "{seed}/gen" stream as the single-scheduler harness:
        # with no external binds/shrinks the event stream is identical,
        # which is what makes fleet-vs-single binding equivalence a
        # meaningful assertion
        self._gen_rng = random.Random(f"{seed}/gen")
        self.clock = FakeClock()
        self.cluster = ClusterState(clock=self.clock)
        self.generator = ChurnGenerator(
            self.profile, self._gen_rng, self.cluster
        )
        for node in self.generator.seed_nodes():
            self.cluster.create_node(node)

        # the hub shares the virtual clock so occupancy-row aging (the
        # staleness bounds) rides the same timeline as everything else.
        # HA mode (profile.hub_failover_at >= 0) runs a PRIMARY +
        # STANDBY hub pair under one HubLease: the primary holds epoch
        # 1, the standby replicates its op log (the harness is the
        # hubs' serving loop — replication polls + lease heartbeats
        # tick once per cycle, deterministic on the virtual clock),
        # and replicas reach them through RemoteOccupancyExchange's
        # endpoint-failover client, in-process (LocalHubClient per
        # hub) or over real gRPC (one bulk server per hub).
        self.ha = self.profile.hub_failover_at >= 0
        self.hub_lease = None
        self.hub_primary = None
        self.hub_standby = None
        self._replicator = None
        self._primary_down = False
        self._promotions = 0
        self._blackout_cycles = 0
        self._old_primary_reads_ok = None
        if self.ha:
            from ..fleet.ha import HubLease, StandbyReplicator

            self.hub_lease = HubLease(
                clock=self.clock, duration_s=self.profile.hub_lease_s
            )
            self.hub_primary = OccupancyExchange(
                clock=self.clock, hub_id="hub-a", lease=self.hub_lease
            )
            assert self.hub_primary.try_promote() == 1
            self.hub_standby = OccupancyExchange(
                clock=self.clock, hub_id="hub-b", lease=self.hub_lease
            )
            # self.exchange is the harness's introspection handle (the
            # invariants' pending-handoff/journal reads, the fault
            # seams): the CURRENT primary — re-pointed at promotion
            self.exchange = self.hub_primary
        else:
            self.exchange = OccupancyExchange(clock=self.clock)
        # gRPC-backed hub: the SAME hub object(s) served behind the
        # bulk boundary's HubOp method on localhost — every replica
        # talks through a RemoteOccupancyExchange over a real socket
        # (real tensorcodec wire framing, real status-code conflict
        # mapping), while the harness keeps direct access for its
        # fault seams (set_partitioned / set_down / retire) and
        # invariants. Virtual time is untouched (RPC wall time never
        # enters the FakeClock) and the drive stays single-threaded
        # round-robin, so same seed + flags reproduce byte-identical
        # journals ACROSS RUNS (--selfcheck). Journals are not
        # byte-identical to the in-process-hub drive: the client's
        # write-behind row buffer legitimately shifts WHEN
        # commit/withdraw bumps land on the hub version counter, which
        # re-times conflict-parked wakeups — every invariant still
        # holds, which is the actual contract.
        self.grpc_hub = grpc_hub
        self._hub_servers: list = []
        self._hub_clients: list = []
        self.universe = tuple(f"r{i}" for i in range(self.n))
        replica_exchange = {rid: self.exchange for rid in self.universe}
        if grpc_hub or self.ha:
            from ..fleet.runtime import RemoteOccupancyExchange

            hubs = (
                [self.hub_primary, self.hub_standby]
                if self.ha
                else [self.exchange]
            )
            if grpc_hub:
                from ..server.bulk import BulkCore, make_grpc_server

                targets = []
                for hub in hubs:
                    core = BulkCore(self.cluster, exchange=hub)
                    server, port = make_grpc_server(core, port=0)
                    server.start()
                    self._hub_servers.append(server)
                    targets.append(f"127.0.0.1:{port}")
                make_clients = lambda rid: dict(  # noqa: E731
                    target=",".join(targets)
                )
            else:
                from ..fleet.ha import LocalHubClient

                make_clients = lambda rid: dict(  # noqa: E731
                    target="", clients=[LocalHubClient(h) for h in hubs]
                )
            replica_exchange = {}
            for rid in self.universe:
                remote = RemoteOccupancyExchange(
                    replica=rid, clock=self.clock,
                    # deterministic flush identity: it only rides RPC
                    # meta (never journals/traces), but a stable id
                    # keeps run-to-run wire traffic identical too
                    flush_client_id=f"{rid}-sim",
                    **make_clients(rid),
                )
                self._hub_clients.append(remote)
                replica_exchange[rid] = remote
            if self.ha:
                from ..server.bulk import BulkClient

                source = (
                    BulkClient(targets[0], retries=0, clock=self.clock)
                    if grpc_hub
                    else LocalHubClient(self.hub_primary)
                )
                self._replicator = StandbyReplicator(
                    self.hub_standby, source
                )
        # gang scheduling (gang profiles): every replica shares the same
        # GangConfig — gangs route whole (by gang id) so one replica
        # assembles and atomically commits each gang, staging members
        # through the fenced hub CAS. Same quarantine-TTL reasoning as
        # the single harness (harness._base_config): park quarantined
        # gangs past the settle horizon.
        self._gang_profile = (
            self.profile.gang_rate > 0 or self.profile.gang_short_at >= 0
        )
        gang_cfg = None
        resilience_cfg = None
        if self._gang_profile:
            from ..gang import GangConfig
            from ..resilience import ResilienceConfig

            gang_cfg = GangConfig(
                min_member_timeout=self.profile.gang_min_member_timeout,
                quarantine_after=self.profile.gang_quarantine_after,
                throughput_weight=self.profile.gang_throughput_weight,
                class_throughput=_gang_throughput_table(self.profile),
            )
            resilience_cfg = ResilienceConfig(quarantine_ttl=3600.0)
        self.schedulers: dict[str, Scheduler] = {}
        for rid in self.universe:
            cfg_kwargs: dict = {}
            if resilience_cfg is not None:
                cfg_kwargs["resilience"] = resilience_cfg
            self.schedulers[rid] = Scheduler(
                self.cluster,
                SchedulerConfig(
                    batch_size=self.profile.batch_size,
                    mesh_devices=1,
                    solver=ExactSolverConfig(
                        tie_break="first",
                        group_size=self.profile.group_size,
                    ),
                    obs=ObsConfig(journal=True),
                    gang=gang_cfg,
                    fleet=FleetConfig(
                        replica=rid,
                        replicas=self.universe,
                        exchange=replica_exchange[rid],
                        max_row_age_s=self.profile.fleet_max_row_age_s,
                    ),
                    **cfg_kwargs,
                ),
                clock=self.clock,
            )
        self.alive: dict[str, bool] = {rid: True for rid in self.universe}
        self.tracker = BindTransitionTracker(self.cluster)
        self.monotonic = MonotonicCounters()
        self.violations: list[Violation] = []
        self._sched_bound: set[str] = set()
        self._binds_by_replica: dict[str, int] = {
            rid: 0 for rid in self.universe
        }
        self._events_applied = 0
        self._gang_counters0 = {
            k: _counter_value(c) for k, c in _GANG_COUNTERS.items()
        }
        self._lost_replica: str | None = None
        # hub-partition / zombie state (the hub_partition profile):
        # the zombie keeps DRIVING while partitioned — unlike a lost
        # replica it is alive, just lease-stale and hub-unreachable —
        # and every bind it attempts must be rejected by its revoked
        # commit fence
        self._zombie: str | None = None
        self._zombie_fenced = False
        self._zombie_binds_while_fenced = 0
        # fleet backlog drain (the fleet_backlog_drain profile): the
        # cycle-0 backlog drains through the hub's drain-lease ledger
        # (fleet/drain.py) instead of plain per-replica streaming
        self._fleet_drain = self.profile.fleet_drain
        self._drain_plan_keys: set[str] | None = None
        self._backlog_keys: set[str] = set()
        # backlog key -> replicas that reported it scheduled: the
        # drain-partition half of the double-bind story (the tracker
        # asserts the cluster-level half every cycle)
        self._drain_bound: dict[str, list[str]] = {}
        self._planner: Scheduler | None = None
        if self._fleet_drain:
            # the coordinator's full-view planner: a NON-fleet
            # Scheduler on the same cluster — replica caches are
            # ownership-filtered to their shard's nodes, so only an
            # unfiltered subscriber can run the relax mega-plan
            # globally. Never driven: it only plans.
            self._planner = Scheduler(
                self.cluster,
                SchedulerConfig(
                    batch_size=self.profile.batch_size,
                    mesh_devices=1,
                    solver=ExactSolverConfig(
                        tie_break="first",
                        group_size=self.profile.group_size,
                    ),
                ),
                clock=self.clock,
            )

    # -- drive --

    def _drive_replica(self, rid: str, cycle: int) -> None:
        sched = self.schedulers[rid]
        results = None
        if self._fleet_drain and self._drain_outstanding():
            # drain mode: claim-adopt-drain one lease chunk through
            # this replica's own drain_backlog slot ring (one chunk
            # per cycle keeps the concurrent-drain interleaving and
            # the mid-lease kill non-vacuous). No claimable lease ->
            # fall through to the normal drive so fresh arrivals and
            # handed-off pods still progress.
            out = sched.fleet_drain_backlog(
                chunk_pods=self.profile.backlog_chunk or 0,
                max_batches=1,
                plan_keys=self._drain_plan_keys,
            )
            if out["leases"]:
                results = out["results"]
        if results is None:
            if self.streaming:
                results = sched.run_streaming(max_batches=200)
            elif self.pipelined:
                results = sched.run_pipelined(max_batches=200)
            else:
                results = sched.run_until_settled(max_batches=200)
        scheduled = [
            (pod, node) for r in results for pod, node in r.scheduled
        ]
        if self._fleet_drain:
            for pod, _node in scheduled:
                if pod in self._backlog_keys:
                    self._drain_bound.setdefault(pod, []).append(rid)
        if rid == self._zombie and self._zombie_fenced and scheduled:
            # a fenced zombie's commit LANDED: the fence leaked
            self._zombie_binds_while_fenced += len(scheduled)
        self.tracker.record_results(scheduled)
        self._sched_bound.update(pod for pod, _ in scheduled)
        self._binds_by_replica[rid] += len(scheduled)
        # ownership half of no-global-overcommit: the binds this
        # replica just reported, against its assignment RIGHT NOW
        # (single-threaded: nothing moved since the bind committed)
        with self.cluster.lock:
            owners = dict(sched.fleet._assignment)
        check_no_global_overcommit(
            self.cluster,
            cycle,
            self.violations,
            binds=[(rid, pod, node) for pod, node in scheduled],
            owners=owners,
        )

    def _drive(self, cycle: int) -> None:
        order = list(self.universe)
        if self._zombie_fenced and self._zombie in order:
            # real replicas run concurrently; the interleaving the
            # commit fence exists for is the zombie racing AHEAD of the
            # survivors that re-owned its shard — so while fenced it
            # drives first each cycle, attempting commits on pods the
            # survivors haven't taken yet (all must reject)
            order.remove(self._zombie)
            order.insert(0, self._zombie)
        for rid in order:
            if self.alive[rid]:
                self._drive_replica(rid, cycle)

    # -- fleet backlog drain (the fleet_backlog_drain profile) --

    def _init_fleet_drain(self) -> None:
        """The coordinator seam, cycle 0: the full-view planner runs
        the relax mega-plan once globally; the first replica partitions
        the backlog by planned-node shard owner and installs the lease
        ledger at the hub (``FleetRuntime.drain_init_from_plan`` ->
        epoch-fenced ``drain_init``). Key order is the planner's queue
        order — the plan order every partition preserves."""
        plan = self._planner.relax_plan_backlog()
        keys = list(plan)
        self._backlog_keys = set(keys)
        self._drain_plan_keys = set(keys)
        self.schedulers[self.universe[0]].fleet.drain_init_from_plan(
            plan, keys
        )

    def _drain_outstanding(self) -> bool:
        st = self.exchange.drain_status()
        return bool(st.get("active")) and st.get("outstanding", 0) > 0

    def _kill_replica(self, rid: str, cycle: int) -> None:
        """A process crash as the rest of the fleet perceives it: the
        watch subscription vanishes, the shard lease goes stale (the
        survivors' membership flips), its exchange rows retire. Its
        journal is retained — the fleet-wide completeness check merges
        it with the survivors'."""
        self.alive[rid] = False
        self._lost_replica = rid
        dead = self.schedulers[rid]
        self.cluster.unsubscribe(dead._on_event)
        self.exchange.retire(rid)
        survivors = [r for r in self.universe if self.alive[r]]
        for r in survivors:
            self.schedulers[r].fleet.set_alive(survivors)

    # -- hub HA (the hub_failover profile) --

    def _ha_tick(self, cycle: int) -> None:
        """One deterministic HA maintenance round per cycle — runs
        AFTER the cycle's clock advance and BEFORE its drive, so the
        serving hub's lease renewal covers the drive's ops even
        through the settle ladder's long (11s/301s) rounds. The
        harness IS the hubs' serving loops on the virtual timeline:
        lease maintenance (``try_promote`` — a same-holder re-acquire
        renews WITHOUT bumping the epoch, so steady state never looks
        like a failover), the standby's replication poll, the
        kill/promote/heal schedule, and the one injected reply-loss
        that proves the idempotent flush path."""
        from ..fleet.occupancy import ExchangeUnreachable

        if not self._primary_down:
            # replication poll BEFORE the kill check: one poll per
            # tick means the standby is caught up to the last
            # completed cycle when the kill lands (lag within the
            # kill's own cycle heals through the clients' retained
            # sealed buffers and the forced republish — rows — while
            # journal lines ride the same retained buffers)
            try:
                self._replicator.poll()
            except ExchangeUnreachable:
                pass
        if cycle == self.profile.hub_failover_at:
            self._kill_primary(cycle)
        if not self._primary_down:
            self.hub_primary.try_promote()  # same-holder lease renew
        elif self._promotions:
            # the promoted standby is the serving hub: keep ITS lease
            # fresh (an unrenewed lease would self-depose it — the
            # exact failure mode the fencing check exists to catch)
            self.hub_standby.try_promote()
        else:
            # blackout: takeover only succeeds once the dead
            # primary's lease expires — the fencing window
            granted = self.hub_standby.try_promote()
            if granted is not None:
                self._promotions += 1
                # the standby is the serving hub now: re-point the
                # harness's introspection handle (invariants, journal
                # aggregation reads, retire calls)
                self.exchange = self.hub_standby
            else:
                self._blackout_cycles += 1
        if cycle == 1:
            # deterministic reply loss: the next apply_ops flush
            # applies server-side, then the reply is lost — the
            # client's sealed-batch retry must dedup (the invariant's
            # dedup_hits >= 1 clause)
            self.hub_primary.set_flush_fault(1)
        if (
            cycle == self.profile.hub_failover_heal
            and self._primary_down
            and self._promotions
        ):
            self._heal_old_primary(cycle)

    def _kill_primary(self, cycle: int) -> None:
        """The primary hub process dies: every op from every replica
        raises ExchangeUnreachable (UNAVAILABLE over the wire), its
        lease renewals stop, and the fleet enters the blackout window
        — conservative admission until the standby's lease grant."""
        self._primary_down = True
        self.hub_primary.set_down(True)
        metrics.sim_faults_injected_total.labels("hub_failover").inc()

    def _heal_old_primary(self, cycle: int) -> None:
        """The OLD primary resurfaces (partitioned-zombie style:
        alive, lease long taken over). It must keep serving its
        debug/read surface — the post-mortem path — while 100% of
        replica-facing writes reject with the typed HubDeposed (its
        own lease-validity check self-deposes it on the first write
        attempt; a replica that failed over already ignores it via
        the epoch-monotone check)."""
        from ..fleet.occupancy import HubDeposed, PodRow

        self.hub_primary.set_down(False)
        try:
            status = self.hub_primary.hub_status()
            self._old_primary_reads_ok = bool(status.get("hub"))
        except Exception:
            self._old_primary_reads_ok = False
        # the write probe: a straggler replica (or the zombie itself)
        # pushing a row at the old primary must get the typed fence
        probe = PodRow(
            pod="probe/stale-write", node="n0", zone="z0",
            namespace="probe", labels=(("app", "probe"),),
        )
        try:
            self.hub_primary.stage(self.universe[0], probe)
        except HubDeposed:
            pass  # counted in deposed_write_rejections — the proof
        else:
            _record(
                self.violations, "hub_failover", cycle,
                "a replica-facing write LANDED on the deposed old "
                "primary — the hub epoch fence leaked",
            )

    def _partition_hub(self, cycle: int) -> None:
        """The hub_partition fault: the last replica loses its network
        path to the occupancy hub AND its lease renewals stall (the
        classic GC-pause zombie). The survivors observe the stale
        lease, mark it dead, and — through the membership transition —
        REVOKE its commit fence at the state service. The zombie keeps
        driving with its stale view; every bind it attempts must now
        reject with Conflict."""
        zombie = self.universe[-1]
        self._zombie = zombie
        self._zombie_fenced = True
        metrics.sim_faults_injected_total.labels("hub_partition").inc()
        metrics.sim_faults_injected_total.labels("lease_fence").inc()
        self.exchange.set_partitioned(zombie, True)
        survivors = [r for r in self.universe if r != zombie]
        for r in survivors:
            # each survivor's poll observes the stale lease: the
            # membership flip re-owns the zombie's shard and revokes
            # its fence (FleetRuntime._membership_changed)
            self.schedulers[r].fleet.set_alive(survivors)

    def _heal_hub(self, cycle: int) -> None:
        """Partition heals: the zombie reaches the hub again,
        re-acquires its lease — a fresh fence token plus a forced full
        resync BEFORE any commit (Scheduler.reacquire_fence) — and
        republishes its rows; the survivors' polls see the lease fresh
        and re-admit it."""
        zombie = self._zombie
        self._zombie_fenced = False
        self.exchange.set_partitioned(zombie, False)
        for r in self.universe:
            if r != zombie:
                self.schedulers[r].fleet.set_alive(self.universe)
        self.schedulers[zombie].reacquire_fence()

    def _check(self, cycle: int) -> None:
        self.tracker.drain(cycle, self.violations)
        check_constraints(self.cluster, cycle, self.violations)
        # fleet-wide: gangs must land atomically no matter which
        # replica owned them (a no-op without gang labels)
        check_no_partial_gangs(self.cluster, cycle, self.violations)
        self._check_fleet_lost_pods(cycle)
        self.monotonic.observe(cycle, self.violations)

    def _check_fleet_lost_pods(self, cycle: int) -> None:
        """Fleet lost-pod accounting: every unbound pod some alive
        replica routes must be tracked by a queue / in-flight map /
        WaitingPods map somewhere, or sit in a pending handoff row."""
        # debug_state bypasses the down seam: mid-blackout the
        # (dead) hub's last-known handoff rows still count as
        # tracked — they replicate to the standby and re-surface
        tracked: set[str] = set(
            self.exchange.debug_state()["pending_handoffs"]
        )
        if self._fleet_drain:
            # mid-reassignment a returned drain lease's keys sit in no
            # replica's queue — the hub ledger tracks them until the
            # next claimant adopts (like an unclaimed handoff row)
            tracked |= set(self.exchange.drain_outstanding_keys())
        solver_names: set[str] = set()
        for rid, sched in self.schedulers.items():
            if not self.alive[rid]:
                continue
            tracked |= set(sched.queue.entries())
            tracked |= set(sched._in_flight)
            tracked |= set(sched._waiting)
            # resilience-quarantined pods are parked with a TTL'd
            # re-admit — tracked, not lost
            tracked |= set(sched._quarantine)
            solver_names |= set(sched.solvers)
        for pod in self.cluster.list_pods():
            if pod.node_name or pod.scheduler_name not in solver_names:
                continue
            if pod.key not in tracked:
                _record(
                    self.violations, "lost_pod", cycle,
                    f"pod {pod.key} is unbound but tracked by no alive "
                    "replica's queue/in-flight/waiting maps nor a "
                    "pending handoff row",
                )

    def _gang_summary(self) -> dict | None:
        if not self._gang_profile:
            return None
        from ..gang import GangTracker

        gang_bound: set[str] = set()
        gang_unbound: set[str] = set()
        for p in self.cluster.list_pods():
            gid = GangTracker.gang_of(p)
            if gid is not None:
                (gang_bound if p.node_name else gang_unbound).add(gid)
        return {
            "partial_gangs": len(gang_bound & gang_unbound),
            **{
                k: int(_counter_value(c) - self._gang_counters0[k])
                for k, c in _GANG_COUNTERS.items()
            },
        }

    def _settled(self) -> bool:
        if self.exchange.debug_state()["pending_handoffs"]:
            return False
        if self._fleet_drain:
            # not settled while the ledger can still grant work whose
            # pods sit in NO queue: unclaimed orphans, an in-flight
            # granted lease, or a residual cohort awaiting its
            # serialized grant (its keys were shed from every queue)
            st = self.exchange.drain_status()
            if st.get("active") and (
                st.get("orphans", 0)
                or st.get("granted", 0)
                or (
                    st.get("residual", 0)
                    and not st.get("residualGranted")
                )
            ):
                return False
        for rid, sched in self.schedulers.items():
            if not self.alive[rid]:
                continue
            if sched._waiting or sched._in_flight:
                return False
            live = set(sched.queue.entries().values())
            if live & {"active", "backoff"}:
                return False
        return True

    def run(self) -> FleetSimResult:
        try:
            return self._run()
        finally:
            for client in self._hub_clients:
                client.close()
            for server in self._hub_servers:
                server.stop(grace=None)

    def _run(self) -> FleetSimResult:
        for cycle in range(self.cycles):
            metrics.sim_cycles_total.inc()
            if cycle == self.profile.replica_loss_at and self.n > 1:
                self._kill_replica(self.universe[-1], cycle)
            if cycle == self.profile.hub_partition_at and self.n > 1:
                self._partition_hub(cycle)
            if (
                self._zombie is not None
                and cycle == self.profile.hub_partition_heal
            ):
                self._heal_hub(cycle)
            for ev in self.generator.generate(cycle):
                apply_event(self.cluster, ev)
                self._events_applied += 1
            self.clock.advance(1.0)
            if self.ha:
                # post-advance, pre-drive: the serving hub's lease
                # renewal covers this drive's ops
                self._ha_tick(cycle)
            if self._fleet_drain and cycle == 0:
                # after the backlog landed, before any replica drives:
                # the coordinator plans globally and installs the
                # drain-lease ledger at the hub
                self._init_fleet_drain()
            self._drive(cycle)
            self._check(cycle)
        settled = self._quiesce()
        if not settled:
            queues = {
                rid: self.schedulers[rid].queue.pending_counts()
                for rid in self.universe
                if self.alive[rid]
            }
            _record(
                self.violations, "progress",
                self.cycles + self.max_settle_rounds,
                "fleet failed to quiesce after churn stopped: "
                f"queues={queues} "
                f"handoffs="
                f"{sorted(self.exchange.debug_state()['pending_handoffs'])}",
            )
        return self._finish(settled)

    def _quiesce(self) -> bool:
        """Same settle ladder as the single harness: 11s rounds clear
        backoff, one 301s round forces the unschedulable-leftover
        flush (cross-shard-rejected pods park unschedulable and the
        flush is their guaranteed retry path once churn stops)."""
        advances = [11.0, 11.0, 301.0] + [11.0] * max(
            self.max_settle_rounds - 3, 0
        )
        flush_round = 2
        for i, adv in enumerate(advances):
            cycle = self.cycles + i
            self.clock.advance(adv)
            if self.ha:
                # post-advance like the main loop: the serving hub's
                # lease renewal covers this round's drive, and a kill
                # near the end of the driven cycles still promotes
                # during the settle ladder instead of deadlocking it
                self._ha_tick(cycle)
            self._drive(cycle)
            self._check(cycle)
            if i >= flush_round and self._settled():
                return True
        return False

    def _finish(self, settled: bool) -> FleetSimResult:
        # final journal ship: drain every alive replica's unshipped
        # segment tail to the hub's aggregation surface (segments are
        # bounded per call, so loop until empty), then flush the
        # remote adapters' write-behind buffers so the piggybacked
        # lines land before the hub is read
        for rid, sched in self.schedulers.items():
            if not self.alive[rid]:
                continue
            while sched.fleet.ship_journal_segment(sched) > 0:
                pass
        for client in self._hub_clients:
            try:
                client.flush()
            except Exception:
                pass  # partitioned teardown: the rows stay buffered
        hub_journal = self.exchange.journal_lines()
        check_fleet_journal_completeness(
            self.cluster,
            list(self.schedulers.values()),
            self.cycles + self.max_settle_rounds,
            self.violations,
            self._sched_bound,
        )
        if self.profile.hub_partition_at >= 0 and self.n > 1:
            zombie_sched = (
                self.schedulers[self._zombie]
                if self._zombie is not None
                else None
            )
            check_hub_partition(
                self.cycles + self.max_settle_rounds,
                self.violations,
                fenced_commits=(
                    zombie_sched._fenced_commits
                    if zombie_sched is not None
                    else 0
                ),
                zombie_binds_while_fenced=self._zombie_binds_while_fenced,
                stale_rejections=sum(
                    s.fleet.stale_rejections
                    for s in self.schedulers.values()
                ),
            )
        hub_ha = None
        if self.ha:
            # journal aggregation completeness after heal: every line
            # each replica's journal holds must be on the SERVING
            # hub's aggregation surface (pre-kill lines arrived via
            # replication, blackout lines via the clients' retained
            # sealed buffers re-flushed from the cursor)
            hub_lines = set(hub_journal)
            hub_journal_missing = sum(
                1
                for rid, sched in self.schedulers.items()
                if self.alive[rid]
                for line in sched.journal.lines
                if line not in hub_lines
            )
            hub_ha = {
                "promotions": self._promotions,
                "epoch": self.exchange.hub_epoch,
                "blackout_cycles": self._blackout_cycles,
                # the OLD primary's count is the stale-primary-fence
                # proof; the standby's own (pre-promotion writes that
                # rotated onto it during the blackout) is reported
                # separately — it is the failover client working, not
                # the fence under test
                "deposed_write_rejections": (
                    self.hub_primary.deposed_write_rejections
                ),
                "standby_write_rejections": (
                    self.hub_standby.deposed_write_rejections
                ),
                "flush_dedup_hits": (
                    self.hub_primary.flush_dedup_hits
                    + self.hub_standby.flush_dedup_hits
                ),
                "client_failovers": sum(
                    c.failovers for c in self._hub_clients
                ),
                "replication_ops": self._replicator.ops_applied,
                "replication_snapshots": (
                    self._replicator.snapshots_installed
                ),
                "old_primary_reads_ok": self._old_primary_reads_ok,
                "hub_journal_missing": hub_journal_missing,
            }
            check_hub_failover(
                self.cycles + self.max_settle_rounds,
                self.violations,
                promotions=self._promotions,
                epoch=self.exchange.hub_epoch,
                deposed_write_rejections=hub_ha[
                    "deposed_write_rejections"
                ],
                flush_dedup_hits=hub_ha["flush_dedup_hits"],
                stale_rejections=sum(
                    s.fleet.stale_rejections
                    for s in self.schedulers.values()
                ),
                hub_journal_missing=hub_journal_missing,
                old_primary_reads_ok=self._old_primary_reads_ok,
            )
        bindings = {
            p.key: p.node_name
            for p in sorted(self.cluster.list_pods(), key=lambda q: q.key)
            if p.node_name
        }
        fleet_drain = None
        if self._fleet_drain:
            st = self.exchange.drain_status()
            lost = sum(
                1 for k in self._backlog_keys if k not in bindings
            )
            double = sum(
                1 for v in self._drain_bound.values() if len(v) > 1
            )
            fleet_drain = {
                "pods": len(self._backlog_keys),
                "partitions": st.get("partitions", 0),
                "residual": st.get("residual", 0),
                "drained": st.get("done", 0),
                "leases": st.get("leases", 0),
                "leases_reassigned": st.get("reassigned", 0),
                "lost": lost,
                "double_bind": double,
            }
            check_fleet_drain(
                self.cycles + self.max_settle_rounds,
                self.violations,
                backlog=len(self._backlog_keys),
                drained=st.get("done", 0),
                double_binds=double,
                lost=lost,
                leases_reassigned=st.get("reassigned", 0),
                expect_reassign=self.profile.replica_loss_at >= 0,
            )
        unbound = sorted(
            p.key for p in self.cluster.list_pods() if not p.node_name
        )
        journals = {
            rid: list(s.journal.lines)
            for rid, s in self.schedulers.items()
        }
        digests = {rid: _digest(lines) for rid, lines in journals.items()}
        summary = {
            "replicas": self.n,
            "alive": sum(self.alive.values()),
            "lost_replica": self._lost_replica,
            "hub": "grpc" if self.grpc_hub else "in-process",
            "cas_conflicts": sum(
                s.fleet.cas_conflicts for s in self.schedulers.values()
            ),
            "pipelined": self.pipelined,
            "events": self._events_applied,
            "bound": len(bindings),
            "unbound": len(unbound),
            "settled": settled,
            "violations": len(self.violations),
            "binds_by_replica": dict(
                sorted(self._binds_by_replica.items())
            ),
            # partition-safety counters (hub_partition): who the zombie
            # was, per-replica fence rejections at the state service,
            # zombie binds that LANDED while fenced (must be 0), and
            # conservative-admission rejections under stale rows
            "zombie": self._zombie,
            "fenced_commits": {
                rid: s._fenced_commits
                for rid, s in sorted(self.schedulers.items())
            },
            "zombie_binds_while_fenced": self._zombie_binds_while_fenced,
            "stale_rejections": sum(
                s.fleet.stale_rejections
                for s in self.schedulers.values()
            ),
            "journal_digests": digests,
            "hub_journal_lines": len(hub_journal),
            "hub_journal_digest": _digest(hub_journal),
            # hub-HA counters (the hub_failover profile; None without)
            "hub_ha": hub_ha,
            # gang scheduling (gang profiles; None without): partial
            # gangs fleet-wide must be 0 — atomicity survives replica
            # loss because gangs route whole and commit through one
            # replica's fenced CAS round
            "gang": self._gang_summary(),
            # fleet backlog drain (fleet_drain profiles; None without):
            # lost counts backlog keys unbound fleet-wide at end — the
            # ledger's own done counter may legitimately trail it when
            # residual pods are handed off and bound by a peer's normal
            # drive, so the invariant anchors on bindings, not the ledger
            "fleet_drain": fleet_drain,
        }
        flight_dumps: dict[str, str] = {}
        if self.violations:
            # the invariant trigger, fleet-wide: dump every replica's
            # recent-history ring next to the violation report (the
            # single harness's contract; no-op without a configured
            # path — FlightRecorder.dump counts the trigger either way)
            for rid in sorted(self.schedulers):
                rec = self.schedulers[rid].flight
                if rec is None:
                    continue
                path = (
                    f"{self.flight_dump_path}.{rid}"
                    if self.flight_dump_path
                    else None
                )
                written = rec.dump(path=path, trigger="invariant")
                if written:
                    flight_dumps[written] = rid
        return FleetSimResult(
            profile=self.profile.name,
            seed=self.seed,
            cycles=self.cycles,
            replicas=self.n,
            bindings=bindings,
            unbound=unbound,
            violations=self.violations,
            settled=settled,
            summary=summary,
            journals=journals,
            journal_digests=digests,
            hub_journal_lines=hub_journal,
            flight_dumps=flight_dumps,
        )


def run_fleet_sim(
    profile: str,
    seed: int = 0,
    cycles: int = 10,
    replicas: int | None = None,
    *,
    pipelined: bool | None = None,
    streaming: bool | None = None,
    grpc_hub: bool = False,
    flight_dump: str | None = None,
) -> FleetSimResult:
    """One fresh seeded fleet run (library entry; CLI and tests).
    ``grpc_hub=True`` serves the occupancy hub behind a localhost bulk
    gRPC server (real wire framing + typed status mapping) instead of
    the shared in-process object — same invariants; byte-determinism
    holds run-to-run (--selfcheck), NOT across transports (the
    write-behind row buffer re-times hub version bumps)."""
    return FleetSimHarness(
        profile, seed=seed, cycles=cycles, replicas=replicas,
        pipelined=pipelined, streaming=streaming, grpc_hub=grpc_hub,
        flight_dump=flight_dump,
    ).run()
