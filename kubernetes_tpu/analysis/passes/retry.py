"""RETRY001 — retry discipline at RPC call sites (project-wide).

The fleet tier's contract (PR 13/15, server/bulk.py + fleet/runtime.py):

- transport faults (``UNAVAILABLE``-class, ``ExchangeUnreachable``)
  are retried with **bounded attempts and full-jitter exponential
  backoff** (``rng.uniform(0, base * 2**attempt)`` before the next
  try) — a fleet of replicas retrying in lockstep against a recovering
  hub is a self-inflicted outage;
- **semantic rejections are never retried**: ``AdmitConflict`` means
  the admission CAS lost — the row changed, and replaying the same
  request can double-place a pod. It must propagate to the conflict
  re-solve path, not sit inside a retry loop.

What counts as a *retry loop* (fixture-pinned, deliberately narrow so
work-drain loops like ``while self._sealed:`` stay out of scope):

- ``for <v> in range(...)`` — the bounded-attempts idiom — or an
  unconditional ``while True:`` loop,
- containing a ``try`` whose handler *swallows* the exception (its
  body does not end in ``raise``/``return``/``break``), letting the
  loop try again.

For such loops two rules fire:

- **RETRY001/non-retryable**: a swallowing handler that names a type
  in ``AnalysisContext.non_retryable_errors`` (default
  ``AdmitConflict``). Handlers that re-raise are fine — that is the
  documented failover idiom.
- **RETRY001/backoff**: no full-jitter backoff anywhere in the loop —
  neither an inline ``sleep(...uniform(...))`` (sync or awaited) nor a
  call resolving, through the cross-module call graph, to a helper
  that performs one.
"""

from __future__ import annotations

import ast

from ..callgraph import own_nodes
from ..core import AnalysisContext, Finding
from ..project import ProjectGraph, ProjectPass

_JITTER_SOURCES = {"uniform", "random", "triangular", "betavariate"}


def _is_sleep_call(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "sleep") or (
        isinstance(f, ast.Name) and f.id == "sleep"
    )


def _has_jitter_arg(node: ast.Call) -> bool:
    for arg in list(node.args) + [kw.value for kw in node.keywords]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Call):
                f = sub.func
                name = (
                    f.attr
                    if isinstance(f, ast.Attribute)
                    else f.id if isinstance(f, ast.Name) else ""
                )
                if name in _JITTER_SOURCES:
                    return True
    return False


def _jittered_sleep_direct(fnode) -> bool:
    for node in own_nodes(fnode):
        if (
            isinstance(node, ast.Call)
            and _is_sleep_call(node)
            and _has_jitter_arg(node)
        ):
            return True
    return False


def _exception_names(type_expr) -> set:
    """Names caught by an except clause (Name, dotted, or tuple)."""
    if type_expr is None:
        return set()
    items = (
        list(type_expr.elts)
        if isinstance(type_expr, ast.Tuple)
        else [type_expr]
    )
    out = set()
    for item in items:
        if isinstance(item, ast.Name):
            out.add(item.id)
        elif isinstance(item, ast.Attribute):
            out.add(item.attr)
    return out


def _swallows(handler: ast.ExceptHandler) -> bool:
    """The handler lets the loop continue to another attempt."""
    if not handler.body:
        return True
    last = handler.body[-1]
    return not isinstance(last, (ast.Raise, ast.Return, ast.Break))


class RetryPass(ProjectPass):
    rule = "RETRY001"
    title = "retry discipline (typed errors, full-jitter backoff)"

    def run_project(
        self, project: ProjectGraph, ctx: AnalysisContext
    ) -> list:
        direct = {
            node_id
            for node_id in project.all_nodes()
            if _jittered_sleep_direct(project.function(node_id).node)
        }
        # nodes from which a jittered sleep is reachable: a loop calling
        # self._backoff(attempt) is properly backed off
        jittery = project.reaches(direct) if direct else set()

        findings: list[Finding] = []
        for rel in sorted(project.graphs):
            graph = project.graphs[rel]
            m = project.modules[rel]
            for qual in sorted(graph.functions):
                finfo = graph.functions[qual]
                self._scan(
                    finfo.node.body,
                    m,
                    rel,
                    finfo,
                    project,
                    jittery,
                    ctx,
                    findings,
                )
        return findings

    # -- loop discovery ----------------------------------------------------

    def _scan(
        self, stmts, m, rel, finfo, project, jittery, ctx, findings
    ) -> None:
        for stmt in stmts:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            if isinstance(stmt, (ast.For, ast.While)) and _retry_shape(stmt):
                self._check_loop(
                    stmt, m, rel, finfo, project, jittery, ctx, findings
                )
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.stmt):
                    self._scan(
                        [child], m, rel, finfo, project, jittery, ctx,
                        findings,
                    )
                elif isinstance(child, (ast.ExceptHandler, ast.match_case)):
                    self._scan(
                        child.body, m, rel, finfo, project, jittery, ctx,
                        findings,
                    )

    def _check_loop(
        self, loop, m, rel, finfo, project, jittery, ctx, findings
    ) -> None:
        swallowing = [
            h
            for t in _tries_in(loop.body)
            for h in t.handlers
            if _swallows(h)
        ]
        if not swallowing:
            return
        bad = set(ctx.non_retryable_errors)
        for h in swallowing:
            caught = _exception_names(h.type) & bad
            for name in sorted(caught):
                findings.append(
                    Finding(
                        rule=self.rule,
                        path=m.path,
                        line=h.lineno,
                        message=(
                            f"non-retryable '{name}' is swallowed inside "
                            "a retry loop — a semantic rejection must "
                            "not be replayed"
                        ),
                        hint=(
                            "re-raise it (the failover idiom: 'except "
                            f"{name}: raise') and let the conflict "
                            "re-solve path handle it"
                        ),
                    )
                )
        if not self._loop_has_backoff(loop, rel, finfo, project, jittery):
            findings.append(
                Finding(
                    rule=self.rule,
                    path=m.path,
                    line=loop.lineno,
                    message=(
                        "retry loop has no full-jitter backoff — "
                        "synchronized retries stampede a recovering "
                        "endpoint"
                    ),
                    hint=(
                        "sleep rng.uniform(0, base * 2**attempt) before "
                        "the next try (see RemoteOccupancyExchange._op), "
                        "or route through a helper that does"
                    ),
                )
            )

    def _loop_has_backoff(
        self, loop, rel, finfo, project, jittery
    ) -> bool:
        env = None
        for node in _walk_no_defs(loop.body):
            if not isinstance(node, ast.Call):
                continue
            if _is_sleep_call(node) and _has_jitter_arg(node):
                return True
            if jittery:
                if env is None:
                    env = project.local_env(rel, finfo)
                if project.call_targets(rel, finfo, node, env) & jittery:
                    return True
        return False


def _retry_shape(loop) -> bool:
    if isinstance(loop, ast.For):
        it = loop.iter
        return (
            isinstance(it, ast.Call)
            and (
                (isinstance(it.func, ast.Name) and it.func.id == "range")
                or (
                    isinstance(it.func, ast.Attribute)
                    and it.func.attr == "range"
                )
            )
        )
    if isinstance(loop, ast.While):
        t = loop.test
        return isinstance(t, ast.Constant) and bool(t.value)
    return False


def _tries_in(stmts) -> list:
    """Try statements within a loop body, not crossing into nested
    loops (their retries are judged on their own) or nested defs."""
    out = []
    for stmt in stmts:
        if isinstance(
            stmt,
            (
                ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                ast.For, ast.AsyncFor, ast.While,
            ),
        ):
            continue
        if isinstance(stmt, ast.Try):
            out.append(stmt)
            out.extend(_tries_in(stmt.body))
            # the else/finally blocks run in the loop too
            out.extend(_tries_in(stmt.orelse))
            out.extend(_tries_in(stmt.finalbody))
            continue
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                out.extend(_tries_in([child]))
            elif isinstance(child, (ast.ExceptHandler, ast.match_case)):
                out.extend(_tries_in(child.body))
    return out


def _walk_no_defs(stmts):
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda),
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))
