"""scheduler_perf-compatible YAML runner tests."""

import textwrap

from kubernetes_tpu.perf.runner import PerfRunner
from kubernetes_tpu.scheduler import SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig


def write_config(tmp_path, text):
    p = tmp_path / "perf.yaml"
    p.write_text(textwrap.dedent(text))
    return p


def runner():
    return PerfRunner(
        SchedulerConfig(batch_size=256, solver=ExactSolverConfig(tie_break="first"))
    )


def test_shipped_performance_config_runs():
    """The in-repo performance-config.yaml (the operator-facing
    scheduler_perf DSL artifact) must parse and schedule its
    SchedulingBasic workload end to end."""
    import pathlib

    import kubernetes_tpu.perf as perf_pkg

    cfg = pathlib.Path(perf_pkg.__file__).parent / "performance-config.yaml"
    results = runner().run_file(cfg, workload_filter="500Nodes")
    basic = [r for r in results if r.test_case == "SchedulingBasic"]
    assert basic and basic[0].scheduled == 1500
    assert basic[0].unschedulable == 0
    # every test case in the file must have executed its 500Nodes workload
    # (a superset assertion would hide a case silently dropping out, so
    # keep the exact set and grow it with the config — ADVICE r5 #1)
    assert {r.test_case for r in results} == {
        "SchedulingBasic",
        "SchedulingPodAntiAffinity",
        "SchedulingPodTopologySpread",
        "SchedulingWithMixedChurn",
        "SchedulingGatedPods",
        "SteadyStateArrival",
    }
    anti = [r for r in results if r.test_case == "SchedulingPodAntiAffinity"][0]
    assert anti.scheduled == 400


def test_scheduling_basic_shape(tmp_path):
    cfg = write_config(
        tmp_path,
        """
        - name: SchedulingBasic
          workloadTemplate:
            - opcode: createNodes
              countParam: $initNodes
            - opcode: createPods
              countParam: $initPods
            - opcode: barrier
            - opcode: createPods
              countParam: $measurePods
              collectMetrics: true
            - opcode: barrier
          workloads:
            - name: 50Nodes
              params: {initNodes: 50, initPods: 50, measurePods: 100}
        """,
    )
    results = runner().run_file(cfg)
    assert len(results) == 1
    r = results[0]
    assert r.test_case == "SchedulingBasic"
    assert r.workload == "50Nodes"
    assert r.scheduled == 150
    assert r.measured_pods == 100
    assert r.unschedulable == 0
    s = r.throughput_summary()
    assert s["avg"] > 0 and s["p50"] > 0


def test_custom_templates_and_params(tmp_path):
    (tmp_path / "node.yaml").write_text(
        textwrap.dedent(
            """
            metadata:
              name: big-{{.Index}}
              labels: {zone: z0}
            status:
              allocatable: {cpu: "64", memory: 256Gi, pods: "200"}
            """
        )
    )
    cfg = write_config(
        tmp_path,
        """
        - name: CustomTemplates
          workloadTemplate:
            - opcode: createNodes
              count: 3
              nodeTemplatePath: node.yaml
            - opcode: createPods
              count: 10
              podTemplate:
                metadata:
                  generateName: app-
                spec:
                  containers:
                    - name: c
                      resources:
                        requests: {cpu: 500m}
              collectMetrics: true
            - opcode: barrier
          workloads:
            - name: only
              params: {}
        """,
    )
    results = runner().run_file(cfg)
    assert results[0].scheduled == 10
    assert results[0].unschedulable == 0


def test_unschedulable_counted(tmp_path):
    cfg = write_config(
        tmp_path,
        """
        - name: Overload
          workloadTemplate:
            - opcode: createNodes
              count: 1
              nodeTemplate:
                metadata: {name: "tiny-{{.Index}}"}
                status:
                  allocatable: {cpu: "2", memory: 8Gi, pods: "110"}
            - opcode: createPods
              count: 4
            - opcode: barrier
          workloads:
            - name: only
              params: {}
        """,
    )
    r = runner().run_file(cfg)[0]
    # default pods want 1 cpu: only 2 fit on the tiny node
    assert r.scheduled == 2
    assert r.unschedulable >= 2


def test_threshold_gates_steady_state_not_avg():
    """The threshold assert gates POST-WARMUP steady-state pods/s: the
    first measured batch (the compile stall) is excluded, time-weighted
    over the rest — an avg dominated by one slow compile must neither
    flake a healthy run nor hide a sustained regression."""
    from kubernetes_tpu.perf.runner import WorkloadResult

    # healthy run, slow first batch: avg ~18 pods/s, steady 100 pods/s
    r = WorkloadResult(
        "t", "w", threshold=50.0, measured_pods=200, measure_seconds=11.0
    )
    r.batch_samples = [(10.0, 100), (0.5, 50), (0.5, 50)]
    r.samples = [10.0, 100.0, 100.0]
    r.check_threshold()
    assert r.passed  # avg (~18) would have failed the 50 floor
    assert r.steady_pods_per_sec() == 100.0
    assert r.throughput_summary()["steady"] == 100.0

    # sustained regression hidden under a fast compile: steady gates it
    r2 = WorkloadResult(
        "t", "w", threshold=50.0, measured_pods=200, measure_seconds=3.0
    )
    r2.batch_samples = [(0.1, 100), (5.0, 50), (5.0, 50)]
    r2.samples = [1000.0, 10.0, 10.0]
    r2.check_threshold()
    assert not r2.passed
    # single-batch runs fall back to the overall avg
    r3 = WorkloadResult(
        "t", "w", threshold=50.0, measured_pods=100, measure_seconds=1.0
    )
    r3.batch_samples = [(1.0, 100)]
    r3.samples = [100.0]
    r3.check_threshold()
    assert r3.passed
