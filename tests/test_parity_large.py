"""Large-scale parity gate (VERDICT r2 #8): oracle == device at ~2k pods x
1k+ nodes with mixed spread/interpod/ports, where padding/bucketing/
normalization edges actually bite. Sampled asserts (SURVEY §8.6): every
step is replayed into oracle state; every 16th step plus every
unschedulable step gets the full tie-set check.

Plus hypothesis property coverage for the spread and interpod kernels
(previously only noderesources + quantity had property tests): randomized
constraint content on FIXED shapes (one executable, no recompile storm),
validated via the oracle replay.
"""

import numpy as np
from _hypothesis_compat import given, settings, st

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.ops.oracle.profile import FullOracle, make_oracle_nodes
from kubernetes_tpu.solver.exact import ExactSolver, ExactSolverConfig
from kubernetes_tpu.tensorize.interpod import build_interpod_tensors
from kubernetes_tpu.tensorize.plugins import (
    build_port_tensors,
    build_static_tensors,
)
from kubernetes_tpu.tensorize.schema import (
    ResourceVocab,
    build_node_batch,
    build_pod_batch,
)
from kubernetes_tpu.tensorize.spread import build_spread_tensors

GB = 1024**3
ZONE = "topology.kubernetes.io/zone"
HOST = "kubernetes.io/hostname"


def solve_and_validate(nodes, pods, sample_every=1):
    """Device solve (full tensorizer pipeline) -> oracle replay."""
    vocab = ResourceVocab.build(pods, nodes)
    nbatch = build_node_batch(nodes, vocab=vocab)
    pbatch = build_pod_batch(pods, vocab)
    slot_nodes = list(nodes) + [None] * (nbatch.padded - len(nodes))
    static = build_static_tensors(pods, pbatch, slot_nodes, nbatch.padded)
    ports = build_port_tensors(pods, pbatch, slot_nodes, {}, nbatch.padded)
    spread = build_spread_tensors(
        pods, static.reps, pbatch, slot_nodes, {}, nbatch.padded, static.c_pad
    )
    interpod = build_interpod_tensors(
        pods, static.reps, pbatch, slot_nodes, {}, nbatch.padded, static.c_pad
    )
    solver = ExactSolver(ExactSolverConfig(tie_break="first"))
    assignments = solver.solve(nbatch, pbatch, static, ports, spread, interpod)

    oracle = FullOracle(make_oracle_nodes(nodes))
    names = [nbatch.names[a] if a >= 0 else None for a in assignments]
    sample = None
    if sample_every > 1:
        sample = {
            i
            for i in range(len(pods))
            if i % sample_every == 0 or assignments[i] < 0
        }
    errors = oracle.validate_assignments(
        pods, list(assignments), names=names, sample=sample
    )
    assert not errors, "\n".join(errors[:5])
    return assignments


def test_large_mixed_cluster_parity():
    """1,040 nodes x 2,048 mixed pods: plain (varied sizes), hard+soft zone
    spread, hostname anti-affinity, preferred affinity, host ports, node
    selectors — one device solve, oracle-replayed with sampled checks."""
    rng = np.random.default_rng(7)
    nodes = []
    for i in range(1040):
        b = (
            MakeNode()
            .name(f"n-{i:04}")
            .capacity({"cpu": "16", "memory": "64Gi", "pods": "110"})
            .label(ZONE, f"z{i % 3}")
            .label(HOST, f"n-{i:04}")
        )
        if i % 40 == 0:
            b = b.taint("dedicated", "batch", "NoSchedule")
        if i % 7 == 0:
            b = b.label("disk", "ssd")
        nodes.append(b.obj())

    pods = []
    for i in range(2048):
        kind = rng.integers(0, 10)
        cpu = int(rng.integers(1, 9)) * 250
        mem = int(rng.integers(1, 5)) * GB
        b = MakePod().name(f"p-{i:05}").req({"cpu": f"{cpu}m", "memory": mem})
        if kind < 3:
            pass  # plain
        elif kind < 5:
            b = b.label("app", "web").spread_constraint(
                1, ZONE, "DoNotSchedule", {"app": "web"}
            )
        elif kind < 6:
            b = b.label("app", "soft").spread_constraint(
                2, ZONE, "ScheduleAnyway", {"app": "soft"}
            )
        elif kind < 8:
            b = b.label("app", f"anti-{i % 4}").pod_anti_affinity(
                HOST, {"app": f"anti-{i % 4}"}
            )
        elif kind < 9:
            b = b.label("app", "pref").preferred_pod_affinity(
                10, ZONE, {"app": "pref"}
            )
        else:
            b = b.node_selector({"disk": "ssd"}).host_port(
                9000 + int(i % 16)
            )
        pods.append(b.obj())

    assignments = solve_and_validate(nodes, pods, sample_every=16)
    placed = int((assignments >= 0).sum())
    # the workload is loose enough that the vast majority must place
    assert placed > 1800, f"only {placed}/2048 placed"


@settings(max_examples=15, deadline=None)
@given(
    skews=st.lists(st.integers(1, 3), min_size=2, max_size=2),
    hard=st.lists(st.booleans(), min_size=2, max_size=2),
    seed=st.integers(0, 2**31 - 1),
)
def test_spread_kernels_property(skews, hard, seed):
    """Random spread-constraint content on fixed shapes: device scan must
    stay inside the oracle tie set at every step."""
    rng = np.random.default_rng(seed)
    nodes = [
        MakeNode()
        .name(f"n{i}")
        .capacity({"cpu": "8", "memory": "32Gi", "pods": "20"})
        .label(ZONE, f"z{i % 3}")
        .label(HOST, f"n{i}")
        .obj()
        for i in range(8)
    ]
    pods = []
    for i in range(12):
        which = int(rng.integers(0, 2))
        b = (
            MakePod()
            .name(f"p{i:02}")
            .label("grp", f"g{which}")
            .req({"cpu": "500m", "memory": "1Gi"})
            .spread_constraint(
                skews[which],
                ZONE if rng.integers(0, 2) else HOST,
                "DoNotSchedule" if hard[which] else "ScheduleAnyway",
                {"grp": f"g{which}"},
            )
        )
        pods.append(b.obj())
    solve_and_validate(nodes, pods)


@settings(max_examples=15, deadline=None)
@given(
    topo=st.sampled_from([ZONE, HOST]),
    weight=st.integers(1, 100),
    seed=st.integers(0, 2**31 - 1),
)
def test_interpod_kernels_property(topo, weight, seed):
    """Random interpod affinity/anti-affinity content on fixed shapes."""
    rng = np.random.default_rng(seed)
    nodes = [
        MakeNode()
        .name(f"n{i}")
        .capacity({"cpu": "8", "memory": "32Gi", "pods": "20"})
        .label(ZONE, f"z{i % 3}")
        .label(HOST, f"n{i}")
        .obj()
        for i in range(8)
    ]
    pods = []
    for i in range(12):
        grp = f"g{int(rng.integers(0, 3))}"
        b = MakePod().name(f"p{i:02}").label("app", grp).req(
            {"cpu": "250m", "memory": "512Mi"}
        )
        mode = int(rng.integers(0, 3))
        if mode == 0:
            b = b.pod_anti_affinity(topo, {"app": grp})
        elif mode == 1:
            b = b.pod_affinity(topo, {"app": grp})
        else:
            b = b.preferred_pod_affinity(weight, topo, {"app": grp})
        pods.append(b.obj())
    solve_and_validate(nodes, pods)
