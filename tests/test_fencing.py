"""Commit-path fencing tokens + occupancy-staleness bounds +
watch-delivery isolation (PR 8's partition-safety layer).

The fencing-token pattern: every scheduler incarnation binds under a
(role, token) pair granted at the state service; revoking or
re-granting the role fences every outstanding holder — a zombie
(lease-lost, partitioned, or superseded) incarnation's commits reject
with Conflict no matter what its stale cache believes."""

import json

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.fleet import FleetConfig, OccupancyExchange
from kubernetes_tpu.fleet.occupancy import (
    ExchangeUnreachable,
    NodeRow,
    PodRow,
)
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ApiError, ClusterState
from kubernetes_tpu.utils.clock import FakeClock

import pytest


def _node(name="n", cpu="4"):
    return (
        MakeNode()
        .name(name)
        .capacity({"cpu": cpu, "memory": "8Gi", "pods": "10"})
        .obj()
    )


def _cfg(**kw):
    kw.setdefault("solver", ExactSolverConfig(tie_break="first"))
    return SchedulerConfig(**kw)


# -- ClusterState fencing tokens --


class TestFenceTokens:
    def test_grant_and_bind(self):
        cs = ClusterState()
        cs.create_node(_node())
        cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
        token = cs.grant_fence("sched", holder="inc-1")
        cs.bind("default", "p", "n", fence=("sched", token))
        assert cs.get_pod("default", "p").node_name == "n"

    def test_revoked_token_rejected_with_conflict(self):
        cs = ClusterState()
        cs.create_node(_node())
        cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
        token = cs.grant_fence("sched")
        cs.revoke_fence("sched")
        with pytest.raises(ApiError) as exc:
            cs.bind("default", "p", "n", fence=("sched", token))
        assert exc.value.reason == "Conflict"
        assert "fenced" in str(exc.value)
        assert cs.get_pod("default", "p").node_name == ""  # never landed
        assert cs.fence_rejections["sched"] == 1

    def test_regrant_supersedes_old_holder(self):
        cs = ClusterState()
        cs.create_node(_node())
        cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
        old = cs.grant_fence("sched", holder="inc-1")
        new = cs.grant_fence("sched", holder="inc-2")
        with pytest.raises(ApiError):
            cs.bind("default", "p", "n", fence=("sched", old))
        cs.bind("default", "p", "n", fence=("sched", new))
        assert cs.get_pod("default", "p").node_name == "n"

    def test_fence_checked_before_anything_else(self):
        """A fenced bind rejects even for a deleted pod / missing node:
        the authority refuses the zombie outright."""
        cs = ClusterState()
        token = cs.grant_fence("sched")
        cs.revoke_fence("sched")
        with pytest.raises(ApiError) as exc:
            cs.bind("default", "ghost", "nowhere", fence=("sched", token))
        assert "fenced" in str(exc.value)


# -- Scheduler-level fencing --


class TestSchedulerFencing:
    def test_superseded_incarnation_cannot_bind(self):
        """A new incarnation acquiring the same fence role structurally
        fences the old one: its approved binds all fail with Conflict,
        the metric ticks, and the pods requeue instead of double-
        binding."""
        from kubernetes_tpu import metrics

        clock = FakeClock()
        cs = ClusterState()
        cs.create_node(_node(cpu="8"))
        s1 = Scheduler(cs, _cfg(fence_role="sched"), clock=clock)
        before = metrics.commit_fenced_total._value.get()

        # incarnation 2 takes over the role: s1 is now a zombie
        cs.unsubscribe(s1._on_event)  # (keep s1 driveable standalone)
        s2 = Scheduler(
            cs, _cfg(fence_role="sched", incarnation=2), clock=clock
        )
        cs.unsubscribe(s2._on_event)
        cs.subscribe(s1._on_event)  # the zombie still watches

        cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
        r = s1.schedule_batch()
        assert r.scheduled == []
        assert [k for k, _ in r.bind_failures] == ["default/p"]
        assert cs.get_pod("default", "p").node_name == ""
        assert s1._fenced_commits == 1
        assert metrics.commit_fenced_total._value.get() == before + 1
        # the pod requeued (backoff) — not lost
        assert len(s1.queue) == 1

    def test_reacquire_fence_restores_commits(self):
        clock = FakeClock()
        cs = ClusterState()
        cs.create_node(_node())
        s1 = Scheduler(cs, _cfg(fence_role="sched"), clock=clock)
        cs.revoke_fence("sched")
        cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
        r = s1.schedule_batch()
        assert r.scheduled == [] and s1._fenced_commits == 1
        s1.reacquire_fence()
        # the fenced pod parked unschedulable: the 5-minute leftover
        # flush is its guaranteed retry path (no waking cluster event)
        clock.advance(301.0)
        r = s1.schedule_batch()
        assert dict(r.scheduled).get("default/p") == "n"

    def test_no_fence_role_means_no_fencing(self):
        cs = ClusterState()
        cs.create_node(_node())
        s = Scheduler(cs, _cfg(), clock=FakeClock())
        assert s._fence_role is None
        cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
        assert dict(s.schedule_batch().scheduled).get("default/p") == "n"


# -- watch-bus delivery isolation --


class TestWatchDeliveryIsolation:
    def test_bad_subscriber_does_not_block_delivery(self):
        from kubernetes_tpu import metrics

        cs = ClusterState()
        seen_first, seen_last = [], []

        def bad(ev):
            raise RuntimeError("subscriber bug")

        cs.subscribe(lambda ev: seen_first.append(ev))
        cs.subscribe(bad)
        cs.subscribe(lambda ev: seen_last.append(ev))
        before = metrics.watch_delivery_error_total._value.get()
        cs.create_node(_node())
        # the mutation landed, both healthy subscribers got the event,
        # the error was counted, and the event seq stayed intact
        assert cs.get_node("n").name == "n"
        assert len(seen_first) == 1 and len(seen_last) == 1
        assert seen_first[0].resource_version == seen_last[0].resource_version
        assert metrics.watch_delivery_error_total._value.get() == before + 1

    def test_bad_filter_is_isolated_too(self):
        cs = ClusterState()
        seen = []

        def bad_filter(ev):
            raise RuntimeError("filter bug")

        cs.subscribe(lambda ev: None, filter=bad_filter)
        cs.subscribe(lambda ev: seen.append(ev))
        cs.create_node(_node())
        assert len(seen) == 1


# -- occupancy-staleness bounds (fleet conservative admission) --


def _fleet_pair(clock, max_row_age_s=5.0):
    """Two fleet replicas on one cluster + one hub (the sim's wiring,
    miniature)."""
    cs = ClusterState(clock=clock)
    hub = OccupancyExchange(clock=clock)
    for i in range(4):
        node = (
            MakeNode()
            .name(f"n{i}")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "10"})
            .label("topology.kubernetes.io/zone", f"z{i % 2}")
            .obj()
        )
        cs.create_node(node)
    scheds = {}
    for rid in ("r0", "r1"):
        scheds[rid] = Scheduler(
            cs,
            _cfg(
                obs=None,
                fleet=FleetConfig(
                    replica=rid,
                    replicas=("r0", "r1"),
                    exchange=hub,
                    max_row_age_s=max_row_age_s,
                ),
            ),
            clock=clock,
        )
    return cs, hub, scheds


class TestStalenessBounds:
    def test_partitioned_replica_turns_conservative_for_risky_pods(self):
        clock = FakeClock()
        cs, hub, scheds = _fleet_pair(clock, max_row_age_s=5.0)
        s0 = scheds["r0"]
        # cut r0 off from the hub and age past the bound
        hub.set_partitioned("r0", True)
        clock.advance(10.0)
        spread = (
            MakePod()
            .name("risky")
            .label("app", "s")
            .req({"cpu": "1"})
            .spread_constraint(
                1, "topology.kubernetes.io/zone", "DoNotSchedule",
                {"app": "s"},
            )
            .obj()
        )
        owned = next(
            n for n in ("n0", "n1", "n2", "n3") if s0.fleet.owns_node(n)
        )
        with cs.lock:
            why = s0.fleet.admit(spread, owned, s0.cache)
        assert why is not None and "stale" in why
        assert s0.fleet.stale_rejections == 1

    def test_plain_pods_unaffected_by_staleness(self):
        clock = FakeClock()
        cs, hub, scheds = _fleet_pair(clock, max_row_age_s=5.0)
        s0 = scheds["r0"]
        hub.set_partitioned("r0", True)
        clock.advance(10.0)
        plain = MakePod().name("plain").req({"cpu": "1"}).obj()
        owned = next(
            n for n in ("n0", "n1", "n2", "n3") if s0.fleet.owns_node(n)
        )
        with cs.lock:
            assert s0.fleet.admit(plain, owned, s0.cache) is None

    def test_silent_peer_ages_the_view(self):
        """A PEER partitioned from the hub stops publishing: the
        healthy replica's view of it ages out and ITS admission turns
        conservative — the overcommit risk is symmetric."""
        clock = FakeClock()
        cs, hub, scheds = _fleet_pair(clock, max_row_age_s=5.0)
        s0 = scheds["r0"]
        hub.set_partitioned("r1", True)  # r0 still reaches the hub
        clock.advance(10.0)
        spread = (
            MakePod()
            .name("risky")
            .label("app", "s")
            .req({"cpu": "1"})
            .spread_constraint(
                1, "topology.kubernetes.io/zone", "DoNotSchedule",
                {"app": "s"},
            )
            .obj()
        )
        owned = next(
            n for n in ("n0", "n1", "n2", "n3") if s0.fleet.owns_node(n)
        )
        with cs.lock:
            why = s0.fleet.admit(spread, owned, s0.cache)
        assert why is not None and "stale" in why

    def test_fresh_view_admits_normally(self):
        clock = FakeClock()
        cs, hub, scheds = _fleet_pair(clock, max_row_age_s=5.0)
        s0 = scheds["r0"]
        clock.advance(10.0)
        # both replicas republish (fresh contact)
        with cs.lock:
            for s in scheds.values():
                s.fleet.publish_inventory()
        spread = (
            MakePod()
            .name("risky")
            .label("app", "s")
            .req({"cpu": "1"})
            .spread_constraint(
                1, "topology.kubernetes.io/zone", "DoNotSchedule",
                {"app": "s"},
            )
            .obj()
        )
        owned = next(
            n for n in ("n0", "n1", "n2", "n3") if s0.fleet.owns_node(n)
        )
        with cs.lock:
            assert s0.fleet.admit(spread, owned, s0.cache) is None

    def test_drain_progress_refreshes_liveness_mid_lease(self):
        """ISSUE 20 satellite: a replica mid-drain-lease may write
        NOTHING to the hub except per-chunk progress reports for long
        stretches — no row traffic, no republish. Those reports must
        refresh its publish stamp, or the lease holder ages past
        max_row_age_s and flips every peer's constrained admission
        conservative for the whole drain (the companion failure mode
        is test_silent_peer_ages_the_view above)."""
        clock = FakeClock()
        cs, hub, scheds = _fleet_pair(clock, max_row_age_s=5.0)
        s0 = scheds["r0"]
        # r1 holds a drain lease and only ever reports chunk progress
        hub.drain_init("r1", {"r1": ["default/d0", "default/d1"]}, [])
        hub.drain_claim("r1")
        for _ in range(4):
            clock.advance(3.0)  # 12s total: far past the 5s bound
            hub.drain_progress("r1", [])  # empty chunk still touches
        with cs.lock:
            s0.fleet.publish_inventory()  # r0's own stamp is fresh
        spread = (
            MakePod()
            .name("risky")
            .label("app", "s")
            .req({"cpu": "1"})
            .spread_constraint(
                1, "topology.kubernetes.io/zone", "DoNotSchedule",
                {"app": "s"},
            )
            .obj()
        )
        owned = next(
            n for n in ("n0", "n1", "n2", "n3") if s0.fleet.owns_node(n)
        )
        with cs.lock:
            assert s0.fleet.admit(spread, owned, s0.cache) is None
        assert s0.fleet.stale_rejections == 0

    def test_partitioned_stage_marks_dirty_and_resync_republishes(self):
        clock = FakeClock()
        cs, hub, scheds = _fleet_pair(clock)
        s0 = scheds["r0"]
        hub.set_partitioned("r0", True)
        pod = MakePod().name("p").label("app", "x").req({"cpu": "1"}).obj()
        owned = next(
            n for n in ("n0", "n1", "n2", "n3") if s0.fleet.owns_node(n)
        )
        with cs.lock:
            s0.fleet.stage(pod, owned, s0.cache)
        assert s0.fleet._exchange_dirty
        hub.set_partitioned("r0", False)
        s0.fleet.maybe_resync(s0)
        assert not s0.fleet._exchange_dirty

    def test_peer_death_revokes_its_fence(self):
        clock = FakeClock()
        cs, hub, scheds = _fleet_pair(clock)
        s0, s1 = scheds["r0"], scheds["r1"]
        role1 = s1.fleet.lease_name
        token1 = s1._fence_token
        assert cs.fence_valid(role1, token1)
        # r0 observes r1's lease stale: membership flip revokes r1's
        # commit fence at the state service
        s0.fleet.set_alive(["r0"])
        assert not cs.fence_valid(role1, token1)


# -- exchange partition seam --


class TestExchangePartitionSeam:
    def test_partitioned_ops_raise(self):
        hub = OccupancyExchange(clock=FakeClock())
        hub.set_partitioned("r0", True)
        with pytest.raises(ExchangeUnreachable):
            hub.peers_view("r0")
        with pytest.raises(ExchangeUnreachable):
            hub.publish_nodes("r0", [NodeRow(node="n")])
        with pytest.raises(ExchangeUnreachable):
            hub.peers_version("r0")
        # other replicas unaffected
        hub.publish_nodes("r1", [NodeRow(node="m")])
        assert hub.peers_view("r1") is not None

    def test_peer_ages_track_publish_times(self):
        clock = FakeClock()
        hub = OccupancyExchange(clock=clock)
        hub.publish_nodes("r0", [NodeRow(node="n")])
        hub.publish_nodes("r1", [NodeRow(node="m")])
        clock.advance(7.0)
        hub.publish_nodes("r1", [NodeRow(node="m")])
        view = hub.peers_view("r1")
        assert dict(view.peer_ages)["r0"] == 7.0
        view0 = hub.peers_view("r0")
        assert dict(view0.peer_ages)["r1"] == 0.0
