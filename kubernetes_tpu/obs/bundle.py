"""Capture-on-anomaly replay bundles: the production forensic loop.

When the sentinel fires (or the breaker trips, a gang quarantines, a
sim invariant fires, or an operator hits ``/debug/profile?capture=1``),
snapshot the most recent batch's **full solve input** — the tensorized
containers exactly as ``ExactSolver.solve`` received them, the solver
config fingerprint, the PRNG step counter, a carry-state tag — plus
the flight-recorder slice, the journal tail, and a metrics snapshot,
into one self-contained directory. ``python -m kubernetes_tpu.obs
replay <bundle>`` then re-executes the solve offline and asserts
bit-identical assignments: the sim's deterministic-repro story,
extended to a serving process.

Capture path (driver thread, always-on safe):

- the scheduler **arms** the capturer immediately before each device
  dispatch (``_dispatch_group``);
- the solver's ``capture_hook`` hands over the resolved inputs at the
  top of ``solve()`` (pre-PRNG-increment, so ``step_count`` is exactly
  what the replayed solve must use); arrays are copied host-side — a
  few hundred KB per batch, no device sync;
- ``note_assignments`` attaches each flight's assignment slice as it
  is read; a record whose parts cover the batch moves into a small
  ring of complete records;
- ``capture(trigger)`` snapshots the newest complete record to disk.

Carry-state tag: a session solve is only **host-determined** (and so
bit-exactly replayable offline) when the session entered the solve
fully healed and not chained on device-resident carry —
``carry_clean = (not session) or (allow_heal and not
chain_occupancy)``. The sync loop's solves are always carry-clean;
pipelined overlap (``allow_heal=False``) and streaming cross-batch
chains are captured for forensics but marked non-replayable rather
than asserted falsely. Replay additionally requires ``split == 1``
(a split solve's sub-batch chain is session machinery; the carry-clean
capture class the CI proves end-to-end dispatches unsplit).
"""

from __future__ import annotations

import dataclasses
import json
import threading
from collections import OrderedDict, deque
from pathlib import Path

import numpy as np

from .. import metrics

BUNDLE_VERSION = 1
TRIGGERS = ("sentinel", "breaker", "quarantine", "invariant", "manual")

# containers a solve payload may carry, in manifest order. Values are
# (module, class) resolved lazily so importing obs never pulls jax in.
_CONTAINERS = OrderedDict(
    nodes=("kubernetes_tpu.tensorize.schema", "NodeBatch"),
    pods=("kubernetes_tpu.tensorize.schema", "PodBatch"),
    static=("kubernetes_tpu.tensorize.plugins", "StaticPluginTensors"),
    ports=("kubernetes_tpu.tensorize.plugins", "PortTensors"),
    spread=("kubernetes_tpu.tensorize.spread", "SpreadTensors"),
    interpod=("kubernetes_tpu.tensorize.interpod", "InterpodTensors"),
    nominated=("kubernetes_tpu.tensorize.schema", "NominatedTensors"),
)

# non-tensor fields that cannot (or need not) ride the wire: the
# static reps list holds live Pod objects the solve never reads
_SKIP_FIELDS = {("static", "reps")}

# solver-config fields nulled in the fingerprint: consumed by the
# tensorizer (their effect is already IN the captured tensors), and
# not JSON-serializable when set
_CONFIG_SKIP = ("added_affinity",)


def _scalarize(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, (np.bool_,)):
        return bool(v)
    return v


def _encode_container(name: str, obj, arrays: dict) -> dict:
    """One container -> a JSON-ready field manifest + npz array refs."""
    from ..tensorize.schema import ResourceVocab

    out: dict = {}
    for f in dataclasses.fields(obj):
        v = getattr(obj, f.name)
        if (name, f.name) in _SKIP_FIELDS:
            out[f.name] = {"skip": True}
        elif v is None:
            out[f.name] = {"none": True}
        elif isinstance(v, np.ndarray):
            key = f"{name}.{f.name}"
            arrays[key] = v
            out[f.name] = {"array": key}
        elif isinstance(v, ResourceVocab):
            out[f.name] = {"vocab": list(v.names)}
        elif isinstance(v, (list, tuple)) and any(
            isinstance(x, tuple) for x in v
        ):
            # e.g. PortTensors.vocab: list[tuple[str, str, int]] —
            # must round-trip to TUPLES (the solver digests its repr)
            out[f.name] = {"tuples": [list(x) for x in v]}
        elif isinstance(v, (list, tuple)):
            out[f.name] = {"list": [_scalarize(x) for x in v]}
        else:
            out[f.name] = {"scalar": _scalarize(v)}
    return out


def _decode_container(name: str, spec: dict, arrays) -> object:
    import importlib

    from ..tensorize.schema import ResourceVocab

    mod_name, cls_name = _CONTAINERS[name]
    cls = getattr(importlib.import_module(mod_name), cls_name)
    declared = {f.name for f in dataclasses.fields(cls)}
    if set(spec) != declared:
        raise ValueError(
            f"bundle container {name!r} fields {sorted(spec)} do not "
            f"match {cls_name} fields {sorted(declared)} — the bundle "
            "was captured by a different schema version"
        )
    kwargs = {}
    for fname, enc in spec.items():
        if "skip" in enc:
            kwargs[fname] = []
        elif "none" in enc:
            kwargs[fname] = None
        elif "array" in enc:
            kwargs[fname] = np.array(arrays[enc["array"]])
        elif "vocab" in enc:
            kwargs[fname] = ResourceVocab(tuple(enc["vocab"]))
        elif "tuples" in enc:
            kwargs[fname] = [tuple(x) for x in enc["tuples"]]
        elif "list" in enc:
            kwargs[fname] = list(enc["list"])
        else:
            kwargs[fname] = enc["scalar"]
    return cls(**kwargs)


class BundleCapturer:
    """Bounded ring of complete solve records + the disk writer.

    ``out_dir=None`` keeps the ring in memory only (captures still
    count — the sim's determinism selfcheck re-runs without a dir and
    must see identical counts)."""

    def __init__(
        self, out_dir: str | None = None, *, keep: int = 4,
        max_bundles: int = 8,
    ) -> None:
        self.out_dir = out_dir
        self.max_bundles = max_bundles
        self._ring: deque[dict] = deque(maxlen=keep)
        self._pending: OrderedDict[int, dict] = OrderedDict()
        self._armed_step: int | None = None
        self._lock = threading.Lock()
        self._seq = 0
        self.captures = 0  # capture events that found a complete record
        self.missed = 0  # triggers with nothing complete to snapshot
        self.counts: dict[str, int] = {}
        self.written: list[str] = []

    # -- driver-thread capture seams --

    def arm(self, step: int, profile: str = "") -> None:
        """Scheduler-side: the next ``capture_hook`` payload belongs to
        this batch step."""
        with self._lock:
            self._pending[step] = {
                "step": step, "profile": profile, "payload": None,
                "parts": [],
            }
            self._armed_step = step
            while len(self._pending) > 8:
                self._pending.popitem(last=False)

    def on_solve_input(self, **payload) -> None:
        """Installed as ``ExactSolver.capture_hook``: the resolved solve
        inputs, copied host-side. Ignored unless armed (host-tier and
        out-of-scheduler solves don't capture)."""
        with self._lock:
            step = self._armed_step
            rec = self._pending.get(step) if step is not None else None
            if rec is None:
                return
            self._armed_step = None
        containers = {}
        for cname in _CONTAINERS:
            obj = payload.get(cname)
            if obj is None:
                containers[cname] = None
                continue
            copied = {}
            for f in dataclasses.fields(obj):
                v = getattr(obj, f.name)
                copied[f.name] = (
                    np.array(v) if isinstance(v, np.ndarray) else v
                )
            containers[cname] = dataclasses.replace(obj, **{
                k: v for k, v in copied.items()
                if isinstance(v, np.ndarray)
            })
        ns = payload.get("nominated_slot")
        session = payload.get("session", False)
        allow_heal = payload.get("allow_heal", True)
        chain = payload.get("chain_occupancy", False)
        rec["payload"] = {
            "containers": containers,
            "nominated_slot": None if ns is None else np.array(ns),
            "step_count": int(payload.get("step_count", 0)),
            "split": int(payload.get("split", 1)),
            "defer_read": bool(payload.get("defer_read", False)),
            "session": bool(session),
            "allow_heal": bool(allow_heal),
            "chain_occupancy": bool(chain),
            "carry_clean": (not session) or (allow_heal and not chain),
            "num_pods": int(payload["pods"].num_pods),
            "config": payload.get("config"),
        }

    def note_assignments(self, step: int, lo: int, assignments) -> None:
        """A flight of this step was read: attach its assignment slice.
        The record completes when the parts cover the batch's pods."""
        with self._lock:
            rec = self._pending.get(step)
            if rec is None or rec["payload"] is None:
                return
            arr = np.asarray(assignments).astype(np.int64).tolist()
            rec["parts"].append({"lo": int(lo), "assignments": arr})
            covered = sum(len(p["assignments"]) for p in rec["parts"])
            if covered >= rec["payload"]["num_pods"]:
                del self._pending[step]
                self._ring.append(rec)

    def drop(self, step: int) -> None:
        """The step's flights were discarded (fence) — its capture
        record dies with them."""
        with self._lock:
            self._pending.pop(step, None)
            if self._armed_step == step:
                self._armed_step = None

    # -- the trigger --

    def capture(
        self, trigger: str, *, note: str = "", journal_tail=(),
        flight_lines=(), metrics_text: bytes = b"",
    ) -> str | None:
        """Snapshot the newest complete record. Returns the bundle
        directory path (None when nothing is complete, the bundle
        budget is spent, or no ``out_dir`` is configured)."""
        with self._lock:
            rec = self._ring[-1] if self._ring else None
            if rec is None:
                self.missed += 1
                return None
            self.captures += 1
            self.counts[trigger] = self.counts.get(trigger, 0) + 1
            seq = self._seq
            self._seq += 1
        metrics.telemetry_bundles_total.labels(
            trigger if trigger in TRIGGERS else "manual"
        ).inc()
        if self.out_dir is None or seq >= self.max_bundles:
            return None
        return self._write(rec, trigger, seq, note, journal_tail,
                           flight_lines, metrics_text)

    def _write(self, rec, trigger, seq, note, journal_tail,
               flight_lines, metrics_text) -> str:
        p = rec["payload"]
        out = Path(self.out_dir) / f"bundle-{seq:05d}-{trigger}"
        out.mkdir(parents=True, exist_ok=True)
        arrays: dict = {}
        containers = {}
        for cname, obj in p["containers"].items():
            containers[cname] = (
                None if obj is None
                else _encode_container(cname, obj, arrays)
            )
        if p["nominated_slot"] is not None:
            arrays["nominated_slot"] = p["nominated_slot"]
        manifest = {
            "version": BUNDLE_VERSION,
            "trigger": trigger,
            "note": note,
            "step": rec["step"],
            "profile": rec["profile"],
            "step_count": p["step_count"],
            "split": p["split"],
            "defer_read": p["defer_read"],
            "session": p["session"],
            "allow_heal": p["allow_heal"],
            "chain_occupancy": p["chain_occupancy"],
            "carry_clean": p["carry_clean"],
            "num_pods": p["num_pods"],
            "config": p["config"],
            "config_skipped": list(_CONFIG_SKIP),
            "containers": containers,
            "parts": rec["parts"],
        }
        (out / "manifest.json").write_text(
            json.dumps(manifest, indent=1, sort_keys=True)
        )
        with (out / "solve_input.npz").open("wb") as fh:
            np.savez_compressed(fh, **arrays)
        (out / "journal_tail.jsonl").write_text(
            "\n".join(journal_tail) + ("\n" if journal_tail else "")
        )
        (out / "flight.jsonl").write_text(
            "\n".join(flight_lines) + ("\n" if flight_lines else "")
        )
        (out / "metrics.prom").write_bytes(metrics_text)
        self.written.append(str(out))
        return str(out)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "captures": self.captures,
                "missed": self.missed,
                "by_trigger": dict(sorted(self.counts.items())),
                "written": list(self.written),
                "ring_complete": len(self._ring),
                "pending": len(self._pending),
            }


def config_fingerprint(cfg) -> dict:
    """JSON-safe ExactSolverConfig snapshot (tensorizer-only fields
    nulled — their effect is already in the captured tensors)."""
    d = dataclasses.asdict(cfg)
    for k in _CONFIG_SKIP:
        d[k] = None
    return json.loads(json.dumps(d, default=str))


def _rebuild_config(d: dict):
    from ..solver.exact import ExactSolverConfig

    kwargs = dict(d)
    kwargs["rtc_shape"] = tuple(tuple(x) for x in kwargs.get("rtc_shape", ()))
    kwargs["disabled_filters"] = tuple(kwargs.get("disabled_filters", ()))
    declared = {f.name for f in dataclasses.fields(ExactSolverConfig)}
    kwargs = {k: v for k, v in kwargs.items() if k in declared}
    return ExactSolverConfig(**kwargs)


def load_bundle(path: str) -> dict:
    """Manifest + decoded containers of one bundle directory."""
    p = Path(path)
    manifest = json.loads((p / "manifest.json").read_text())
    if manifest.get("version") != BUNDLE_VERSION:
        raise ValueError(
            f"bundle version {manifest.get('version')} != {BUNDLE_VERSION}"
        )
    arrays = np.load(p / "solve_input.npz")
    containers = {}
    for cname, spec in manifest["containers"].items():
        containers[cname] = (
            None if spec is None else _decode_container(cname, spec, arrays)
        )
    nominated_slot = (
        np.array(arrays["nominated_slot"])
        if "nominated_slot" in arrays
        else None
    )
    return {
        "manifest": manifest,
        "containers": containers,
        "nominated_slot": nominated_slot,
    }


def replay_bundle(path: str) -> dict:
    """Re-execute the captured solve offline and compare assignments.

    Returns ``{"replayable", "ok", "detail", "pods", "parts"}`` —
    ``ok`` is only meaningful when ``replayable``: a non-carry-clean
    capture (pipelined overlap / streaming chain) is forensic data,
    not a replay contract."""
    bundle = load_bundle(path)
    m = bundle["manifest"]
    if not m["carry_clean"] or m["split"] != 1:
        return {
            "replayable": False, "ok": False, "pods": m["num_pods"],
            "parts": len(m["parts"]),
            "detail": (
                "not host-determined: "
                + ("device-resident carry (allow_heal=False or "
                   "chain_occupancy)" if not m["carry_clean"]
                   else f"split={m['split']} sub-batch chain")
            ),
        }
    from ..solver.exact import ExactSolver

    cfg = _rebuild_config(m["config"])
    solver = ExactSolver(cfg)
    solver._step_count = m["step_count"]
    c = bundle["containers"]
    # standalone mode (col_versions=None): a carry-clean session solve
    # is host-determined, and the standalone path runs the identical
    # scan over the identical arrays with the identical PRNG key —
    # bit-identical assignments (the sharding-equivalence discipline)
    assignments = solver.solve(
        c["nodes"], c["pods"], c["static"], c["ports"], c["spread"],
        c["interpod"], nominated=c["nominated"],
        nominated_slot=bundle["nominated_slot"],
    )
    replayed = np.asarray(assignments).astype(np.int64)
    mismatches = []
    for part in m["parts"]:
        lo = part["lo"]
        want = np.array(part["assignments"], dtype=np.int64)
        got = replayed[lo: lo + len(want)]
        if not np.array_equal(got, want):
            bad = int(np.count_nonzero(got != want))
            mismatches.append(f"[{lo}:{lo + len(want)}]: {bad} differ")
    detail = (
        "assignments bit-identical"
        if not mismatches
        else "assignment mismatch " + "; ".join(mismatches)
    )
    return {
        "replayable": True, "ok": not mismatches,
        "pods": m["num_pods"], "parts": len(m["parts"]),
        "detail": detail,
    }
