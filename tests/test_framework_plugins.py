"""The extension-point-shaped plugin API (SURVEY §8.2; VERDICT r2 L5c's
"still missing" item): framework/interface.py + runtime.py as the
upstream-test-shaped fixture, and out-of-tree plugins folded into the
device solve via SchedulerConfig.out_of_tree_plugins."""

import numpy as np
import pytest

from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.framework import (
    CycleState,
    FilterPlugin,
    Framework,
    ScorePlugin,
    Status,
)
from kubernetes_tpu.framework.interface import Registry
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState
from kubernetes_tpu.utils.clock import FakeClock


class OddNodesOnly(FilterPlugin):
    """Rejects nodes with an even trailing index."""

    def filter(self, state, pod, node, placed=()):
        if int(node.name.rsplit("-", 1)[-1]) % 2 == 0:
            return Status.unschedulable("even node")
        return Status.success()


class PreferHighIndex(ScorePlugin):
    def __init__(self, weight=5):
        self._w = weight

    def score(self, state, pod, node):
        return min(int(node.name.rsplit("-", 1)[-1]) * 10, 100)

    def weight(self):
        return self._w


def mk_nodes(n=6):
    return [
        MakeNode()
        .name(f"n-{i}")
        .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"})
        .obj()
        for i in range(n)
    ]


# -- the host-side runtime (the upstream-test fixture shape) ----------------


def test_framework_run_all_with_custom_plugins():
    fw = Framework(
        nodes=mk_nodes(),
        registry=Registry(
            filter=[OddNodesOnly()], score=[PreferHighIndex()]
        ),
    )
    pod = MakePod().name("p").req({"cpu": "1"}).obj()
    feasible, scores, st = fw.run_all(pod)
    assert st.is_success
    assert [n.name for n in feasible] == ["n-1", "n-3", "n-5"]
    # custom score steers toward the highest index among feasible
    assert max(scores, key=scores.get) == "n-5"


def test_framework_cycle_state_and_status():
    state = CycleState()
    state.write("k", {"x": 1})
    assert state.read("k") == {"x": 1}
    clone = state.clone()
    clone.write("k", "other")
    assert state.read("k") == {"x": 1}  # clone is independent
    with pytest.raises(KeyError):
        state.read("missing")
    assert Status.unschedulable("r").is_rejection
    assert not Status.error("boom").is_rejection


def test_framework_rejects_out_of_range_scores():
    class Bad(ScorePlugin):
        def score(self, state, pod, node):
            return 101

    fw = Framework(nodes=mk_nodes(2), registry=Registry(score=[Bad()]))
    pod = MakePod().name("p").req({"cpu": "1"}).obj()
    with pytest.raises(ValueError):
        fw.run_score_plugins(CycleState(), pod, list(fw.nodes))


def test_framework_in_tree_pipeline_included():
    """with_default_plugins: in-tree filters run before custom ones."""
    nodes = mk_nodes(3)
    fw = Framework(nodes=nodes)
    big = MakePod().name("big").req({"cpu": "64"}).obj()
    feasible, _, st = fw.run_all(big)
    assert not feasible and st.is_rejection


# -- out-of-tree plugins inside the device solve ----------------------------


def _sched(cs, plugins, group=64):
    return Scheduler(
        cs,
        SchedulerConfig(
            solver=ExactSolverConfig(tie_break="first", group_size=group),
            out_of_tree_plugins=tuple(plugins),
        ),
        clock=FakeClock(),
    )


def test_out_of_tree_filter_gates_the_solve():
    cs = ClusterState()
    for n in mk_nodes():
        cs.create_node(n)
    sched = _sched(cs, [OddNodesOnly()])
    for i in range(4):
        cs.create_pod(MakePod().name(f"p{i}").req({"cpu": "1"}).obj())
    r = sched.schedule_batch()
    assert len(r.scheduled) == 4
    for _, node_name in r.scheduled:
        assert int(node_name.rsplit("-", 1)[-1]) % 2 == 1


def test_out_of_tree_score_steers_the_solve():
    cs = ClusterState()
    for n in mk_nodes():
        cs.create_node(n)
    # heavy custom weight dominates the default headroom scoring
    sched = _sched(cs, [PreferHighIndex(weight=50)])
    cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
    r = sched.schedule_batch()
    assert dict(r.scheduled).get("default/p") == "n-5"


class GoldOnly(FilterPlugin):
    """Label-sensitive filter: only tier=gold pods may use node n-5."""

    def filter(self, state, pod, node, placed=()):
        if node.name == "n-5" and pod.labels.get("tier") != "gold":
            return Status.unschedulable("n-5 reserved for gold")
        return Status.success()


def test_label_sensitive_plugin_splits_classes():
    """Two pods identical except for a label a custom plugin reads must
    NOT share one class representative's verdicts (review-caught)."""
    cs = ClusterState()
    for n in mk_nodes():
        cs.create_node(n)
    sched = _sched(cs, [GoldOnly(), PreferHighIndex(weight=50)])
    cs.create_pod(
        MakePod().name("gold").label("tier", "gold").req({"cpu": "1"}).obj()
    )
    cs.create_pod(
        MakePod().name("bronze").label("tier", "bronze").req({"cpu": "1"}).obj()
    )
    r = sched.schedule_batch()
    placed = dict(r.scheduled)
    assert placed.get("default/gold") == "n-5"
    assert placed.get("default/bronze") not in (None, "n-5")


def test_error_status_aborts_instead_of_masking():
    class Flaky(FilterPlugin):
        def filter(self, state, pod, node, placed=()):
            return Status.error("backend down")

    cs = ClusterState()
    for n in mk_nodes(2):
        cs.create_node(n)
    sched = _sched(cs, [Flaky()])
    cs.create_pod(MakePod().name("p").req({"cpu": "1"}).obj())
    with pytest.raises(RuntimeError, match="backend down"):
        sched.schedule_batch()


def test_out_of_tree_plugins_work_with_grouped_path():
    """Identical pods (grouped fast path) must also see custom tables —
    extra scores fold into the frontier table like ImageLocality."""
    cs = ClusterState()
    for n in mk_nodes():
        cs.create_node(n)
    sched = _sched(cs, [OddNodesOnly(), PreferHighIndex(weight=50)], group=4)
    for i in range(8):
        cs.create_pod(MakePod().name(f"w{i}").req({"cpu": "1"}).obj())
    r = sched.schedule_batch()
    assert len(r.scheduled) == 8
    landed = {node for _, node in r.scheduled}
    assert all(int(n.rsplit("-", 1)[-1]) % 2 == 1 for n in landed)
    # first pods go to n-5 until headroom drops below the custom margin
    assert dict(r.scheduled)["default/w0"] == "n-5"
